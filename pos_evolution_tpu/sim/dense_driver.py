"""Mainnet-scale end-to-end dense simulation on a device mesh (ISSUE 9).

The spec-level ``sim/driver.py`` carries per-message Python objects —
the right tool for adversarial/faulted protocol audits, and the wrong
one for 10^6 validators (building one slot's attestations would cost
minutes of host Python). This driver is the **array level of the whole
simulation loop**: the registry, the latest-message table and the
participation flags live as sharded device columns from genesis, and
every per-slot protocol step is one of the three validator-axis sweeps
run as ``shard_map`` kernels over the ``(pods, shard)`` mesh:

- **fork choice** (north-star config #1): the head query rebuilds the
  per-block vote buckets with the sharded segment-sum vote pass
  (``parallel/sharded.vote_weights_for`` — psum ICI-first, DCN-second),
  then descends on the replicated O(B) block tree
  (``ops/forkchoice.head_from_buckets``);
- **attestation flow**: committee assignment via the swap-or-not
  shuffle (sharded per ``sharded_shuffle``'s index-parallel form), votes
  land as masked elementwise updates on the sharded message/flag
  columns — the dense image of one slot's gossip;
- **aggregation verify** (config #3): each slot's committee aggregates
  run through ``aggregate_verify_batch`` sharded over the committee
  axis;
- **epoch processing** (config #4): the fused ``epoch_core`` sweep as a
  ``shard_map`` with two-axis psum; justification bits and the 4-case
  finalization rule drive real finality.

Everything is integer math, so the sharded run is **bit-identical** to
the single-device one (``mesh=None``) on every mesh shape — pinned in
tests/test_sharded_e2e.py together with the host-walk oracle
(``resident_head_equals_spec_walk``: the device head must equal the
vectorized NumPy walk ``ops/forkchoice.head_host`` over the gathered
message table, subsampled every ``check_walk_every`` slots).

Checkpoint/resume gathers the sharded columns to host (`.npz` + JSON
meta) and re-shards on the mesh active at resume time — resuming on a
*different* mesh shape (or a single device) is bit-identical by the
same kernel contracts.

``scripts/multichip_demo.py`` drives this at 1M validators for
``MULTICHIP_r09.json``; ``bench_all.py`` times a small configuration as
the ``bench_shard`` history emission.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json

import numpy as np

from pos_evolution_tpu.config import Config, mainnet_config

__all__ = ["DenseSimulation"]


def _hash(*parts) -> bytes:
    h = hashlib.sha256()
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
    return h.digest()


from pos_evolution_tpu.ops.variant_tally import (  # noqa: E402
    next_pow2 as _next_pow2,
)


class DenseSimulation:
    """Honest synchronous multi-epoch run, entirely at the array level.

    ``mesh=None`` runs the identical loop on a single device (the
    differential twin). ``n_validators`` must divide by ``mesh.size``
    when a mesh is given (the shuffle shards the index axis evenly).
    """

    def __init__(self, n_validators: int, cfg: Config | None = None,
                 mesh=None, seed: int = 0, shuffle_rounds: int = 10,
                 verify_aggregates: bool = True, capacity: int = 256,
                 check_walk_every: int = 16, autocheckpoint=None):
        import jax.numpy as jnp
        self.cfg = cfg or mainnet_config()
        self.n = int(n_validators)
        self.mesh = mesh
        self.seed = int(seed)
        self.shuffle_rounds = int(shuffle_rounds)
        self.verify_aggregates = bool(verify_aggregates)
        self.check_walk_every = int(check_walk_every)
        self.S = int(self.cfg.slots_per_epoch)
        if mesh is not None and self.n % mesh.size != 0:
            raise ValueError(
                f"n_validators={self.n} must divide by the mesh device "
                f"count {mesh.size}")
        self._npad = self.n  # registry rows incl. inert padding (== n here)

        # --- registry: sharded-resident from genesis -----------------------
        gwei = 10**9
        far = np.int64(2**62)  # FAR_FUTURE_I64

        def fill_const(v, dtype):
            return lambda lo, hi: np.full(hi - lo, v, dtype)

        col_fills = {
            "effective_balance": (32 * gwei, np.int64),
            "balance": (32 * gwei, np.int64),
            "activation_epoch": (0, np.int64),
            "exit_epoch": (far, np.int64),
            "withdrawable_epoch": (far, np.int64),
            "slashed": (False, bool),
            "prev_flags": (0, np.uint8),
            "cur_flags": (0, np.uint8),
            "inactivity_scores": (0, np.int64),
        }
        from pos_evolution_tpu.ops.epoch import DenseRegistry
        if mesh is not None:
            # never materialized unsharded: each device fills its slice,
            # placed per the partition rules (registry/* and messages/*)
            from pos_evolution_tpu.parallel.partition import (
                build_sharded,
                spec_for,
            )
            self.registry = DenseRegistry(**{
                f: build_sharded(mesh, spec_for(f"registry/{f}"), (self.n,),
                                 dt, fill_const(v, dt))
                for f, (v, dt) in col_fills.items()})
            self.msg_block = build_sharded(
                mesh, spec_for("messages/msg_block"), (self.n,),
                np.int32, fill_const(-1, np.int32))
            self.msg_epoch = build_sharded(
                mesh, spec_for("messages/msg_epoch"), (self.n,),
                np.int64, fill_const(0, np.int64))
        else:
            self.registry = DenseRegistry(**{
                f: jnp.full(self.n, v, dtype=dt)
                for f, (v, dt) in col_fills.items()})
            self.msg_block = jnp.full(self.n, -1, dtype=jnp.int32)
            self.msg_epoch = jnp.zeros(self.n, dtype=jnp.int64)

        # --- replicated O(B) block tree ------------------------------------
        self.capacity = _next_pow2(capacity)
        self.roots: list[bytes] = []
        self.parents: list[int] = []
        self.block_slots: list[int] = []
        self._parent_d = jnp.full(self.capacity, -1, dtype=jnp.int32)
        self._slot_d = jnp.zeros(self.capacity, dtype=jnp.int32)
        self._rank_d = jnp.zeros(self.capacity, dtype=jnp.int32)
        self._real_d = jnp.zeros(self.capacity, dtype=bool)
        self._viable_d = jnp.ones(self.capacity, dtype=bool)

        # --- FFG scalars ----------------------------------------------------
        self.slot = 0
        self.bits = np.zeros(4, dtype=bool)
        self.prev_just = (0, 0)   # (epoch, block index)
        self.cur_just = (0, 0)
        self.finalized = (0, 0)
        self.epoch_start_idx: dict[int, int] = {0: 0}
        self.metrics: list[dict] = []
        self.aggregates_verified = 0
        self.walk_checks: list[bool] = []
        self._epoch_ready = -1
        self._perm_host: np.ndarray | None = None

        # synthetic per-validator pubkeys -> replicated signature midstates
        # (the pk table is replicated by design, SURVEY's config #3 note)
        from pos_evolution_tpu.ops.aggregation import precompute_pk_states
        rng = np.random.default_rng(self.seed)
        self.pk_states = precompute_pk_states(
            rng.integers(0, 256, (self.n, 48)).astype(np.uint8))

        self._append_block(_hash(b"genesis", self.seed), -1, 0)

        # Run supervision (resilience/, ISSUE 10, DESIGN.md §18): the
        # dense driver's async capture is the gather-then-compress
        # split — columns come to host synchronously (host_gather, the
        # cheap device-synchronous part), npz compression runs on the
        # manager's writer thread, so multi-epoch walls never stall on
        # serialization.
        self.supervision = None
        if autocheckpoint is not None:
            self.attach_autocheckpoint(autocheckpoint)

    # -- block tree ------------------------------------------------------------

    def _append_block(self, root: bytes, parent: int, slot: int) -> int:
        import jax.numpy as jnp
        i = len(self.roots)
        if i >= self.capacity:
            self._grow(self.capacity * 2)
        self.roots.append(root)
        self.parents.append(parent)
        self.block_slots.append(slot)
        self._parent_d = self._parent_d.at[i].set(parent)
        self._slot_d = self._slot_d.at[i].set(slot)
        self._real_d = self._real_d.at[i].set(True)
        order = np.argsort(np.argsort(np.array(self.roots, dtype=object)))
        rank = np.zeros(self.capacity, np.int32)
        rank[: len(self.roots)] = order
        self._rank_d = jnp.asarray(rank)
        return i

    def _grow(self, new_capacity: int) -> None:
        import jax.numpy as jnp
        new_capacity = _next_pow2(new_capacity)
        b = len(self.roots)
        parent = np.full(new_capacity, -1, np.int32)
        parent[:b] = self.parents
        slot = np.zeros(new_capacity, np.int32)
        slot[:b] = self.block_slots
        real = np.zeros(new_capacity, bool)
        real[:b] = True
        self.capacity = new_capacity
        self._parent_d = jnp.asarray(parent)
        self._slot_d = jnp.asarray(slot)
        self._rank_d = jnp.zeros(new_capacity, jnp.int32)
        self._real_d = jnp.asarray(real)
        self._viable_d = jnp.ones(new_capacity, bool)

    # -- committees ------------------------------------------------------------

    def _start_epoch(self, epoch: int) -> None:
        """Shuffle the registry into this epoch's slot assignment
        (config #2: the index axis is embarrassingly parallel)."""
        import jax.numpy as jnp
        seed = _hash(b"shuffle", self.seed, epoch)[:32]
        if self.mesh is not None:
            from pos_evolution_tpu.ops.shuffle import _seed_words, host_pivots
            from pos_evolution_tpu.parallel.sharded import shuffle_for
            shuf = shuffle_for(self.mesh, self.n, self.shuffle_rounds)
            perm = shuf(jnp.asarray(_seed_words(seed)),
                        jnp.asarray(host_pivots(seed, self.n,
                                                self.shuffle_rounds)),
                        jnp.arange(self.n, dtype=jnp.int32))
        else:
            from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
            perm = shuffle_permutation_jax(seed, self.n, self.shuffle_rounds)
        perm_host = np.asarray(perm).astype(np.int64)
        self._perm_host = perm_host
        self._inv_perm = np.argsort(perm_host).astype(np.int64)
        assigned = perm_host * self.S // self.n
        self._assigned = self._place_validator_col(assigned.astype(np.int64))
        self._epoch_ready = epoch

    def _place_validator_col(self, a: np.ndarray,
                             name: str = "messages/assigned"):
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(a)
        from pos_evolution_tpu.parallel.partition import shard_leaf, spec_for
        return shard_leaf(self.mesh, spec_for(name), a)

    def _slot_attesters(self, slot_in_epoch: int) -> np.ndarray:
        t = int(slot_in_epoch)
        lo = (t * self.n + self.S - 1) // self.S
        hi = ((t + 1) * self.n + self.S - 1) // self.S
        return self._inv_perm[lo:hi]

    # -- fork choice -----------------------------------------------------------

    def _head(self) -> int:
        import jax.numpy as jnp

        from pos_evolution_tpu.ops.forkchoice import (
            head_from_buckets,
            rebuild_buckets,
        )
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import vote_weights_for
            buckets = vote_weights_for(self.mesh, self.capacity)(
                self.msg_block, self.registry.effective_balance)
        else:
            buckets = rebuild_buckets(self.msg_block,
                                      self.registry.effective_balance,
                                      self.capacity)
        head_idx, _ = head_from_buckets(
            self._parent_d, self._real_d, self._rank_d, self._viable_d,
            jnp.int32(self.cur_just[1]), buckets, jnp.int32(-1),
            jnp.int64(0), self.capacity)
        return int(head_idx)

    def head_host_walk(self) -> bytes:
        """The spec-walk oracle: gather the message table, accumulate
        vote weights and subtree sums in NumPy, descend greedily — the
        ``resident_head_equals_spec_walk`` pin of MULTICHIP_r09."""
        from pos_evolution_tpu.ops.forkchoice import head_host
        msg = np.asarray(self.msg_block)[: self.n]
        eff = np.asarray(self.registry.effective_balance)[: self.n]
        valid = msg >= 0
        vw = np.zeros(self.capacity + 1, np.int64)
        np.add.at(vw, np.where(valid, msg, self.capacity),
                  np.where(valid, eff, 0))
        b = len(self.roots)
        parent = np.full(self.capacity, -1, np.int32)
        parent[:b] = self.parents
        real = np.zeros(self.capacity, bool)
        real[:b] = True
        rank = np.asarray(self._rank_d)
        idx = head_host(parent, real, rank, np.ones(self.capacity, bool),
                        self.cur_just[1], vw[: self.capacity], -1, 0)
        return self.roots[idx]

    # -- votes -----------------------------------------------------------------

    def _cast_votes(self, slot_in_epoch: int, block_idx: int,
                    epoch: int) -> None:
        import jax.numpy as jnp
        global _VOTE_KERNEL
        if _VOTE_KERNEL is None:
            import jax

            def kern(msg_block, msg_epoch, cur_flags, assigned, t, idx, ep):
                mask = assigned == t
                return (jnp.where(mask, idx, msg_block),
                        jnp.where(mask, ep, msg_epoch),
                        jnp.where(mask, cur_flags | np.uint8(7), cur_flags))
            _VOTE_KERNEL = jax.jit(kern)
        self.msg_block, self.msg_epoch, cur = _VOTE_KERNEL(
            self.msg_block, self.msg_epoch, self.registry.cur_flags,
            self._assigned, jnp.int64(slot_in_epoch),
            jnp.int32(block_idx), jnp.int64(epoch))
        self.registry = self.registry._replace(cur_flags=cur)

    # -- aggregation verify ----------------------------------------------------

    def _verify_slot(self, slot_in_epoch: int, block_root: bytes) -> None:
        import jax.numpy as jnp

        from pos_evolution_tpu.ops.aggregation import messages_to_words
        attesters = self._slot_attesters(slot_in_epoch)
        if attesters.size == 0:
            return
        a_real = int(self.cfg.max_committees_per_slot)
        lanes = _next_pow2(-(-attesters.size // a_real))
        committees = np.zeros((a_real, lanes), np.int32)
        bits = np.zeros((a_real, lanes), bool)
        for c in range(a_real):
            member = attesters[c::a_real]
            committees[c, : member.size] = member
            bits[c, : member.size] = True
        msg = messages_to_words(
            np.frombuffer(block_root, dtype=np.uint8)[None, :].repeat(
                a_real, axis=0))
        sigs = _make_aggregates(self.pk_states, jnp.asarray(committees),
                                jnp.asarray(bits), jnp.asarray(msg))
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import (
                aggregation_verify_for,
            )
            a_pad = -(-a_real // self.mesh.size) * self.mesh.size
            if a_pad != a_real:
                committees = np.concatenate(
                    [committees, np.zeros((a_pad - a_real, lanes), np.int32)])
                bits_p = np.concatenate(
                    [bits, np.zeros((a_pad - a_real, lanes), bool)])
                msg = np.concatenate(
                    [msg, np.zeros((a_pad - a_real, 8), np.uint32)])
                sigs = jnp.concatenate(
                    [sigs, jnp.zeros((a_pad - a_real, 24), jnp.uint32)])
            else:
                bits_p = bits
            ok = aggregation_verify_for(self.mesh)(
                self.pk_states, jnp.asarray(committees),
                jnp.asarray(bits_p), jnp.asarray(msg), sigs)
        else:
            from pos_evolution_tpu.ops.aggregation import (
                aggregate_verify_batch,
            )
            ok = aggregate_verify_batch(self.pk_states,
                                        jnp.asarray(committees),
                                        jnp.asarray(bits), jnp.asarray(msg),
                                        sigs)
        ok = np.asarray(ok)[:a_real]
        nonempty = bits.any(axis=1)
        if not ok[nonempty].all():
            raise AssertionError(
                f"aggregate verification failed at slot {self.slot + 1}")
        self.aggregates_verified += int(nonempty.sum())

    # -- epoch boundary --------------------------------------------------------

    def _epoch_boundary(self, entering_epoch: int) -> None:
        """Spec-mirrored epoch processing when entering ``entering_epoch``
        (``current_epoch`` = the epoch just completed, exactly like
        ``process_epoch`` running at slot E*S - 1)."""
        import jax.numpy as jnp
        cur_e = entering_epoch - 1
        if self.mesh is not None:
            from pos_evolution_tpu.parallel.sharded import epoch_step_for
            import jax
            step = epoch_step_for(self.mesh, self.cfg,
                                  donate=jax.default_backend() != "cpu")
        else:
            from pos_evolution_tpu.ops.epoch import process_epoch_dense
            step = lambda *a: process_epoch_dense(*a, self.cfg)  # noqa: E731
        out = step(self.registry, jnp.int64(cur_e),
                   jnp.int64(self.finalized[0]), jnp.asarray(self.bits),
                   jnp.int64(self.prev_just[0]), jnp.int64(self.cur_just[0]),
                   jnp.int64(0))
        self.registry = out.registry
        if cur_e > 1:
            old_prev, old_cur = self.prev_just, self.cur_just
            self.prev_just = self.cur_just
            if bool(out.justify_prev):
                self.cur_just = (cur_e - 1, self.epoch_start_idx[cur_e - 1])
            if bool(out.justify_cur):
                self.cur_just = (cur_e, self.epoch_start_idx[cur_e])
            self.bits = np.asarray(out.new_justification_bits)
            fin = int(out.finalize_epoch)
            if fin >= 0:
                # later finalization cases use the old CURRENT justified
                # checkpoint and win in the spec — check it first
                if fin == old_cur[0]:
                    self.finalized = old_cur
                elif fin == old_prev[0]:
                    self.finalized = old_prev

    # -- main loop -------------------------------------------------------------

    def run_slot(self) -> None:
        s = self.slot + 1
        epoch = s // self.S
        if s % self.S == 0 and s > 0:
            self._epoch_boundary(epoch)
        if self._epoch_ready < epoch:
            self._start_epoch(epoch)
        head = self._head()
        root = _hash(b"block", self.seed, s, self.roots[head])
        idx = self._append_block(root, head, s)
        if s % self.S == 0:
            self.epoch_start_idx[epoch] = idx
        self._cast_votes(s % self.S, idx, epoch)
        if self.verify_aggregates:
            self._verify_slot(s % self.S, root)
        self.slot = s
        if self.check_walk_every and s % self.check_walk_every == 0:
            self.walk_checks.append(self.head_host_walk() == root)
        self.metrics.append({
            "slot": s, "head_root": root.hex()[:16],
            "justified_epoch": self.cur_just[0],
            "finalized_epoch": self.finalized[0],
            "n_blocks": len(self.roots),
        })
        if self.supervision is not None:
            self.supervision.tick(self, s, self._checkpoint_async_capture)

    def run_epochs(self, n_epochs: int) -> None:
        """Run through the first slot of epoch ``n_epochs`` (inclusive),
        so the boundary entering it — the one that can finalize epoch
        ``n_epochs - 2`` — has been processed (the spec driver's
        ``run_epochs`` shape)."""
        while self.slot < n_epochs * self.S:
            self.run_slot()

    # -- results ---------------------------------------------------------------

    def summary(self) -> dict:
        self.walk_checks.append(self.head_host_walk() == self.roots[-1])
        return {
            "n_validators": self.n,
            "mesh": (None if self.mesh is None else
                     {a: int(s) for a, s in zip(self.mesh.axis_names,
                                                self.mesh.devices.shape)}),
            "slots": self.slot,
            "epochs": self.slot // self.S,
            "n_blocks": len(self.roots),
            "justified_epoch": self.cur_just[0],
            "finalized_epoch": self.finalized[0],
            "finality_reached": self.finalized[0] > 0,
            "aggregates_verified": self.aggregates_verified,
            "resident_head_equals_spec_walk": all(self.walk_checks),
            "walk_checks": len(self.walk_checks),
            "head_root": self.roots[-1].hex()[:16],
        }

    # -- checkpoint / resume (gather -> host -> re-shard) ----------------------

    def checkpoint(self, path: str | None = None) -> bytes:
        """Gather every device column to host and serialize. The layout
        (mesh shape, sharding) is deliberately NOT part of the format:
        ``resume`` re-places columns on whatever mesh it is given —
        checkpoint on 2x4, resume on 4x2/1x8/single-device, bit-identical
        (tests/test_sharded_e2e.py pins the round trip). ``path``
        additionally lands the bytes on disk atomically
        (``utils/snapshot.atomic_write_bytes``)."""
        data = self._checkpoint_serialize(*self._checkpoint_capture())
        if path is not None:
            from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
            atomic_write_bytes(path, data)
        return data

    def _checkpoint_capture(self):
        """The device-synchronous half: JSON-able meta plus host copies
        of every sharded column (``parallel/sharded.host_gather``).
        Cheap relative to compression — this is all that runs on the
        epoch loop's critical path in async autocheckpoint mode."""
        meta = {
            "version": 1, "n": self.n, "seed": self.seed,
            "shuffle_rounds": self.shuffle_rounds,
            "verify_aggregates": self.verify_aggregates,
            "capacity": self.capacity,
            "check_walk_every": self.check_walk_every,
            "cfg": {k: (["__bytes__", v.hex()] if isinstance(v, bytes) else v)
                    for k, v in dataclasses.asdict(self.cfg).items()},
            "slot": self.slot,
            "bits": [bool(b) for b in self.bits],
            "prev_just": list(self.prev_just),
            "cur_just": list(self.cur_just),
            "finalized": list(self.finalized),
            "epoch_start_idx": {str(k): v
                                for k, v in self.epoch_start_idx.items()},
            # every mutable collection is COPIED here, not referenced:
            # in async mode the writer thread serializes this meta while
            # the loop keeps appending blocks — a live reference would
            # tear the snapshot (roots of length B beside parents of
            # length B+1, caught by the tier-1 suite under load)
            "roots": [r.hex() for r in self.roots],
            "parents": list(self.parents),
            "block_slots": list(self.block_slots),
            "aggregates_verified": self.aggregates_verified,
            "walk_checks": [bool(b) for b in self.walk_checks],
            "metrics": list(self.metrics),
            "epoch_ready": self._epoch_ready,
        }
        from pos_evolution_tpu.parallel.sharded import host_gather
        cols = host_gather({f: getattr(self.registry, f)
                            for f in self.registry._fields})
        cols = {f: a[: self.n] for f, a in cols.items()}
        cols["msg_block"] = np.asarray(self.msg_block)[: self.n]
        cols["msg_epoch"] = np.asarray(self.msg_epoch)[: self.n]
        if self._perm_host is not None:
            cols["perm"] = self._perm_host
        return meta, cols

    @staticmethod
    def _checkpoint_serialize(meta: dict, cols: dict) -> bytes:
        """The expensive half (json + npz compression): pure function
        of the captured host state, safe on a background thread."""
        out = io.BytesIO()
        head = json.dumps(meta).encode()
        out.write(np.uint64(len(head)).tobytes())
        out.write(head)
        np.savez_compressed(out, **cols)
        return out.getvalue()

    def _checkpoint_async_capture(self):
        """RunSupervision capture: gather now, serialize whenever the
        writer thread gets to it (the captured host copies are frozen —
        the loop mutating ``self`` no longer races the write)."""
        meta, cols = self._checkpoint_capture()
        return lambda: self._checkpoint_serialize(meta, cols)

    @classmethod
    def resume(cls, data: bytes, mesh=None) -> "DenseSimulation":
        buf = io.BytesIO(data)
        (n_head,) = np.frombuffer(buf.read(8), dtype=np.uint64)
        meta = json.loads(buf.read(int(n_head)).decode())
        assert meta["version"] == 1
        cfg = Config(**{
            k: (bytes.fromhex(v[1])
                if isinstance(v, list) and len(v) == 2 and v[0] == "__bytes__"
                else v)
            for k, v in meta["cfg"].items()})
        sim = cls(meta["n"], cfg=cfg, mesh=mesh, seed=meta["seed"],
                  shuffle_rounds=meta["shuffle_rounds"],
                  verify_aggregates=meta["verify_aggregates"],
                  capacity=meta["capacity"],
                  check_walk_every=meta["check_walk_every"])
        with np.load(buf) as z:
            from pos_evolution_tpu.ops.epoch import DenseRegistry
            sim.registry = DenseRegistry(**{
                f: sim._place_validator_col(z[f], f"registry/{f}")
                for f in DenseRegistry._fields})
            sim.msg_block = sim._place_validator_col(z["msg_block"],
                                                     "messages/msg_block")
            sim.msg_epoch = sim._place_validator_col(z["msg_epoch"],
                                                     "messages/msg_epoch")
            perm = z["perm"] if "perm" in z.files else None
        sim.roots = [bytes.fromhex(r) for r in meta["roots"]]
        sim.parents = list(meta["parents"])
        sim.block_slots = list(meta["block_slots"])
        b = len(sim.roots)
        import jax.numpy as jnp
        parent = np.full(sim.capacity, -1, np.int32)
        parent[:b] = sim.parents
        slot = np.zeros(sim.capacity, np.int32)
        slot[:b] = sim.block_slots
        real = np.zeros(sim.capacity, bool)
        real[:b] = True
        order = np.argsort(np.argsort(np.array(sim.roots, dtype=object)))
        rank = np.zeros(sim.capacity, np.int32)
        rank[:b] = order
        sim._parent_d = jnp.asarray(parent)
        sim._slot_d = jnp.asarray(slot)
        sim._rank_d = jnp.asarray(rank)
        sim._real_d = jnp.asarray(real)
        sim.slot = meta["slot"]
        sim.bits = np.asarray(meta["bits"], dtype=bool)
        sim.prev_just = tuple(meta["prev_just"])
        sim.cur_just = tuple(meta["cur_just"])
        sim.finalized = tuple(meta["finalized"])
        sim.epoch_start_idx = {int(k): v
                               for k, v in meta["epoch_start_idx"].items()}
        sim.aggregates_verified = meta["aggregates_verified"]
        sim.walk_checks = list(meta["walk_checks"])
        sim.metrics = list(meta["metrics"])
        sim._epoch_ready = meta["epoch_ready"]
        if perm is not None and sim._epoch_ready >= 0:
            sim._perm_host = perm.astype(np.int64)
            sim._inv_perm = np.argsort(sim._perm_host).astype(np.int64)
            assigned = sim._perm_host * sim.S // sim.n
            sim._assigned = sim._place_validator_col(
                assigned.astype(np.int64))
        return sim

    # -- run supervision (resilience/, ISSUE 10) -------------------------------

    def attach_autocheckpoint(self, spec) -> None:
        """Arm (or re-arm, after a resume) run supervision — see
        ``Simulation.attach_autocheckpoint``; the dense driver's capture
        additionally backgrounds the npz compression."""
        from pos_evolution_tpu.resilience import RunSupervision
        self.supervision = RunSupervision(spec, kind="dense",
                                          cfg_obj=self.cfg)

    def finish_autocheckpoint(self) -> dict | None:
        """Final checkpoint at the current slot + writer drain; returns
        the manager's overhead stats (None when unsupervised)."""
        if self.supervision is None:
            return None
        return self.supervision.finish(self.slot,
                                       self._checkpoint_async_capture)

    @classmethod
    def resume_latest(cls, dir, mesh=None,
                      autocheckpoint=None) -> "DenseSimulation":
        """Resume from the newest *valid* checkpoint under ``dir``,
        quarantining and rolling past corrupt steps — onto whatever
        mesh is ACTIVE now (``mesh=None`` = single device), which is
        the device-loss path: a run checkpointed on 2x4 resumes
        bit-identically on 1x4 or one device. Raises
        ``FileNotFoundError`` when nothing valid exists."""
        # no fingerprint pin here: the dense checkpoint carries its own
        # Config in-band and ``resume`` reconstructs from it, so there
        # is no "active config" to cross-check (unlike the spec driver)
        from pos_evolution_tpu.resilience import CheckpointManager
        found = CheckpointManager(dir).latest_valid()
        if found is None:
            raise FileNotFoundError(
                f"no valid checkpoint under {dir!r} to resume from")
        step, payloads = found
        sim = cls.resume(payloads["payload.bin"], mesh=mesh)
        if autocheckpoint is not None:
            sim.attach_autocheckpoint(autocheckpoint)
        from pos_evolution_tpu.telemetry import emit_global
        import os as _os
        emit_global("run_resumed", step=step, slot=sim.slot,
                    dir=_os.fspath(dir))
        return sim


_VOTE_KERNEL = None


def _make_aggregates(pk_states, committees, bits, msg_words):
    """Each slot's aggregation duty: the honest committee aggregates
    from ``ops.aggregation.aggregate_signatures_batch`` (the signer side
    of the verification sweep)."""
    from pos_evolution_tpu.ops.aggregation import aggregate_signatures_batch
    return aggregate_signatures_batch(pk_states, committees, bits,
                                      msg_words)
