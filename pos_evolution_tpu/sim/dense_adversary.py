"""Vectorized Byzantine strategies for the dense driver (ISSUE 13).

``sim/adversary.py`` acts per MESSAGE: each hook builds spec containers,
signs them, and routes them through the per-object delivery path — the
right fidelity for protocol audits, and six orders of magnitude too much
Python for 10^6 validators. This module is the same adversary expressed
at the array level: a strategy is a **masked transform over the sharded
message/vote tables** — its per-slot output is a handful of
``VoteBatch``\\ es (a bool[N] origination mask + a target block index)
and, for the chain-building strategies, extra entries in the replicated
block tree. The driver applies batches through the identical masked
vote kernel the honest path uses (``parallel/sharded.vote_apply_for``),
so adversarial traffic suffers the same ``DenseFaultPlan``
drop/delay/crash masks, is observed by the dense monitors at
origination, and stays bit-stable across mesh shapes and backends: every
decision is a pure function of (strategy seed, slot, validator) via the
``stateless_unit``/``stateless_unit_array`` hashes — the same
determinism discipline as the spec strategies and ``FaultPlan``.

What survives the translation, per strategy (DESIGN.md §20 spells out
exactly what is kept and what is deliberately coarsened):

- ``DenseEquivocator`` — double proposals (a sibling block per active
  slot) and double votes (the controlled committee slice votes BOTH
  tips); a pure evidence generator, the accountable-safety monitor must
  implicate every double voter.
- ``DenseWithholder`` — the ex-ante reorg: a private chain grown behind
  a visibility mask, controlled committee votes banked as unapplied
  batches, everything released in one burst at ``release_slot``.
- ``DenseSplitVoter`` — the accountable-safety worst case on a fully
  partitioned 2-view network: every controlled validator votes BOTH
  views' heads every slot; with exactly 1/3 controlled both views
  finalize conflicting checkpoints and the double-vote masks ARE the
  >= 1/3 evidence.
- ``DenseBalancer`` — swayer balancing against pre-boost fork choice on
  a delay-partitioned 2-view network: instead of releasing individual
  withheld votes "just before the deadline", the vectorized form
  computes each slot's honest committee imbalance from the gathered
  group tallies and splits its controlled committee slice to cancel it
  exactly, holding the global tie (and with it: no 2/3 target quorum,
  no justification — the liveness attack outcome).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pos_evolution_tpu.sim.faults import stateless_unit

__all__ = [
    "VoteBatch", "DenseAdversaryStrategy", "DenseEquivocator",
    "DenseWithholder", "DenseExAnteReorg", "DenseSplitVoter",
    "DenseBalancer", "DENSE_STRATEGIES", "dense_adversary_from_config",
]


@dataclass
class VoteBatch:
    """One masked vote broadcast: ``mask`` validators vote ``block`` with
    target ``epoch``, delivered to ``views`` (None = every view). The
    mask is the ORIGINATION set — the driver composes the fault-plan
    drop/delay/crash masks on top before the table write, and the
    monitors tap the origination mask (evidence of a violation can be
    observed even when some recipients never get the vote)."""

    mask: np.ndarray
    block: int
    epoch: int
    views: tuple | None = None
    # None: the driver derives the FFG target-match per view (the flag
    # lands only where the vote's chain matches the view's checkpoint);
    # an explicit bool forces it (used by tests)
    flag: bool | None = None
    faultable: bool = True
    # origination slot (None = the delivery slot). Carried so expiry
    # windows and the per-slot variant tallies judge the CAST slot even
    # when the batch lands late (fault delays, banked releases)
    slot: int | None = None

    def for_view(self, g: int) -> bool:
        return self.views is None or g in self.views


class DenseAdversaryStrategy:
    """Base: holds the controlled index set and no-ops every hook.

    Hook contract (driven by ``DenseSimulation.run_slot``):

    - ``before_propose(sim, slot)``: before heads are computed — the
      release point (withheld chains become visible, banked votes go
      through the fault-masked apply path so a timely release lands
      ahead of the slot's honest votes);
    - ``on_proposals(sim, slot, new_idx)``: after the per-view honest
      blocks land in the tree — append equivocating siblings / private
      extensions via ``sim.adversary_block``;
    - ``vote_batches(sim, slot, new_idx)``: the slot's adversarial vote
      transforms, as ``VoteBatch``\\ es applied after the honest batch.

    Controlled validators are excluded from the honest duty mask at
    bind (the dense mirror of folding into ``Schedule.corrupted``):
    Byzantine actions happen only through the hooks.
    """

    name = "dense_adversary"

    def __init__(self, controlled=()):
        self.controlled = np.asarray(sorted(int(v) for v in controlled),
                                     dtype=np.int64)

    def bind(self, sim) -> None:
        self.sim = sim
        self.controlled_mask = np.zeros(sim.n, dtype=bool)
        self.controlled_mask[self.controlled[self.controlled < sim.n]] = True

    def describe(self) -> dict:
        """Config fingerprint for checkpoints and repro bundles; the
        controlled set is stored as [lo, hi) ranges when contiguous so a
        1M-validator bundle stays readable."""
        return {"kind": type(self).__name__,
                "controlled": _ranges(self.controlled)}

    # -- hooks -----------------------------------------------------------------

    def before_propose(self, sim, slot: int) -> None:
        pass

    def on_proposals(self, sim, slot: int, new_idx: list) -> None:
        pass

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        return []

    # -- checkpoint support ----------------------------------------------------

    def state_meta(self) -> dict:
        """JSON-able mutable state (checkpoint/resume mid-attack)."""
        return {}

    def state_arrays(self) -> dict:
        """Large mutable state as numpy arrays (land in the npz)."""
        return {}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        pass

    # -- shared helpers --------------------------------------------------------

    def _mine(self, sim, slot: int) -> np.ndarray:
        """Controlled members of this slot's duty set, as a mask —
        the slot committee under Gasper, everyone under a
        full-participation variant (the adversary votes on the same
        schedule the honest set does)."""
        return self.controlled_mask & sim.duty_mask(slot)


def _ranges(idx: np.ndarray) -> list:
    """Compress a sorted index array to [lo, hi) ranges (JSON-able)."""
    idx = np.asarray(idx, dtype=np.int64)
    if idx.size == 0:
        return []
    cuts = np.where(np.diff(idx) != 1)[0]
    starts = np.concatenate([[0], cuts + 1])
    ends = np.concatenate([cuts, [idx.size - 1]])
    return [[int(idx[s]), int(idx[e]) + 1] for s, e in zip(starts, ends)]


def _from_ranges(ranges: list) -> np.ndarray:
    if not ranges:
        return np.zeros(0, dtype=np.int64)
    return np.concatenate([np.arange(lo, hi, dtype=np.int64)
                           for lo, hi in ranges])


class DenseEquivocator(DenseAdversaryStrategy):
    """Double blocks and double votes at the array level: on active
    slots (a ``stateless_unit`` draw per slot), the slot's block gets an
    equivocating SIBLING (same parent, different root) and the
    controlled committee slice votes BOTH tips — two overlapping masked
    batches with different targets, which is exactly the double-vote
    shape the accountable-safety monitor implicates. On inactive slots
    the controlled slice votes the honest head, so a <1/3 equivocator
    never costs the run its finality. Single-view strategy (acts on
    view 0)."""

    name = "dense_equivocator"

    def __init__(self, controlled=(), p_fork: float = 0.5, seed: int = 0):
        super().__init__(controlled)
        self.p_fork = float(p_fork)
        self.seed = int(seed)
        self._sibling: int | None = None
        self._sibling_slot = -1

    def describe(self) -> dict:
        d = super().describe()
        d.update(p_fork=self.p_fork, seed=self.seed)
        return d

    def _active(self, slot: int) -> bool:
        return stateless_unit(self.seed, 30, slot) < self.p_fork

    def on_proposals(self, sim, slot: int, new_idx: list) -> None:
        self._sibling = None
        if not self._active(slot):
            return
        honest = new_idx[0]
        parent = sim.parents[honest]
        self._sibling = sim.adversary_block(parent, slot,
                                            tag=(b"equiv", self.seed))
        self._sibling_slot = slot

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        mine = self._mine(sim, slot)
        if not mine.any():
            return []
        epoch = slot // sim.S
        if self._sibling is None or self._sibling_slot != slot:
            return [VoteBatch(mine, new_idx[0], epoch, views=(0,))]
        # the double vote: same mask, two targets, observed by the tap
        return [VoteBatch(mine, new_idx[0], epoch, views=(0,)),
                VoteBatch(mine.copy(), self._sibling, epoch, views=(0,))]

    def state_meta(self) -> dict:
        return {"sibling": self._sibling, "sibling_slot": self._sibling_slot}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self._sibling = meta.get("sibling")
        self._sibling_slot = meta.get("sibling_slot", -1)


class DenseWithholder(DenseAdversaryStrategy):
    """The ex-ante reorg as masks: from ``fork_slot`` the strategy grows
    a PRIVATE chain (blocks appended behind the per-view visibility
    mask — honest fork choice cannot see them) while banking its
    controlled committee votes for the private tip as unapplied
    batches; at ``release_slot`` the chain flips visible and the bank
    goes through the normal fault-masked vote apply in one burst,
    before the slot's honest votes. The reorg succeeds iff the banked
    weight beats the honest weight on the competing public blocks —
    against an honest majority it must fail (the clean-episode pin)."""

    name = "dense_withholder"

    def __init__(self, controlled=(), fork_slot: int = 2,
                 release_slot: int = 4):
        super().__init__(controlled)
        self.fork_slot = int(fork_slot)
        self.release_slot = int(release_slot)
        self.priv: list[int] = []       # private block indices
        self.bank: list[VoteBatch] = []
        self.released = False

    def describe(self) -> dict:
        d = super().describe()
        d.update(fork_slot=self.fork_slot, release_slot=self.release_slot)
        return d

    @property
    def tip(self) -> int | None:
        return self.priv[-1] if self.priv else None

    def before_propose(self, sim, slot: int) -> None:
        if self.released or slot != self.release_slot or not self.priv:
            if slot == self.release_slot:
                self.released = True
            return
        self.released = True
        sim.reveal_blocks(self.priv)
        # the timed release: banked votes land through the fault-masked
        # apply path NOW, so the head every honest validator computes
        # this slot already weighs the private chain
        sim.apply_votes_now(self.bank, slot)
        self.bank = []

    def on_proposals(self, sim, slot: int, new_idx: list) -> None:
        if not (self.fork_slot <= slot < self.release_slot):
            return
        parent = self.tip if self.tip is not None \
            else sim.parents[new_idx[0]]
        self.priv.append(sim.adversary_block(
            parent, slot, tag=(b"withheld", self.fork_slot),
            visible=False))

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        mine = self._mine(sim, slot)
        if not mine.any():
            return []
        epoch = slot // sim.S
        if self.fork_slot <= slot < self.release_slot and self.tip is not None:
            # private votes: banked, not broadcast (nothing to observe)
            self.bank.append(VoteBatch(mine, self.tip, epoch))
            return []
        return [VoteBatch(mine, new_idx[0], epoch, views=(0,))]

    def state_meta(self) -> dict:
        return {"priv": list(self.priv), "released": self.released,
                "bank": [{"block": b.block, "epoch": b.epoch}
                         for b in self.bank]}

    def state_arrays(self) -> dict:
        return {f"bank{j}_idx": np.flatnonzero(b.mask).astype(np.int64)
                for j, b in enumerate(self.bank)}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.priv = [int(i) for i in meta.get("priv", [])]
        self.released = bool(meta.get("released", False))
        self.bank = []
        for j, b in enumerate(meta.get("bank", [])):
            mask = np.zeros(self.sim.n, dtype=bool)
            mask[arrays[f"bank{j}_idx"]] = True
            self.bank.append(VoteBatch(mask, int(b["block"]),
                                       int(b["epoch"])))


class DenseExAnteReorg(DenseAdversaryStrategy):
    """Committee-targeted multi-slot ex-ante reorg (ISSUE 20) — the
    attack the proposer-boost / full-participation matrix cells judge.

    Unlike ``DenseWithholder`` (private SIBLING chain + vote bank held
    OUT of the table), this is the pos-evolution.md:1495 shape: the
    adversary **controls the slot-F proposer**, withholds that slot's
    legitimate proposal (``sim.withhold_proposal`` — honest duty falls
    back to voting its parent), and for ``span`` slots votes the hidden
    block with its controlled duty slices THROUGH the normal table
    path. The banked weight is real latest-message state, but the head
    kernels weigh only visible blocks, so it is inert until
    ``reveal_blocks`` at slot ``F + span`` — where it lands all at once
    against the public branch the honest committees built meanwhile.

    Per-variant verdicts (the dense matrix pins):

    - Gasper, no boost: disjoint committees mean the bank accumulates
      ``span * f`` committees against the single honest committee
      backing the public tip — at f=0.35, span=2 the reorg SUCCEEDS;
    - Gasper, boost=40: the propose-time head query at the release slot
      carries the previous proposal's boost, outweighing the bank —
      defended;
    - Goldfish/RLMD/SSF (full participation): every honest validator
      re-votes the public branch every slot while the bank collapses to
      one latest-message stamp of ``f * total`` — structurally
      defended (and under Goldfish's eta=1 the early stamps expire
      outright).
    """

    name = "dense_exante_reorg"

    def __init__(self, controlled=(), fork_slot: int = 2, span: int = 2):
        super().__init__(controlled)
        self.fork_slot = int(fork_slot)
        self.span = max(int(span), 1)
        self.priv: list[int] = []       # the withheld proposal
        self.honest_tip: int | None = None  # public tip at release
        self.released = False

    def describe(self) -> dict:
        d = super().describe()
        d.update(fork_slot=self.fork_slot, span=self.span)
        return d

    def before_propose(self, sim, slot: int) -> None:
        if (slot == self.fork_slot + self.span and not self.released
                and self.priv):
            self.released = True
            # public tip NOW is what the reorg must beat; the matrix
            # verdict compares the post-release head against both
            self.honest_tip = sim._head(0)
            sim.reveal_blocks(self.priv)

    def on_proposals(self, sim, slot: int, new_idx: list) -> None:
        if slot == self.fork_slot and not self.priv:
            sim.withhold_proposal(0, new_idx[0])
            self.priv = [new_idx[0]]

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        mine = self._mine(sim, slot)
        if not mine.any():
            return []
        epoch = slot // sim.S
        if (self.priv and not self.released
                and self.fork_slot <= slot < self.fork_slot + self.span):
            # the bank: real table writes for an invisible target —
            # weightless in every honest head query until the release
            return [VoteBatch(mine, self.priv[0], epoch)]
        return [VoteBatch(mine, new_idx[0], epoch, views=(0,))]

    def state_meta(self) -> dict:
        return {"priv": list(self.priv), "released": self.released,
                "honest_tip": self.honest_tip}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.priv = [int(i) for i in meta.get("priv", [])]
        self.released = bool(meta.get("released", False))
        ht = meta.get("honest_tip")
        self.honest_tip = None if ht is None else int(ht)


class DenseSplitVoter(DenseAdversaryStrategy):
    """Coherent equivocation that kills safety: on a fully partitioned
    2-view network every controlled committee member votes BOTH views'
    heads every slot — one masked batch per view, each delivered only
    to its view. With exactly 1/3 of stake controlled and the honest
    set split evenly, each view tallies 2/3 target participation,
    justifies and finalizes its own chain, and the cross-view
    double-vote masks implicate exactly the controlled third: the
    Casper FFG accountable-safety theorem, operational at mainnet
    scale (the CHAOS_DENSE acceptance pin)."""

    name = "dense_split_voter"

    def bind(self, sim) -> None:
        super().bind(sim)
        assert sim.n_groups == 2, "DenseSplitVoter needs two views"
        plan = sim.fault_plan
        assert plan is not None and plan.partition == "full", \
            "DenseSplitVoter needs a fully partitioned network"

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        mine = self._mine(sim, slot)
        if not mine.any():
            return []
        epoch = slot // sim.S
        return [VoteBatch(mine.copy(), new_idx[g], epoch, views=(g,))
                for g in range(sim.n_groups)]


class DenseBalancer(DenseAdversaryStrategy):
    """Swayer balancing, vectorized. The per-message strategy banks
    withheld votes and releases them per view "just before the
    attestation deadline"; DESIGN.md §20 derives why this aggregate
    form is the same attack. The key dense fact is that fork-choice
    weight lives in a LATEST-message table: an honest validator
    re-voting its own chain moves nothing, a swayer flipping chains
    swings the tie by 2, and a first-time voter by 1. So the strategy
    balances the TABLE, not a vote stream:

    - it tracks every controlled validator's current table chain
      (``assign``) and which honest validators have voted at all
      (``voted`` — only first votes move weight);
    - each slot it cancels the honest first-vote imbalance with its
      controlled committee slice (±1 moves) and any carried residual
      with chain switches (±2 moves), keeping the global A-minus-B
      weight within ±1 forever;
    - it keeps the two views APART with one paired switch per slot
      (one A->B swayer and one B->A swayer), each delivered to the
      favored view immediately and to the other a slot late — every
      view's slot-start snapshot shows its own chain leading by ~2,
      the dense image of the deadline-timed release (swayers never
      double-vote: one vote per epoch, chain flips across epochs are
      honest-looking LMD updates, exactly as in the reference).

    Result: no view ever flips, each view's target quorum stays pinned
    near 1/2 < 2/3, and justification stalls — the balancing liveness
    attack, sustained for as long as every slot's controlled committee
    slice carries both chains (the reference's :1330 precondition,
    surfaced in ``infeasible_slots`` when it fails)."""

    name = "dense_balancer"

    def __init__(self, controlled=()):
        super().__init__(controlled)
        self.residual = 0   # table A-minus-B imbalance carried forward
        self.infeasible_slots: list[int] = []

    def bind(self, sim) -> None:
        super().bind(sim)
        assert sim.n_groups == 2, "DenseBalancer needs two views"
        plan = sim.fault_plan
        assert plan is not None and plan.partition == "delay", \
            "DenseBalancer needs the one-slot cross-view delay"
        self._assign = np.full(sim.n, -1, dtype=np.int8)  # -1/0=A/1=B
        self._voted = np.zeros(sim.n, dtype=bool)

    def vote_batches(self, sim, slot: int, new_idx: list) -> list:
        committee = sim.committee_mask(slot)
        honest = committee & ~sim.controlled_any
        first_a = honest & (sim.group_of == 0) & ~self._voted
        first_b = honest & (sim.group_of == 1) & ~self._voted
        self._voted |= honest
        t = self.residual + int(first_a.sum()) - int(first_b.sum())
        members = np.flatnonzero(self.controlled_mask & committee)
        epoch = slot // sim.S
        if members.size == 0:
            if t != self.residual:
                self.infeasible_slots.append(slot)
            self.residual = t
            return []
        to_a: list[int] = []
        to_b: list[int] = []
        switch_a = switch_b = None   # the per-slot view-separating pair
        # phase 1: first-time swayers cancel the ±1 imbalance
        fresh = members[self._assign[members] == -1]
        seasoned = members[self._assign[members] != -1]
        for m in fresh:
            if t <= 0:
                to_a.append(m); self._assign[m] = 0; t += 1
            else:
                to_b.append(m); self._assign[m] = 1; t -= 1
        # phase 2: corrective switches (±2) until |t| <= 1
        pool_a = [m for m in seasoned if self._assign[m] == 0]
        pool_b = [m for m in seasoned if self._assign[m] == 1]
        while t > 1 and pool_a:
            m = pool_a.pop(0)
            to_b.append(m); self._assign[m] = 1; t -= 2
            switch_b = m
        while t < -1 and pool_b:
            m = pool_b.pop(0)
            to_a.append(m); self._assign[m] = 0; t += 2
            switch_a = m
        if abs(t) > 1:
            self.infeasible_slots.append(slot)
        # phase 3: the oscillating pair keeps each view's own chain
        # ahead at its decision point (net-zero on the global tie)
        if switch_a is None and switch_b is None and pool_a and pool_b:
            m_ab = pool_a.pop(0)
            to_b.append(m_ab); self._assign[m_ab] = 1
            switch_b = m_ab
            m_ba = pool_b.pop(0)
            to_a.append(m_ba); self._assign[m_ba] = 0
            switch_a = m_ba
        for m in pool_a:
            to_a.append(m)
        for m in pool_b:
            to_b.append(m)
        self.residual = t
        out = []
        for chain, voters in ((0, to_a), (1, to_b)):
            if not voters:
                continue
            mask = np.zeros(sim.n, dtype=bool)
            mask[voters] = True
            # favored view sees the vote now; the other a slot late —
            # the deadline-timed release, one slot of skew
            out.append(VoteBatch(mask, new_idx[chain], epoch,
                                 views=(chain,)))
            late = VoteBatch(mask.copy(), new_idx[chain], epoch,
                             views=(1 - chain,))
            sim.views[1 - chain].pending.append(late)
        return out

    def state_meta(self) -> dict:
        return {"residual": self.residual,
                "infeasible_slots": list(self.infeasible_slots)}

    def state_arrays(self) -> dict:
        return {"assign": self._assign, "voted": self._voted}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.residual = int(meta.get("residual", 0))
        self.infeasible_slots = [int(s) for s in
                                 meta.get("infeasible_slots", [])]
        self._assign = np.asarray(arrays["assign"], dtype=np.int8).copy()
        self._voted = np.asarray(arrays["voted"], dtype=bool).copy()


DENSE_STRATEGIES = {
    "DenseEquivocator": DenseEquivocator,
    "DenseWithholder": DenseWithholder,
    "DenseExAnteReorg": DenseExAnteReorg,
    "DenseSplitVoter": DenseSplitVoter,
    "DenseBalancer": DenseBalancer,
}


def dense_adversary_from_config(d: dict) -> DenseAdversaryStrategy:
    """Rebuild a strategy from its ``describe()`` dict (checkpoint
    resume and chaos-bundle replay)."""
    kind = d["kind"]
    cls = DENSE_STRATEGIES.get(kind)
    if cls is None:
        raise ValueError(f"unknown dense strategy kind {kind!r}")
    controlled = _from_ranges(d.get("controlled", []))
    kwargs = {}
    if kind == "DenseEquivocator":
        kwargs = {"p_fork": d.get("p_fork", 0.5), "seed": d.get("seed", 0)}
    elif kind == "DenseWithholder":
        kwargs = {"fork_slot": d.get("fork_slot", 2),
                  "release_slot": d.get("release_slot", 4)}
    elif kind == "DenseExAnteReorg":
        kwargs = {"fork_slot": d.get("fork_slot", 2),
                  "span": d.get("span", 2)}
    return cls(controlled=controlled, **kwargs)
