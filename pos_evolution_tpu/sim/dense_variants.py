"""Dense-tier protocol variants: the ProtocolVariant seam at 10^6 (ISSUE 20).

The spec tier runs Goldfish / RLMD-GHOST / SSF through ``variants/`` as
per-message Python over a 64K registry. This module is the **array
image of that seam** for ``sim/dense_driver.DenseSimulation``: each
variant is a small policy object whose decisions are computed from the
sharded latest-message columns by the same reductions the spec backend
dispatches to (``ops/variant_tally.py``), now running as ``shard_map``
twins over the ``(pods, shard)`` mesh:

- **expiry window** (Goldfish eta=1, RLMD eta>1): the head query filters
  the message table through ``parallel/sharded.expiry_mask_for`` (its
  single-device jit twin lives here) before the unchanged vote-weights
  pass — votes older than the window carry no fork-choice weight
  (pos-evolution.md:1585);
- **per-slot confirmation / SSF gadget**: ``on_slot_end`` tallies the
  slot's full-participation votes with
  ``parallel/sharded.windowed_tally_for`` at ``lo == hi == slot`` (the
  justification support) and the acknowledgment pass with
  ``expiry_mask_for`` + ``link_tally_for`` (pos-evolution.md:1626,
  1646) — both ICI-first DCN-second allreduces, bit-identical to the
  ``ops/variant_tally`` host oracles (``variant_tally_parity`` is the
  audit the driver runs at its host-walk cadence);
- **view-merge** (pos-evolution.md:1560): the driver votes one merged
  target per slot (the proposer group's proposal) and reveals proposals
  across views immediately — disabled under a full partition, where
  there is no channel to merge through;
- **proposer boost**: rides the ``boost_idx/boost_amount`` arguments the
  head kernels (``ops/forkchoice.head_from_buckets`` / ``head_host``)
  already carry; weight is the spec's committee-sized fraction
  ``total_stake // slots_per_epoch * pct // 100`` — exact integer math,
  identical in the device descent and the host-walk oracle.

Full participation is the point of the dense tier: every validator
re-votes every slot, so a multi-slot ex-ante vote bank collapses to one
latest-message stamp (the LMD table keeps one vote per validator) —
Goldfish/RLMD/SSF structurally defeat the reorg that succeeds against
Gasper's disjoint per-slot committees. That divergence is the pinned
verdict of ``VARIANT_MATRIX_DENSE_r20.json``.

Variants are checkpoint fingerprints: ``describe()`` rides the dense
checkpoint meta and ``DenseSimulation.resume(expect_variant=...)``
refuses a cross-variant resume loudly (the DAS-scheme posture of
PR 17). ``doctor()`` forges conflicting finality/confirmation into the
variant's own state — the dense-monitor negative control.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "DenseProtocolVariant",
    "DenseGasper",
    "DenseGoldfish",
    "DenseRlmd",
    "DenseSsf",
    "DENSE_VARIANTS",
    "dense_variant_from_config",
    "dense_rider_from_config",
    "slot_vote_tally",
    "slot_ack_tally",
    "variant_tally_parity",
]


# --- tally plumbing -----------------------------------------------------------


def _active_col(sim):
    """Cached all-true active column placed under the validator spec —
    the dense tier discounts equivocators at the monitor layer, not in
    the tally (the spec tier's ``active`` argument)."""
    col = getattr(sim, "_active_ones", None)
    if col is None:
        col = sim._place_validator_col(np.ones(sim.n, dtype=bool),
                                       "messages/ok")
        sim._active_ones = col
    return col


def slot_vote_tally(sim, g: int, slot: int) -> np.ndarray:
    """int64[capacity]: per-block stake of view ``g``'s latest head
    votes stamped exactly ``slot`` — the justification-support input of
    the per-slot gadgets. Sharded ``windowed_tally_for`` on a mesh, the
    ``ops/variant_tally`` host oracle on a single device (bit-identical:
    int64 adds reassociate exactly)."""
    view = sim.views[g]
    if sim.mesh is not None:
        import jax.numpy as jnp

        from pos_evolution_tpu.parallel.sharded import windowed_tally_for
        counts = windowed_tally_for(sim.mesh, sim.capacity)(
            view.msg_block, view.msg_slot,
            view.registry.effective_balance, _active_col(sim),
            jnp.int64(slot), jnp.int64(slot))
        return np.asarray(counts)
    from pos_evolution_tpu.ops.variant_tally import windowed_vote_tally_host
    return windowed_vote_tally_host(
        np.asarray(view.msg_block)[: sim.n],
        np.asarray(view.msg_slot)[: sim.n],
        np.asarray(view.registry.effective_balance)[: sim.n],
        np.ones(sim.n, dtype=bool), slot, slot, sim.capacity)


def slot_ack_tally(sim, g: int, slot: int) -> np.ndarray:
    """int64[capacity]: the acknowledgment tally (pos-evolution.md:1646)
    — per-block stake acknowledging this slot's justification. Honest
    participants acknowledge what they voted, so the ack id is the
    slot-stamped head vote: ``expiry_mask_for`` masks the message table
    to this slot's votes and ``link_tally_for`` segment-sums them —
    the supermajority-link reduction on its live sharded path."""
    view = sim.views[g]
    if sim.mesh is not None:
        import jax.numpy as jnp

        from pos_evolution_tpu.parallel.sharded import (
            expiry_mask_for,
            link_tally_for,
        )
        link_col = expiry_mask_for(sim.mesh)(
            view.msg_block, view.msg_slot, jnp.int64(slot), jnp.int64(slot))
        counts = link_tally_for(sim.mesh, sim.capacity)(
            link_col, view.registry.effective_balance, _active_col(sim))
        return np.asarray(counts)
    from pos_evolution_tpu.ops.variant_tally import link_tally_host
    mb = np.asarray(view.msg_block)[: sim.n]
    ms = np.asarray(view.msg_slot)[: sim.n]
    return link_tally_host(
        np.where(ms == slot, mb, -1),
        np.asarray(view.registry.effective_balance)[: sim.n],
        np.ones(sim.n, dtype=bool), sim.capacity)


def variant_tally_parity(sim, g: int, slot: int) -> bool:
    """Audit (driver host-walk cadence): the sharded windowed tally vs
    the ``ops/variant_tally`` host oracle over the gathered columns —
    must be bit-identical on every mesh shape. Trivially true on a
    single device, where ``slot_vote_tally`` IS the oracle."""
    if sim.mesh is None:
        return True
    from pos_evolution_tpu.ops.variant_tally import windowed_vote_tally_host
    dev = slot_vote_tally(sim, g, slot)
    view = sim.views[g]
    host = windowed_vote_tally_host(
        np.asarray(view.msg_block)[: sim.n],
        np.asarray(view.msg_slot)[: sim.n],
        np.asarray(view.registry.effective_balance)[: sim.n],
        np.ones(sim.n, dtype=bool), slot, slot, sim.capacity)
    return bool(np.array_equal(dev, host))


_EXPIRY_KERNEL = None


def expiry_kernel():
    """Single-device jit twin of ``parallel/sharded.expiry_mask_for``:
    identical elementwise math, one executable per process."""
    global _EXPIRY_KERNEL
    if _EXPIRY_KERNEL is None:
        import jax
        import jax.numpy as jnp

        def kern(msg_block, msg_slot, lo, hi):
            live = (msg_slot >= lo) & (msg_slot <= hi)
            return jnp.where(live, msg_block, jnp.int32(-1))
        _EXPIRY_KERNEL = jax.jit(kern)
    return _EXPIRY_KERNEL


# --- the variant policy objects -----------------------------------------------


class DenseProtocolVariant:
    """Base policy = dense Gasper: committee duty, LMD (no expiry), FFG
    finality from the driver's epoch machinery, optional proposer boost.

    The driver consults exactly these hooks:

    - ``window(at_slot)``     -> expiry window for the head query (None
      = LMD), applied identically in the device descent and the
      host-walk oracle;
    - ``anchor(g)``           -> descent-start override (None = the
      view's FFG-justified index);
    - ``admit(vote_slot, at)``-> landing-time staleness gate (RLMD);
    - ``on_slot_end``         -> the per-slot tallies/gadgets, charged
      to the ``variant_tally`` phase;
    - ``describe()``          -> the checkpoint fingerprint;
    - ``doctor()``            -> forged fault for monitor negatives.
    """

    name = "gasper"
    full_participation = False   # duty = slot committee
    view_merge = False
    eta: int | None = None       # expiry window in slots (None = LMD)
    kappa: int | None = None     # confirmation depth
    fast_confirm: tuple[int, int] | None = None  # (num, den) threshold

    def __init__(self, boost_percent: int = 0):
        self.boost_percent = int(boost_percent)
        self.sim = None
        self.decisions: list[dict] = []

    def bind(self, sim) -> None:
        self.sim = sim

    def describe(self) -> dict:
        return {"kind": self.name, "boost_percent": self.boost_percent}

    def window(self, at_slot: int) -> tuple[int, int] | None:
        if self.eta is None:
            return None
        return (max(at_slot - self.eta, 0), at_slot - 1)

    def admit(self, vote_slot: int, at_slot: int) -> bool:
        return True

    def anchor(self, g: int) -> int | None:
        return None

    def latest_decision(self, sim, g: int) -> tuple[int, int] | None:
        """(slot, block index) of the view's newest finality-grade
        decision — what the dense light clients follow. Gasper's is the
        FFG-finalized checkpoint (epoch granularity)."""
        e, idx = sim.views[g].finalized
        if e == 0 and idx == 0:
            return None
        return (int(e) * sim.S, int(idx))

    def on_slot_end(self, sim, slot: int, targets) -> None:
        return None

    def doctor(self, sim, slot: int) -> bool:
        return False

    def summary_fields(self, sim) -> dict:
        """Variant-specific run-summary block (empty for Gasper, whose
        finality already lives in the driver's FFG fields)."""
        return {}

    def state_meta(self) -> dict:
        return {"decisions": [dict(d) for d in self.decisions]}

    def restore_state(self, meta: dict) -> None:
        self.decisions = [dict(d) for d in meta.get("decisions", [])]

    def _log(self, sim, g: int, slot: int, rule: str, idx: int,
             weight: int | None = None) -> None:
        d = {"slot": int(slot), "view": int(g), "rule": rule,
             "idx": int(idx), "root": sim.roots[idx].hex()[:16]}
        if weight is not None:
            d["weight"] = int(weight)
        self.decisions.append(d)
        sim._emit("variant_decision", variant=self.name, **d)

    def _doctor_pair(self, sim, slot: int) -> tuple[int, int] | None:
        """Two freshly forged sibling blocks for the negative controls
        (deterministic roots, visible everywhere)."""
        if sim.n_groups < 2:
            return None
        a = sim.adversary_block(0, slot, tag=(b"doctor", 0))
        b = sim.adversary_block(0, slot, tag=(b"doctor", 1))
        return a, b


class DenseGasper(DenseProtocolVariant):
    """The PR 13 dense driver's protocol, now named: committee LMD-GHOST
    + epoch FFG, with the proposer-boost knob the ex-ante matrix cells
    flip (``boost_percent=0`` reproduces the reorg, 40 defends)."""

    name = "gasper"


class _DenseExpiryVariant(DenseProtocolVariant):
    """Shared Goldfish/RLMD machinery: full-participation per-slot
    voting, view-merge, expiry-windowed heads, fast (3/4) + kappa-deep
    confirmation anchoring the descent."""

    full_participation = True
    view_merge = True
    kappa = 4
    fast_confirm = (3, 4)

    def bind(self, sim) -> None:
        super().bind(sim)
        self.conf_idx = [0] * sim.n_groups

    def describe(self) -> dict:
        d = super().describe()
        d.update(eta=self.eta, kappa=self.kappa,
                 fast_confirm=list(self.fast_confirm))
        return d

    def anchor(self, g: int) -> int:
        return self.conf_idx[g]

    def latest_decision(self, sim, g: int) -> tuple[int, int] | None:
        for d in reversed(self.decisions):
            if d["view"] == g:
                return (d["slot"], d["idx"])
        return None

    def on_slot_end(self, sim, slot: int, targets) -> None:
        num, den = self.fast_confirm
        for g in range(sim.n_groups):
            tgt = int(targets[g])
            w = int(slot_vote_tally(sim, g, slot)[tgt])
            if w * den >= sim.total_stake * num:
                cand, rule = tgt, "fast_confirm"
            else:
                # kappa-deep: the chain kappa blocks above the slot's
                # target has survived kappa rounds of voting
                cand, rule = tgt, "kappa_confirm"
                for _ in range(self.kappa):
                    if cand <= 0:
                        break
                    cand = sim.parents[cand]
                cand = max(cand, 0)
            if cand != self.conf_idx[g] and sim._descends(
                    cand, self.conf_idx[g]):
                self.conf_idx[g] = cand
                self._log(sim, g, slot, rule, cand, w)

    def doctor(self, sim, slot: int) -> bool:
        pair = self._doctor_pair(sim, slot)
        if pair is None:
            return False
        a, b = pair
        self.conf_idx[0], self.conf_idx[1] = a, b
        self._log(sim, 0, slot, "fast_confirm", a)
        self._log(sim, 1, slot, "fast_confirm", b)
        return True

    def summary_fields(self, sim) -> dict:
        return {"confirmed_idx": [int(x) for x in self.conf_idx],
                "confirmed_roots": [sim.roots[x].hex()[:16]
                                    for x in self.conf_idx]}

    def state_meta(self) -> dict:
        m = super().state_meta()
        m["conf_idx"] = [int(x) for x in self.conf_idx]
        return m

    def restore_state(self, meta: dict) -> None:
        super().restore_state(meta)
        if "conf_idx" in meta:
            self.conf_idx = [int(x) for x in meta["conf_idx"]]


class DenseGoldfish(_DenseExpiryVariant):
    """Goldfish at the array level: eta=1 (only the previous slot's
    votes weigh — GHOST-Eph, pos-evolution.md:1549), view-merge, full
    participation. Vote banking dies by construction: a banked vote is
    expired before it can sway anything."""

    name = "goldfish"
    eta = 1


class DenseRlmd(_DenseExpiryVariant):
    """RLMD-GHOST: expiry window eta slots plus the landing-time
    staleness gate — a vote originated before ``at_slot - 1`` is not
    merged into the view at all (pos-evolution.md:1596), so a withheld
    release of old votes lands nothing."""

    name = "rlmd"
    eta = 4

    def admit(self, vote_slot: int, at_slot: int) -> bool:
        return vote_slot >= at_slot - 1


class DenseSsf(DenseProtocolVariant):
    """The per-slot SSF gadget over the dense columns: justification
    support = this slot's windowed tally at the view's target, the
    acknowledgment tally finalizes in-slot (pos-evolution.md:1624-1650,
    the vote-then-ack round collapsed onto the honest schedule where
    acks equal votes). Justified anchors the descent; conflicting
    per-view finalizations are the accountable-safety evidence the
    dense variant monitor prices at exactly the double-voting third."""

    name = "ssf"
    full_participation = True
    view_merge = True
    eta = 4

    def bind(self, sim) -> None:
        super().bind(sim)
        self.just = [[0, 0] for _ in range(sim.n_groups)]  # [slot, idx]
        self.fin = [[0, 0] for _ in range(sim.n_groups)]
        self.fin_log: list[list[list[int]]] = [
            [] for _ in range(sim.n_groups)]

    def describe(self) -> dict:
        d = super().describe()
        d["eta"] = self.eta
        return d

    def anchor(self, g: int) -> int:
        return self.just[g][1]

    def latest_decision(self, sim, g: int) -> tuple[int, int] | None:
        s, idx = self.fin[g]
        if s == 0 and idx == 0:
            return None
        return (int(s), int(idx))

    def on_slot_end(self, sim, slot: int, targets) -> None:
        for g in range(sim.n_groups):
            tgt = int(targets[g])
            if not sim._descends(tgt, self.just[g][1]):
                continue
            support = int(slot_vote_tally(sim, g, slot)[tgt])
            if 3 * support < 2 * sim.total_stake:
                continue
            self.just[g] = [slot, tgt]
            self._log(sim, g, slot, "justify", tgt, support)
            ack = int(slot_ack_tally(sim, g, slot)[tgt])
            if 3 * ack >= 2 * sim.total_stake:
                self.fin[g] = [slot, tgt]
                self.fin_log[g].append([slot, tgt])
                self._log(sim, g, slot, "finalize", tgt, ack)

    def doctor(self, sim, slot: int) -> bool:
        pair = self._doctor_pair(sim, slot)
        if pair is None:
            return False
        a, b = pair
        self.fin[0], self.fin[1] = [slot, a], [slot, b]
        self.fin_log[0].append([slot, a])
        self.fin_log[1].append([slot, b])
        self._log(sim, 0, slot, "finalize", a)
        self._log(sim, 1, slot, "finalize", b)
        return True

    def summary_fields(self, sim) -> dict:
        return {"justified": [list(x) for x in self.just],
                "finalized": [list(x) for x in self.fin],
                "finalizations": [len(lg) for lg in self.fin_log]}

    def state_meta(self) -> dict:
        m = super().state_meta()
        m.update(just=[list(x) for x in self.just],
                 fin=[list(x) for x in self.fin],
                 fin_log=[[list(e) for e in lg] for lg in self.fin_log])
        return m

    def restore_state(self, meta: dict) -> None:
        super().restore_state(meta)
        if "just" in meta:
            self.just = [[int(a), int(b)] for a, b in meta["just"]]
            self.fin = [[int(a), int(b)] for a, b in meta["fin"]]
            self.fin_log = [[[int(a), int(b)] for a, b in lg]
                            for lg in meta["fin_log"]]


DENSE_VARIANTS = {
    "gasper": DenseGasper,
    "goldfish": DenseGoldfish,
    "rlmd": DenseRlmd,
    "ssf": DenseSsf,
}


def dense_variant_from_config(d) -> DenseProtocolVariant:
    """Variant from a ``describe()`` dict / name / instance — the resume
    side of the checkpoint fingerprint (round-trips ``describe()``)."""
    if d is None:
        return DenseGasper()
    if isinstance(d, DenseProtocolVariant):
        return d
    if isinstance(d, str):
        return DENSE_VARIANTS[d]()
    return DENSE_VARIANTS[d["kind"]](
        boost_percent=int(d.get("boost_percent", 0)))


def dense_rider_from_config(d):
    """Workload rider from its ``describe()`` dict (DAS sidecar plane /
    light-client population) — lazy imports keep this module free of
    das/lightclient dependencies until a rider is actually configured."""
    if d is None:
        return None
    if not isinstance(d, dict):
        return d
    kind = d["kind"]
    if kind == "das":
        from pos_evolution_tpu.das.dense_rider import DenseDasRider
        return DenseDasRider.from_config(d)
    if kind == "lightclient":
        from pos_evolution_tpu.lightclient.population import (
            DenseLightClientPopulation,
        )
        return DenseLightClientPopulation.from_config(d)
    raise ValueError(f"unknown dense rider kind {kind!r}")
