"""Attack scenario reproductions (SURVEY.md §2.10; pos-evolution.md:1319-1527).

Two tiers:

- **Simulation-driven** (the public entry points): ``run_ex_ante_reorg``,
  ``run_ex_ante_reorg_with_boost`` and ``run_lmd_balancing_attack`` run
  the adversary *inside* ``Simulation`` as ``AdversaryStrategy``
  instances (sim/adversary.py) — honest proposers/attesters follow the
  ordinary duty loop, the adversary acts through the per-slot hooks, and
  monitors/telemetry/faults can be layered on top. Their asserted
  outcomes are pinned bit-identical to the scripted originals by
  tests/test_attacks.py.
- **Scripted oracles** (``scripted_run_*``): the original one-shot
  reproductions against raw fork-choice stores, with the reference's
  exact numbers. Kept as the ground truth the sim-driven versions are
  compared against, and for the scenarios whose store-level mechanics
  the driver deliberately does not model (``run_bouncing_attack_step``,
  ``run_balancing_attack``).

The scenarios (pos-evolution.md):

- ex-ante reorg (:1516-1522): a hidden block + 1 private attestation
  beats the next honest proposal pre-boost; the mainline W/4 boost kills
  it (:1350); the 7%-adversary / 0.8W-boost variant (:1525-1526) defeats
  even the boost (W=100 per slot, 7 Byzantine per slot).
- LMD balancing despite boost (:1379-1403): equivocating release blocks
  credit each view's LMD table 80:0 for its own chain; honest votes
  split forever.
- swayer balancing (:1321-1348): withheld votes keep two chains tied so
  neither reaches 2/3 and finality halts (pre-boost protocol).

The adversary capabilities used are exactly the reference's model: knowing
honest decision times, targeted just-in-time delivery, and inability of
honest validators to re-gossip instantly (pos-evolution.md:1328).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.sim.adversary import committee_attestations
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.genesis import make_genesis
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_beacon_committee,
    get_committee_count_per_slot,
)
from pos_evolution_tpu.specs.transition import state_transition
from pos_evolution_tpu.specs.validator import (
    advance_state_to_slot,
    build_block,
)
from pos_evolution_tpu.ssz import hash_tree_root


def _tick(store: fc.Store, slot: int, offset: int = 0) -> None:
    fc.on_tick(store, store.genesis_time + slot * cfg().seconds_per_slot + offset)


def _attest_interval(c) -> int:
    return c.seconds_per_slot // c.intervals_per_slot


def _chain_contains(store: fc.Store, head: bytes, root: bytes) -> bool:
    cur = head
    while True:
        if cur == root:
            return True
        block = store.blocks[cur]
        parent = bytes(block.parent_root)
        if parent == cur or parent not in store.blocks:
            return False
        cur = parent


# committee-restricted aggregates now live in sim/adversary.py (the same
# routine the in-loop strategies use); keep the historical private name
# for the scripted oracles' call sites
_committee_attestations = committee_attestations


# --- ex-ante reorg (pos-evolution.md:1503-1526) -------------------------------

def scripted_run_ex_ante_reorg(n_validators: int = 64) -> dict:
    """Simple 1-block ex-ante reorg (pos-evolution.md:1516-1522).

    Slot layout (all within epoch 0):
      slot 1: honest block B1
      slot 2: adversary privately builds B2 on B1 and attests to it; honest
              slot-2 committee sees nothing and attests B1
      slot 3: honest proposer publishes B3 on B1; adversary simultaneously
              releases B2 + its attestation; honest slot-3 committee sees B2
              outweighing B3
      slot 4: next proposer builds on the head
    Returns whether B3 (the honest slot-3 block) was reorged out.
    """
    c = cfg()
    state, anchor = make_genesis(n_validators)
    store = fc.get_forkchoice_store(state, anchor)

    # slot 1: honest block B1.
    _tick(store, 1)
    sb1 = build_block(state, 1)
    fc.on_block(store, sb1)
    r1 = hash_tree_root(sb1.message)
    s1 = store.block_states[r1]

    # slot 2: adversary hides B2; honest committee attests B1.
    s2_view = advance_state_to_slot(s1, 2)
    committee2 = np.concatenate([
        get_beacon_committee(s2_view, 2, i)
        for i in range(get_committee_count_per_slot(s2_view, 0))])
    adversary = int(committee2[0])
    honest2 = committee2[committee2 != adversary]

    sb2_hidden = build_block(s1, 2, graffiti=b"\xad" * 32)
    r2 = hash_tree_root(sb2_hidden.message)
    hidden_state = advance_state_to_slot(s1, 2)
    hidden_att = _committee_attestations(
        hidden_state, 2, r2, participants=np.array([adversary]))
    _tick(store, 2)
    honest_atts2 = _committee_attestations(s2_view, 2, r1,
                                           participants=honest2)

    # slot 3: honest B3 on B1 (published at slot start but boost may be 0),
    # adversary releases B2 + private attestation just before attest time.
    _tick(store, 3)
    for att in honest_atts2:
        fc.on_attestation(store, att)
    sb3 = build_block(s1, 3, graffiti=b"\x33" * 32)
    fc.on_block(store, sb3)
    r3 = hash_tree_root(sb3.message)
    fc.on_block(store, sb2_hidden)
    for att in hidden_att:
        fc.on_attestation(store, att)

    # honest slot-3 committee votes for the head they now see
    head_at_3 = fc.get_head(store)
    s3_view = advance_state_to_slot(store.block_states[head_at_3], 3)
    committee3 = np.concatenate([
        get_beacon_committee(s3_view, 3, i)
        for i in range(get_committee_count_per_slot(s3_view, 0))])
    honest3 = committee3[committee3 != adversary]
    atts3 = _committee_attestations(s3_view, 3, head_at_3, participants=honest3)

    # slot 4: head after honest votes land.
    _tick(store, 4)
    for att in atts3:
        fc.on_attestation(store, att)
    head = fc.get_head(store)
    return {
        "b2_root": r2,
        "b3_root": r3,
        "head_at_slot_3": head_at_3,
        "final_head": head,
        "b3_reorged": not _chain_contains(store, head, r3),
        "b2_canonical": _chain_contains(store, head, r2),
    }


def scripted_run_ex_ante_reorg_with_boost(n_validators: int = 800) -> dict:
    """Ex-ante reorg despite boost (pos-evolution.md:1525-1526).

    Reference numbers: W = 100 validators per slot, boost W_p = 0.8W,
    7 Byzantine per slot. The adversary hides B2 (slot 2) with 7 votes,
    lets the honest B3 (slot 3, boosted) collect 93 honest votes but votes
    its own 7 of slot 3 for B2, then proposes B4 on B2 at slot 4 timely:
    left subtree 7 + 7 + 80(boost) = 94 > 93 — honest validators switch.
    """
    c = cfg()
    assert c.proposer_score_boost_percent == 80, "scenario expects 0.8W boost"
    state, anchor = make_genesis(n_validators)
    per_slot = n_validators // c.slots_per_epoch
    store = fc.get_forkchoice_store(state, anchor)

    _tick(store, 1)
    sb1 = build_block(state, 1)
    fc.on_block(store, sb1)
    r1 = hash_tree_root(sb1.message)
    s1 = store.block_states[r1]

    def slot_committee(view_state, slot):
        return np.concatenate([
            get_beacon_committee(view_state, slot, i)
            for i in range(get_committee_count_per_slot(view_state, 0))])

    # slot 2: hidden adversarial B2 + 7 private votes.
    s2_view = advance_state_to_slot(s1, 2)
    committee2 = slot_committee(s2_view, 2)
    adv2 = committee2[:7]
    honest2 = committee2[7:]
    sb2_hidden = build_block(s1, 2, graffiti=b"\xad" * 32)
    r2 = hash_tree_root(sb2_hidden.message)
    adv_atts2 = _committee_attestations(advance_state_to_slot(s1, 2), 2, r2,
                                        participants=adv2)
    honest_atts2 = _committee_attestations(s2_view, 2, r1, participants=honest2)

    # slot 3: honest B3 published timely (gets the 0.8W boost), honest
    # committee votes it; adversary's 7 vote for still-hidden B2.
    _tick(store, 3)
    for att in honest_atts2:
        fc.on_attestation(store, att)
    sb3 = build_block(s1, 3, graffiti=b"\x33" * 32)
    fc.on_block(store, sb3)  # timely -> boost while slot 3 lasts
    r3 = hash_tree_root(sb3.message)
    assert store.proposer_boost_root == r3
    s3_view = advance_state_to_slot(store.block_states[r3], 3)
    committee3 = slot_committee(s3_view, 3)
    adv3 = committee3[:7]
    honest3 = committee3[7:]
    honest_atts3 = _committee_attestations(s3_view, 3, r3, participants=honest3)
    adv_atts3 = _committee_attestations(advance_state_to_slot(s1, 3), 3, r2,
                                        participants=adv3)

    # slot 4: adversary releases everything and proposes B4 on B2, timely.
    _tick(store, 4)
    for att in honest_atts3:
        fc.on_attestation(store, att)
    fc.on_block(store, sb2_hidden)
    for att in adv_atts2 + adv_atts3:
        fc.on_attestation(store, att)
    sb4 = build_block(store.block_states[r2], 4, graffiti=b"\x44" * 32)
    fc.on_block(store, sb4)  # timely -> 0.8W boost on the adversarial branch
    r4 = hash_tree_root(sb4.message)

    head = fc.get_head(store)
    return {
        "per_slot_committee": per_slot,
        "head": head,
        "b3_reorged": not _chain_contains(store, head, r3),
        "b4_canonical": _chain_contains(store, head, r4),
        "b2_canonical": _chain_contains(store, head, r2),
    }


# --- bouncing attack step (pos-evolution.md:1065-1072) ------------------------

def run_bouncing_attack_step(n_validators: int = 64) -> dict:
    """One full bounce step with real states, and the mitigation in action.

    The bounce (pos-evolution.md:1067-1071): the store follows chain A with
    justified checkpoint (2, A); the adversary releases a chain-B block
    whose post-state carries a *higher, conflicting* justification (3, B).
    Released mid-epoch this would flip every validator's fork choice; the
    mitigation (:1054, :1072) defers the conflicting update to
    ``best_justified_checkpoint`` when it arrives past
    SAFE_SLOTS_TO_UPDATE_JUSTIFIED, promoting only at the epoch boundary
    (:950-955).

    Two forks diverge at genesis (identical committees — the RANDAO mixes
    match, so seeds do too). Chain A withholds its epoch-2 target
    attestations from blocks until slot 2C+0' and crosses the 3->4 boundary
    to justify (2, A-EBB2); chain B does the same one epoch later to
    justify (3, B-EBB3). Honest validators voted target epoch 2 on A and
    target epoch 3 on B — different target epochs, NOT slashable, exactly
    the chain-switching behavior the bounce exploits.
    """
    c = cfg()
    spe = c.slots_per_epoch
    state, anchor = make_genesis(n_validators)
    store = fc.get_forkchoice_store(state, anchor)
    everyone = np.arange(n_validators, dtype=np.int64)

    def extend(parent_state, slot, atts=(), tag=0):
        sb = build_block(parent_state, slot, attestations=list(atts),
                         graffiti=bytes([tag]) * 32)
        post = parent_state.copy()
        state_transition(post, sb, True)
        return sb, post

    # --- chain A: justifies epoch 2 in its slot-4C block ---
    a1, sa1 = extend(state, 1, tag=0xA1)
    a16, sa16 = extend(sa1, 2 * spe, tag=0xA2)           # A's epoch-2 EBB
    atts_a = []
    for slot in range(2 * spe, 3 * spe):                  # epoch-2 votes
        view = advance_state_to_slot(sa16, slot)
        atts_a.extend(_committee_attestations(
            view, slot, hash_tree_root(a16.message), participants=everyone))
    a24, sa24 = extend(sa16, 3 * spe, atts=atts_a[: c.max_attestations], tag=0xA3)
    a32, sa32 = extend(sa24, 4 * spe, tag=0xA4)           # crosses 3->4: justifies 2
    assert int(sa32.current_justified_checkpoint.epoch) == 2

    # --- chain B: justifies epoch 3 in its slot-5C block ---
    b1, sb1 = extend(state, 1, tag=0xB1)
    b24, sb24 = extend(sb1, 3 * spe, tag=0xB2)            # B's epoch-3 EBB
    atts_b = []
    for slot in range(3 * spe, 4 * spe):                  # epoch-3 votes
        view = advance_state_to_slot(sb24, slot)
        atts_b.extend(_committee_attestations(
            view, slot, hash_tree_root(b24.message), participants=everyone))
    b32, sb32 = extend(sb24, 4 * spe, atts=atts_b[: c.max_attestations], tag=0xB3)
    b40, sb40 = extend(sb32, 5 * spe, tag=0xB4)           # crosses 4->5: justifies 3
    assert int(sb40.current_justified_checkpoint.epoch) == 3

    # Phase 1: chain A delivered early in epoch 4 -> store adopts (2, A).
    early = 4 * spe + 1
    assert early % spe < c.safe_slots_to_update_justified
    _tick(store, early)
    for sb in (a1, a16, a24, a32):
        fc.on_block(store, sb)
    justified_a = int(store.justified_checkpoint.epoch)
    root_a = bytes(store.justified_checkpoint.root)

    # Phase 2: chain B (with the conflicting higher justification) released
    # LATE in epoch 5 -> mitigation defers it.
    late = 5 * spe + c.safe_slots_to_update_justified + 1
    _tick(store, late)
    for sb in (b1, b24, b32, b40):
        fc.on_block(store, sb)
    deferred_justified = int(store.justified_checkpoint.epoch)
    deferred_root = bytes(store.justified_checkpoint.root)
    best = int(store.best_justified_checkpoint.epoch)

    # Phase 3: the next epoch boundary promotes best_justified.
    _tick(store, 6 * spe)
    promoted = int(store.justified_checkpoint.epoch)
    promoted_root = bytes(store.justified_checkpoint.root)

    return {
        "phase1_justified": justified_a,
        "phase1_is_chain_a": root_a == hash_tree_root(a16.message),
        "deferred_justified": deferred_justified,
        "deferral_held": deferred_root == root_a and deferred_justified == justified_a,
        "best_after_release": best,
        "promoted_at_boundary": promoted,
        "promoted_is_chain_b": promoted_root == hash_tree_root(b24.message),
    }


# --- LMD balancing despite proposer boost (pos-evolution.md:1379-1403) --------

def scripted_run_lmd_balancing_attack(n_validators: int = 800) -> dict:
    """The balancing attack that survives proposer boost, using the LMD
    first-received rule (pos-evolution.md:1383: equal-epoch votes never
    replace the table entry).

    Reference numbers (:1385): W = 100 validators per slot, 20% Byzantine
    (20 per slot), five consecutive Byzantine proposers. Slots 1-4 build
    two private chains with equivocating votes on each; at slot 5 two
    equivocating blocks carrying the 80 votes per chain are released to the
    two honest halves. Each half's LMD table permanently credits its chain
    80:0 (:1394), so honest votes split every slot thereafter despite the
    boost flipping temporarily (:1396-1399).
    """
    c = cfg()
    state, anchor = make_genesis(n_validators)
    store_A = fc.get_forkchoice_store(state, anchor)
    store_B = fc.get_forkchoice_store(state, anchor)
    stores = (store_A, store_B)

    def committee_of(slot):
        view = advance_state_to_slot(state, slot)
        count = get_committee_count_per_slot(view, compute_epoch_at_slot(slot))
        return [get_beacon_committee(view, slot, i) for i in range(count)]

    # Adaptive corruption: the proposers of slots 1-5 (they equivocate) +
    # 20 members of each slot committee (the adversary picks whom to
    # corrupt, :183-185).
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
    corrupted: set[int] = set()
    per_slot_byz: dict[int, list[int]] = {}
    for slot in range(1, 6):
        flat = [int(v) for com in committee_of(slot) for v in com]
        per_slot_byz[slot] = flat[:20]
        corrupted.update(per_slot_byz[slot])
        corrupted.add(int(get_beacon_proposer_index(
            advance_state_to_slot(state, slot))))

    # --- slots 1-4: two private chains, equivocating votes on both ---
    chain_states = {"L": state, "R": state}
    chain_blocks = {"L": [], "R": []}
    chain_votes = {"L": [], "R": []}
    for slot in range(1, 5):
        for side, graffiti in (("L", b"\x1f" * 32), ("R", b"\xf1" * 32)):
            sb = build_block(chain_states[side], slot, graffiti=graffiti)
            chain_blocks[side].append(sb)
            post = chain_states[side].copy()
            state_transition(post, sb, True)
            chain_states[side] = post
            head_root = hash_tree_root(sb.message)
            head_state = advance_state_to_slot(post, slot)
            # the slot's 20 Byzantine attesters vote this chain's head too
            # (equivocation across chains)
            votes = _committee_attestations(
                head_state, slot, head_root,
                participants=np.array(per_slot_byz[slot], dtype=np.int64))
            chain_votes[side].extend(votes)

    # --- slot 5: equivocating blocks carry each chain's 80 votes ---
    release_blocks = {}
    for side in ("L", "R"):
        assert len(chain_votes[side]) <= c.max_attestations, \
            "equivocating votes exceed the block's attestation capacity"
        sb5 = build_block(chain_states[side], 5,
                          attestations=chain_votes[side],
                          graffiti=(b"\x55" if side == "L" else b"\xaa") * 32)
        release_blocks[side] = sb5

    def deliver(store, side):
        for sb in chain_blocks[side] + [release_blocks[side]]:
            fc.on_block(store, sb)
            for att in sb.message.body.attestations:
                try:
                    fc.on_attestation(store, att, is_from_block=True)
                except AssertionError:
                    pass

    # deliver: each view gets "its" chain timely at slot 5 (boost applies),
    # the other chain only after the attesting interval (no boost, and the
    # equal-epoch LMD entries keep the first-received chain, :1383, :1394)
    for s in stores:
        _tick(s, 5)
    deliver(store_A, "L")
    deliver(store_B, "R")
    for s in stores:
        _tick(s, 5, offset=_attest_interval(c) + 1)
    deliver(store_A, "R")
    deliver(store_B, "L")

    gwei32 = 32 * 10**9
    firstL = hash_tree_root(chain_blocks["L"][0].message)
    firstR = hash_tree_root(chain_blocks["R"][0].message)
    wA_L = fc.get_latest_attesting_balance(store_A, firstL)
    wA_R = fc.get_latest_attesting_balance(store_A, firstR)
    wB_L = fc.get_latest_attesting_balance(store_B, firstL)
    wB_R = fc.get_latest_attesting_balance(store_B, firstR)

    # --- slots 6+: honest halves keep voting their own side ---
    heads_disagree = []
    honest = [v for v in range(n_validators) if v not in corrupted]
    halves = (set(honest[0::2]), set(honest[1::2]))
    pending_cross: list[tuple[int, object]] = []  # (dst_store_idx, att)
    for slot in range(6, 11):
        for s in stores:
            _tick(s, slot)
        # last slot's cross-view votes arrive now (gossip delay Delta; they
        # never displace equal-epoch LMD entries, :1383)
        for dst, a in pending_cross:
            try:
                fc.on_attestation(stores[dst], a, is_from_block=True)
            except AssertionError:
                pass
        pending_cross = []
        for idx, (store, half) in enumerate(zip(stores, halves)):
            head = fc.get_head(store)
            head_state = advance_state_to_slot(store.block_states[head], slot)
            atts = _committee_attestations(
                head_state, slot, head,
                participants=np.array(sorted(half), dtype=np.int64))
            for a in atts:
                try:
                    fc.on_attestation(store, a, is_from_block=True)
                except AssertionError:
                    pass
                pending_cross.append((1 - idx, a))
        heads_disagree.append(fc.get_head(store_A) != fc.get_head(store_B))

    return {
        "viewA_L_votes": wA_L // gwei32, "viewA_R_votes": wA_R // gwei32,
        "viewB_L_votes": wB_L // gwei32, "viewB_R_votes": wB_R // gwei32,
        "heads_disagree": heads_disagree,
        "justified_A": int(store_A.justified_checkpoint.epoch),
        "justified_B": int(store_B.justified_checkpoint.epoch),
    }


# --- balancing attack (pos-evolution.md:1321-1348) ----------------------------

@dataclass
class BalancingResult:
    slots_run: int
    justified_epoch_L: int
    justified_epoch_R: int
    finalized_epoch_L: int
    finalized_epoch_R: int
    head_L: bytes
    head_R: bytes
    tie_maintained: bool


def run_balancing_attack(n_validators: int = 64, n_epochs: int = 3,
                         corrupted_fraction: float = 0.25,
                         debug: bool = False) -> BalancingResult:
    """The original balancing attack against pre-boost Gasper.

    Strategy (pos-evolution.md:1330-1348): an adversarial slot-1 proposer
    equivocates into BL/BR; honest committees are split into two views L/R
    by targeted just-in-time delivery; per slot, withheld adversarial
    ("swayer") votes are released one to each side just before attesting so
    that each side sees its own chain leading by one vote. Honest votes are
    gossiped to everyone and stay tied.
    """
    c = cfg()
    assert c.proposer_score_boost_percent == 0, \
        "the original balancing attack targets pre-boost Gasper"
    state, anchor = make_genesis(n_validators)
    anchor_root = hash_tree_root(anchor)
    store_L = fc.get_forkchoice_store(state, anchor)
    store_R = fc.get_forkchoice_store(state, anchor)
    stores = (store_L, store_R)

    n_corrupted = int(n_validators * corrupted_fraction)
    corrupted = set(range(n_corrupted))  # adversary corrupts f validators
    end_slot = n_epochs * c.slots_per_epoch

    # slot 1: the adversarial proposer equivocates: BL and BR on genesis.
    for s in stores:
        _tick(s, 1)
    sb_L = build_block(state, 1, graffiti=b"\x1f" * 32)
    sb_R = build_block(state, 1, graffiti=b"\xf1" * 32)
    rL, rR = hash_tree_root(sb_L.message), hash_tree_root(sb_R.message)
    # Each side sees "its" block in time to attest; the other arrives later
    # in the slot (still before Δ after the release).
    fc.on_block(store_L, sb_L)
    fc.on_block(store_R, sb_R)

    # Per-side chain states (tips).
    tip = {0: rL, 1: rR}

    # Swayer vote banks: withheld votes for the left/right tip.
    bank: dict[int, list] = {0: [], 1: []}
    pending_honest: list = []   # honest votes gossiped to everyone next slot
    pending_cross: list = []    # late cross-delivery of each side's block
    pending_cross.append(("block", sb_L, 1))
    pending_cross.append(("block", sb_R, 0))

    tie_maintained = True
    for slot in range(1, end_slot + 1):
        if slot > 1:
            for s in stores:
                _tick(s, slot)
            # deliver last slot's gossip to both sides
            for att in pending_honest:
                for s in stores:
                    try:
                        fc.on_attestation(s, att)
                    except AssertionError:
                        pass
            pending_honest = []
            for kind, payload, side in pending_cross:
                try:
                    if kind == "block":
                        fc.on_block(stores[side], payload)
                    else:
                        fc.on_attestation(stores[side], payload)
                except AssertionError:
                    pass
            pending_cross = []

            # Swayer release: deliver exactly as many banked withheld votes
            # to each side as needed for that side to see its own chain
            # strictly leading, just before the proposer/attesters of this
            # slot act. (The adversary knows honest decision times and
            # targets delivery, pos-evolution.md:1328; LMD epoch rollover
            # replaces old votes unevenly, so the required number varies.)
            # Released votes reach the other side a slot later via gossip.
            fork_roots = (rL, rR)
            for side in (0, 1):
                own, other = fork_roots[side], fork_roots[1 - side]
                while bank[side]:
                    w_own = fc.get_latest_attesting_balance(stores[side], own)
                    w_other = fc.get_latest_attesting_balance(stores[side], other)
                    if w_own > w_other:
                        break
                    att = bank[side].pop(0)
                    try:
                        fc.on_attestation(stores[side], att)
                    except AssertionError:
                        pass
                    pending_cross.append(("att", att, 1 - side))

            # Honest proposer of this slot extends their side's head. The
            # proposer's side is wherever the adversary put them; resolve by
            # computing the proposer on side L's view (identical registries).
            head_sides = []
            for side, s in enumerate(stores):
                head = fc.get_head(s)
                head_sides.append(head)
            # Proposer proposes on its own view; deliver the block to both
            # sides within the slot.
            from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index
            view = advance_state_to_slot(
                stores[0].block_states[head_sides[0]], slot)
            proposer = get_beacon_proposer_index(view)
            proposer_side = int(proposer) % 2  # adversary-chosen view assignment
            if int(proposer) not in corrupted:
                parent = head_sides[proposer_side]
                sb = build_block(stores[proposer_side].block_states[parent], slot)
                new_root = hash_tree_root(sb.message)
                for s in stores:
                    try:
                        fc.on_block(s, sb)
                    except AssertionError:
                        pass
                tip[proposer_side] = new_root

        # Committee of this slot, split adaptively: corrupted members feed
        # the swayer banks; honest members are split half/half between views.
        view0 = advance_state_to_slot(stores[0].block_states[fc.get_head(stores[0])],
                                      slot)
        epoch = compute_epoch_at_slot(slot)
        committee = np.concatenate([
            get_beacon_committee(view0, slot, i)
            for i in range(get_committee_count_per_slot(view0, epoch))])
        corrupted_here = [int(v) for v in committee if int(v) in corrupted]
        honest_here = np.array([int(v) for v in committee if int(v) not in corrupted],
                               dtype=np.int64)
        # Sticky view assignment by validator-index parity: each honest
        # validator is targeted with the same side every epoch, so LMD
        # epoch-rollover replacements never move weight across the fork
        # (the adversary's targeted-delivery power, pos-evolution.md:1328).
        halves = (honest_here[honest_here % 2 == 0],
                  honest_here[honest_here % 2 == 1])

        # Honest halves attest to their side's current head.
        for side, half in enumerate(halves):
            if half.size == 0:
                continue
            s = stores[side]
            head = fc.get_head(s)
            head_state = advance_state_to_slot(s.block_states[head], slot)
            atts = _committee_attestations(head_state, slot, head, participants=half)
            pending_honest.extend(atts)

        # Prune withheld votes whose target epoch fell out of the
        # on_attestation validity window (current/previous epoch).
        for side in (0, 1):
            bank[side] = [a for a in bank[side]
                          if int(a.data.target.epoch) >= epoch - 1]

        # Corrupted members bank fresh withheld votes for each side's tip,
        # alternating so both banks stay stocked.
        for k, v in enumerate(corrupted_here):
            side = (k + slot) % 2
            s = stores[side]
            head = fc.get_head(s)
            head_state = advance_state_to_slot(s.block_states[head], slot)
            atts = _committee_attestations(head_state, slot, head,
                                           participants=np.array([v]))
            bank[side].extend(atts)

        # Check the split is alive: the two views disagree on the head.
        if slot >= 2 and fc.get_head(store_L) == fc.get_head(store_R):
            tie_maintained = False
        if debug:
            def wf(s, r):
                try:
                    return fc.get_latest_attesting_balance(s, r) // (32 * 10**9)
                except KeyError:
                    return -1
            print(f"slot {slot}: same_head={fc.get_head(store_L) == fc.get_head(store_R)}"
                  f" bank=({len(bank[0])},{len(bank[1])})"
                  f" L:(L={wf(store_L, rL)},R={wf(store_L, rR)})"
                  f" R:(L={wf(store_R, rL)},R={wf(store_R, rR)})")

    return BalancingResult(
        slots_run=end_slot,
        justified_epoch_L=int(store_L.justified_checkpoint.epoch),
        justified_epoch_R=int(store_R.justified_checkpoint.epoch),
        finalized_epoch_L=int(store_L.finalized_checkpoint.epoch),
        finalized_epoch_R=int(store_R.finalized_checkpoint.epoch),
        head_L=fc.get_head(store_L),
        head_R=fc.get_head(store_R),
        tie_maintained=tie_maintained,
    )


# --- Simulation-driven scenarios (sim/adversary.py strategies) ----------------
#
# The entry points below run the SAME attacks inside ``Simulation``: honest
# proposers/attesters follow the ordinary duty loop, the adversary acts
# through AdversaryStrategy hooks, and the asserted outcome fields are
# pinned equal to the scripted oracles above by tests/test_attacks.py.


def balanced_split_schedule(n_validators: int, corrupted: set,
                            isolate: bool = False) -> "Schedule":
    """Two view groups with the HONEST set split exactly in half by rank
    (the reference's halves, pos-evolution.md:1330: the adversary assigns
    each honest validator a sticky side). A plain ``partition_schedule``
    splits by index parity, which leaves the halves unequal once the
    corrupted set is removed — and an unequal split erodes the balancing
    margin (own-side equivocating votes minus cross-side boost) until the
    attack collapses for the wrong reason. ``isolate=True`` additionally
    withholds ALL cross-group delivery (blocks and attestations), the
    split-brain network of ``sim/adversary.SplitVoter``."""
    from pos_evolution_tpu.sim.schedule import Schedule
    group_of = np.zeros(n_validators, dtype=np.int64)
    honest = [v for v in range(n_validators) if v not in corrupted]
    for k, v in enumerate(honest):
        group_of[v] = k % 2
    for k, v in enumerate(sorted(corrupted)):
        group_of[v] = k % 2
    kwargs = {}
    if isolate:
        kwargs["block_delay"] = (
            lambda proposer, slot, group:
            0.0 if int(group_of[proposer]) == group else None)
        kwargs["attestation_delay"] = (
            lambda src_group, slot, group:
            0.0 if src_group == group else None)
    return Schedule(n_validators=n_validators, group_of=group_of,
                    corrupted=set(corrupted), **kwargs)


def split_brain_schedule(n_validators: int, corrupted: set) -> "Schedule":
    """Total 2-way partition: no message ever crosses groups. The network
    ``SplitVoter`` needs to force conflicting finality."""
    return balanced_split_schedule(n_validators, corrupted, isolate=True)


def committee_balanced_split_schedule(n_validators: int,
                                      corrupted: set) -> "Schedule":
    """Two view groups whose honest members split evenly within EVERY
    epoch-0 slot committee — the reference's idealized balancing setup
    (pos-evolution.md:1330 assumes per-slot symmetric halves). The
    adversary knows the epoch's committees in advance and targets
    delivery per validator, so this assignment is within its declared
    powers; committees reshuffle at the epoch boundary, which is exactly
    where the swayer banks start paying for the imbalance."""
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.sim.schedule import Schedule
    from pos_evolution_tpu.specs.genesis import make_genesis
    state, _ = make_genesis(n_validators)
    group_of = np.zeros(n_validators, dtype=np.int64)
    for slot in range(cfg().slots_per_epoch):
        committee = [int(v) for v in slot_committee(
            advance_state_to_slot(state, max(slot, 1)), slot)]
        honest = [v for v in committee if v not in corrupted]
        for k, v in enumerate(honest):
            group_of[v] = k % 2
    for k, v in enumerate(sorted(corrupted)):
        group_of[v] = k % 2
    return Schedule(n_validators=n_validators, group_of=group_of,
                    corrupted=set(corrupted))


def run_ex_ante_reorg(n_validators: int = 64) -> dict:
    """Sim-driven 1-block ex-ante reorg: the ``Withholder`` strategy hides
    B2 + one private vote at slot 2 and releases just before the slot-3
    attestation deadline (see ``scripted_run_ex_ante_reorg`` for the slot
    layout). The slot-2 proposer is corrupted (the scripted scenario has
    no honest slot-2 block), everything else is the honest duty loop."""
    from pos_evolution_tpu.sim.adversary import Withholder, slot_committee
    from pos_evolution_tpu.sim.driver import Simulation
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index

    state, _ = make_genesis(n_validators)
    s2_view = advance_state_to_slot(state, 2)
    adversary = int(slot_committee(s2_view, 2)[0])
    proposer2 = int(get_beacon_proposer_index(s2_view))
    controlled = {adversary, proposer2}
    for s in (1, 3, 4):
        p = int(get_beacon_proposer_index(advance_state_to_slot(state, s)))
        assert p not in controlled, \
            f"scenario needs an honest slot-{s} proposer"

    strat = Withholder(controlled=controlled, fork_slot=2, release_slot=3,
                       release_phase="before_attest", vote_slots=(2,),
                       private_attesters={2: [adversary]})
    sim = Simulation(n_validators, adversaries=[strat])
    sim.run_until_slot(4)

    store = sim.store(0)
    head = fc.get_head(store)
    r2 = strat.chain.tip
    (r3,) = [r for r, b in store.blocks.items() if int(b.slot) == 3]
    return {
        "b2_root": r2,
        "b3_root": r3,
        "final_head": head,
        "b3_reorged": not _chain_contains(store, head, r3),
        "b2_canonical": _chain_contains(store, head, r2),
    }


def run_ex_ante_reorg_with_boost(n_validators: int = 800) -> dict:
    """Sim-driven 7%-adversary / 0.8W-boost ex-ante reorg: ``Withholder``
    banks 7 private votes in each of slots 2 and 3 and releases at slot 4
    ``before_propose`` with a timely proposal on the private tip — the
    boost-stealing step (see ``scripted_run_ex_ante_reorg_with_boost``
    for the arithmetic). Slot-2 and slot-4 proposers are corrupted (the
    scripted scenario has no honest block in either slot)."""
    from pos_evolution_tpu.sim.adversary import Withholder, slot_committee
    from pos_evolution_tpu.sim.driver import Simulation
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index

    c = cfg()
    assert c.proposer_score_boost_percent == 80, "scenario expects 0.8W boost"
    state, _ = make_genesis(n_validators)
    adv2 = [int(v) for v in
            slot_committee(advance_state_to_slot(state, 2), 2)[:7]]
    adv3 = [int(v) for v in
            slot_committee(advance_state_to_slot(state, 3), 3)[:7]]
    proposer2 = int(get_beacon_proposer_index(advance_state_to_slot(state, 2)))
    proposer4 = int(get_beacon_proposer_index(advance_state_to_slot(state, 4)))
    controlled = set(adv2) | set(adv3) | {proposer2, proposer4}
    for s in (1, 3):
        p = int(get_beacon_proposer_index(advance_state_to_slot(state, s)))
        assert p not in controlled, \
            f"scenario needs an honest slot-{s} proposer"

    strat = Withholder(controlled=controlled, fork_slot=2, release_slot=4,
                       release_phase="before_propose", vote_slots=(2, 3),
                       private_attesters={2: adv2, 3: adv3},
                       propose_on_release=True)
    sim = Simulation(n_validators, adversaries=[strat])
    sim.run_until_slot(4)

    store = sim.store(0)
    head = fc.get_head(store)
    r2 = strat.chain.tip
    (r3,) = [r for r, b in store.blocks.items() if int(b.slot) == 3]
    (r4,) = [r for r, b in store.blocks.items() if int(b.slot) == 4]
    return {
        "per_slot_committee": n_validators // c.slots_per_epoch,
        "head": head,
        "b3_reorged": not _chain_contains(store, head, r3),
        "b4_canonical": _chain_contains(store, head, r4),
        "b2_canonical": _chain_contains(store, head, r2),
    }


class LMDBalancer:
    """Strategy form of the LMD balancing attack (pos-evolution.md:
    1379-1403): slots 1-4 build two private chains with 20 equivocating
    votes per chain per slot; slot 5 releases two equivocating blocks
    carrying each chain's 80 votes, each view receiving "its" chain
    timely (boost) and the other past the attesting interval — the LMD
    first-received rule then credits each view's table 80:0 for its own
    chain, permanently. Implements the ``AdversaryStrategy`` protocol
    structurally (duck-typed, the protocol's point) rather than by
    inheritance."""

    name = "lmd_balancer"

    def __init__(self, controlled, per_slot_byz: dict[int, list[int]],
                 build_slots=(1, 2, 3, 4), release_slot: int = 5):
        self.controlled = tuple(sorted(int(v) for v in controlled))
        self.per_slot_byz = {int(k): list(v) for k, v in per_slot_byz.items()}
        self.build_slots = tuple(build_slots)
        self.release_slot = int(release_slot)
        self.chain_states = None
        self.chain_blocks = {"L": [], "R": []}
        self.chain_votes = {"L": [], "R": []}
        self.first_roots: tuple | None = None
        self.release_tips: dict | None = None
        self.measured: dict | None = None
        self.tie_log: list[tuple[int, bool]] = []

    def bind(self, sim) -> None:
        self.sim = sim
        assert len(sim.groups) == 2, "LMDBalancer needs exactly two views"

    def describe(self) -> dict:
        return {"kind": type(self).__name__,
                "controlled": list(self.controlled),
                "build_slots": list(self.build_slots),
                "release_slot": self.release_slot}

    def _extend_both(self, ctx) -> None:
        for side, graffiti in (("L", b"\x1f" * 32), ("R", b"\xf1" * 32)):
            sb = build_block(self.chain_states[side], ctx.slot,
                             graffiti=graffiti)
            self.chain_blocks[side].append(sb)
            post = self.chain_states[side].copy()
            state_transition(post, sb, True)
            self.chain_states[side] = post
            head_root = hash_tree_root(sb.message)
            head_state = advance_state_to_slot(post, ctx.slot)
            # the slot's 20 Byzantine attesters vote this chain's head too
            # (equivocation across chains)
            self.chain_votes[side].extend(committee_attestations(
                head_state, ctx.slot, head_root,
                np.array(self.per_slot_byz[ctx.slot], dtype=np.int64)))
        if self.first_roots is None:
            self.first_roots = (
                hash_tree_root(self.chain_blocks["L"][0].message),
                hash_tree_root(self.chain_blocks["R"][0].message))

    def _release(self, ctx) -> None:
        c = cfg()
        # own side timely (boost applies), cross side one tick past the
        # attesting interval (no boost; equal-epoch LMD entries keep the
        # first-received chain, pos-evolution.md:1383, :1394)
        offset = float(_attest_interval(c) + 1)
        tips = {}
        for side, own in (("L", 0), ("R", 1)):
            assert len(self.chain_votes[side]) <= c.max_attestations, \
                "equivocating votes exceed the block's attestation capacity"
            sb5 = build_block(self.chain_states[side], ctx.slot,
                              attestations=self.chain_votes[side],
                              graffiti=(b"\x55" if side == "L" else b"\xaa") * 32)
            tips[side] = hash_tree_root(sb5.message)
            delay = {own: 0.0, 1 - own: offset}
            for sb in self.chain_blocks[side] + [sb5]:
                ctx.broadcast("block", sb,
                              src=int(sb.message.proposer_index), delay=delay)
        self.release_tips = tips
        ctx.deliver()

    def before_propose(self, ctx) -> None:
        if self.chain_states is None:
            base = ctx.store(0).block_states[ctx.head(0)]
            self.chain_states = {"L": base, "R": base}
        if self.first_roots is not None and ctx.slot > self.release_slot + 1:
            # head-tie audit for the PREVIOUS slot, read after the slot
            # boundary tick cleared its proposer boost (the scripted
            # oracle has no boost live at its per-slot head checks)
            self.tie_log.append((ctx.slot - 1, ctx.head(0) != ctx.head(1)))
        if ctx.slot in self.build_slots:
            self._extend_both(ctx)
        elif ctx.slot == self.release_slot:
            self._release(ctx)

    def before_attest(self, ctx) -> None:
        pass

    def after_attest(self, ctx) -> None:
        if ctx.slot == self.release_slot and self.measured is None:
            firstL, firstR = self.first_roots
            gwei32 = 32 * 10**9
            self.measured = {
                "viewA_L_votes": int(fc.get_latest_attesting_balance(
                    ctx.store(0), firstL)) // gwei32,
                "viewA_R_votes": int(fc.get_latest_attesting_balance(
                    ctx.store(0), firstR)) // gwei32,
                "viewB_L_votes": int(fc.get_latest_attesting_balance(
                    ctx.store(1), firstL)) // gwei32,
                "viewB_R_votes": int(fc.get_latest_attesting_balance(
                    ctx.store(1), firstR)) // gwei32,
            }


def run_lmd_balancing_attack(n_validators: int = 800,
                             end_slot: int = 10) -> dict:
    """Sim-driven LMD balancing despite boost, reference numbers (W=100
    per slot, 20 Byzantine per slot, five corrupted proposers). The
    adversary additionally censors the proposers of the post-release
    window (adaptive corruption, pos-evolution.md:183-185): the scripted
    oracle models no blocks after the release, and an honest proposal's
    boost would otherwise perturb the vote ledger the oracle pins."""
    from pos_evolution_tpu.sim.adversary import slot_committee
    from pos_evolution_tpu.sim.driver import Simulation
    from pos_evolution_tpu.specs.genesis import make_genesis
    from pos_evolution_tpu.specs.helpers import get_beacon_proposer_index

    state, _ = make_genesis(n_validators)
    per_slot_byz: dict[int, list[int]] = {}
    corrupted: set[int] = set()
    for slot in range(1, 6):
        flat = [int(v) for v in
                slot_committee(advance_state_to_slot(state, slot), slot)]
        per_slot_byz[slot] = flat[:20]
        corrupted.update(per_slot_byz[slot])
    for slot in range(1, end_slot + 1):
        corrupted.add(int(get_beacon_proposer_index(
            advance_state_to_slot(state, slot))))

    sched = balanced_split_schedule(n_validators, corrupted)
    strat = LMDBalancer(corrupted, per_slot_byz)
    sim = Simulation(n_validators, schedule=sched, adversaries=[strat])
    sim.run_until_slot(end_slot + 1)

    ties = dict(strat.tie_log)
    return {
        **strat.measured,
        "heads_disagree": [ties[s] for s in range(6, end_slot + 1)],
        "justified_A": sim.justified_epoch(0),
        "justified_B": sim.justified_epoch(1),
    }
