"""Online protocol-property monitors (Jepsen-style invariant checking).

The reference's central guarantees are *accountable safety* — conflicting
finalized checkpoints imply >= 1/3 of stake provably violated a slashing
condition (pos-evolution.md:233-238, the Casper FFG theorem) — and
*plausible liveness* — finality resumes after GST given < 1/3 adversarial
stake (:243, :1184-1190). ``sim/attacks.py`` exercises the attacks;
nothing so far *audited the properties they threaten, continuously,
inside the driver*. These monitors do: every slot, across every live
honest store, the protocol either holds its guarantees or the monitor
yields cryptographic evidence against the attackers.

- ``AccountableSafetyMonitor``: observes every originated attestation and
  block (honest and adversarial) through the driver's broadcast path,
  feeds the ``specs/slasher.Slasher``, and on conflicting finalized (or
  same-epoch justified) checkpoints across views computes the implicated
  slashable set from the vote logs. Evidence covering >= 1/3 of stake is
  the theorem holding (an *accountable* fault, attributable to the
  attackers); anything less is a genuine protocol violation. With
  ``broadcast_evidence=True`` detected ``AttesterSlashing``s are also
  fed back onto the wire as ``slashing`` messages — the in-loop
  watchtower closing the evidence -> ``on_attester_slashing`` ->
  discounting loop.
- ``FinalityLivenessMonitor``: after GST (and every crash window's end),
  with < 1/3 adversarial stake, the best finalized epoch across live
  views must trail the current epoch by at most ``bound_epochs``.
- ``ForkChoiceParityMonitor``: the resident device head must equal the
  spec head on every live accelerated view, every slot — the
  ``ops/resident.py`` periodic self-check promoted to a continuous,
  attack-time audit.

Violations are returned as dicts, recorded on
``Simulation.monitor_violations``, and emitted as ``monitor`` telemetry
events; ``scripts/chaos_fuzz.py`` turns them into repro bundles and
``scripts/run_report.py`` folds them into the property-audit section.
"""

from __future__ import annotations

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs import forkchoice as fc
from pos_evolution_tpu.specs.containers import (
    BeaconBlockHeader,
    SignedBeaconBlockHeader,
)
from pos_evolution_tpu.specs.helpers import (
    get_indexed_attestation,
    get_total_active_balance,
)
from pos_evolution_tpu.specs.slasher import Slasher
from pos_evolution_tpu.specs.validator import advance_state_to_slot
from pos_evolution_tpu.ssz import hash_tree_root

import numpy as np

# src id for monitor-originated slashing gossip (see adversary.ATT_SRC_BASE
# for the adversarial namespace; the watchtower gets its own)
SLASHING_SRC = 2_000


class Monitor:
    """Base monitor: observes originated messages, checks once per slot.

    ``observe`` sees every message at ORIGINATION (before FaultPlan
    drops), which is exactly the watchtower model: evidence of a
    violation can be observed by someone (pos-evolution.md:238) even if
    some recipients never get the message. ``on_slot_end`` returns a
    list of violation dicts; an empty list is a clean slot."""

    name = "monitor"

    def bind(self, sim) -> None:
        self.sim = sim

    def describe(self) -> dict:
        return {"kind": type(self).__name__}

    def observe(self, kind: str, payload) -> None:
        pass

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        return []


def _live_groups(sim):
    return [g for g in sim.groups if not g.crashed]


class AccountableSafetyMonitor(Monitor):
    """Safety auditor + watchtower (see module docstring)."""

    name = "accountable_safety"

    def __init__(self, broadcast_evidence: bool = False):
        self.broadcast_evidence = broadcast_evidence

    def bind(self, sim) -> None:
        super().bind(sim)
        self.slasher = Slasher()
        self.evidence: list = []          # every AttesterSlashing emitted
        self.proposer_evidence: list = []  # ProposerSlashings (equivocating
        #   proposals; recorded for the audit trail, not stake attribution —
        #   the 1/3 bound is about double/surround VOTES)
        self.implicated: set[int] = set()  # validators covered by evidence
        self._pending: list = []          # attestations awaiting a target state
        self._seen_atts: set = set()      # hash_tree_root of every buffered
        #   attestation: block-packed copies of already-observed votes are
        #   dropped at the tap instead of re-running committee indexing
        self._target_states: dict = {}    # (epoch, root) -> advanced state
        self._reported: set = set()       # conflict keys already reported
        self._slash_seq = 0

    def describe(self) -> dict:
        return {"kind": type(self).__name__,
                "broadcast_evidence": self.broadcast_evidence}

    # -- observation -----------------------------------------------------------

    def _buffer(self, att) -> None:
        key = hash_tree_root(att)
        if key in self._seen_atts:
            return
        self._seen_atts.add(key)
        self._pending.append(att)

    def observe(self, kind: str, payload) -> None:
        if kind == "attestation":
            self._buffer(payload)
        elif kind == "block":
            block = payload.message
            for att in block.body.attestations:
                self._buffer(att)
            header = SignedBeaconBlockHeader(
                message=BeaconBlockHeader(
                    slot=int(block.slot),
                    proposer_index=int(block.proposer_index),
                    parent_root=bytes(block.parent_root),
                    state_root=bytes(block.state_root),
                    body_root=hash_tree_root(block.body)),
                signature=bytes(payload.signature))
            ps = self.slasher.on_block_header(header)
            if ps is not None:
                self.proposer_evidence.append(ps)

    def _target_state(self, target):
        """The committee-resolving state for an attestation target, from
        whichever view or archived block knows the target root."""
        key = (int(target.epoch), bytes(target.root))
        state = self._target_states.get(key)
        if state is not None:
            return state
        root = bytes(target.root)
        base = None
        for g in self.sim.groups:
            base = g.store.block_states.get(root)
            if base is not None:
                break
        if base is None:
            return None
        state = advance_state_to_slot(
            base, int(target.epoch) * cfg().slots_per_epoch)
        self._target_states[key] = state
        return state

    def _ingest_pending(self) -> list:
        """Index and feed every observed attestation whose target is now
        resolvable; returns newly emitted evidence."""
        new_evidence = []
        still = []
        for att in self._pending:
            state = self._target_state(att.data.target)
            if state is None:
                # target chain never surfaced in any view yet; retry while
                # the vote is recent, then drop (bounds the buffer)
                horizon = (int(att.data.target.epoch) + 2) * cfg().slots_per_epoch
                if self.sim.slot <= horizon:
                    still.append(att)
                continue
            try:
                indexed = get_indexed_attestation(state, att)
            except (AssertionError, IndexError):
                continue  # malformed for this committee layout: unusable
            new_evidence.extend(self.slasher.on_attestation(indexed))
        self._pending = still
        for ev in new_evidence:
            a = set(int(i) for i in np.asarray(ev.attestation_1.attesting_indices))
            b = set(int(i) for i in np.asarray(ev.attestation_2.attesting_indices))
            self.implicated |= (a & b)
        self.evidence.extend(new_evidence)
        return new_evidence

    # -- per-slot check --------------------------------------------------------

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        new_evidence = self._ingest_pending()
        if new_evidence:
            if sim.telemetry is not None:
                sim.telemetry.bus.emit(
                    "slashing_detected", monitor=self.name, slot=slot,
                    n_new=len(new_evidence),
                    implicated_total=len(self.implicated))
            if self.broadcast_evidence:
                t = sim.slot_start(slot + 1)
                for ev in new_evidence:
                    for dst in sim.groups:
                        sim._send(dst, t, 0.0, "slashing", ev, slot,
                                  src=SLASHING_SRC, msg_id=self._slash_seq)
                    self._slash_seq += 1
        return self._check_conflicts(sim, slot)

    def _stake_of(self, indices) -> int:
        reg = self.sim.genesis_state.validators
        return sum(int(reg.effective_balance[i]) for i in indices
                   if i < len(reg))

    def _ancestor_in_archive(self, root: bytes, ancestor: bytes,
                             ancestor_slot: int) -> bool:
        """Ancestry via the global block archive (views may not hold each
        other's chains). Unknown roots resolve to 'not an ancestor'."""
        cur = root
        while True:
            sb = self.sim.block_archive.get(cur)
            if sb is None:
                # the anchor itself is not archived; a walk that dead-ends
                # exactly there can still match by identity
                return cur == ancestor
            if int(sb.message.slot) <= ancestor_slot:
                return cur == ancestor
            cur = bytes(sb.message.parent_root)

    def _conflicting(self, cp_a, cp_b) -> bool:
        ea, ra = int(cp_a.epoch), bytes(cp_a.root)
        eb, rb = int(cp_b.epoch), bytes(cp_b.root)
        if ea == 0 or eb == 0:
            return False  # genesis conflicts with nothing
        if ea == eb:
            return ra != rb
        lo, hi = ((ea, ra), (eb, rb)) if ea < eb else ((eb, rb), (ea, ra))
        lo_slot = int(self.sim.block_archive[lo[1]].message.slot) \
            if lo[1] in self.sim.block_archive else lo[0] * cfg().slots_per_epoch
        return not self._ancestor_in_archive(hi[1], lo[1], lo_slot)

    def _check_conflicts(self, sim, slot: int) -> list[dict]:
        out = []
        live = _live_groups(sim)
        for i in range(len(live)):
            for j in range(i + 1, len(live)):
                gi, gj = live[i], live[j]
                pairs = [
                    ("finalized", gi.store.finalized_checkpoint,
                     gj.store.finalized_checkpoint),
                    ("justified", gi.store.justified_checkpoint,
                     gj.store.justified_checkpoint),
                ]
                for label, ca, cb in pairs:
                    # conflicting *justified* checkpoints are slashable
                    # only at the SAME epoch (2/3 + 2/3 overlap); lagging
                    # views legitimately justify different epochs
                    if label == "justified" and int(ca.epoch) != int(cb.epoch):
                        continue
                    if not self._conflicting(ca, cb):
                        continue
                    key = (label, min(gi.id, gj.id), max(gi.id, gj.id),
                           int(ca.epoch), bytes(ca.root),
                           int(cb.epoch), bytes(cb.root))
                    if key in self._reported:
                        continue
                    self._reported.add(key)
                    stake = self._stake_of(self.implicated)
                    total = get_total_active_balance(sim.genesis_state)
                    accountable = 3 * stake >= total
                    out.append({
                        "monitor": self.name,
                        "kind": ("accountable_fault" if accountable
                                 else "protocol_violation"),
                        "checkpoint": label,
                        "groups": [gi.id, gj.id],
                        "epochs": [int(ca.epoch), int(cb.epoch)],
                        "roots": [bytes(ca.root).hex()[:16],
                                  bytes(cb.root).hex()[:16]],
                        "evidence_size": len(self.implicated),
                        "slashable_stake": stake,
                        "total_stake": total,
                        "detail": (
                            f"conflicting {label} checkpoints between "
                            f"groups {gi.id}/{gj.id}; slashable evidence "
                            f"covers {stake}/{total} stake"
                            + ("" if accountable else
                               " — BELOW the 1/3 accountable-safety bound")),
                    })
        return out


class FinalityLivenessMonitor(Monitor):
    """Plausible-liveness auditor: finality must advance within
    ``bound_epochs`` of the current epoch once the network is past GST
    and every declared crash window, given < 1/3 adversarial stake.
    Disarmed (checks nothing, loudly recorded in ``describe``) when the
    preconditions cannot hold: >= 1/3 corrupted, or message faults with
    no GST."""

    name = "finality_liveness"

    def __init__(self, bound_epochs: int = 4,
                 armed_after_epoch: int | None = None):
        self.bound_epochs = int(bound_epochs)
        self.armed_after_epoch = armed_after_epoch
        self.disarmed_reason: str | None = None
        self._worst_lag = 0

    def describe(self) -> dict:
        return {"kind": type(self).__name__,
                "bound_epochs": self.bound_epochs,
                "armed_after_epoch": self.armed_after_epoch,
                "disarmed": self.disarmed_reason}

    def bind(self, sim) -> None:
        super().bind(sim)
        c = cfg()
        n = sim.n_validators
        n_corrupt = len(sim.schedule.corrupted)
        if 3 * n_corrupt >= n:
            self.disarmed_reason = (
                f"{n_corrupt}/{n} corrupted >= 1/3: liveness not guaranteed")
            return
        if self.armed_after_epoch is not None:
            return
        armed = 0
        plan = sim.schedule.faults
        if plan is not None:
            if (plan.drop_p or plan.duplicate_p or plan.reorder_p):
                if plan.gst is None:
                    self.disarmed_reason = \
                        "message faults with no GST: no synchrony to rely on"
                    return
                sec_per_epoch = c.seconds_per_slot * c.slots_per_epoch
                armed = max(armed, -(-int(plan.gst) // sec_per_epoch))
            for w in plan.crashes:
                armed = max(armed, -(-w.rejoin_slot // c.slots_per_epoch))
        self.armed_after_epoch = armed

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        if self.disarmed_reason is not None:
            return []
        c = cfg()
        epoch = slot // c.slots_per_epoch
        if epoch < (self.armed_after_epoch or 0) + self.bound_epochs:
            return []
        live = _live_groups(sim)
        if not live:
            return []
        best = max(int(g.store.finalized_checkpoint.epoch) for g in live)
        lag = epoch - best
        if lag <= self.bound_epochs or lag <= self._worst_lag:
            # report once per lag level, not every slot of a stall
            return []
        self._worst_lag = lag
        return [{
            "monitor": self.name,
            "kind": "liveness_violation",
            "epoch": epoch,
            "best_finalized_epoch": best,
            "lag_epochs": lag,
            "bound_epochs": self.bound_epochs,
            "armed_after_epoch": self.armed_after_epoch,
            "detail": (f"finality lag {lag} epochs > bound "
                       f"{self.bound_epochs} at epoch {epoch} "
                       f"(post-GST, < 1/3 adversarial)"),
        }]


class ForkChoiceParityMonitor(Monitor):
    """Device/spec head parity on every live accelerated view, every
    slot — under attack traffic, not just the honest benches the
    ``ops/resident.py`` periodic self-check mostly sees. A degraded
    mirror answers from the spec path and so stays trivially at parity;
    the monitor additionally surfaces NEW degradations as audit events
    rather than violations (degradation is the designed response)."""

    name = "forkchoice_parity"

    def bind(self, sim) -> None:
        super().bind(sim)
        self._seen_incidents = {g.id: 0 for g in sim.groups}

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        out = []
        for g in _live_groups(sim):
            if g.resident is None:
                continue
            spec_head = fc.get_head(g.store)
            device_head = g.resident.head(g.store)
            if device_head != spec_head:
                out.append({
                    "monitor": self.name,
                    "kind": "parity_violation",
                    "group": g.id,
                    "slot": slot,
                    "device_head": device_head.hex()[:16],
                    "spec_head": spec_head.hex()[:16],
                    "detail": (f"group {g.id} device head diverged from "
                               f"spec head at slot {slot}"),
                })
            n_inc = len(g.resident.incidents)
            if n_inc < self._seen_incidents.get(g.id, 0):
                # crash-rejoin rebuilt the resident with a fresh incident
                # list; restart the watermark or post-rejoin degradations
                # would be suppressed until the new list outgrew the old
                self._seen_incidents[g.id] = 0
            if n_inc > self._seen_incidents.get(g.id, 0):
                self._seen_incidents[g.id] = n_inc
                if sim.telemetry is not None:
                    sim.telemetry.bus.emit(
                        "monitor_note", monitor=self.name, group=g.id,
                        slot=slot, incidents=list(g.resident.incidents))
        return out


class VariantSafetyMonitor(Monitor):
    """Safety auditor for the protocol-variant layer (variants/,
    DESIGN.md §16) — the accountable-safety theorem at the successor
    protocols' granularity:

    - **conflicting variant-finalized checkpoints** across live views
      (SSF per-slot FFG, pos-evolution.md:1626, 1646): two finalized
      (block, slot) pairs, same slot with different blocks or
      non-ancestral chains, require two 2/3 quorums — the variant's
      cross-view evidence log (double per-slot FFG votes,
      surround-the-ack) must implicate >= 1/3 of stake, else the break
      is a genuine ``protocol_violation``;
    - **conflicting same-slot fast confirmations** (:1562-1569): two
      > 3/4 quorums for different blocks of one slot overlap in >= 1/2 of
      the eligible voters, all of whom double-voted — same accountable /
      protocol_violation split.

    Reporting contract: at most one report per (view pair, checkpoint
    label, verdict kind) — SSF finalizes every slot, so per-checkpoint
    reporting would flood the audit with one conflict repeated per slot;
    an ``accountable_fault`` never suppresses a later
    ``protocol_violation`` (a forged or genuinely unexplained break must
    surface even after an explained one), and a ``protocol_violation``
    re-reports once as ``accountable_fault`` when committee rotation
    accumulates the evidence past the bound (committee-subsampled SSF
    implicates the adversary round by round).

    Under the Gasper default (no overlay) the monitor is inert; the FFG
    layer stays ``AccountableSafetyMonitor``'s job."""

    name = "variant_safety"

    def bind(self, sim) -> None:
        super().bind(sim)
        self._reported: set = set()      # (label, gi, gj, kind)
        self._scan_idx: dict = {}        # (label, gi, gj) -> (len_a, len_b)
        self._first_violation: dict = {} # key -> (ca, cb) awaiting upgrade

    def _archive_descends(self, root: bytes, ancestor: bytes) -> bool:
        """Chain walk over the global block archive; no slot cutoff — an
        SSF checkpoint's BLOCK can be older than its checkpoint slot
        (e.g. the anchor finalized at slot 1), so cutting the walk at
        the checkpoint slot would declare ancestral same-chain
        checkpoints conflicting. The walk dead-ends at the anchor
        (never broadcast, so never archived)."""
        cur = root
        while True:
            if cur == ancestor:
                return True
            sb = self.sim.block_archive.get(cur)
            if sb is None:
                return False
            cur = bytes(sb.message.parent_root)

    def _conflicting(self, a: tuple[bytes, int], b: tuple[bytes, int]) -> bool:
        (ra, sa), (rb, sb) = a, b
        if ra == rb:
            return False
        if sa == sb:
            return True
        hi_r = ra if sa > sb else rb
        lo_r = rb if sa > sb else ra
        return not self._archive_descends(hi_r, lo_r)

    def _stake_of(self, indices) -> int:
        reg = self.sim.genesis_state.validators
        return sum(int(reg.effective_balance[i]) for i in indices
                   if i < len(reg))

    def _classify(self, stake: int, total: int,
                  ca: tuple[bytes, int], cb: tuple[bytes, int]) -> tuple:
        """(kind, scale): a SAME-SLOT conflict was finalized/confirmed by
        two quorums of that slot's committee — the theorem's bound is
        1/3 of one slot's committee weight W (the carrier subsamples the
        paper's full participation; W = total / slots_per_epoch, the
        same W as proposer boost). Cross-slot conflicts have disjoint
        committees and keep the full-stake bound."""
        scale = total
        if ca[1] == cb[1]:
            scale = total // cfg().slots_per_epoch
        kind = ("accountable_fault" if 3 * stake >= scale
                else "protocol_violation")
        return kind, scale

    def on_slot_end(self, sim, slot: int) -> list[dict]:
        variant = getattr(sim, "variant", None)
        if variant is None or not variant.needs_view:
            return []
        out = []
        live = _live_groups(sim)
        evidence = variant.slashable()
        stake = self._stake_of(evidence)
        total = int(get_total_active_balance(sim.genesis_state))
        for i in range(len(live)):
            for j in range(i + 1, len(live)):
                gi, gj = live[i], live[j]
                pairs = [("finalized",
                          variant.finalized_checkpoints(gi.id),
                          variant.finalized_checkpoints(gj.id)),
                         ("fast_confirmed",
                          variant.fast_confirmations(gi.id),
                          variant.fast_confirmations(gj.id))]
                for label, cps_a, cps_b in pairs:
                    key = (label, min(gi.id, gj.id), max(gi.id, gj.id))
                    # Incremental scan for the append-only finalized
                    # chains: pairs of already-examined entries never
                    # re-walk the archive (SSF finalizes per slot — a
                    # full rescan would be O(slots^2) walks per run).
                    # fast_confirmed REPLACES its single entry per view,
                    # so it is always rescanned (length <= 1).
                    na0 = nb0 = 0
                    if label == "finalized":
                        na0, nb0 = self._scan_idx.get(key, (0, 0))
                        self._scan_idx[key] = (len(cps_a), len(cps_b))
                    conflicts = []
                    for ia, ca in enumerate(cps_a):
                        for jb, cb in enumerate(cps_b):
                            if ia < na0 and jb < nb0:
                                continue
                            if label == "fast_confirmed" \
                                    and ca[1] != cb[1]:
                                # fast confirmations of different slots
                                # on different chains are the normal
                                # life of competing forks, not a quorum
                                # overlap
                                continue
                            if self._conflicting(ca, cb):
                                conflicts.append((ca, cb))
                    # re-classify the first still-unaccountable conflict
                    # so evidence growth upgrades the verdict once
                    if key in self._first_violation:
                        conflicts.append(self._first_violation[key])
                    for ca, cb in conflicts:
                        kind, scale = self._classify(stake, total, ca, cb)
                        if (key + (kind,)) in self._reported:
                            continue
                        self._reported.add(key + (kind,))
                        if kind == "protocol_violation":
                            self._first_violation.setdefault(key, (ca, cb))
                        else:
                            self._first_violation.pop(key, None)
                        accountable = kind == "accountable_fault"
                        out.append({
                            "monitor": self.name,
                            "kind": kind,
                            "variant": variant.name,
                            "checkpoint": label,
                            "groups": [gi.id, gj.id],
                            "slots": [ca[1], cb[1]],
                            "roots": [ca[0].hex()[:16], cb[0].hex()[:16]],
                            "evidence_size": len(evidence),
                            "slashable_stake": stake,
                            "total_stake": total,
                            "accountability_scale": scale,
                            "detail": (
                                f"conflicting {label} variant checkpoints "
                                f"({variant.name}) between groups "
                                f"{gi.id}/{gj.id}; variant evidence covers "
                                f"{stake}/{scale} accountable-scale stake"
                                + ("" if accountable else
                                   " — BELOW the 1/3 accountable-safety"
                                   " bound")),
                        })
        return out


def default_monitors(accountable_broadcast: bool = True) -> list[Monitor]:
    """The full audit stack (chaos fuzzing default)."""
    return [AccountableSafetyMonitor(broadcast_evidence=accountable_broadcast),
            FinalityLivenessMonitor(),
            ForkChoiceParityMonitor(),
            VariantSafetyMonitor()]
