"""Fault injection as data, beyond delivery delays (pos-evolution.md:183-199).

``Schedule`` (sim/schedule.py) already expresses Byzantine corruption,
per-round sleepiness, and adversary-chosen *delays*. The reference's
adversary is richer: messages can be lost, duplicated, and reordered
arbitrarily before GST (partial synchrony, :197-199), and validators can
crash outright and later rejoin by syncing from a weak-subjectivity
checkpoint (:1198-1317, "checkpoints that act as new genesis" :1216).

A ``FaultPlan`` captures that as *data* composable with any ``Schedule``:

- per-(message, recipient-group) drop / duplicate / reorder probabilities,
  decided by a **stateless seeded hash** of the message identity — no RNG
  cursor, so a simulation checkpointed and resumed mid-run replays the
  exact same fault pattern (the bit-identical-resume contract of
  ``Simulation.checkpoint``);
- a GST (global stabilization time) after which the network is synchronous
  and all message faults switch off (:199); finalization must then resume
  — the ebb-and-flow claim (:1184-1190) the fault tests pin;
- ``CrashWindow``\\ s: a view group that stops processing entirely for a
  slot range, loses its in-flight messages, and rejoins via the
  weak-subjectivity checkpoint-sync path (``utils/snapshot.resume_store``
  gated by ``is_within_weak_subjectivity_period``) — the driver performs
  the sync; the plan only declares the window, so crash state needs no
  serialization (it is a pure function of the current slot).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

# integer tags for message kinds (stable fault-decision identity)
_KIND_TAG = {"block": 0, "attestation": 1, "slashing": 2}


def stateless_unit(seed: int, *key: int) -> float:
    """Uniform [0, 1) from a hash of (seed, key): no RNG stream, no
    call-order dependence — the same identity always draws the same
    number, before or after a checkpoint/resume, and independent of any
    array backend (pure ``hashlib``, never NumPy/JAX). Shared by
    ``FaultPlan`` and ``sim/adversary.RandomByzantine`` so the two
    adversaries cannot drift apart in determinism discipline
    (byte-stability is pinned by tests/test_adversary.py)."""
    h = hashlib.blake2b(
        struct.pack(f"<{len(key) + 1}q", seed, *key),
        digest_size=8).digest()
    return int.from_bytes(h, "little") / 2.0**64


@dataclass(frozen=True)
class CrashWindow:
    """View group ``group`` is down for slots [crash_slot, rejoin_slot):
    it processes nothing, receives nothing (messages in flight are lost),
    and at ``rejoin_slot`` rejoins by checkpoint sync from a live peer."""

    group: int
    crash_slot: int
    rejoin_slot: int

    def __post_init__(self):
        assert self.crash_slot < self.rejoin_slot, "empty crash window"


@dataclass
class FaultPlan:
    """Composable message-fault policy; attach via ``Schedule.faults``."""

    seed: int = 0
    # Per-(message, recipient-group) probabilities, active before GST.
    drop_p: float = 0.0
    duplicate_p: float = 0.0
    reorder_p: float = 0.0
    # A reordered (or duplicated) copy lands up to this many seconds late —
    # the adversary's "target a message for delivery ... just before a
    # certain point in time" capability (pos-evolution.md:1328) expressed
    # as bounded jitter.
    reorder_max_delay: float = 4.0
    # Global stabilization time in seconds since genesis; None = faults
    # stay active for the whole run (no partial-synchrony window).
    gst: float | None = None
    crashes: tuple = ()
    # Observability: when True, every non-trivial fault decision appends a
    # dict to ``log`` (tests assert drop invariants against it). The log
    # is NOT part of simulation state: a resumed run re-records only
    # post-resume decisions.
    record_log: bool = False
    log: list = field(default_factory=list)
    # Telemetry sink (an ``EventBus`` or anything with ``.emit``): every
    # non-trivial decision also lands as a ``fault`` event carrying the
    # seeded hash inputs that decided it — (seed, kind tag, slot, src,
    # msg_id, dst) plus the drawn uniform and its threshold — so a run
    # report can attribute "why did THIS message vanish" without the live
    # plan. Like ``log``, the sink is not simulation state (the driver
    # re-attaches it on resume alongside the schedule).
    sink: object = None

    # -- stateless randomness --------------------------------------------------

    def _unit(self, *key: int) -> float:
        """Uniform [0, 1) from a hash of (seed, key): no RNG stream, no
        call-order dependence — the same message identity always draws the
        same number, before or after a checkpoint/resume."""
        return stateless_unit(self.seed, *key)

    # -- message faults --------------------------------------------------------

    def active(self, time: float) -> bool:
        """Message faults apply only before GST (pos-evolution.md:199)."""
        return self.gst is None or time < self.gst

    def delivery_offsets(self, kind: str, slot: int, src: int, msg_id: int,
                         dst_group: int, base_time: float) -> list[float]:
        """Extra delays (seconds, added to the scheduled delivery time) for
        each copy of one (message, recipient-group) delivery. ``[]`` means
        dropped; two entries mean duplicated; a single nonzero entry is a
        reorder past later-sent messages."""
        if not self.active(base_time):
            return [0.0]
        tag = _KIND_TAG.get(kind, 3)
        key = (tag, slot, src, msg_id, dst_group)
        if self.drop_p > 0.0:
            u = self._unit(0, *key)
            if u < self.drop_p:
                self._log("drop", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.drop_p)
                return []
        offsets = [0.0]
        if self.reorder_p > 0.0:
            u = self._unit(1, *key)
            if u < self.reorder_p:
                offsets = [self._unit(2, *key) * self.reorder_max_delay]
                self._log("reorder", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.reorder_p, delay_s=offsets[0])
        if self.duplicate_p > 0.0:
            u = self._unit(3, *key)
            if u < self.duplicate_p:
                extra = self._unit(4, *key) * self.reorder_max_delay
                offsets.append(extra)
                self._log("duplicate", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.duplicate_p, delay_s=extra)
        return offsets

    def _log(self, action: str, kind: str, slot: int, src: int, msg_id: int,
             dst_group: int, u: float | None = None, p: float | None = None,
             delay_s: float | None = None) -> None:
        if self.record_log:
            self.log.append({"action": action, "kind": kind, "slot": slot,
                             "src": src, "msg_id": msg_id, "dst": dst_group})
        if self.sink is not None:
            # fault attribution: the full seeded-hash identity that decided
            # this fate, replayable via _unit(seed, tag, slot, src, msg_id,
            # dst) — enough for run_report to explain any one lost message
            ev = {"action": action, "kind": kind, "slot": slot, "src": src,
                  "msg_id": msg_id, "dst": dst_group, "seed": self.seed,
                  "tag": _KIND_TAG.get(kind, 3)}
            if u is not None:
                # unrounded: JSON round-trips doubles losslessly, and the
                # replay contract (DESIGN.md §11) is EXACT equality with
                # re-drawing this identity through _unit
                ev["u"] = u
                ev["threshold"] = p
            if delay_s is not None:
                ev["delay_s"] = round(delay_s, 6)
            self.sink.emit("fault", **ev)

    def dropped(self, kind: str | None = None) -> list[dict]:
        """Recorded drop events (requires ``record_log=True``)."""
        return [e for e in self.log if e["action"] == "drop"
                and (kind is None or e["kind"] == kind)]

    # -- crash windows ---------------------------------------------------------

    def crashed(self, group: int, slot: int) -> bool:
        """Pure function of the slot — no crash state to checkpoint."""
        return any(w.group == group and w.crash_slot <= slot < w.rejoin_slot
                   for w in self.crashes)

    def rejoins(self, group: int, slot: int) -> bool:
        """True exactly at the slot where ``group`` comes back up (and is
        not immediately re-crashed by an overlapping window)."""
        return (any(w.group == group and w.rejoin_slot == slot
                    for w in self.crashes)
                and not self.crashed(group, slot))


def lossy_plan(seed: int = 0, drop_p: float = 0.1,
               gst: float | None = None) -> FaultPlan:
    """Message loss only — the minimal ebb-and-flow adversary."""
    return FaultPlan(seed=seed, drop_p=drop_p, gst=gst)


def chaos_plan(seed: int = 0, drop_p: float = 0.05, duplicate_p: float = 0.05,
               reorder_p: float = 0.1, gst: float | None = None,
               crashes: tuple = ()) -> FaultPlan:
    """Drops + duplicates + reorders + optional crash windows."""
    return FaultPlan(seed=seed, drop_p=drop_p, duplicate_p=duplicate_p,
                     reorder_p=reorder_p, gst=gst, crashes=tuple(crashes))
