"""Fault injection as data, beyond delivery delays (pos-evolution.md:183-199).

``Schedule`` (sim/schedule.py) already expresses Byzantine corruption,
per-round sleepiness, and adversary-chosen *delays*. The reference's
adversary is richer: messages can be lost, duplicated, and reordered
arbitrarily before GST (partial synchrony, :197-199), and validators can
crash outright and later rejoin by syncing from a weak-subjectivity
checkpoint (:1198-1317, "checkpoints that act as new genesis" :1216).

A ``FaultPlan`` captures that as *data* composable with any ``Schedule``:

- per-(message, recipient-group) drop / duplicate / reorder probabilities,
  decided by a **stateless seeded hash** of the message identity — no RNG
  cursor, so a simulation checkpointed and resumed mid-run replays the
  exact same fault pattern (the bit-identical-resume contract of
  ``Simulation.checkpoint``);
- a GST (global stabilization time) after which the network is synchronous
  and all message faults switch off (:199); finalization must then resume
  — the ebb-and-flow claim (:1184-1190) the fault tests pin;
- ``CrashWindow``\\ s: a view group that stops processing entirely for a
  slot range, loses its in-flight messages, and rejoins via the
  weak-subjectivity checkpoint-sync path (``utils/snapshot.resume_store``
  gated by ``is_within_weak_subjectivity_period``) — the driver performs
  the sync; the plan only declares the window, so crash state needs no
  serialization (it is a pure function of the current slot).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

import numpy as np

# integer tags for message kinds (stable fault-decision identity)
_KIND_TAG = {"block": 0, "attestation": 1, "slashing": 2}


def stateless_unit(seed: int, *key: int) -> float:
    """Uniform [0, 1) from a hash of (seed, key): no RNG stream, no
    call-order dependence — the same identity always draws the same
    number, before or after a checkpoint/resume, and independent of any
    array backend (pure ``hashlib``, never NumPy/JAX). Shared by
    ``FaultPlan`` and ``sim/adversary.RandomByzantine`` so the two
    adversaries cannot drift apart in determinism discipline
    (byte-stability is pinned by tests/test_adversary.py)."""
    h = hashlib.blake2b(
        struct.pack(f"<{len(key) + 1}q", seed, *key),
        digest_size=8).digest()
    return int.from_bytes(h, "little") / 2.0**64


def stateless_word(seed: int, *key: int) -> int:
    """The raw 64-bit word behind ``stateless_unit`` — the full-entropy
    form used to key *vectorized* draws (``stateless_unit_array``): one
    blake2b of the identity seeds a whole axis worth of decisions."""
    h = hashlib.blake2b(
        struct.pack(f"<{len(key) + 1}q", seed, *key),
        digest_size=8).digest()
    return int.from_bytes(h, "little")


# splitmix64 constants (Steele et al.) — the per-index expansion of one
# stateless_word over a validator axis. Pure uint64 numpy arithmetic:
# identical bytes on every backend and every mesh shape (the masks are
# computed replicated on host and only then placed on devices).
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)


def stateless_unit_array(seed: int, *key: int, n: int) -> np.ndarray:
    """Vectorized ``stateless_unit``: uniform [0, 1) per index 0..n-1,
    derived by expanding one ``stateless_word(seed, *key)`` with a
    splitmix64 finalizer over the index axis. No RNG cursor, no
    call-order dependence — the dense drivers' per-(slot, validator)
    fault and adversary decisions are a pure function of the identity,
    byte-stable across checkpoint/resume, mesh shapes, and backends
    (pinned in tests/test_dense_chaos.py)."""
    base = np.uint64(stateless_word(seed, *key))
    with np.errstate(over="ignore"):
        z = base + np.arange(1, n + 1, dtype=np.uint64) * _SM_GAMMA
        z = (z ^ (z >> np.uint64(30))) * _SM_M1
        z = (z ^ (z >> np.uint64(27))) * _SM_M2
        z = z ^ (z >> np.uint64(31))
    return z.astype(np.float64) / 2.0**64


@dataclass(frozen=True)
class CrashWindow:
    """View group ``group`` is down for slots [crash_slot, rejoin_slot):
    it processes nothing, receives nothing (messages in flight are lost),
    and at ``rejoin_slot`` rejoins by checkpoint sync from a live peer."""

    group: int
    crash_slot: int
    rejoin_slot: int

    def __post_init__(self):
        assert self.crash_slot < self.rejoin_slot, "empty crash window"


@dataclass
class FaultPlan:
    """Composable message-fault policy; attach via ``Schedule.faults``."""

    seed: int = 0
    # Per-(message, recipient-group) probabilities, active before GST.
    drop_p: float = 0.0
    duplicate_p: float = 0.0
    reorder_p: float = 0.0
    # A reordered (or duplicated) copy lands up to this many seconds late —
    # the adversary's "target a message for delivery ... just before a
    # certain point in time" capability (pos-evolution.md:1328) expressed
    # as bounded jitter.
    reorder_max_delay: float = 4.0
    # Global stabilization time in seconds since genesis; None = faults
    # stay active for the whole run (no partial-synchrony window).
    gst: float | None = None
    crashes: tuple = ()
    # Observability: when True, every non-trivial fault decision appends a
    # dict to ``log`` (tests assert drop invariants against it). The log
    # is NOT part of simulation state: a resumed run re-records only
    # post-resume decisions.
    record_log: bool = False
    log: list = field(default_factory=list)
    # Telemetry sink (an ``EventBus`` or anything with ``.emit``): every
    # non-trivial decision also lands as a ``fault`` event carrying the
    # seeded hash inputs that decided it — (seed, kind tag, slot, src,
    # msg_id, dst) plus the drawn uniform and its threshold — so a run
    # report can attribute "why did THIS message vanish" without the live
    # plan. Like ``log``, the sink is not simulation state (the driver
    # re-attaches it on resume alongside the schedule).
    sink: object = None

    # -- stateless randomness --------------------------------------------------

    def _unit(self, *key: int) -> float:
        """Uniform [0, 1) from a hash of (seed, key): no RNG stream, no
        call-order dependence — the same message identity always draws the
        same number, before or after a checkpoint/resume."""
        return stateless_unit(self.seed, *key)

    # -- message faults --------------------------------------------------------

    def active(self, time: float) -> bool:
        """Message faults apply only before GST (pos-evolution.md:199)."""
        return self.gst is None or time < self.gst

    def delivery_offsets(self, kind: str, slot: int, src: int, msg_id: int,
                         dst_group: int, base_time: float) -> list[float]:
        """Extra delays (seconds, added to the scheduled delivery time) for
        each copy of one (message, recipient-group) delivery. ``[]`` means
        dropped; two entries mean duplicated; a single nonzero entry is a
        reorder past later-sent messages."""
        if not self.active(base_time):
            return [0.0]
        tag = _KIND_TAG.get(kind, 3)
        key = (tag, slot, src, msg_id, dst_group)
        if self.drop_p > 0.0:
            u = self._unit(0, *key)
            if u < self.drop_p:
                self._log("drop", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.drop_p)
                return []
        offsets = [0.0]
        if self.reorder_p > 0.0:
            u = self._unit(1, *key)
            if u < self.reorder_p:
                offsets = [self._unit(2, *key) * self.reorder_max_delay]
                self._log("reorder", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.reorder_p, delay_s=offsets[0])
        if self.duplicate_p > 0.0:
            u = self._unit(3, *key)
            if u < self.duplicate_p:
                extra = self._unit(4, *key) * self.reorder_max_delay
                offsets.append(extra)
                self._log("duplicate", kind, slot, src, msg_id, dst_group,
                          u=u, p=self.duplicate_p, delay_s=extra)
        return offsets

    def _log(self, action: str, kind: str, slot: int, src: int, msg_id: int,
             dst_group: int, u: float | None = None, p: float | None = None,
             delay_s: float | None = None) -> None:
        if self.record_log:
            self.log.append({"action": action, "kind": kind, "slot": slot,
                             "src": src, "msg_id": msg_id, "dst": dst_group})
        if self.sink is not None:
            # fault attribution: the full seeded-hash identity that decided
            # this fate, replayable via _unit(seed, tag, slot, src, msg_id,
            # dst) — enough for run_report to explain any one lost message
            ev = {"action": action, "kind": kind, "slot": slot, "src": src,
                  "msg_id": msg_id, "dst": dst_group, "seed": self.seed,
                  "tag": _KIND_TAG.get(kind, 3)}
            if u is not None:
                # unrounded: JSON round-trips doubles losslessly, and the
                # replay contract (DESIGN.md §11) is EXACT equality with
                # re-drawing this identity through _unit
                ev["u"] = u
                ev["threshold"] = p
            if delay_s is not None:
                ev["delay_s"] = round(delay_s, 6)
            self.sink.emit("fault", **ev)

    def dropped(self, kind: str | None = None) -> list[dict]:
        """Recorded drop events (requires ``record_log=True``)."""
        return [e for e in self.log if e["action"] == "drop"
                and (kind is None or e["kind"] == kind)]

    # -- crash windows ---------------------------------------------------------

    def crashed(self, group: int, slot: int) -> bool:
        """Pure function of the slot — no crash state to checkpoint."""
        return any(w.group == group and w.crash_slot <= slot < w.rejoin_slot
                   for w in self.crashes)

    def rejoins(self, group: int, slot: int) -> bool:
        """True exactly at the slot where ``group`` comes back up (and is
        not immediately re-crashed by an overlapping window)."""
        return (any(w.group == group and w.rejoin_slot == slot
                    for w in self.crashes)
                and not self.crashed(group, slot))


def lossy_plan(seed: int = 0, drop_p: float = 0.1,
               gst: float | None = None) -> FaultPlan:
    """Message loss only — the minimal ebb-and-flow adversary."""
    return FaultPlan(seed=seed, drop_p=drop_p, gst=gst)


def chaos_plan(seed: int = 0, drop_p: float = 0.05, duplicate_p: float = 0.05,
               reorder_p: float = 0.1, gst: float | None = None,
               crashes: tuple = ()) -> FaultPlan:
    """Drops + duplicates + reorders + optional crash windows."""
    return FaultPlan(seed=seed, drop_p=drop_p, duplicate_p=duplicate_p,
                     reorder_p=reorder_p, gst=gst, crashes=tuple(crashes))


# --- dense (array-level) fault plans ------------------------------------------
#
# The spec FaultPlan above decides fates per MESSAGE, which is the right
# granularity for the per-object driver and hopeless at 10^6 validators.
# The dense form (ISSUE 13) is the same adversary expressed as masks over
# the validator axis: per (slot, view, validator) drop/delay decisions
# from ``stateless_unit_array``, index-range crash blackouts as pure
# functions of the slot, and the view partition as data. The masks are
# ANDed into the sharded vote pass (sim/dense_driver.py), with
# padded-inert semantics: an all-pass mask is bit-identical to no mask.

# stateless_unit_array decision domains (dense plans)
_D_DENSE_DROP, _D_DENSE_DELAY = 20, 21


@dataclass(frozen=True)
class DenseCrashWindow:
    """Validators [lo, hi) are down for slots [crash_slot, rejoin_slot):
    they cast nothing (their in-flight votes are the masks that never
    apply) and resume duty at ``rejoin_slot``. A pure function of the
    slot — no crash state to checkpoint, exactly like ``CrashWindow``."""

    lo: int
    hi: int
    crash_slot: int
    rejoin_slot: int

    def __post_init__(self):
        assert self.lo < self.hi, "empty validator range"
        assert self.crash_slot < self.rejoin_slot, "empty crash window"


@dataclass(frozen=True)
class DenseFaultPlan:
    """Composable fault masks for the dense driver.

    - ``drop_p`` / ``delay_p``: per-(slot, view, validator) stateless
      draws; a dropped vote never lands, a delayed one lands at the next
      slot (before that slot's fresh votes, so LMD latest-wins holds);
    - ``gst_slot``: message faults switch off from this slot on (the
      partial-synchrony window of pos-evolution.md:197-199);
    - ``crashes``: index-range blackouts (``DenseCrashWindow``);
    - ``partition``: cross-view delivery for multi-view runs — ``None``
      (single view), ``"full"`` (views never exchange traffic: the
      SplitVoter network), or ``"delay"`` (cross-view blocks and votes
      land one slot late: the Balancer network).
    """

    seed: int = 0
    drop_p: float = 0.0
    delay_p: float = 0.0
    gst_slot: int | None = None
    crashes: tuple = ()
    partition: str | None = None

    def __post_init__(self):
        assert self.partition in (None, "full", "delay"), self.partition

    def active(self, slot: int) -> bool:
        """Message faults apply only before GST."""
        return self.gst_slot is None or slot < self.gst_slot

    def delivery_masks(self, slot: int, view: int,
                       n: int) -> tuple[np.ndarray, np.ndarray]:
        """(dropped, delayed) bool[n] for one (slot, view): disjoint —
        a vote is dropped, delayed, or delivered. All-False past GST."""
        if not self.active(slot) or (self.drop_p <= 0 and self.delay_p <= 0):
            z = np.zeros(n, dtype=bool)
            return z, z
        dropped = np.zeros(n, dtype=bool)
        delayed = np.zeros(n, dtype=bool)
        if self.drop_p > 0:
            u = stateless_unit_array(self.seed, _D_DENSE_DROP, slot, view,
                                     n=n)
            dropped = u < self.drop_p
        if self.delay_p > 0:
            u = stateless_unit_array(self.seed, _D_DENSE_DELAY, slot, view,
                                     n=n)
            delayed = (u < self.delay_p) & ~dropped
        return dropped, delayed

    def crashed_mask(self, slot: int, n: int) -> np.ndarray:
        """bool[n]: validators blacked out at ``slot``."""
        out = np.zeros(n, dtype=bool)
        for w in self.crashes:
            if w.crash_slot <= slot < w.rejoin_slot:
                out[w.lo:min(w.hi, n)] = True
        return out

    def describe(self) -> dict:
        """Config fingerprint for dense checkpoints and repro bundles."""
        return {
            "kind": type(self).__name__, "seed": self.seed,
            "drop_p": self.drop_p, "delay_p": self.delay_p,
            "gst_slot": self.gst_slot, "partition": self.partition,
            "crashes": [{"lo": w.lo, "hi": w.hi,
                         "crash_slot": w.crash_slot,
                         "rejoin_slot": w.rejoin_slot}
                        for w in self.crashes],
        }

    @classmethod
    def from_config(cls, d: dict | None) -> "DenseFaultPlan | None":
        if d is None:
            return None
        return cls(seed=d.get("seed", 0), drop_p=d.get("drop_p", 0.0),
                   delay_p=d.get("delay_p", 0.0),
                   gst_slot=d.get("gst_slot"),
                   partition=d.get("partition"),
                   crashes=tuple(DenseCrashWindow(
                       w["lo"], w["hi"], w["crash_slot"], w["rejoin_slot"])
                       for w in d.get("crashes", ())))
