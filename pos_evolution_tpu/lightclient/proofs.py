"""Server-side proof construction: merkle branches into a ``BeaconState``.

A full node proving facts to light clients builds branches over the same
field-root chunks ``Container.htr`` hashes (``Container.field_roots``), so a
proof is correct by construction against ``hash_tree_root(state)``. All
branch hashing runs through the batched SHA-256 in ``ssz/merkle`` — building
every per-slot proof is a handful of 32-leaf sweeps, not a tree walk.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.lightclient.containers import (
    CURRENT_SYNC_COMMITTEE_INDEX,
    FINALIZED_ROOT_DEPTH,
    FINALIZED_ROOT_INDEX,
    NEXT_SYNC_COMMITTEE_INDEX,
    STATE_TREE_DEPTH,
)
from pos_evolution_tpu.specs.containers import BeaconBlock, BeaconBlockHeader, BeaconState
from pos_evolution_tpu.ssz import hash_tree_root
from pos_evolution_tpu.ssz.core import uint64
from pos_evolution_tpu.ssz.merkle import merkle_tree_branch

__all__ = [
    "state_field_roots",
    "state_field_branch",
    "finality_branch",
    "current_sync_committee_branch",
    "next_sync_committee_branch",
    "header_for_block",
    "branch_array",
]


def state_field_roots(state: BeaconState) -> np.ndarray:
    """(n_fields, 32) chunk roots of the state's field tree."""
    return BeaconState.field_roots(state)


def branch_array(branch: list[bytes]) -> np.ndarray:
    """List of 32-byte siblings -> (depth, 32) uint8 rows (container form)."""
    return np.frombuffer(b"".join(branch), dtype=np.uint8).reshape(-1, 32).copy()


def state_field_branch(chunks: np.ndarray, field_index: int) -> np.ndarray:
    """Depth-``STATE_TREE_DEPTH`` branch for one state field leaf."""
    return branch_array(merkle_tree_branch(chunks, field_index, STATE_TREE_DEPTH))


def finality_branch(state: BeaconState, chunks: np.ndarray | None = None) -> np.ndarray:
    """Branch proving ``state.finalized_checkpoint.root``.

    Level 0 is inside the Checkpoint container (sibling = the epoch chunk);
    the remaining levels walk the state field tree from field
    ``finalized_checkpoint``. Verifies at depth ``FINALIZED_ROOT_DEPTH``,
    index ``FINALIZED_ROOT_INDEX`` against ``hash_tree_root(state)``.
    """
    if chunks is None:
        chunks = state_field_roots(state)
    epoch_chunk = uint64.htr(state.finalized_checkpoint.epoch)
    upper = merkle_tree_branch(chunks, FINALIZED_ROOT_INDEX >> 1, STATE_TREE_DEPTH)
    return branch_array([epoch_chunk] + upper)


def current_sync_committee_branch(state: BeaconState,
                                  chunks: np.ndarray | None = None) -> np.ndarray:
    if chunks is None:
        chunks = state_field_roots(state)
    return state_field_branch(chunks, CURRENT_SYNC_COMMITTEE_INDEX)


def next_sync_committee_branch(state: BeaconState,
                               chunks: np.ndarray | None = None) -> np.ndarray:
    if chunks is None:
        chunks = state_field_roots(state)
    return state_field_branch(chunks, NEXT_SYNC_COMMITTEE_INDEX)


def header_for_block(block: BeaconBlock) -> BeaconBlockHeader:
    """Header whose hash_tree_root equals the block root (body collapsed to
    its root; state_root as recorded in the block)."""
    return BeaconBlockHeader(
        slot=int(block.slot),
        proposer_index=int(block.proposer_index),
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body_root=hash_tree_root(block.body),
    )
