"""Sync-committee & light-client subsystem (Altair capability surface).

Four layers: containers + proofs (spec dialect), the store state machine
(lightclient/spec.py), batched device verification (ops/sync_verify.py via
lightclient/verify.py), and the simulation participant (lightclient/node.py,
served by sim/driver.py through lightclient/server.py).
"""

from pos_evolution_tpu.lightclient.containers import (
    CURRENT_SYNC_COMMITTEE_INDEX,
    FINALIZED_ROOT_DEPTH,
    FINALIZED_ROOT_INDEX,
    NEXT_SYNC_COMMITTEE_INDEX,
    STATE_TREE_DEPTH,
    LightClientBootstrap,
    LightClientFinalityUpdate,
    LightClientHeader,
    LightClientOptimisticUpdate,
    LightClientUpdate,
)
from pos_evolution_tpu.lightclient.node import LightClientNode
from pos_evolution_tpu.lightclient.proofs import (
    current_sync_committee_branch,
    finality_branch,
    header_for_block,
    next_sync_committee_branch,
    state_field_roots,
)
from pos_evolution_tpu.lightclient.server import (
    bootstrap_from_store,
    build_head_update,
    build_update,
    make_bootstrap,
)
from pos_evolution_tpu.lightclient.spec import (
    MIN_SYNC_COMMITTEE_PARTICIPANTS,
    LightClientStore,
    apply_light_client_update,
    finality_update_from,
    initialize_light_client_store,
    is_better_update,
    optimistic_update_from,
    process_light_client_finality_update,
    process_light_client_optimistic_update,
    process_light_client_store_force_update,
    process_light_client_update,
    sync_period_at_slot,
    update_timeout_slots,
    validate_light_client_update,
)
from pos_evolution_tpu.lightclient.verify import (
    is_finality_update,
    is_sync_committee_update,
    signing_root_for_update,
    updates_to_batch,
    verify_updates,
)

__all__ = [
    "CURRENT_SYNC_COMMITTEE_INDEX",
    "FINALIZED_ROOT_DEPTH",
    "FINALIZED_ROOT_INDEX",
    "NEXT_SYNC_COMMITTEE_INDEX",
    "STATE_TREE_DEPTH",
    "MIN_SYNC_COMMITTEE_PARTICIPANTS",
    "LightClientBootstrap",
    "LightClientFinalityUpdate",
    "LightClientHeader",
    "LightClientNode",
    "LightClientOptimisticUpdate",
    "LightClientStore",
    "LightClientUpdate",
    "apply_light_client_update",
    "bootstrap_from_store",
    "build_head_update",
    "build_update",
    "current_sync_committee_branch",
    "finality_branch",
    "finality_update_from",
    "header_for_block",
    "initialize_light_client_store",
    "is_better_update",
    "is_finality_update",
    "is_sync_committee_update",
    "make_bootstrap",
    "next_sync_committee_branch",
    "optimistic_update_from",
    "process_light_client_finality_update",
    "process_light_client_optimistic_update",
    "process_light_client_store_force_update",
    "process_light_client_update",
    "signing_root_for_update",
    "state_field_roots",
    "sync_period_at_slot",
    "update_timeout_slots",
    "updates_to_batch",
    "validate_light_client_update",
    "verify_updates",
]
