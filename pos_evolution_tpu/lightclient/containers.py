"""Light-client containers (Altair sync protocol, pyspec dialect).

Sync committees exist solely so resource-constrained clients can follow the
chain without replaying state transitions (pos-evolution.md:542): a light
client holds a ~500-key committee, verifies one aggregate signature per
update, and checks two merkle branches into the attested ``BeaconState``.

The branch geometry is *derived from the container layout* rather than
hard-coded: ``BeaconState`` has 25 fields, so its field tree is depth
``STATE_TREE_DEPTH`` (= 5, padded to 32 chunks), ``finalized_checkpoint``
sits at field index 20 and its ``root`` one level deeper (generalized index
2**6 + 41 — the Altair ``FINALIZED_ROOT_INDEX`` layout, which this state
reproduces field-for-field), and the two sync committees at field indices
22/23. If a later fork appends state fields the constants move with it.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.specs.containers import (
    BeaconBlockHeader,
    BeaconState,
    RootVector,
    SyncAggregate,
    SyncCommittee,
)
from pos_evolution_tpu.ssz.core import Container, uint64
from pos_evolution_tpu.ssz.merkle import next_pow_of_two

__all__ = [
    "STATE_TREE_DEPTH",
    "FINALIZED_ROOT_DEPTH",
    "FINALIZED_ROOT_INDEX",
    "CURRENT_SYNC_COMMITTEE_INDEX",
    "NEXT_SYNC_COMMITTEE_INDEX",
    "LightClientHeader",
    "LightClientBootstrap",
    "LightClientUpdate",
    "LightClientFinalityUpdate",
    "LightClientOptimisticUpdate",
    "sync_committee_lanes",
    "participation_bits",
]

_STATE_FIELDS = list(BeaconState._fields)

#: Depth of the BeaconState field tree (fields padded to a power of two).
STATE_TREE_DEPTH = (next_pow_of_two(len(_STATE_FIELDS)) - 1).bit_length()

#: ``state.finalized_checkpoint.root``: one Checkpoint level below the field
#: tree — leaf is the checkpoint's ``root`` chunk (right child, hence ``*2+1``).
FINALIZED_ROOT_DEPTH = STATE_TREE_DEPTH + 1
FINALIZED_ROOT_INDEX = _STATE_FIELDS.index("finalized_checkpoint") * 2 + 1

#: ``state.current_sync_committee`` / ``state.next_sync_committee`` field leaves.
CURRENT_SYNC_COMMITTEE_INDEX = _STATE_FIELDS.index("current_sync_committee")
NEXT_SYNC_COMMITTEE_INDEX = _STATE_FIELDS.index("next_sync_committee")


class LightClientHeader(Container):
    """Altair-style header envelope (just the beacon header; later forks add
    execution fields here, which is why it is a container and not an alias)."""

    beacon: BeaconBlockHeader


class LightClientBootstrap(Container):
    """Trusted starting point: the checkpoint header plus its state's current
    sync committee, proven into ``header.beacon.state_root``."""

    header: LightClientHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: RootVector(STATE_TREE_DEPTH)


class LightClientUpdate(Container):
    """One step of the sync protocol: a sync-aggregate-signed attested header,
    optional proof of the attested state's next sync committee, and optional
    proof of its finalized checkpoint."""

    attested_header: LightClientHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: RootVector(STATE_TREE_DEPTH)
    finalized_header: LightClientHeader
    finality_branch: RootVector(FINALIZED_ROOT_DEPTH)
    sync_aggregate: SyncAggregate
    signature_slot: uint64


class LightClientFinalityUpdate(Container):
    attested_header: LightClientHeader
    finalized_header: LightClientHeader
    finality_branch: RootVector(FINALIZED_ROOT_DEPTH)
    sync_aggregate: SyncAggregate
    signature_slot: uint64


class LightClientOptimisticUpdate(Container):
    attested_header: LightClientHeader
    sync_aggregate: SyncAggregate
    signature_slot: uint64


def sync_committee_lanes(committee: SyncCommittee) -> int:
    """Runtime lane count of a committee (``cfg().sync_committee_size``; the
    container's declared 512 limit is the mainnet preset)."""
    return len(committee.pubkeys)


def participation_bits(aggregate: SyncAggregate, lanes: int) -> np.ndarray:
    """First ``lanes`` bits of the (container-width) sync committee bitvector."""
    bits = np.asarray(aggregate.sync_committee_bits, dtype=bool)
    return bits[:lanes]
