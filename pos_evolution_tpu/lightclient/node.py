"""``LightClientNode``: a simulation participant that follows the chain
through light-client updates only.

The node never holds a ``BeaconState``: it boots from a weak-subjectivity
checkpoint (``LightClientBootstrap``), consumes one update per slot from a
serving full node (subject to the run's ``FaultPlan`` — dropped updates are
simply never seen), force-updates after a sync-committee-period timeout, and
reports head-lag / finality-lag through ``utils/metrics``.
"""

from __future__ import annotations

from pos_evolution_tpu.lightclient.spec import (
    LightClientStore,
    initialize_light_client_store,
    process_light_client_store_force_update,
    process_light_client_update,
)
from pos_evolution_tpu.ssz import hash_tree_root
from pos_evolution_tpu.utils.metrics import HandlerTimer, light_client_lag_record

__all__ = ["LightClientNode"]


class LightClientNode:
    """One light client following a simulated chain."""

    def __init__(self, store: LightClientStore, node_id: int = 0):
        self.store = store
        self.id = node_id
        self.records: list[dict] = []
        self.timer = HandlerTimer()
        self.updates_applied = 0
        self.updates_rejected = 0
        self.forced_updates = 0

    @classmethod
    def from_bootstrap(cls, trusted_block_root: bytes, bootstrap,
                       fork_version: bytes, genesis_validators_root: bytes,
                       node_id: int = 0) -> "LightClientNode":
        store = initialize_light_client_store(
            trusted_block_root, bootstrap, fork_version, genesis_validators_root)
        return cls(store, node_id=node_id)

    # -- protocol events -------------------------------------------------------

    def on_update(self, update, current_slot: int) -> bool:
        """Process one served update; invalid updates are counted and
        dropped (a real client would also descore the peer)."""
        try:
            with self.timer.track("process_light_client_update"):
                process_light_client_update(self.store, update, current_slot)
            self.updates_applied += 1
            return True
        except AssertionError:
            self.updates_rejected += 1
            return False

    def advance(self, slot: int, full_head_slot: int,
                full_finalized_epoch: int) -> dict:
        """End-of-slot housekeeping: run the force-update timeout and record
        how far this client trails the full node it follows."""
        before = int(self.store.finalized_header.slot)
        with self.timer.track("force_update"):
            process_light_client_store_force_update(self.store, slot)
        if int(self.store.finalized_header.slot) != before:
            self.forced_updates += 1
        record = light_client_lag_record(
            self.store, slot, full_head_slot, full_finalized_epoch)
        self.records.append(record)
        return record

    # -- accessors --------------------------------------------------------------

    @property
    def head_slot(self) -> int:
        return int(self.store.optimistic_header.slot)

    @property
    def finalized_slot(self) -> int:
        return int(self.store.finalized_header.slot)

    def finalized_root(self) -> bytes:
        return hash_tree_root(self.store.finalized_header)

    def summary(self) -> dict:
        return {
            "applied": self.updates_applied,
            "rejected": self.updates_rejected,
            "forced": self.forced_updates,
            "head_slot": self.head_slot,
            "finalized_slot": self.finalized_slot,
            "timing": self.timer.summary(),
        }
