"""Container -> array bridge: light-client updates as a ``SyncUpdateBatch``.

The store state machine (lightclient/spec.py) verifies every update through
this module, so the light client is a true second consumer of the crypto
kernels: with the ``jax`` backend active the sync-aggregate signature and
both merkle branches of each update are checked on device; the ``numpy``
backend runs the bit-identical host path.
"""

from __future__ import annotations

import hashlib

import numpy as np

from pos_evolution_tpu.config import DOMAIN_SYNC_COMMITTEE
from pos_evolution_tpu.lightclient.containers import (
    FINALIZED_ROOT_INDEX,
    NEXT_SYNC_COMMITTEE_INDEX,
    LightClientUpdate,
    participation_bits,
    sync_committee_lanes,
)
from pos_evolution_tpu.ops.sync_verify import SyncUpdateBatch, verify_sync_update_batch
from pos_evolution_tpu.specs.containers import SyncCommittee
from pos_evolution_tpu.specs.helpers import compute_domain
from pos_evolution_tpu.specs.transition import compute_signing_root_bytes
from pos_evolution_tpu.ssz import hash_tree_root

__all__ = [
    "is_finality_update",
    "is_sync_committee_update",
    "signing_root_for_update",
    "updates_to_batch",
    "verify_updates",
]


def _branch_rows(branch) -> np.ndarray:
    return np.ascontiguousarray(branch, dtype=np.uint8).reshape(-1, 32)


# Committees change once per sync-committee period (256 epochs at the
# mainnet preset) but validation runs per update — cache the derived (S, 48)
# pubkey table and the committee's hash_tree_root, keyed by a digest of the
# ORDERED pubkey bytes + aggregate (the XOR aggregate alone is
# order-insensitive and duplicate-canceling, so distinct lane layouts would
# alias). One flat sha256 over the member bytes is an order of magnitude
# cheaper than either derivation.
_COMMITTEE_CACHE: dict = {}
_COMMITTEE_CACHE_MAX = 8


def _committee_entry(committee: SyncCommittee) -> dict:
    key = hashlib.sha256(
        b"".join(bytes(pk) for pk in committee.pubkeys)
        + bytes(committee.aggregate_pubkey)).digest()
    entry = _COMMITTEE_CACHE.get(key)
    if entry is None:
        table = np.zeros((len(committee.pubkeys), 48), dtype=np.uint8)
        for j, pk in enumerate(committee.pubkeys):
            table[j] = np.frombuffer(bytes(pk), dtype=np.uint8)
        table.setflags(write=False)
        entry = {"table": table, "root": hash_tree_root(committee)}
        if len(_COMMITTEE_CACHE) >= _COMMITTEE_CACHE_MAX:
            _COMMITTEE_CACHE.pop(next(iter(_COMMITTEE_CACHE)))
        _COMMITTEE_CACHE[key] = entry
    return entry


def _committee_pubkey_table(committee: SyncCommittee) -> np.ndarray:
    return _committee_entry(committee)["table"]


def _committee_root(committee: SyncCommittee) -> bytes:
    return _committee_entry(committee)["root"]


def _nonzero_branch(branch) -> bool:
    return bool(_branch_rows(branch).any())


def is_finality_update(update) -> bool:
    """An update proves finality iff it carries a non-empty finality branch."""
    return _nonzero_branch(update.finality_branch)


def is_sync_committee_update(update: LightClientUpdate) -> bool:
    return _nonzero_branch(update.next_sync_committee_branch)


def signing_root_for_update(update, fork_version: bytes,
                            genesis_validators_root: bytes) -> bytes:
    """What the sync committee signed: the attested block root under the
    sync-committee domain (specs/transition.process_sync_aggregate)."""
    domain = compute_domain(DOMAIN_SYNC_COMMITTEE, fork_version,
                            genesis_validators_root)
    return compute_signing_root_bytes(
        hash_tree_root(update.attested_header.beacon), domain)


def updates_to_batch(updates: list, committees: list[SyncCommittee],
                     fork_version: bytes, genesis_validators_root: bytes,
                     weights: np.ndarray | None = None) -> SyncUpdateBatch:
    """Dense batch for ``updates[i]`` signed by ``committees[i]``.

    ``weights`` (B, S) defaults to ones, making the weighted output a plain
    participation count; pass effective balances for stake weighting.
    Updates may be full ``LightClientUpdate``s or finality/optimistic slices
    (missing proof groups flow through with ``*_present=False``).
    """
    b = len(updates)
    assert b == len(committees) and b > 0
    s = sync_committee_lanes(committees[0])
    pubkeys = np.zeros((b, s, 48), dtype=np.uint8)
    bits = np.zeros((b, s), dtype=bool)
    messages = np.zeros((b, 32), dtype=np.uint8)
    signatures = np.zeros((b, 96), dtype=np.uint8)
    fin_leaf = np.zeros((b, 32), dtype=np.uint8)
    fin_depth = LightClientUpdate._fields["finality_branch"].limit
    sc_depth = LightClientUpdate._fields["next_sync_committee_branch"].limit
    fin_branch = np.zeros((b, fin_depth, 32), dtype=np.uint8)
    fin_root = np.zeros((b, 32), dtype=np.uint8)
    fin_present = np.zeros(b, dtype=bool)
    sc_leaf = np.zeros((b, 32), dtype=np.uint8)
    sc_branch = np.zeros((b, sc_depth, 32), dtype=np.uint8)
    sc_root = np.zeros((b, 32), dtype=np.uint8)
    sc_present = np.zeros(b, dtype=bool)

    for i, (update, committee) in enumerate(zip(updates, committees)):
        assert sync_committee_lanes(committee) == s, "mixed committee sizes"
        pubkeys[i] = _committee_pubkey_table(committee)
        bits[i] = participation_bits(update.sync_aggregate, s)
        messages[i] = np.frombuffer(
            signing_root_for_update(update, fork_version, genesis_validators_root),
            dtype=np.uint8)
        signatures[i] = np.frombuffer(
            bytes(update.sync_aggregate.sync_committee_signature), dtype=np.uint8)
        attested_state_root = bytes(update.attested_header.beacon.state_root)
        if hasattr(update, "finality_branch") and is_finality_update(update):
            fin_leaf[i] = np.frombuffer(
                hash_tree_root(update.finalized_header.beacon), dtype=np.uint8)
            fin_branch[i] = _branch_rows(update.finality_branch)
            fin_root[i] = np.frombuffer(attested_state_root, dtype=np.uint8)
            fin_present[i] = True
        if (hasattr(update, "next_sync_committee_branch")
                and is_sync_committee_update(update)):
            sc_leaf[i] = np.frombuffer(
                _committee_root(update.next_sync_committee), dtype=np.uint8)
            sc_branch[i] = _branch_rows(update.next_sync_committee_branch)
            sc_root[i] = np.frombuffer(attested_state_root, dtype=np.uint8)
            sc_present[i] = True

    if weights is None:
        weights = np.ones((b, s), dtype=np.int64)
    return SyncUpdateBatch(
        pubkeys=pubkeys, bits=bits, weights=np.asarray(weights, dtype=np.int64),
        messages=messages, signatures=signatures,
        fin_leaf=fin_leaf, fin_branch=fin_branch,
        fin_index=np.full(b, FINALIZED_ROOT_INDEX, dtype=np.int64),
        fin_root=fin_root, fin_present=fin_present,
        sc_leaf=sc_leaf, sc_branch=sc_branch,
        sc_index=np.full(b, NEXT_SYNC_COMMITTEE_INDEX, dtype=np.int64),
        sc_root=sc_root, sc_present=sc_present,
    )


def verify_updates(updates: list, committees: list[SyncCommittee],
                   fork_version: bytes, genesis_validators_root: bytes,
                   weights: np.ndarray | None = None) -> dict:
    """Batch-verify through the active ExecutionBackend (numpy ⇄ jax)."""
    batch = updates_to_batch(updates, committees, fork_version,
                             genesis_validators_root, weights)
    return verify_sync_update_batch(batch)
