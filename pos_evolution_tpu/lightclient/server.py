"""Full-node side of the sync protocol: derive bootstraps and updates.

A full node (here: a simulation view group's fork-choice store plus the
block archive) serves light clients by packaging what the chain already
contains — the sync aggregate a block carried, its attested (parent) header,
and merkle proofs built from the attested post-state's field roots
(lightclient/proofs.py). Bootstraps come from the node's finalized
checkpoint and pass the weak-subjectivity gate before being served
(specs/weak_subjectivity.checkpoint_for_state).
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.lightclient.containers import (
    LightClientBootstrap,
    LightClientHeader,
    LightClientUpdate,
)
from pos_evolution_tpu.lightclient.proofs import (
    current_sync_committee_branch,
    finality_branch,
    header_for_block,
    next_sync_committee_branch,
    state_field_roots,
)
from pos_evolution_tpu.lightclient.spec import sync_period_at_slot
from pos_evolution_tpu.ssz import hash_tree_root

__all__ = ["make_bootstrap", "bootstrap_from_store", "build_update",
           "build_head_update"]


def make_bootstrap(state, block) -> tuple[bytes, LightClientBootstrap]:
    """(trusted_block_root, bootstrap) for a checkpoint ``block`` whose
    post-state is ``state``."""
    header = header_for_block(block)
    bootstrap = LightClientBootstrap(
        header=LightClientHeader(beacon=header),
        current_sync_committee=state.current_sync_committee.copy(),
        current_sync_committee_branch=current_sync_committee_branch(state),
    )
    return hash_tree_root(header), bootstrap


def bootstrap_from_store(store) -> tuple[bytes, LightClientBootstrap]:
    """Bootstrap from the node's finalized checkpoint — the same anchor a
    crash-restarted full node would sync from — after checking it is still
    within the weak-subjectivity period (pos-evolution.md:1293-1302)."""
    from pos_evolution_tpu.specs.weak_subjectivity import (
        checkpoint_for_state,
        is_within_weak_subjectivity_period,
    )
    froot = bytes(store.finalized_checkpoint.root)
    state = store.block_states[froot]
    block = store.blocks[froot]
    ws_state, ws_checkpoint = checkpoint_for_state(state)
    assert is_within_weak_subjectivity_period(store, ws_state, ws_checkpoint), (
        "finalized checkpoint outside the weak-subjectivity period — a light "
        "client syncing from it would be vulnerable to long-range forks")
    return make_bootstrap(state, block)


def _lookup_block(store, archive, root: bytes):
    block = store.blocks.get(root)
    if block is not None:
        return block
    if archive is not None:
        signed = archive.get(root)
        if signed is not None:
            return signed.message
    return None


def _update_for(attested_block, attested_state, aggregate, signature_slot: int,
                store, archive: dict | None) -> LightClientUpdate:
    """Assemble an update around one (attested block, sync aggregate) pair.

    Proofs come from the attested block's post-state. The
    next-sync-committee proof is only attached when the attested slot and
    the signature slot share a sync-committee period (otherwise the proof
    would be for the wrong period's committee).
    """
    chunks = state_field_roots(attested_state)
    update = LightClientUpdate(
        attested_header=LightClientHeader(beacon=header_for_block(attested_block)),
        sync_aggregate=aggregate.copy(),
        signature_slot=int(signature_slot),
    )
    finalized_root = bytes(attested_state.finalized_checkpoint.root)
    finalized_block = _lookup_block(store, archive, finalized_root)
    if finalized_block is not None:
        update.finalized_header = LightClientHeader(
            beacon=header_for_block(finalized_block))
        update.finality_branch = finality_branch(attested_state, chunks)
    if (sync_period_at_slot(int(attested_block.slot))
            == sync_period_at_slot(int(signature_slot))):
        update.next_sync_committee = attested_state.next_sync_committee.copy()
        update.next_sync_committee_branch = next_sync_committee_branch(
            attested_state, chunks)
    return update


def build_update(store, head_root: bytes,
                 archive: dict | None = None) -> LightClientUpdate | None:
    """Best update derivable from the head block, or None.

    The head block's sync aggregate attests to its parent, so this is the
    on-chain serving path (one update per included block).
    """
    block = store.blocks.get(bytes(head_root))
    if block is None or int(block.slot) == 0:
        return None
    aggregate = block.body.sync_aggregate
    if not np.asarray(aggregate.sync_committee_bits, dtype=bool).any():
        return None
    parent_root = bytes(block.parent_root)
    attested_block = _lookup_block(store, archive, parent_root)
    attested_state = store.block_states.get(parent_root)
    if attested_block is None or attested_state is None:
        return None
    return _update_for(attested_block, attested_state, aggregate,
                       int(block.slot), store, archive)


def build_head_update(store, head_root: bytes, aggregate, signature_slot: int,
                      archive: dict | None = None) -> LightClientUpdate | None:
    """Off-chain serving path: an update whose attested header is the head
    itself, signed by a sync aggregate that has not been packed into a
    block yet. Real light-client networks gossip exactly this
    (FinalityUpdates assembled from sync-committee messages), which is what
    lets a client reach the full node's *current* finalized head instead of
    trailing one inclusion round behind."""
    head_root = bytes(head_root)
    head_block = store.blocks.get(head_root)
    head_state = store.block_states.get(head_root)
    if head_block is None or head_state is None:
        return None
    if not np.asarray(aggregate.sync_committee_bits, dtype=bool).any():
        return None
    return _update_for(head_block, head_state, aggregate,
                       int(signature_slot), store, archive)
