"""Light-client store state machine (Altair sync protocol, pyspec dialect).

The client holds only headers and sync committees — never a ``BeaconState``
— and advances by verifying ``LightClientUpdate``s: check the two merkle
branches into the attested state root, check the sync-aggregate signature
with the committee for the signature period, then

- finalize when a supermajority-signed update carries a finality proof
  (``process_light_client_update``);
- track the best-seen update per period otherwise, and **force-apply** it
  when no finalizing update has arrived for a whole sync-committee period
  (``process_light_client_store_force_update``) — the liveness escape hatch
  for lossy links where every finality update was dropped.

Crypto and hashing route through lightclient/verify.py, i.e. through the
ExecutionBackend dispatch (batched on device under the ``jax`` backend).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.lightclient.containers import (
    CURRENT_SYNC_COMMITTEE_INDEX,
    STATE_TREE_DEPTH,
    LightClientBootstrap,
    LightClientFinalityUpdate,
    LightClientOptimisticUpdate,
    LightClientUpdate,
    participation_bits,
    sync_committee_lanes,
)
from pos_evolution_tpu.lightclient.verify import (
    is_finality_update,
    is_sync_committee_update,
    verify_updates,
)
from pos_evolution_tpu.specs.containers import BeaconBlockHeader, SyncCommittee
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    compute_sync_committee_period,
)
from pos_evolution_tpu.ssz import hash_tree_root, is_valid_merkle_branch

__all__ = [
    "LightClientStore",
    "MIN_SYNC_COMMITTEE_PARTICIPANTS",
    "initialize_light_client_store",
    "validate_light_client_update",
    "apply_light_client_update",
    "process_light_client_update",
    "process_light_client_finality_update",
    "process_light_client_optimistic_update",
    "process_light_client_store_force_update",
    "is_better_update",
    "sync_period_at_slot",
    "update_timeout_slots",
    "finality_update_from",
    "optimistic_update_from",
]

MIN_SYNC_COMMITTEE_PARTICIPANTS = 1


def sync_period_at_slot(slot: int) -> int:
    return compute_sync_committee_period(compute_epoch_at_slot(int(slot)))


def update_timeout_slots() -> int:
    """Force-update timeout: one full sync-committee period of slots."""
    c = cfg()
    return c.epochs_per_sync_committee_period * c.slots_per_epoch


@dataclass
class LightClientStore:
    """Everything a light client persists (pos-evolution.md:542 capability)."""

    finalized_header: BeaconBlockHeader
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee | None = None
    best_valid_update: LightClientUpdate | None = None
    optimistic_header: BeaconBlockHeader = field(default_factory=BeaconBlockHeader)
    previous_max_active_participants: int = 0
    current_max_active_participants: int = 0
    # Signature-domain inputs captured at bootstrap (the client never sees a
    # state to call get_domain on).
    fork_version: bytes = b"\x00" * 4
    genesis_validators_root: bytes = b"\x00" * 32

    def finalized_period(self) -> int:
        return sync_period_at_slot(int(self.finalized_header.slot))


def initialize_light_client_store(trusted_block_root: bytes,
                                  bootstrap: LightClientBootstrap,
                                  fork_version: bytes,
                                  genesis_validators_root: bytes) -> LightClientStore:
    """Bootstrap from a trusted (weak-subjectivity) block root: the header
    must hash to the trusted root and the committee must prove into its
    state root."""
    header = bootstrap.header.beacon
    assert hash_tree_root(header) == bytes(trusted_block_root), \
        "bootstrap header does not match trusted root"
    branch = bootstrap.current_sync_committee_branch
    assert is_valid_merkle_branch(
        leaf=hash_tree_root(bootstrap.current_sync_committee),
        branch=[branch[i].tobytes() for i in range(branch.shape[0])],
        depth=STATE_TREE_DEPTH,
        index=CURRENT_SYNC_COMMITTEE_INDEX,
        root=bytes(header.state_root),
    ), "invalid current-sync-committee proof"
    return LightClientStore(
        finalized_header=header.copy(),
        current_sync_committee=bootstrap.current_sync_committee.copy(),
        optimistic_header=header.copy(),
        fork_version=bytes(fork_version),
        genesis_validators_root=bytes(genesis_validators_root),
    )


def _participation(store: LightClientStore, update) -> int:
    return int(participation_bits(
        update.sync_aggregate,
        sync_committee_lanes(store.current_sync_committee)).sum())


def validate_light_client_update(store: LightClientStore, update,
                                 current_slot: int) -> None:
    """All asserts of one update; crypto via the ExecutionBackend batch op."""
    assert _participation(store, update) >= MIN_SYNC_COMMITTEE_PARTICIPANTS, \
        "no sync committee participation"
    attested = update.attested_header.beacon
    assert int(current_slot) >= int(update.signature_slot) > int(attested.slot), \
        "update from the future / signature not after attested slot"

    store_period = store.finalized_period()
    sig_period = sync_period_at_slot(int(update.signature_slot))
    if store.next_sync_committee is not None:
        assert sig_period in (store_period, store_period + 1), \
            "signature period out of range"
    else:
        assert sig_period == store_period, \
            "next committee unknown: can only verify the current period"

    # Relevance: new finality, or teaches us the unknown next committee.
    attested_period = sync_period_at_slot(int(attested.slot))
    has_next = is_sync_committee_update(update)
    assert (int(attested.slot) > int(store.finalized_header.slot)
            or (attested_period == store_period and has_next
                and store.next_sync_committee is None)), "irrelevant update"

    if is_finality_update(update):
        finalized = update.finalized_header.beacon
        assert int(attested.slot) >= int(finalized.slot), \
            "finalized header newer than attested"
    if has_next:
        assert attested_period == sig_period, \
            "next-committee proof must come from the signature period"

    committee = (store.current_sync_committee if sig_period == store_period
                 else store.next_sync_committee)
    res = verify_updates([update], [committee], store.fork_version,
                         store.genesis_validators_root)
    assert bool(res["sig_ok"][0]), "bad sync aggregate signature"
    if is_finality_update(update):
        assert bool(res["fin_ok"][0]), "invalid finality proof"
    if has_next:
        assert bool(res["sc_ok"][0]), "invalid next-sync-committee proof"


def _effective_finalized(update) -> BeaconBlockHeader:
    """Header an applied update finalizes: the proven finalized header, or —
    for force-applied proofless updates — the attested header itself."""
    if is_finality_update(update):
        return update.finalized_header.beacon
    return update.attested_header.beacon


def apply_light_client_update(store: LightClientStore, update,
                              finalized: BeaconBlockHeader | None = None) -> None:
    store_period = store.finalized_period()
    if finalized is None:
        finalized = _effective_finalized(update)
    finalized_period = sync_period_at_slot(int(finalized.slot))
    if store.next_sync_committee is None:
        assert finalized_period == store_period
        if is_sync_committee_update(update):
            store.next_sync_committee = update.next_sync_committee.copy()
    elif finalized_period == store_period + 1:
        store.current_sync_committee = store.next_sync_committee
        store.next_sync_committee = (update.next_sync_committee.copy()
                                     if is_sync_committee_update(update) else None)
        store.previous_max_active_participants = store.current_max_active_participants
        store.current_max_active_participants = 0
    if int(finalized.slot) > int(store.finalized_header.slot):
        store.finalized_header = finalized.copy()
        if int(finalized.slot) > int(store.optimistic_header.slot):
            store.optimistic_header = finalized.copy()


def is_better_update(store: LightClientStore, new, old) -> bool:
    """Ranked preference for the force-update candidate: supermajority, then
    finality proof, then participation, then newer attested head."""
    lanes = sync_committee_lanes(store.current_sync_committee)

    def score(u):
        p = _participation(store, u)
        return (int(p * 3 >= lanes * 2), int(is_finality_update(u)), p,
                int(u.attested_header.beacon.slot))

    return score(new) > score(old)


def process_light_client_update(store: LightClientStore, update,
                                current_slot: int) -> None:
    validate_light_client_update(store, update, current_slot)
    participation = _participation(store, update)
    lanes = sync_committee_lanes(store.current_sync_committee)

    if (store.best_valid_update is None
            or is_better_update(store, update, store.best_valid_update)):
        store.best_valid_update = update
    store.current_max_active_participants = max(
        store.current_max_active_participants, participation)

    # Optimistic head: enough participation to beat the safety threshold.
    safety_threshold = max(store.previous_max_active_participants,
                           store.current_max_active_participants) // 2
    attested = update.attested_header.beacon
    if (participation > safety_threshold
            and int(attested.slot) > int(store.optimistic_header.slot)):
        store.optimistic_header = attested.copy()

    # Finalize on a 2/3-supermajority update that makes finality PROGRESS
    # (or teaches the unknown next committee). Without the progress gate, a
    # long non-finality stretch of updates re-proving the same old
    # checkpoint would repeatedly clear ``best_valid_update`` and starve
    # the force-update escape hatch.
    finalized = update.finalized_header.beacon if is_finality_update(update) else None
    teaches_next_committee = (
        store.next_sync_committee is None
        and is_sync_committee_update(update) and finalized is not None
        and sync_period_at_slot(int(finalized.slot))
        == sync_period_at_slot(int(attested.slot)))
    makes_progress = (finalized is not None
                      and int(finalized.slot) > int(store.finalized_header.slot))
    if (participation * 3 >= lanes * 2
            and (makes_progress or teaches_next_committee)):
        apply_light_client_update(store, update)
        store.best_valid_update = None


def process_light_client_finality_update(store: LightClientStore,
                                         finality_update: LightClientFinalityUpdate,
                                         current_slot: int) -> None:
    process_light_client_update(store, _expand(finality_update), current_slot)


def process_light_client_optimistic_update(store: LightClientStore,
                                           optimistic_update: LightClientOptimisticUpdate,
                                           current_slot: int) -> None:
    process_light_client_update(store, _expand(optimistic_update), current_slot)


def process_light_client_store_force_update(store: LightClientStore,
                                            current_slot: int) -> None:
    """Timeout path: if a whole sync-committee period has elapsed without a
    finalizing update, trust the best-seen valid update. A stale finality
    proof (during a finality stall every served update re-proves the OLD
    checkpoint) is substituted with the attested header — otherwise the
    escape hatch would never advance the store and the client would wedge
    once signature slots outran its known committee periods."""
    if (int(current_slot) > int(store.finalized_header.slot) + update_timeout_slots()
            and store.best_valid_update is not None):
        update = store.best_valid_update
        finalized = _effective_finalized(update)
        if int(finalized.slot) <= int(store.finalized_header.slot):
            finalized = update.attested_header.beacon
        apply_light_client_update(store, update, finalized=finalized)
        store.best_valid_update = None


def _expand(partial_update) -> LightClientUpdate:
    """Lift a finality/optimistic slice to a full update (absent proof
    groups stay zeroed, i.e. "not present")."""
    kw = dict(attested_header=partial_update.attested_header,
              sync_aggregate=partial_update.sync_aggregate,
              signature_slot=int(partial_update.signature_slot))
    if hasattr(partial_update, "finalized_header"):
        kw["finalized_header"] = partial_update.finalized_header
        kw["finality_branch"] = partial_update.finality_branch
    return LightClientUpdate(**kw)


def finality_update_from(update: LightClientUpdate) -> LightClientFinalityUpdate:
    return LightClientFinalityUpdate(
        attested_header=update.attested_header,
        finalized_header=update.finalized_header,
        finality_branch=update.finality_branch,
        sync_aggregate=update.sync_aggregate,
        signature_slot=int(update.signature_slot),
    )


def optimistic_update_from(update: LightClientUpdate) -> LightClientOptimisticUpdate:
    return LightClientOptimisticUpdate(
        attested_header=update.attested_header,
        sync_aggregate=update.sync_aggregate,
        signature_slot=int(update.signature_slot),
    )
