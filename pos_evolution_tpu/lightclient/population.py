"""Dense light-client population: finality followers as arrays (ISSUE 20).

The spec light-client stack (``lightclient/node.py``) verifies sync
committees and merkle branches per update — per-object Python, right
for protocol audits, wrong for populations. This is its dense twin: N
clients tracked as struct-of-arrays (the ``das/sampler.py`` posture),
each following the **active variant's own finality-grade decision
stream** — Gasper clients track the FFG-finalized checkpoint, Goldfish/
RLMD clients the fast/kappa confirmation, SSF clients the per-slot
finalization — with a seeded per-client propagation lag, so the
population's convergence lag is itself a variant-level observable
(``stats()`` lands in the dense run summary and the run report).

Clients attach round-robin to view groups: under a partition the two
halves follow conflicting decision streams, which is exactly the
condition the dense variant monitor prices — the population is the
consumer-side witness of the same divergence.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DenseLightClientPopulation"]

_MAX_LAG = 4  # slots; per-client draw is uniform over [0, _MAX_LAG)


class DenseLightClientPopulation:
    """N finality followers with seeded per-client lag."""

    kind = "lightclient"

    def __init__(self, n_clients: int = 256, seed: int = 0):
        self.n = int(n_clients)
        self.seed = int(seed)
        self.sim = None
        self.updates_applied = 0

    def bind(self, sim) -> None:
        from pos_evolution_tpu.ssz.hash import sha256_batch
        self.sim = sim
        msgs = np.zeros((self.n, 16), dtype=np.uint8)
        msgs[:, :8] = np.frombuffer(self.seed.to_bytes(8, "little"),
                                    dtype=np.uint8)
        msgs[:, 8:16] = np.arange(self.n, dtype="<u8").view(
            np.uint8).reshape(self.n, 8)
        self.lag = (sha256_batch(msgs)[:, 0] % _MAX_LAG).astype(np.int64)
        self.view_of = (np.arange(self.n, dtype=np.int64)
                        % sim.n_groups).astype(np.int8)
        # newest adopted decision per client: slot and block index
        self.head_slot = np.full(self.n, -1, dtype=np.int64)
        self.head_idx = np.full(self.n, -1, dtype=np.int64)
        # per-view publication log of (decision slot, block index)
        self._published: list[list[tuple[int, int]]] = [
            [] for _ in range(sim.n_groups)]

    def on_slot_end(self, sim, slot: int) -> None:
        for g in range(sim.n_groups):
            dec = sim.variant.latest_decision(sim, g)
            if dec is None:
                continue
            log = self._published[g]
            if not log or log[-1] != (int(dec[0]), int(dec[1])):
                log.append((int(dec[0]), int(dec[1])))
        # clients adopt the newest decision published at least ``lag``
        # slots ago (publication slot = the slot the decision was made)
        for g in range(sim.n_groups):
            log = self._published[g]
            if not log:
                continue
            slots = np.array([s for s, _ in log], dtype=np.int64)
            idxs = np.array([i for _, i in log], dtype=np.int64)
            mine = self.view_of == g
            # per-client newest visible publication index (-1 = none)
            vis = slots[None, :] + self.lag[mine, None] <= slot
            pick = np.where(vis.any(axis=1),
                            vis.shape[1] - 1 - np.argmax(vis[:, ::-1],
                                                         axis=1), -1)
            has = pick >= 0
            new_slot = np.where(has, slots[np.clip(pick, 0, None)], -1)
            new_idx = np.where(has, idxs[np.clip(pick, 0, None)], -1)
            old = self.head_slot[mine]
            adv = new_slot > old
            self.updates_applied += int(np.count_nonzero(adv))
            self.head_slot[mine] = np.where(adv, new_slot, old)
            self.head_idx[mine] = np.where(adv, new_idx,
                                           self.head_idx[mine])

    def stats(self) -> dict:
        synced = self.head_slot >= 0
        return {"clients": self.n,
                "updates_applied": self.updates_applied,
                "clients_synced": int(np.count_nonzero(synced)),
                "max_head_slot": int(self.head_slot.max(initial=-1)),
                "max_lag_slots": int(self.lag.max(initial=0))}

    def describe(self) -> dict:
        return {"kind": self.kind, "n_clients": self.n, "seed": self.seed}

    @classmethod
    def from_config(cls, d: dict) -> "DenseLightClientPopulation":
        return cls(n_clients=int(d.get("n_clients", 256)),
                   seed=int(d.get("seed", 0)))

    # -- checkpoint state ------------------------------------------------------

    def state_meta(self) -> dict:
        return {"updates_applied": self.updates_applied,
                "published": [[[int(s), int(i)] for s, i in log]
                              for log in self._published]}

    def state_arrays(self) -> dict:
        return {"head_slot": self.head_slot, "head_idx": self.head_idx}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.updates_applied = int(meta.get("updates_applied", 0))
        self._published = [[(int(s), int(i)) for s, i in log]
                           for log in meta.get("published", [])]
        while len(self._published) < (self.sim.n_groups if self.sim else 1):
            self._published.append([])
        if "head_slot" in arrays:
            self.head_slot = np.asarray(arrays["head_slot"], np.int64)
            self.head_idx = np.asarray(arrays["head_idx"], np.int64)
