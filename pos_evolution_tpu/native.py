"""ctypes bindings for the native (C++) runtime components.

Loads ``native/build/libhashtree.so`` (component N2, SURVEY.md §2.7),
building it with the in-tree Makefile on first use when a toolchain is
available. Falls back cleanly to the NumPy/hashlib paths when absent, so
the framework stays importable without a compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from functools import lru_cache

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libhashtree.so")


@lru_cache(maxsize=1)
def _load():
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ht_sha256_batch.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint64, u8p]
    lib.ht_merkleize.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint32, u8p, u8p]
    lib.ht_validator_roots.argtypes = [u8p, ctypes.c_uint64, u8p]
    lib.ht_mix_in_length.argtypes = [u8p, ctypes.c_uint64, u8p]
    return lib


def available() -> bool:
    return _load() is not None


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sha256_batch(msgs: np.ndarray) -> np.ndarray:
    """(N, L) uint8 -> (N, 32) digests via the C++ core."""
    lib = _load()
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n, length = msgs.shape
    out = np.empty((n, 32), dtype=np.uint8)
    if n:
        lib.ht_sha256_batch(_ptr(msgs), n, length, _ptr(out))
    return out


def merkleize_chunks(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """Whole-tree SSZ merkleize in one native call."""
    lib = _load()
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8).reshape(-1, 32)
    count = chunks.shape[0]
    if limit is None:
        limit = max(count, 1)
    if count > limit:
        raise ValueError(f"{count} chunks exceed limit {limit}")
    depth = (max(limit, 1) - 1).bit_length() if limit > 1 else 0
    out = np.empty(32, dtype=np.uint8)
    scratch = np.empty(max(count, 1) * 32, dtype=np.uint8)
    lib.ht_merkleize(_ptr(chunks), count, depth, _ptr(scratch), _ptr(out))
    return out.tobytes()


def validator_roots(leaves: np.ndarray) -> np.ndarray:
    """(N, 8, 32) field-leaf chunks -> (N, 32) Validator roots."""
    lib = _load()
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8).reshape(-1, 256)
    n = leaves.shape[0]
    out = np.empty((n, 32), dtype=np.uint8)
    if n:
        lib.ht_validator_roots(_ptr(leaves), n, _ptr(out))
    return out


def mix_in_length(root: bytes, length: int) -> bytes:
    lib = _load()
    root_arr = np.frombuffer(bytes(root), dtype=np.uint8).copy()
    out = np.empty(32, dtype=np.uint8)
    lib.ht_mix_in_length(_ptr(root_arr), length, _ptr(out))
    return out.tobytes()
