"""Spec containers (L1): every ``class X(Container)`` of the reference.

Covers the full container inventory of SURVEY.md §2.1
(pos-evolution.md:36-45, 84-107, 219-221, 251-259, 286-289, 338-374,
548-557, 632-676, 689-717, 1154-1162) plus the referenced-but-not-inlined
envelope types (SignedBeaconBlock, BeaconBlockHeader, IndexedAttestation,
Eth1Data, Fork, SyncCommittee, SyncAggregate, ExecutionPayload).

Design departure from the reference (TPU-first, SURVEY.md §7): the validator
registry inside ``BeaconState`` is a dense struct-of-arrays
(``ValidatorRegistry``) rather than a Python list of ``Validator`` objects,
so registry-wide sweeps and merkleization are vectorized; ``Validator``
container views materialize on indexing for spec-level code.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import FAR_FUTURE_EPOCH, cfg
from pos_evolution_tpu.ssz.core import (
    Bitlist, Bitvector, ByteList, ByteVector, Bytes4, Bytes20, Bytes32, Bytes48,
    Bytes96, Container, List, Sedes, Vector, _UInt, boolean, uint8, uint64,
)
from pos_evolution_tpu.ssz.hash import sha256_pairs
from pos_evolution_tpu.ssz.merkle import merkleize_chunks, mix_in_length

uint256 = _UInt(32)

# Type aliases used by the reference throughout.
Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96
ParticipationFlags = uint8
DomainType = Bytes4


# --- dynamic-limit sedes helpers ---------------------------------------------
# Several BeaconState fields have config-dependent lengths; the reference
# resolves these from preset constants. We bind them at class definition to
# mainnet-scale limits and let ``Bytes32Rows``/registry adapters handle the
# actual runtime lengths (runtime arrays carry their own shape).


class Bytes32Rows(Sedes):
    """Vector/List of 32-byte roots stored as an (N, 32) uint8 array.

    Vectorized counterpart of ``Vector[Root, N]`` / ``List[Root, N]``
    (block_roots / state_roots / randao_mixes, pos-evolution.md:346-357).
    """

    def __init__(self, limit: int, is_list: bool):
        self.limit = limit
        self.is_list = is_list

    def is_fixed(self):
        # Offset-framed even in the Vector case: the runtime length is
        # config-dependent (minimal vs mainnet presets share the class).
        return False

    def serialize(self, value) -> bytes:
        return np.ascontiguousarray(value, dtype=np.uint8).tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        return np.frombuffer(data, dtype=np.uint8).reshape(-1, 32).copy()

    def htr(self, value) -> bytes:
        arr = np.ascontiguousarray(value, dtype=np.uint8).reshape(-1, 32)
        if self.is_list:
            return mix_in_length(merkleize_chunks(arr, self.limit), arr.shape[0])
        return merkleize_chunks(arr, max(arr.shape[0], 1))

    def default(self) -> np.ndarray:
        n = 0 if self.is_list else self.limit
        return np.zeros((n, 32), dtype=np.uint8)


def RootVector(length: int) -> Bytes32Rows:
    return Bytes32Rows(length, is_list=False)


def RootList(limit: int) -> Bytes32Rows:
    return Bytes32Rows(limit, is_list=True)


# --- simple containers --------------------------------------------------------

class Fork(Container):
    previous_version: Bytes4
    current_version: Bytes4
    epoch: Epoch


class Checkpoint(Container):
    """Casper FFG checkpoint: (epoch, root) pair (pos-evolution.md:219-221)."""
    epoch: Epoch
    root: Root

    def as_key(self) -> tuple:
        return (int(self.epoch), bytes(self.root))


class Validator(Container):
    """Registry entry (pos-evolution.md:36-45)."""
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    effective_balance: Gwei
    slashed: boolean
    activation_eligibility_epoch: Epoch
    activation_epoch: Epoch
    exit_epoch: Epoch
    withdrawable_epoch: Epoch


class DepositMessage(Container):
    """Deposit intent (pos-evolution.md:84-87)."""
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei


class DepositData(Container):
    """Signed deposit (pos-evolution.md:91-95)."""
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei
    signature: BLSSignature


class Deposit(Container):
    """Merkle-proved deposit (pos-evolution.md:105-107)."""
    proof: RootVector(33)  # DEPOSIT_CONTRACT_TREE_DEPTH + 1 (length mix-in)
    data: DepositData


class VoluntaryExit(Container):
    """pos-evolution.md:251-253."""
    epoch: Epoch
    validator_index: ValidatorIndex


class SignedVoluntaryExit(Container):
    message: VoluntaryExit
    signature: BLSSignature


class Eth1Data(Container):
    deposit_root: Root
    deposit_count: uint64
    block_hash: Bytes32


class BeaconBlockHeader(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body_root: Root


class SignedBeaconBlockHeader(Container):
    message: BeaconBlockHeader
    signature: BLSSignature


class AttestationData(Container):
    """LMD-GHOST vote + FFG vote (pos-evolution.md:689-696)."""
    slot: Slot
    index: CommitteeIndex
    beacon_block_root: Root
    source: Checkpoint
    target: Checkpoint


class Attestation(Container):
    """Aggregate attestation (pos-evolution.md:714-717)."""
    aggregation_bits: Bitlist(2048)  # MAX_VALIDATORS_PER_COMMITTEE
    data: AttestationData
    signature: BLSSignature


class IndexedAttestation(Container):
    """Referenced at pos-evolution.md:736, 975-976, 1456-1457."""
    attesting_indices: List(uint64, 2048)
    data: AttestationData
    signature: BLSSignature


class PendingAttestation(Container):
    aggregation_bits: Bitlist(2048)
    data: AttestationData
    inclusion_delay: Slot
    proposer_index: ValidatorIndex


class ProposerSlashing(Container):
    """pos-evolution.md:1154-1156."""
    signed_header_1: SignedBeaconBlockHeader
    signed_header_2: SignedBeaconBlockHeader


class AttesterSlashing(Container):
    """pos-evolution.md:1160-1162."""
    attestation_1: IndexedAttestation
    attestation_2: IndexedAttestation


class SyncCommittee(Container):
    """512 pubkeys rotated every 256 epochs (pos-evolution.md:542)."""
    pubkeys: List(Bytes48, 512)  # stored as list; length = cfg.sync_committee_size
    aggregate_pubkey: BLSPubkey


class SyncAggregate(Container):
    sync_committee_bits: Bitvector(512)
    sync_committee_signature: BLSSignature


class SyncCommitteeMessage(Container):
    """pos-evolution.md:548-557."""
    slot: Slot
    beacon_block_root: Root
    validator_index: ValidatorIndex
    signature: BLSSignature


class ExecutionPayloadHeader(Container):
    """Bellatrix execution payload header (pos-evolution.md:374)."""
    parent_hash: Bytes32
    fee_recipient: Bytes20
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector(256)
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList(32)
    base_fee_per_gas: uint256
    block_hash: Bytes32
    transactions_root: Root


class ExecutionPayload(Container):
    """pos-evolution.md:644 — transactions ride in this record."""
    parent_hash: Bytes32
    fee_recipient: Bytes20
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector(256)
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList(32)
    base_fee_per_gas: uint256
    block_hash: Bytes32
    transactions: List(ByteList(1073741824), 1048576)


class BeaconBlockBody(Container):
    """pos-evolution.md:632-644."""
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List(ProposerSlashing, 16)
    attester_slashings: List(AttesterSlashing, 2)
    attestations: List(Attestation, 128)
    deposits: List(Deposit, 16)
    voluntary_exits: List(SignedVoluntaryExit, 16)
    sync_aggregate: SyncAggregate
    execution_payload: ExecutionPayload


class BeaconBlock(Container):
    """pos-evolution.md:671-676."""
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


# --- dense validator registry -------------------------------------------------

_VALIDATOR_FIXED_SIZE = 48 + 32 + 8 + 1 + 8 * 4  # 121 bytes


class ValidatorRegistry:
    """Struct-of-arrays mirror of ``List[Validator, LIMIT]``.

    The array level of SURVEY.md §7: every per-epoch sweep
    (process_effective_balance_updates pos-evolution.md:122-133, activity
    masks, churn) runs on these columns; ``registry[i]`` materializes a
    ``Validator`` container for spec-level call sites; hash_tree_root is
    computed with ~15 batched SHA-256 sweeps instead of 8N hashlib calls.
    """

    __slots__ = ("pubkeys", "withdrawal_credentials", "effective_balance", "slashed",
                 "activation_eligibility_epoch", "activation_epoch", "exit_epoch",
                 "withdrawable_epoch", "_pubkey_index")

    def __init__(self, n: int = 0):
        self._pubkey_index = None
        self.pubkeys = np.zeros((n, 48), dtype=np.uint8)
        self.withdrawal_credentials = np.zeros((n, 32), dtype=np.uint8)
        self.effective_balance = np.zeros(n, dtype=np.uint64)
        self.slashed = np.zeros(n, dtype=bool)
        self.activation_eligibility_epoch = np.full(n, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self.activation_epoch = np.full(n, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self.exit_epoch = np.full(n, FAR_FUTURE_EPOCH, dtype=np.uint64)
        self.withdrawable_epoch = np.full(n, FAR_FUTURE_EPOCH, dtype=np.uint64)

    def __len__(self) -> int:
        return self.effective_balance.shape[0]

    def __getitem__(self, i: int) -> Validator:
        return Validator(
            pubkey=self.pubkeys[i].tobytes(),
            withdrawal_credentials=self.withdrawal_credentials[i].tobytes(),
            effective_balance=int(self.effective_balance[i]),
            slashed=bool(self.slashed[i]),
            activation_eligibility_epoch=int(self.activation_eligibility_epoch[i]),
            activation_epoch=int(self.activation_epoch[i]),
            exit_epoch=int(self.exit_epoch[i]),
            withdrawable_epoch=int(self.withdrawable_epoch[i]),
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def set_pubkeys(self, pubkeys: np.ndarray) -> None:
        """Bulk-write the pubkey column (invalidates the lookup index).
        Use this (or set_validator) rather than writing ``.pubkeys`` rows
        directly — direct writes would leave ``find_pubkey`` stale."""
        self._pubkey_index = None
        self.pubkeys[:] = pubkeys

    def set_validator(self, i: int, v: Validator) -> None:
        self._pubkey_index = None
        self.pubkeys[i] = np.frombuffer(bytes(v.pubkey), dtype=np.uint8)
        self.withdrawal_credentials[i] = np.frombuffer(
            bytes(v.withdrawal_credentials), dtype=np.uint8)
        self.effective_balance[i] = v.effective_balance
        self.slashed[i] = v.slashed
        self.activation_eligibility_epoch[i] = v.activation_eligibility_epoch
        self.activation_epoch[i] = v.activation_epoch
        self.exit_epoch[i] = v.exit_epoch
        self.withdrawable_epoch[i] = v.withdrawable_epoch

    def append(self, v: Validator) -> None:
        n = len(self)
        self.pubkeys = np.vstack([self.pubkeys, np.zeros((1, 48), dtype=np.uint8)])
        self.withdrawal_credentials = np.vstack(
            [self.withdrawal_credentials, np.zeros((1, 32), dtype=np.uint8)])
        for f in ("effective_balance", "slashed", "activation_eligibility_epoch",
                  "activation_epoch", "exit_epoch", "withdrawable_epoch"):
            col = getattr(self, f)
            setattr(self, f, np.append(col, np.zeros(1, dtype=col.dtype)))
        self.set_validator(n, v)

    def find_pubkey(self, pubkey: bytes) -> int | None:
        """Index of ``pubkey`` in the registry, or None (pos-evolution.md:154-155).

        Backed by a lazily built dict (invalidated on registry growth):
        sync-aggregate processing does hundreds of lookups per block, and a
        linear scan is O(n) each at mainnet registry sizes.
        """
        cache = getattr(self, "_pubkey_index", None)
        if cache is None or len(cache) != len(self):
            cache = {self.pubkeys[i].tobytes(): i for i in range(len(self))}
            self._pubkey_index = cache
        return cache.get(bytes(pubkey))

    def copy(self) -> "ValidatorRegistry":
        out = ValidatorRegistry(0)
        for f in self.__slots__:
            if f == "_pubkey_index":
                continue
            setattr(out, f, getattr(self, f).copy())
        return out

    # -- vectorized SSZ -------------------------------------------------------
    def validator_roots(self) -> np.ndarray:
        """(N, 32) hash_tree_root of each Validator, fully batched."""
        n = len(self)
        if n == 0:
            return np.empty((0, 32), dtype=np.uint8)
        leaves = np.zeros((n, 8, 32), dtype=np.uint8)
        # pubkey: 48 bytes -> 2 chunks -> 1 hash
        pk_hi = np.zeros((n, 32), dtype=np.uint8)
        pk_hi[:, :16] = self.pubkeys[:, 32:]
        leaves[:, 0] = sha256_pairs(np.ascontiguousarray(self.pubkeys[:, :32]), pk_hi)
        leaves[:, 1] = self.withdrawal_credentials
        leaves[:, 2, :8] = self.effective_balance.astype("<u8").view(np.uint8).reshape(n, 8)
        leaves[:, 3, 0] = self.slashed.astype(np.uint8)
        for k, f in enumerate(("activation_eligibility_epoch", "activation_epoch",
                               "exit_epoch", "withdrawable_epoch")):
            leaves[:, 4 + k, :8] = getattr(self, f).astype("<u8").view(np.uint8).reshape(n, 8)
        # depth-3 merkle over the 8 field leaves, batched across validators
        layer = leaves.reshape(n * 8, 32)
        for _ in range(3):
            layer = sha256_pairs(layer[0::2], layer[1::2])
        return layer.reshape(n, 32)

    def __ssz_root__(self) -> bytes:
        root = merkleize_chunks(self.validator_roots(), cfg().validator_registry_limit)
        return mix_in_length(root, len(self))

    def serialize_bytes(self) -> bytes:
        n = len(self)
        buf = np.zeros((n, _VALIDATOR_FIXED_SIZE), dtype=np.uint8)
        buf[:, 0:48] = self.pubkeys
        buf[:, 48:80] = self.withdrawal_credentials
        buf[:, 80:88] = self.effective_balance.astype("<u8").view(np.uint8).reshape(n, 8)
        buf[:, 88] = self.slashed.astype(np.uint8)
        for k, f in enumerate(("activation_eligibility_epoch", "activation_epoch",
                               "exit_epoch", "withdrawable_epoch")):
            buf[:, 89 + 8 * k:97 + 8 * k] = getattr(self, f).astype(
                "<u8").view(np.uint8).reshape(n, 8)
        return buf.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ValidatorRegistry":
        buf = np.frombuffer(data, dtype=np.uint8).reshape(-1, _VALIDATOR_FIXED_SIZE)
        n = buf.shape[0]
        out = cls(n)
        out.pubkeys = buf[:, 0:48].copy()
        out.withdrawal_credentials = buf[:, 48:80].copy()
        out.effective_balance = buf[:, 80:88].copy().view("<u8").reshape(n).astype(np.uint64)
        out.slashed = buf[:, 88].astype(bool)
        for k, f in enumerate(("activation_eligibility_epoch", "activation_epoch",
                               "exit_epoch", "withdrawable_epoch")):
            setattr(out, f, buf[:, 89 + 8 * k:97 + 8 * k].copy().view(
                "<u8").reshape(n).astype(np.uint64))
        return out


class _RegistrySedes(Sedes):
    def is_fixed(self):
        return False

    def serialize(self, value: ValidatorRegistry) -> bytes:
        return value.serialize_bytes()

    def deserialize(self, data: bytes) -> ValidatorRegistry:
        return ValidatorRegistry.from_bytes(data)

    def htr(self, value: ValidatorRegistry) -> bytes:
        return value.__ssz_root__()

    def default(self) -> ValidatorRegistry:
        return ValidatorRegistry(0)


class _U64ListSedes(Sedes):
    """List[uint64/uint8, VALIDATOR_REGISTRY_LIMIT] over numpy columns."""

    def __init__(self, dtype, limit: int):
        self.dtype = dtype
        self.byte_len = np.dtype(dtype).itemsize
        self.limit = limit

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        return np.asarray(value, dtype=self.dtype).astype(f"<u{self.byte_len}").tobytes()

    def deserialize(self, data: bytes):
        return np.frombuffer(data, dtype=f"<u{self.byte_len}").astype(self.dtype).copy()

    def htr(self, value) -> bytes:
        arr = np.asarray(value, dtype=self.dtype)
        raw = arr.astype(f"<u{self.byte_len}").view(np.uint8)
        n_bytes = raw.size
        padded = np.zeros((max((n_bytes + 31) // 32, 1)) * 32, dtype=np.uint8)
        padded[:n_bytes] = raw
        per_chunk = 32 // self.byte_len
        limit_chunks = (self.limit + per_chunk - 1) // per_chunk
        chunks = (padded.reshape(-1, 32) if n_bytes
                  else np.empty((0, 32), dtype=np.uint8))
        return mix_in_length(merkleize_chunks(chunks, limit_chunks), arr.shape[0])

    def default(self):
        return np.zeros(0, dtype=self.dtype)


class _U64VectorSedes(Sedes):
    """Config-length Vector[uint64, N] over a numpy column (e.g. slashings).

    Declared variable-size so mainnet and minimal presets share one class;
    the runtime array carries its length.
    """

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        return np.asarray(value, dtype=np.uint64).astype("<u8").tobytes()

    def deserialize(self, data: bytes):
        return np.frombuffer(data, dtype="<u8").astype(np.uint64).copy()

    def htr(self, value) -> bytes:
        arr = np.asarray(value, dtype=np.uint64)
        raw = arr.astype("<u8").view(np.uint8)
        padded = np.zeros(max((raw.size + 31) // 32, 1) * 32, dtype=np.uint8)
        padded[:raw.size] = raw
        return merkleize_chunks(padded.reshape(-1, 32))

    def default(self):
        return np.zeros(0, dtype=np.uint64)


_REG_LIMIT = 2**40


class BeaconState(Container):
    """The replicated state (pos-evolution.md:338-374).

    Registry-scale fields are dense numpy columns; everything else is
    spec-shaped. This is the single source of truth both levels share.
    """

    # Versioning
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    # History
    latest_block_header: BeaconBlockHeader
    block_roots: RootVector(8192)
    state_roots: RootVector(8192)
    historical_roots: RootList(2**24)
    # Eth1
    eth1_data: Eth1Data
    eth1_data_votes: List(Eth1Data, 2048)
    eth1_deposit_index: uint64
    # Registry (dense columns)
    validators: _RegistrySedes()
    balances: _U64ListSedes(np.uint64, _REG_LIMIT)
    # Randomness
    randao_mixes: RootVector(65536)
    # Slashings
    slashings: _U64VectorSedes()
    # Participation (dense uint8 flag columns)
    previous_epoch_participation: _U64ListSedes(np.uint8, _REG_LIMIT)
    current_epoch_participation: _U64ListSedes(np.uint8, _REG_LIMIT)
    # Finality
    justification_bits: Bitvector(4)
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    # Inactivity
    inactivity_scores: _U64ListSedes(np.uint64, _REG_LIMIT)
    # Sync
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee
    # Execution
    latest_execution_payload_header: ExecutionPayloadHeader

    def copy(self) -> "BeaconState":
        out = BeaconState.__new__(BeaconState)
        for f in self._fields:
            v = getattr(self, f)
            if isinstance(v, np.ndarray):
                setattr(out, f, v.copy())
            elif isinstance(v, (ValidatorRegistry, Container)):
                setattr(out, f, v.copy())
            elif isinstance(v, list):
                setattr(out, f, [x.copy() if hasattr(x, "copy") else x for x in v])
            else:
                setattr(out, f, v)
        # Share the incremental-merkleization cache with the copy: the
        # cache diffs against whatever it last hashed, so one cache serves
        # the whole copy lineage (ssz/incremental.py sharing contract).
        cache = self.__dict__.get("_htr_cache")
        if cache is not None:
            out._htr_cache = cache
        return out

    def __ssz_root__(self) -> bytes:
        """Route ``hash_tree_root(state)`` through the incremental
        merkleizer (ssz/incremental.py): only dirty subtrees re-hash.
        Bit-identical to ``BeaconState.htr`` (property-pinned)."""
        from pos_evolution_tpu.ssz.incremental import state_root
        return state_root(self)


class LatestMessage:
    """Latest (epoch, root) vote per validator (pos-evolution.md:286-289)."""

    __slots__ = ("epoch", "root")

    def __init__(self, epoch: int, root: bytes):
        self.epoch = int(epoch)
        self.root = bytes(root)

    def __eq__(self, other):
        return (isinstance(other, LatestMessage)
                and self.epoch == other.epoch and self.root == other.root)

    def __hash__(self):
        return hash((self.epoch, self.root))

    def __repr__(self):
        return f"LatestMessage(epoch={self.epoch}, root={self.root[:4].hex()}..)"
