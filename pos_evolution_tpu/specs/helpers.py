"""Spec helper functions (L2/L3 support; SURVEY.md §2.3, §2.6).

All ~35 helpers the reference calls but does not inline
(pos-evolution.md:412-424, 467, 485, 729-749, 798-811, 832-836, 953-976,
1005-1058, 1104-1116, 1234, 1267-1270), plus the committee/randomness/
proposer machinery it does inline (:461-624). Registry-wide predicates are
vectorized over the dense columns; the full shuffle permutation is computed
once per (seed, count) through the ExecutionBackend and memoized.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from pos_evolution_tpu.backend import get_backend
from pos_evolution_tpu.config import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_SYNC_COMMITTEE,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    PROPOSER_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    cfg,
)
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import (
    Attestation,
    AttestationData,
    BeaconState,
    DepositData,
    IndexedAttestation,
    Validator,
)
from pos_evolution_tpu.ssz import hash_eth2, hash_tree_root
from pos_evolution_tpu.ssz.core import Container, Bytes4, Bytes32, uint64


# --- math / time -------------------------------------------------------------

def integer_squareroot(n: int) -> int:
    import math
    return math.isqrt(int(n))


def compute_epoch_at_slot(slot: int) -> int:
    return int(slot) // cfg().slots_per_epoch


def compute_start_slot_at_epoch(epoch: int) -> int:
    return int(epoch) * cfg().slots_per_epoch


def compute_activation_exit_epoch(epoch: int) -> int:
    return int(epoch) + 1 + cfg().max_seed_lookahead


def get_current_epoch(state: BeaconState) -> int:
    return compute_epoch_at_slot(state.slot)


def get_previous_epoch(state: BeaconState) -> int:
    current = get_current_epoch(state)
    return GENESIS_EPOCH if current == GENESIS_EPOCH else current - 1


def uint_to_bytes(value: int, length: int = 8) -> bytes:
    return int(value).to_bytes(length, "little")


def bytes_to_uint64(data: bytes) -> int:
    return int.from_bytes(data, "little")


# --- validator predicates (vectorized over the dense registry) ---------------

def is_active_validator(validator: Validator, epoch: int) -> bool:
    """pos-evolution.md:467 contract: activation <= epoch < exit."""
    return validator.activation_epoch <= epoch < validator.exit_epoch


def active_validator_mask(state: BeaconState, epoch: int) -> np.ndarray:
    reg = state.validators
    e = np.uint64(epoch)
    return (reg.activation_epoch <= e) & (e < reg.exit_epoch)


def get_active_validator_indices(state: BeaconState, epoch: int) -> np.ndarray:
    """Referenced at pos-evolution.md:467, 1234, 1267."""
    return np.nonzero(active_validator_mask(state, epoch))[0]


def get_validator_churn_limit(state: BeaconState) -> int:
    """pos-evolution.md:1270."""
    c = cfg()
    active = int(active_validator_mask(state, get_current_epoch(state)).sum())
    return max(c.min_per_epoch_churn_limit, active // c.churn_limit_quotient)


def is_slashable_validator(validator: Validator, epoch: int) -> bool:
    return (not validator.slashed) and (
        validator.activation_epoch <= epoch < validator.withdrawable_epoch)


def is_slashable_attestation_data(data_1: AttestationData, data_2: AttestationData) -> bool:
    """Double vote or surround vote (pos-evolution.md:1134-1143)."""
    double = data_1 != data_2 and data_1.target.epoch == data_2.target.epoch
    surround = (data_1.source.epoch < data_2.source.epoch
                and data_2.target.epoch < data_1.target.epoch)
    return double or surround


# --- domains / signing roots --------------------------------------------------

class ForkData(Container):
    current_version: Bytes4
    genesis_validators_root: Bytes32


class SigningData(Container):
    object_root: Bytes32
    domain: Bytes32


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return hash_tree_root(ForkData(current_version=current_version,
                                   genesis_validators_root=genesis_validators_root))


def compute_domain(domain_type: bytes, fork_version: bytes | None = None,
                   genesis_validators_root: bytes | None = None) -> bytes:
    """pos-evolution.md:162."""
    if fork_version is None:
        fork_version = b"\x00" * 4
    if genesis_validators_root is None:
        genesis_validators_root = b"\x00" * 32
    fork_data_root = compute_fork_data_root(fork_version, genesis_validators_root)
    return bytes(domain_type) + fork_data_root[:28]


def get_domain(state: BeaconState, domain_type: bytes, epoch: int | None = None) -> bytes:
    if epoch is None:
        epoch = get_current_epoch(state)
    fork_version = (state.fork.previous_version if epoch < state.fork.epoch
                    else state.fork.current_version)
    return compute_domain(domain_type, fork_version, state.genesis_validators_root)


def compute_signing_root(ssz_object, domain: bytes, sedes=None) -> bytes:
    """pos-evolution.md:163."""
    return hash_tree_root(SigningData(object_root=hash_tree_root(ssz_object, sedes),
                                      domain=domain))


# --- history accessors --------------------------------------------------------

def get_block_root_at_slot(state: BeaconState, slot: int) -> bytes:
    assert slot < state.slot <= slot + state.block_roots.shape[0]
    return state.block_roots[slot % state.block_roots.shape[0]].tobytes()


def get_block_root(state: BeaconState, epoch: int) -> bytes:
    """EBB root for ``epoch`` (pos-evolution.md:832, 836)."""
    return get_block_root_at_slot(state, compute_start_slot_at_epoch(epoch))


def get_randao_mix(state: BeaconState, epoch: int) -> bytes:
    """pos-evolution.md:485."""
    return state.randao_mixes[epoch % state.randao_mixes.shape[0]].tobytes()


# --- balances ----------------------------------------------------------------

def increase_balance(state: BeaconState, index: int, delta: int) -> None:
    """pos-evolution.md:174, 754."""
    state.balances[index] += np.uint64(delta)


def decrease_balance(state: BeaconState, index: int, delta: int) -> None:
    bal = int(state.balances[index])
    state.balances[index] = np.uint64(max(bal - int(delta), 0))


def get_total_balance(state: BeaconState, indices) -> int:
    """Sum of effective balances over ``indices``; floored at one increment
    (pos-evolution.md:807-811)."""
    idx = np.asarray(list(indices) if not isinstance(indices, np.ndarray) else indices,
                     dtype=np.int64)
    total = int(state.validators.effective_balance[idx].sum()) if idx.size else 0
    return max(cfg().effective_balance_increment, total)


def get_total_active_balance(state: BeaconState) -> int:
    mask = active_validator_mask(state, get_current_epoch(state))
    total = int(state.validators.effective_balance[mask].sum())
    return max(cfg().effective_balance_increment, total)


# --- participation flags ------------------------------------------------------

def has_flag(flags: int, flag_index: int) -> bool:
    return bool((int(flags) >> flag_index) & 1)


def add_flag(flags: int, flag_index: int) -> int:
    return int(flags) | (1 << flag_index)


def get_unslashed_participating_indices(state: BeaconState, flag_index: int,
                                        epoch: int) -> np.ndarray:
    """pos-evolution.md:798-799 — vectorized flag/slash/activity mask."""
    assert epoch in (get_previous_epoch(state), get_current_epoch(state))
    participation = (state.current_epoch_participation
                     if epoch == get_current_epoch(state)
                     else state.previous_epoch_participation)
    mask = (active_validator_mask(state, epoch)
            & (((participation >> np.uint8(flag_index)) & np.uint8(1)).astype(bool))
            & ~state.validators.slashed)
    return np.nonzero(mask)[0]


def get_base_reward_per_increment(state: BeaconState) -> int:
    c = cfg()
    return (c.effective_balance_increment * c.base_reward_factor
            // integer_squareroot(get_total_active_balance(state)))


def get_base_reward(state: BeaconState, index: int) -> int:
    """pos-evolution.md:749."""
    c = cfg()
    increments = int(state.validators.effective_balance[index]) // c.effective_balance_increment
    return increments * get_base_reward_per_increment(state)


def get_finality_delay(state: BeaconState) -> int:
    return get_previous_epoch(state) - int(state.finalized_checkpoint.epoch)


def is_in_inactivity_leak(state: BeaconState) -> bool:
    return get_finality_delay(state) > 4  # MIN_EPOCHS_TO_INACTIVITY_PENALTY


# --- committees (L3) ----------------------------------------------------------

def get_committee_count_per_slot(state: BeaconState, epoch: int) -> int:
    """pos-evolution.md:461-469."""
    c = cfg()
    active = int(active_validator_mask(state, epoch).sum())
    return max(1, min(c.max_committees_per_slot,
                      active // c.slots_per_epoch // c.target_committee_size))


def get_seed(state: BeaconState, epoch: int, domain_type: bytes) -> bytes:
    """pos-evolution.md:481-487."""
    c = cfg()
    mix = get_randao_mix(
        state, epoch + c.epochs_per_historical_vector - c.min_seed_lookahead - 1)
    return hash_eth2(bytes(domain_type) + uint_to_bytes(epoch) + mix)


def compute_shuffled_index(index: int, index_count: int, seed: bytes) -> int:
    """Scalar swap-or-not shuffle (pos-evolution.md:513-535).

    Kept for spec fidelity and as the oracle for the vectorized backend
    permutation; hot paths use ``get_shuffled_permutation``.
    """
    assert index < index_count
    rounds = cfg().shuffle_round_count
    for r in range(rounds):
        pivot = bytes_to_uint64(hash_eth2(seed + bytes([r]))[:8]) % index_count
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = hash_eth2(seed + bytes([r]) + uint_to_bytes(position // 256, 4))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) % 2:
            index = flip
    return index


@lru_cache(maxsize=128)
def _cached_permutation(backend_name: str, seed: bytes, index_count: int,
                        rounds: int) -> np.ndarray:
    perm = np.asarray(get_backend().shuffle_permutation(seed, index_count, rounds))
    perm.setflags(write=False)
    return perm


def get_shuffled_permutation(seed: bytes, index_count: int) -> np.ndarray:
    """p[i] = compute_shuffled_index(i, index_count, seed), via the backend."""
    return _cached_permutation(get_backend().name, bytes(seed), int(index_count),
                               cfg().shuffle_round_count)


def compute_committee(indices: np.ndarray, seed: bytes, index: int, count: int) -> np.ndarray:
    """pos-evolution.md:495-506, on the cached full permutation."""
    n = len(indices)
    start = (n * index) // count
    end = (n * (index + 1)) // count
    perm = get_shuffled_permutation(seed, n)
    return np.asarray(indices)[perm[start:end].astype(np.int64)]


def get_beacon_committee(state: BeaconState, slot: int, index: int) -> np.ndarray:
    """pos-evolution.md:729."""
    epoch = compute_epoch_at_slot(slot)
    committees_per_slot = get_committee_count_per_slot(state, epoch)
    return compute_committee(
        indices=get_active_validator_indices(state, epoch),
        seed=get_seed(state, epoch, DOMAIN_BEACON_ATTESTER),
        index=(slot % cfg().slots_per_epoch) * committees_per_slot + index,
        count=committees_per_slot * cfg().slots_per_epoch,
    )


def compute_proposer_index(state: BeaconState, indices: np.ndarray, seed: bytes) -> int:
    """Effective-balance-weighted rejection sampling (pos-evolution.md:604-619)."""
    assert len(indices) > 0
    c = cfg()
    total = len(indices)
    perm = get_shuffled_permutation(seed, total)
    i = 0
    while True:
        candidate_index = int(np.asarray(indices)[perm[i % total]])
        random_byte = hash_eth2(seed + uint_to_bytes(i // 32))[i % 32]
        effective_balance = int(state.validators.effective_balance[candidate_index])
        if effective_balance * c.max_random_byte >= c.max_effective_balance * random_byte:
            return candidate_index
        i += 1


def get_beacon_proposer_index(state: BeaconState) -> int:
    """Proposer for the current slot (pos-evolution.md:597, 604)."""
    epoch = get_current_epoch(state)
    seed = hash_eth2(get_seed(state, epoch, DOMAIN_BEACON_PROPOSER)
                     + uint_to_bytes(int(state.slot)))
    indices = get_active_validator_indices(state, epoch)
    return compute_proposer_index(state, indices, seed)


# --- sync committee (pos-evolution.md:542, 564-589) ---------------------------

def compute_sync_committee_period(epoch: int) -> int:
    return int(epoch) // cfg().epochs_per_sync_committee_period


def get_next_sync_committee_indices(state: BeaconState) -> list[int]:
    """Balance-weighted sampling of the next 512-validator sync committee."""
    c = cfg()
    epoch = get_current_epoch(state) + 1
    indices = get_active_validator_indices(state, epoch)
    total = len(indices)
    assert total > 0
    seed = get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
    perm = get_shuffled_permutation(seed, total)
    out: list[int] = []
    i = 0
    while len(out) < c.sync_committee_size:
        candidate_index = int(indices[perm[i % total]])
        random_byte = hash_eth2(seed + uint_to_bytes(i // 32))[i % 32]
        effective_balance = int(state.validators.effective_balance[candidate_index])
        if effective_balance * c.max_random_byte >= c.max_effective_balance * random_byte:
            out.append(candidate_index)
        i += 1
    return out


def get_next_sync_committee(state: BeaconState):
    from pos_evolution_tpu.specs.containers import SyncCommittee
    indices = get_next_sync_committee_indices(state)
    pubkeys = [state.validators.pubkeys[i].tobytes() for i in indices]
    return SyncCommittee(pubkeys=pubkeys, aggregate_pubkey=bls.AggregatePKs(pubkeys))


def is_assigned_to_sync_committee(state: BeaconState, epoch: int,
                                  validator_index: int) -> bool:
    """pos-evolution.md:564-578."""
    sync_committee_period = compute_sync_committee_period(epoch)
    current_period = compute_sync_committee_period(get_current_epoch(state))
    assert sync_committee_period in (current_period, current_period + 1)
    pubkey = state.validators.pubkeys[validator_index].tobytes()
    committee = (state.current_sync_committee if sync_committee_period == current_period
                 else state.next_sync_committee)
    return pubkey in [bytes(pk) for pk in committee.pubkeys]


# --- attestation machinery ----------------------------------------------------

def get_attesting_indices(state: BeaconState, data: AttestationData,
                          bits: np.ndarray) -> np.ndarray:
    """pos-evolution.md:745."""
    committee = get_beacon_committee(state, int(data.slot), int(data.index))
    bits = np.asarray(bits, dtype=bool)
    assert bits.shape[0] == committee.shape[0]
    return np.unique(committee[bits])


def get_indexed_attestation(state: BeaconState, attestation: Attestation) -> IndexedAttestation:
    """pos-evolution.md:736, 975."""
    attesting = get_attesting_indices(state, attestation.data, attestation.aggregation_bits)
    return IndexedAttestation(
        attesting_indices=np.sort(attesting).astype(np.uint64),
        data=attestation.data,
        signature=attestation.signature,
    )


def is_valid_indexed_attestation(state: BeaconState, indexed: IndexedAttestation) -> bool:
    """pos-evolution.md:736, 976, 1456-1457: sorted non-empty indices and a
    valid aggregate signature over the attestation data."""
    indices = np.asarray(indexed.attesting_indices, dtype=np.int64)
    if indices.size == 0 or not np.all(indices[:-1] < indices[1:]):
        return False
    pubkeys = [state.validators.pubkeys[i].tobytes() for i in indices]
    domain = get_domain(state, DOMAIN_BEACON_ATTESTER, int(indexed.data.target.epoch))
    signing_root = compute_signing_root(indexed.data, domain)
    return bls.FastAggregateVerify(pubkeys, signing_root, indexed.signature)


def get_attestation_participation_flag_indices(state: BeaconState, data: AttestationData,
                                               inclusion_delay: int) -> list[int]:
    """Altair participation flags (pos-evolution.md:733)."""
    c = cfg()
    if data.target.epoch == get_current_epoch(state):
        justified_checkpoint = state.current_justified_checkpoint
    else:
        justified_checkpoint = state.previous_justified_checkpoint

    is_matching_source = data.source == justified_checkpoint
    is_matching_target = is_matching_source and bytes(data.target.root) == get_block_root(
        state, int(data.target.epoch))
    is_matching_head = is_matching_target and bytes(data.beacon_block_root) == \
        get_block_root_at_slot(state, int(data.slot))
    assert is_matching_source

    flags = []
    if is_matching_source and inclusion_delay <= integer_squareroot(c.slots_per_epoch):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= c.slots_per_epoch:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == c.min_attestation_inclusion_delay:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


# --- validator lifecycle ------------------------------------------------------

def initiate_validator_exit(state: BeaconState, index: int) -> None:
    """Queue an exit, respecting the per-epoch churn limit."""
    c = cfg()
    reg = state.validators
    if reg.exit_epoch[index] != np.uint64(FAR_FUTURE_EPOCH):
        return
    exiting = reg.exit_epoch[reg.exit_epoch != np.uint64(FAR_FUTURE_EPOCH)]
    exit_queue_epoch = max(
        int(exiting.max()) if exiting.size else 0,
        compute_activation_exit_epoch(get_current_epoch(state)),
    )
    exit_queue_churn = int((exiting == np.uint64(exit_queue_epoch)).sum())
    if exit_queue_churn >= get_validator_churn_limit(state):
        exit_queue_epoch += 1
    reg.exit_epoch[index] = exit_queue_epoch
    reg.withdrawable_epoch[index] = exit_queue_epoch + c.min_validator_withdrawability_delay


def slash_validator(state: BeaconState, slashed_index: int,
                    whistleblower_index: int | None = None) -> None:
    """Slash + penalize + reward whistleblower/proposer."""
    c = cfg()
    epoch = get_current_epoch(state)
    initiate_validator_exit(state, slashed_index)
    reg = state.validators
    reg.slashed[slashed_index] = True
    reg.withdrawable_epoch[slashed_index] = max(
        int(reg.withdrawable_epoch[slashed_index]),
        epoch + c.epochs_per_slashings_vector)
    eff = int(reg.effective_balance[slashed_index])
    state.slashings[epoch % state.slashings.shape[0]] += np.uint64(eff)
    decrease_balance(state, slashed_index, eff // c.min_slashing_penalty_quotient)

    proposer_index = get_beacon_proposer_index(state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = eff // c.whistleblower_reward_quotient
    proposer_reward = whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index, whistleblower_reward - proposer_reward)


def get_validator_from_deposit(state: BeaconState, deposit_data: DepositData) -> Validator:
    """pos-evolution.md:166."""
    c = cfg()
    amount = int(deposit_data.amount)
    effective = min(amount - amount % c.effective_balance_increment, c.max_effective_balance)
    return Validator(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        effective_balance=effective,
        slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH,
        exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH,
    )
