"""Validator duties (L5): proposing and attesting (pos-evolution.md:597,
681-683, 762-764).

Proposers build a ``BeaconBlock`` on the head output of their fork choice;
attesters cast a combined LMD-GHOST head vote + FFG source/target vote
(pos-evolution.md:683). These builders are used by the round-based
simulation driver (L6) and by the transition/fork-choice tests.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    cfg,
)
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import (
    Attestation,
    AttestationData,
    BeaconBlock,
    BeaconBlockBody,
    BeaconState,
    Checkpoint,
    SignedBeaconBlock,
    SyncAggregate,
)
from pos_evolution_tpu.specs.genesis import validator_secret_key
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    compute_signing_root,
    compute_start_slot_at_epoch,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    get_domain,
)
from pos_evolution_tpu.specs.transition import (
    process_block,
    process_slots,
    verify_block_signature,
)
from pos_evolution_tpu.ssz import hash_tree_root
from pos_evolution_tpu.ssz.core import uint64
from pos_evolution_tpu.config import DOMAIN_BEACON_PROPOSER


def get_committee_assignment(state: BeaconState, epoch: int,
                             validator_index: int):
    """Duty lookup: (committee, committee_index, slot) for the validator's
    attestation duty in ``epoch``, or None (pos-evolution.md:450-455: one
    committee per validator per epoch)."""
    from pos_evolution_tpu.specs.helpers import get_committee_count_per_slot
    next_epoch = get_current_epoch(state) + 1
    assert epoch <= next_epoch
    start_slot = compute_start_slot_at_epoch(epoch)
    committees_per_slot = get_committee_count_per_slot(state, epoch)
    for slot in range(start_slot, start_slot + cfg().slots_per_epoch):
        for index in range(committees_per_slot):
            committee = get_beacon_committee(state, slot, index)
            if validator_index in committee:
                return committee, index, slot
    return None


def advance_state_to_slot(state: BeaconState, slot: int) -> BeaconState:
    """Copy of ``state`` advanced through empty slots to ``slot``."""
    out = state.copy()
    if int(out.slot) < slot:
        process_slots(out, slot)
    return out


def sign_block(state: BeaconState, block: BeaconBlock) -> SignedBeaconBlock:
    sk = validator_secret_key(int(block.proposer_index))
    signing_root = compute_signing_root(block, get_domain(state, DOMAIN_BEACON_PROPOSER))
    return SignedBeaconBlock(message=block, signature=bls.Sign(sk, signing_root))


def make_sync_aggregate(state: BeaconState, block_root: bytes,
                        participants=None) -> SyncAggregate:
    """Sync-committee duty (pos-evolution.md:548-557): current committee
    members sign the head ``block_root`` for inclusion in the next block.

    ``state`` must be advanced to the including block's slot, so the signed
    root is what ``process_sync_aggregate`` reconstructs (the block root at
    the previous slot — the proposal's parent). ``participants`` restricts
    signing to a validator-index subset (sleepy/corrupted members abstain);
    None signs with the full committee. Bits are container-width (the
    mainnet 512 limit) with one lane per committee pubkey.
    """
    previous_slot = max(int(state.slot), 1) - 1
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE,
                        compute_epoch_at_slot(previous_slot))
    from pos_evolution_tpu.specs.transition import compute_signing_root_bytes
    signing_root = compute_signing_root_bytes(bytes(block_root), domain)
    width = SyncAggregate._fields["sync_committee_bits"].length
    bits = np.zeros(width, dtype=bool)
    participant_set = (set(int(v) for v in participants)
                       if participants is not None else None)
    sigs = []
    for lane, pubkey in enumerate(state.current_sync_committee.pubkeys):
        index = state.validators.find_pubkey(bytes(pubkey))
        if index is None:
            continue
        if participant_set is not None and index not in participant_set:
            continue
        bits[lane] = True
        sigs.append(bls.Sign(validator_secret_key(index), signing_root))
    if not sigs:
        return SyncAggregate()
    return SyncAggregate(sync_committee_bits=bits,
                         sync_committee_signature=bls.Aggregate(sigs))


def build_block(parent_state: BeaconState, slot: int, attestations=(),
                attester_slashings=(), deposits=(), voluntary_exits=(),
                graffiti: bytes = b"\x00" * 32,
                execution_payload=None, sync_aggregate=None) -> SignedBeaconBlock:
    """Produce a valid signed block for ``slot`` on top of ``parent_state``.

    Follows the proposer duty of pos-evolution.md:597: run the state forward,
    pick the proposer, reveal RANDAO, pack operations, then fill in the
    post-state root (pos-evolution.md:423 check).
    """
    state = advance_state_to_slot(parent_state, slot)
    proposer_index = get_beacon_proposer_index(state)
    epoch = get_current_epoch(state)

    sk = validator_secret_key(proposer_index)
    randao_reveal = bls.Sign(
        sk, compute_signing_root(epoch, get_domain(state, DOMAIN_RANDAO), uint64))

    body = BeaconBlockBody(
        randao_reveal=randao_reveal,
        eth1_data=state.eth1_data.copy(),
        graffiti=graffiti,
        attestations=list(attestations),
        attester_slashings=list(attester_slashings),
        deposits=list(deposits),
        voluntary_exits=list(voluntary_exits),
    )
    if execution_payload is not None:
        body.execution_payload = execution_payload
    if sync_aggregate is not None:
        body.sync_aggregate = sync_aggregate
    block = BeaconBlock(
        slot=slot,
        proposer_index=proposer_index,
        parent_root=hash_tree_root(state.latest_block_header),
        state_root=b"\x00" * 32,
        body=body,
    )
    # Compute the post-state root by applying the block to the advanced state.
    post = state.copy()
    process_block(post, block)
    block.state_root = hash_tree_root(post)
    return sign_block(state, block)


def make_attestation_data(state: BeaconState, slot: int, index: int,
                          head_root: bytes) -> AttestationData:
    """Combined GHOST + FFG vote (pos-evolution.md:681-683, 689-696).

    ``state`` must be (a copy of) the head state advanced to ``slot``.
    """
    epoch = compute_epoch_at_slot(slot)
    start_slot = compute_start_slot_at_epoch(epoch)
    if start_slot == int(state.slot):
        epoch_boundary_root = bytes(head_root)
    else:
        epoch_boundary_root = get_block_root_at_slot(state, start_slot)
    return AttestationData(
        slot=slot,
        index=index,
        beacon_block_root=bytes(head_root),
        source=state.current_justified_checkpoint.copy(),
        target=Checkpoint(epoch=epoch, root=epoch_boundary_root),
    )


def sign_attestation_data(state: BeaconState, data: AttestationData,
                          validator_index: int) -> bytes:
    domain = get_domain(state, DOMAIN_BEACON_ATTESTER, int(data.target.epoch))
    signing_root = compute_signing_root(data, domain)
    return bls.Sign(validator_secret_key(validator_index), signing_root)


def make_committee_attestation(state: BeaconState, slot: int, index: int,
                               head_root: bytes,
                               participants: np.ndarray | None = None) -> Attestation:
    """Aggregate attestation by (a subset of) committee ``index`` at ``slot``."""
    committee = get_beacon_committee(state, slot, index)
    data = make_attestation_data(state, slot, index, head_root)
    bits = np.zeros(committee.shape[0], dtype=bool)
    sigs = []
    participant_set = set(int(v) for v in participants) if participants is not None else None
    for pos, vidx in enumerate(committee):
        vidx = int(vidx)
        if participant_set is not None and vidx not in participant_set:
            continue
        bits[pos] = True
        sigs.append(sign_attestation_data(state, data, vidx))
    if not sigs:
        raise ValueError("no participants in committee")
    return Attestation(aggregation_bits=bits, data=data, signature=bls.Aggregate(sigs))


def attest_all_committees(state: BeaconState, slot: int, head_root: bytes,
                          participants: np.ndarray | None = None) -> list[Attestation]:
    """One aggregate per committee of ``slot`` (full or masked participation)."""
    epoch = compute_epoch_at_slot(slot)
    count = get_committee_count_per_slot(state, epoch)
    out = []
    for index in range(count):
        try:
            out.append(make_committee_attestation(state, slot, index, head_root,
                                                  participants))
        except ValueError:
            continue
    return out
