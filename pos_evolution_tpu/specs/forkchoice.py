"""Fork choice (L4): the HLMD-GHOST store and handlers.

Implements the fork-choice spoiler of the reference
(pos-evolution.md:884-1126): ``Store`` (:889-901), ``get_forkchoice_store``
(:1077-1095), ``on_tick`` (:934-955, bouncing-attack promotion),
``on_attestation`` (:963-979 and the ``is_from_block`` variant :1423-1428),
``on_block`` (:986-1036, proposer boost :1020-1024),
``should_update_justified_checkpoint`` (:1046-1062), ``get_head``
(:1102-1116), ``update_latest_messages`` with equivocation discounting
(:1435-1441), and ``on_attester_slashing`` (:1447-1461).

Handler atomicity (pos-evolution.md:1041: invalid handler calls must not
modify the store) is guaranteed structurally: every handler performs all
validation before its first store mutation.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from pos_evolution_tpu.config import GENESIS_EPOCH, cfg
from pos_evolution_tpu.specs.containers import (
    Attestation,
    AttesterSlashing,
    BeaconBlock,
    BeaconState,
    Checkpoint,
    LatestMessage,
    SignedBeaconBlock,
)
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_current_epoch,
    get_indexed_attestation,
    get_total_active_balance,
    is_slashable_attestation_data,
    is_valid_indexed_attestation,
)
from pos_evolution_tpu.specs.transition import process_slots, state_transition
from pos_evolution_tpu.ssz import hash_tree_root


@dataclass
class Store:
    """A validator's view G (pos-evolution.md:889-901)."""

    time: int
    genesis_time: int
    justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint
    best_justified_checkpoint: Checkpoint
    proposer_boost_root: bytes = b"\x00" * 32
    equivocating_indices: set = field(default_factory=set)
    blocks: dict = field(default_factory=dict)            # Root -> BeaconBlock
    block_states: dict = field(default_factory=dict)      # Root -> BeaconState
    checkpoint_states: dict = field(default_factory=dict)  # (epoch, root) -> BeaconState
    latest_messages: dict = field(default_factory=dict)   # ValidatorIndex -> LatestMessage
    # PoW-chain view for merge-transition validation; None falls back to the
    # module-level default registry in specs.merge (Simulation installs a
    # fresh per-instance view so sims never share PoW state).
    pow_chain: object = None
    # Data-availability view (das/engine.BlobStore): when attached, on_block
    # refuses blocks whose committed blob sidecars this view has not
    # verified — the DAS analogue of the merge payload gate. Like pow_chain
    # it is a live per-view object, never serialized (the driver reattaches
    # it on resume).
    blob_store: object = None
    # Protocol-variant overlay (variants/base.VariantVoteLog): when a
    # successor variant (Goldfish/RLMD-GHOST/SSF, DESIGN.md §16) drives the
    # simulation, the handlers notify it of every applied vote POST-commit
    # so the variant's slot-granular tables stay exactly in sync with this
    # view — gossip, block-carried and backfilled attestations alike. None
    # (the Gasper default) keeps the handlers byte-identical to the spec.
    variant_view: object = None


def get_forkchoice_store(anchor_state: BeaconState, anchor_block: BeaconBlock,
                         pow_chain: object = None) -> Store:
    """Init from a trusted anchor (pos-evolution.md:1077-1095); the anchor is
    genesis or a weak-subjectivity checkpoint (:1221)."""
    assert bytes(anchor_block.state_root) == hash_tree_root(anchor_state), \
        "anchor block/state mismatch"
    anchor_root = hash_tree_root(anchor_block)
    anchor_epoch = get_current_epoch(anchor_state)
    justified = Checkpoint(epoch=anchor_epoch, root=anchor_root)
    finalized = Checkpoint(epoch=anchor_epoch, root=anchor_root)
    return Store(
        time=int(anchor_state.genesis_time) + cfg().seconds_per_slot * int(anchor_state.slot),
        genesis_time=int(anchor_state.genesis_time),
        justified_checkpoint=justified,
        finalized_checkpoint=finalized,
        best_justified_checkpoint=justified,
        blocks={anchor_root: anchor_block.copy()},
        block_states={anchor_root: anchor_state.copy()},
        checkpoint_states={justified.as_key(): anchor_state.copy()},
        pow_chain=pow_chain,
    )


# --- time helpers -------------------------------------------------------------

def get_slots_since_genesis(store: Store) -> int:
    return (store.time - store.genesis_time) // cfg().seconds_per_slot


def get_current_slot(store: Store) -> int:
    return get_slots_since_genesis(store)


def compute_slots_since_epoch_start(slot: int) -> int:
    return slot - compute_start_slot_at_epoch(compute_epoch_at_slot(slot))


# --- tree walks ---------------------------------------------------------------

def get_ancestor(store: Store, root: bytes, slot: int) -> bytes:
    """Walk parents until ``slot`` (pos-evolution.md:953, 1005, 1058).

    A store initialized from a weak-subjectivity checkpoint (:1216) is
    anchored mid-chain: history below the anchor does not exist in this
    view. Asking for an ancestor older than the anchor answers with the
    anchor itself — the deepest known ancestor — rather than crashing on
    the missing parent (every known block descends from the anchor, so
    checkpoint-descent checks against it remain correct). Genesis-anchored
    stores never take this branch: the walk stops at slot 0 first."""
    root = bytes(root)
    block = store.blocks[root]
    while int(block.slot) > slot:
        parent = bytes(block.parent_root)
        if parent not in store.blocks:
            return root
        root = parent
        block = store.blocks[root]
    return root


def get_checkpoint_block(store: Store, root: bytes, epoch: int) -> bytes:
    return get_ancestor(store, root, compute_start_slot_at_epoch(epoch))


# --- weights ------------------------------------------------------------------

def justified_checkpoint_state(store: Store) -> BeaconState:
    """The justified checkpoint's state, materialized on demand.

    The cache is normally filled by ``on_attestation`` (whose targets led
    justification there), but a checkpoint-synced store can have its
    justified checkpoint advanced by BACKFILLED blocks before any
    attestation targeting it arrives — compute and commit the state then,
    exactly as ``compute_target_checkpoint_state`` would have."""
    key = store.justified_checkpoint.as_key()
    state = store.checkpoint_states.get(key)
    if state is None:
        state = compute_target_checkpoint_state(store,
                                                store.justified_checkpoint)
        store.checkpoint_states[key] = state
    return state


def get_proposer_boost(store: Store) -> int:
    """Boost fraction of one slot's committee weight W (pos-evolution.md:1355:
    W/4 mainline; the attack analyses use 0.7W/0.8W)."""
    justified_state = justified_checkpoint_state(store)
    committee_weight = get_total_active_balance(justified_state) // cfg().slots_per_epoch
    return committee_weight * cfg().proposer_score_boost_percent // 100


def get_latest_attesting_balance(store: Store, root: bytes) -> int:
    """Σ effective balance whose latest message is in ``root``'s subtree,
    skipping equivocators, plus proposer boost (pos-evolution.md:322, 916,
    1116, 1438)."""
    root = bytes(root)
    state = justified_checkpoint_state(store)
    block_slot = int(store.blocks[root].slot)
    reg = state.validators
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    attestation_score = 0
    for i, message in store.latest_messages.items():
        if i in store.equivocating_indices:
            continue
        if i >= len(reg):
            continue
        active = reg.activation_epoch[i] <= current_epoch < reg.exit_epoch[i]
        if not active or reg.slashed[i]:
            continue
        if message.root not in store.blocks:
            continue
        if get_ancestor(store, message.root, block_slot) == root:
            attestation_score += int(reg.effective_balance[i])

    boost_score = 0
    if store.proposer_boost_root != b"\x00" * 32:
        if get_ancestor(store, store.proposer_boost_root, block_slot) == root:
            boost_score = get_proposer_boost(store)
    return attestation_score + boost_score


# --- viable-branch filtering (pos-evolution.md:874-880, 1104-1106) ------------

def _leaf_is_viable(store: Store, root: bytes) -> bool:
    """A leaf is viable when its chain's justified view has caught up to the
    store's (pos-evolution.md:874-880): its voting source matches the
    store's justified epoch (with a 2-epoch catch-up grace so anchors
    resumed mid-chain stay viable), and it descends from the store's
    finalized checkpoint."""
    head_state = store.block_states[root]
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    voting_source = head_state.current_justified_checkpoint
    correct_justified = (
        int(store.justified_checkpoint.epoch) == GENESIS_EPOCH
        or int(voting_source.epoch) == int(store.justified_checkpoint.epoch)
        or int(voting_source.epoch) + 2 >= current_epoch)
    finalized_slot = compute_start_slot_at_epoch(int(store.finalized_checkpoint.epoch))
    correct_finalized = (
        int(store.finalized_checkpoint.epoch) == GENESIS_EPOCH
        or (int(store.blocks[root].slot) > finalized_slot
            and get_ancestor(store, root, finalized_slot)
            == bytes(store.finalized_checkpoint.root)))
    return correct_justified and correct_finalized


def get_filtered_block_tree(store: Store) -> dict:
    """Subtree rooted at the justified checkpoint, pruned to branches whose
    leaves carry the store's justified/finalized view.

    Iterative post-order traversal: long-running simulations grow chains
    past Python's recursion limit (~1000 frames), so no recursion here.
    """
    base = bytes(store.justified_checkpoint.root)
    children: dict[bytes, list[bytes]] = {}
    for root, block in store.blocks.items():
        children.setdefault(bytes(block.parent_root), []).append(root)

    from pos_evolution_tpu.utils.traversal import postorder

    blocks: dict[bytes, BeaconBlock] = {}
    keep: dict[bytes, bool] = {}
    for root in postorder(children, base):
        kids = children.get(root, [])
        if kids:
            keep[root] = any(keep[k] for k in kids)
        else:
            keep[root] = _leaf_is_viable(store, root)
        if keep[root]:
            blocks[root] = store.blocks[root]
    return blocks


def get_head(store: Store) -> bytes:
    """HLMD-GHOST greedy descent (pos-evolution.md:1102-1116)."""
    blocks = get_filtered_block_tree(store)
    head = bytes(store.justified_checkpoint.root)
    children_of: dict[bytes, list[bytes]] = {}
    for root, block in blocks.items():
        children_of.setdefault(bytes(block.parent_root), []).append(root)
    while True:
        children = children_of.get(head, [])
        if not children:
            return head
        # max by (weight, root): lexicographic tie-break on the root
        head = max(children,
                   key=lambda r: (get_latest_attesting_balance(store, r), r))


# --- handlers -----------------------------------------------------------------

def on_tick(store: Store, time: int) -> None:
    """pos-evolution.md:934-955."""
    previous_slot = get_current_slot(store)
    store.time = int(time)
    current_slot = get_current_slot(store)

    if current_slot > previous_slot:
        store.proposer_boost_root = b"\x00" * 32

    if not (current_slot > previous_slot
            and compute_slots_since_epoch_start(current_slot) == 0):
        return

    # Epoch boundary: promote best_justified (bouncing-attack defense :1043).
    if int(store.best_justified_checkpoint.epoch) > int(store.justified_checkpoint.epoch):
        finalized_slot = compute_start_slot_at_epoch(int(store.finalized_checkpoint.epoch))
        ancestor = get_ancestor(store, store.best_justified_checkpoint.root, finalized_slot)
        if ancestor == bytes(store.finalized_checkpoint.root):
            store.justified_checkpoint = store.best_justified_checkpoint


def validate_on_attestation(store: Store, attestation: Attestation,
                            is_from_block: bool) -> None:
    """pos-evolution.md:970 contract."""
    target = attestation.data.target
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    previous_epoch = current_epoch - 1 if current_epoch > GENESIS_EPOCH else GENESIS_EPOCH
    assert int(target.epoch) in (current_epoch, previous_epoch), "target epoch not recent"
    assert int(target.epoch) == compute_epoch_at_slot(int(attestation.data.slot))
    assert bytes(target.root) in store.blocks, "unknown target block"
    beacon_block_root = bytes(attestation.data.beacon_block_root)
    assert beacon_block_root in store.blocks, "unknown head block"
    assert int(store.blocks[beacon_block_root].slot) <= int(attestation.data.slot), \
        "attestation head from the future"
    target_slot = compute_start_slot_at_epoch(int(target.epoch))
    assert bytes(target.root) == get_ancestor(store, beacon_block_root, target_slot), \
        "LMD vote inconsistent with FFG target"
    if not is_from_block:
        assert get_current_slot(store) >= int(attestation.data.slot) + 1, \
            "attestation from current slot"


def compute_target_checkpoint_state(store: Store, target: Checkpoint) -> BeaconState:
    base_state = store.block_states[bytes(target.root)].copy()
    target_slot = compute_start_slot_at_epoch(int(target.epoch))
    if int(base_state.slot) < target_slot:
        process_slots(base_state, target_slot)
    return base_state


def update_latest_messages(store: Store, attesting_indices, attestation: Attestation) -> None:
    """LMD table update skipping equivocators (pos-evolution.md:1435-1441)."""
    target = attestation.data.target
    beacon_block_root = bytes(attestation.data.beacon_block_root)
    for i in attesting_indices:
        i = int(i)
        if i in store.equivocating_indices:
            continue
        prev = store.latest_messages.get(i)
        if prev is None or int(target.epoch) > prev.epoch:
            store.latest_messages[i] = LatestMessage(epoch=int(target.epoch),
                                                     root=beacon_block_root)


def on_attestation(store: Store, attestation: Attestation,
                   is_from_block: bool = False):
    """pos-evolution.md:963-979 / :1423-1428.

    Returns the attesting indices (the pyspec handler returns None; the
    value is surplus for spec fidelity but lets accelerated mirrors
    forward the vote batch without re-deriving the committee)."""
    validate_on_attestation(store, attestation, is_from_block)
    target_key = attestation.data.target.as_key()
    if target_key in store.checkpoint_states:
        target_state = store.checkpoint_states[target_key]
        commit_checkpoint_state = None
    else:
        target_state = compute_target_checkpoint_state(store, attestation.data.target)
        commit_checkpoint_state = target_state

    indexed_attestation = get_indexed_attestation(target_state, attestation)
    assert is_valid_indexed_attestation(target_state, indexed_attestation), \
        "invalid indexed attestation"

    # Validation done — commit mutations (atomicity contract :1041).
    if commit_checkpoint_state is not None:
        store.checkpoint_states[target_key] = commit_checkpoint_state
    update_latest_messages(store, indexed_attestation.attesting_indices, attestation)
    if store.variant_view is not None:
        # variant overlay (DESIGN.md §16): slot-granular vote record for
        # the expiry-windowed successor protocols — post-commit, so a
        # rejected attestation never reaches the overlay
        store.variant_view.note_vote(
            indexed_attestation.attesting_indices,
            int(attestation.data.slot),
            bytes(attestation.data.beacon_block_root))
    return indexed_attestation.attesting_indices


def should_update_justified_checkpoint(store: Store,
                                       new_justified_checkpoint: Checkpoint) -> bool:
    """Bouncing-attack mitigation (pos-evolution.md:1046-1062)."""
    if compute_slots_since_epoch_start(get_current_slot(store)) \
            < cfg().safe_slots_to_update_justified:
        return True
    justified_slot = compute_start_slot_at_epoch(int(store.justified_checkpoint.epoch))
    if get_ancestor(store, new_justified_checkpoint.root, justified_slot) \
            != bytes(store.justified_checkpoint.root):
        return False
    return True


def on_block(store: Store, signed_block: SignedBeaconBlock) -> None:
    """pos-evolution.md:986-1036."""
    c = cfg()
    block = signed_block.message
    parent_root = bytes(block.parent_root)
    assert parent_root in store.block_states, "unknown parent"
    pre_state = store.block_states[parent_root]
    assert get_current_slot(store) >= int(block.slot), "block from the future"

    finalized_slot = compute_start_slot_at_epoch(int(store.finalized_checkpoint.epoch))
    assert int(block.slot) > finalized_slot, "block at or before finalized slot"
    assert get_ancestor(store, parent_root, finalized_slot) \
        == bytes(store.finalized_checkpoint.root), "not a descendant of finalized"

    # [DAS] availability gate (das/, DESIGN.md §15): a block whose graffiti
    # commits to blob sidecars imports only once this view holds and has
    # verified all of them — same shape as the merge payload gate below,
    # and before the (expensive) state transition like the spec's
    # is_data_available check.
    if store.blob_store is not None:
        assert store.blob_store.is_available(hash_tree_root(block), block), \
            "blob data not available"

    # Full state transition on a copy (pos-evolution.md:1009).
    state = pre_state.copy()
    state_transition(state, signed_block, True)

    # [New in Bellatrix] merge-transition validation (pos-evolution.md:1011-1013).
    from pos_evolution_tpu.specs.merge import (
        is_merge_transition_block, validate_merge_block)
    if is_merge_transition_block(pre_state, block.body):
        validate_merge_block(block, pow_view=store.pow_chain)

    block_root = hash_tree_root(block)
    store.blocks[block_root] = block
    store.block_states[block_root] = state

    # Proposer boost if timely: first 1/3 of the slot (pos-evolution.md:1020-1024).
    time_into_slot = (store.time - store.genesis_time) % c.seconds_per_slot
    is_before_attesting_interval = time_into_slot < c.seconds_per_slot // c.intervals_per_slot
    if get_current_slot(store) == int(block.slot) and is_before_attesting_interval:
        store.proposer_boost_root = block_root

    # Justified / finalized checkpoint updates (pos-evolution.md:1026-1036).
    if int(state.current_justified_checkpoint.epoch) > int(store.justified_checkpoint.epoch):
        if int(state.current_justified_checkpoint.epoch) \
                > int(store.best_justified_checkpoint.epoch):
            store.best_justified_checkpoint = state.current_justified_checkpoint
        if should_update_justified_checkpoint(store, state.current_justified_checkpoint):
            store.justified_checkpoint = state.current_justified_checkpoint

    if int(state.finalized_checkpoint.epoch) > int(store.finalized_checkpoint.epoch):
        store.finalized_checkpoint = state.finalized_checkpoint
        store.justified_checkpoint = state.current_justified_checkpoint


def on_block_batch(store: Store, signed_blocks: list) -> None:
    """A parent-linked run of blocks applied as one batch — the req/resp
    backfill / checkpoint-sync path of real clients, with per-block
    semantics exactly those of ``on_block`` (pos-evolution.md:986-1036):
    same asserts, same per-block commit points, and a mid-run failure
    leaves the already-committed prefix in place precisely like the
    sequential loop would. What the batch amortizes:

    - the finalized-descent ``get_ancestor`` walk runs once, for the run's
      first parent. Each later block's parent is the in-run block just
      committed, which descends from the finalized checkpoint by
      induction — including when finalization advances *mid-run*: the new
      finalized root then lies on this very chain, and every remaining
      block descends through it. (The per-block ``slot > finalized_slot``
      assert is still evaluated against the live store.) This turns the
      O(K · chain-depth) backfill walk into O(depth + K).
    - the pre-state is copied once and carried through the run via the
      ``ExecutionBackend``'s ``multi_block_apply``, so the fused block
      sweep's device-resident columns stay hot across consecutive blocks
      instead of re-uploading per block, and only the *stored* snapshots
      are copied.
    """
    c = cfg()
    if not signed_blocks:
        return
    parent_root = bytes(signed_blocks[0].message.parent_root)
    assert parent_root in store.block_states, "unknown parent"
    finalized_slot = compute_start_slot_at_epoch(int(store.finalized_checkpoint.epoch))
    assert get_ancestor(store, parent_root, finalized_slot) \
        == bytes(store.finalized_checkpoint.root), "not a descendant of finalized"

    # Linkage + from-the-future checks for the whole run before any mutation
    # (the sequential loop would also reject these before touching the store).
    prev_root = parent_root
    for sb in signed_blocks:
        block = sb.message
        assert bytes(block.parent_root) == prev_root, "batch not parent-linked"
        assert get_current_slot(store) >= int(block.slot), "block from the future"
        prev_root = hash_tree_root(block)

    from pos_evolution_tpu.backend import get_backend
    from pos_evolution_tpu.specs.merge import (
        is_merge_transition_block, validate_merge_block)

    state = store.block_states[parent_root].copy()
    last_root = prev_root
    merge_flag = [False]

    def pre_block(sb, pre_state):
        block = sb.message
        fslot = compute_start_slot_at_epoch(int(store.finalized_checkpoint.epoch))
        assert int(block.slot) > fslot, "block at or before finalized slot"
        if store.blob_store is not None:
            # same per-block availability gate as on_block; a mid-run
            # unavailable block keeps the committed prefix (prefix-commit
            # contract) exactly like any other per-block reject
            assert store.blob_store.is_available(hash_tree_root(block),
                                                 block), \
                "blob data not available"
        merge_flag[0] = is_merge_transition_block(pre_state, block.body)

    def commit(sb, post_state):
        block = sb.message
        if merge_flag[0]:
            validate_merge_block(block, pow_view=store.pow_chain)
        block_root = hash_tree_root(block)
        store.blocks[block_root] = block
        # the working state keeps advancing; store a snapshot (the run's
        # last block stores the working state itself)
        store.block_states[block_root] = (
            post_state if block_root == last_root else post_state.copy())

        time_into_slot = (store.time - store.genesis_time) % c.seconds_per_slot
        is_before_attesting_interval = \
            time_into_slot < c.seconds_per_slot // c.intervals_per_slot
        if get_current_slot(store) == int(block.slot) and is_before_attesting_interval:
            store.proposer_boost_root = block_root

        if int(post_state.current_justified_checkpoint.epoch) \
                > int(store.justified_checkpoint.epoch):
            if int(post_state.current_justified_checkpoint.epoch) \
                    > int(store.best_justified_checkpoint.epoch):
                store.best_justified_checkpoint = post_state.current_justified_checkpoint
            if should_update_justified_checkpoint(
                    store, post_state.current_justified_checkpoint):
                store.justified_checkpoint = post_state.current_justified_checkpoint
        if int(post_state.finalized_checkpoint.epoch) \
                > int(store.finalized_checkpoint.epoch):
            store.finalized_checkpoint = post_state.finalized_checkpoint
            store.justified_checkpoint = post_state.current_justified_checkpoint

    get_backend().multi_block_apply(state, signed_blocks, validate_result=True,
                                    pre_block=pre_block, on_applied=commit)


# Prefix-commit contract marker: a mid-run reject leaves the committed
# prefix in the store by design (exactly like the sequential loop). The
# debug StoreInvariantChecker honors this instead of flagging a torn write.
on_block_batch.commits_prefix = True


def prune_store(store: Store) -> int:
    """Drop blocks/states that cannot affect fork choice anymore: everything
    not descending from (or equal to) the finalized checkpoint block.

    The reference guarantees the fork-choice never walks behind the
    finalized checkpoint (pos-evolution.md:407: "the fork-choice rule does
    not need to go back more than this checkpoint"), so pruned entries are
    unreachable. Returns the number of blocks removed.
    """
    finalized_root = bytes(store.finalized_checkpoint.root)
    if finalized_root not in store.blocks:
        return 0
    finalized_slot = int(store.blocks[finalized_root].slot)
    keep = set()
    for root in store.blocks:
        try:
            if get_ancestor(store, root, finalized_slot) == finalized_root:
                keep.add(root)
        except KeyError:
            continue
    keep.add(finalized_root)
    dropped = [r for r in store.blocks if r not in keep]
    for r in dropped:
        del store.blocks[r]
        store.block_states.pop(r, None)
    for key in [k for k in store.checkpoint_states
                if k[0] < int(store.finalized_checkpoint.epoch)]:
        del store.checkpoint_states[key]
    return len(dropped)


def on_attester_slashing(store: Store, attester_slashing: AttesterSlashing):
    """Equivocation evidence feeds the discounting set (pos-evolution.md:1447-1461).

    Returns the newly discounted indices (surplus over the pyspec's None
    return, so accelerated mirrors see exactly the set the handler used)."""
    a1, a2 = attester_slashing.attestation_1, attester_slashing.attestation_2
    assert is_slashable_attestation_data(a1.data, a2.data), "not slashable"
    state = store.block_states[bytes(store.justified_checkpoint.root)]
    assert is_valid_indexed_attestation(state, a1)
    assert is_valid_indexed_attestation(state, a2)
    indices = set(int(i) for i in np.asarray(a1.attesting_indices)) \
        & set(int(i) for i in np.asarray(a2.attesting_indices))
    for index in indices:
        store.equivocating_indices.add(index)
    if store.variant_view is not None:
        # variant overlays discount slasher-evidenced equivocators too
        # (pos-evolution.md:1438)
        store.variant_view.note_equivocators(indices)
    return indices
