"""Beacon state transition (L2): ``state_transition`` and block processing.

The single mutation entry point of the reference (pos-evolution.md:412-424)
with slot processing, signature verification, and the per-operation
processors: attestations (:722-755), deposits (:139-175), proposer/attester
slashings (:1154-1162), voluntary exits (:251-259), RANDAO, eth1 data,
sync aggregate (:642), execution payload (:374, simulated).
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import (
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_DEPOSIT,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    WEIGHT_DENOMINATOR,
    cfg,
)
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import (
    Attestation,
    AttesterSlashing,
    BeaconBlock,
    BeaconBlockHeader,
    BeaconState,
    Deposit,
    DepositMessage,
    ProposerSlashing,
    SignedBeaconBlock,
    SignedVoluntaryExit,
    SyncAggregate,
)
from pos_evolution_tpu.specs.epoch import process_epoch
from pos_evolution_tpu.specs.helpers import (
    compute_domain,
    compute_epoch_at_slot,
    compute_signing_root,
    decrease_balance,
    get_attestation_participation_flag_indices,
    get_attesting_indices,
    get_base_reward_per_increment,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    get_domain,
    get_indexed_attestation,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    get_validator_from_deposit,
    increase_balance,
    is_active_validator,
    is_slashable_attestation_data,
    is_slashable_validator,
    is_valid_indexed_attestation,
    slash_validator,
)
from pos_evolution_tpu.ssz import hash_eth2, hash_tree_root, is_valid_merkle_branch
from pos_evolution_tpu.ssz.core import uint64


def state_transition(state: BeaconState, signed_block: SignedBeaconBlock,
                     validate_result: bool = True) -> None:
    """pos-evolution.md:412-424: slots -> signature -> block -> state root."""
    block = signed_block.message
    process_slots(state, int(block.slot))
    if validate_result:
        assert verify_block_signature(state, signed_block), "invalid block signature"
    process_block(state, block)
    if validate_result:
        assert bytes(block.state_root) == hash_tree_root(state), "state root mismatch"


def process_slots(state: BeaconState, slot: int) -> None:
    """Advance through (possibly empty) slots; run epoch processing at
    boundaries (pos-evolution.md:415, 426)."""
    assert state.slot < slot
    c = cfg()
    while state.slot < slot:
        process_slot(state)
        if (int(state.slot) + 1) % c.slots_per_epoch == 0:
            process_epoch(state)
        state.slot = int(state.slot) + 1


def process_slot(state: BeaconState) -> None:
    """Cache the state root and block root for the slot just completed."""
    sphr = state.state_roots.shape[0]
    previous_state_root = hash_tree_root(state)
    state.state_roots[int(state.slot) % sphr] = np.frombuffer(
        previous_state_root, dtype=np.uint8)
    if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
        state.latest_block_header.state_root = previous_state_root
    previous_block_root = hash_tree_root(state.latest_block_header)
    state.block_roots[int(state.slot) % sphr] = np.frombuffer(
        previous_block_root, dtype=np.uint8)


def verify_block_signature(state: BeaconState, signed_block: SignedBeaconBlock) -> bool:
    """pos-evolution.md:418."""
    proposer_pubkey = state.validators.pubkeys[
        int(signed_block.message.proposer_index)].tobytes()
    signing_root = compute_signing_root(
        signed_block.message, get_domain(state, DOMAIN_BEACON_PROPOSER))
    return bls.Verify(proposer_pubkey, signing_root, signed_block.signature)


def process_block(state: BeaconState, block: BeaconBlock) -> None:
    """pos-evolution.md:420 umbrella."""
    process_block_header(state, block)
    process_randao(state, block.body)
    process_eth1_data(state, block.body)
    process_operations(state, block.body)
    process_sync_aggregate(state, block.body.sync_aggregate)
    process_execution_payload(state, block.body)


def process_block_header(state: BeaconState, block: BeaconBlock) -> None:
    assert int(block.slot) == int(state.slot), "block/state slot mismatch"
    assert int(block.slot) > int(state.latest_block_header.slot), "not newer than head"
    assert int(block.proposer_index) == get_beacon_proposer_index(state), "wrong proposer"
    assert bytes(block.parent_root) == hash_tree_root(state.latest_block_header), \
        "parent root mismatch"
    state.latest_block_header = BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x00" * 32,  # overwritten at the next process_slot
        body_root=hash_tree_root(block.body),
    )
    assert not state.validators.slashed[int(block.proposer_index)], "proposer slashed"


def process_randao(state: BeaconState, body) -> None:
    epoch = get_current_epoch(state)
    proposer_pubkey = state.validators.pubkeys[get_beacon_proposer_index(state)].tobytes()
    signing_root = compute_signing_root(epoch, get_domain(state, DOMAIN_RANDAO), uint64)
    assert bls.Verify(proposer_pubkey, signing_root, body.randao_reveal), "bad randao reveal"
    mix = bytes(a ^ b for a, b in zip(get_randao_mix(state, epoch),
                                      hash_eth2(bytes(body.randao_reveal))))
    state.randao_mixes[epoch % state.randao_mixes.shape[0]] = np.frombuffer(
        mix, dtype=np.uint8)


def process_eth1_data(state: BeaconState, body) -> None:
    c = cfg()
    state.eth1_data_votes.append(body.eth1_data)
    period_len = c.epochs_per_eth1_voting_period * c.slots_per_epoch
    votes = sum(1 for v in state.eth1_data_votes if v == body.eth1_data)
    if votes * 2 > period_len:
        state.eth1_data = body.eth1_data


def process_operations(state: BeaconState, body) -> None:
    c = cfg()
    expected_deposits = min(c.max_deposits,
                            int(state.eth1_data.deposit_count) - int(state.eth1_deposit_index))
    assert len(body.deposits) == expected_deposits, "wrong deposit count in block"
    for op in body.proposer_slashings:
        process_proposer_slashing(state, op)
    for op in body.attester_slashings:
        process_attester_slashing(state, op)
    # Attestations: validate sequentially (spec order), then apply the whole
    # block's batch as ONE fused sweep through the ExecutionBackend
    # (ops/transition.py). Bit-identical to the per-attestation reference
    # loop: validation reads only state that attestation application never
    # mutates (committees/seeds, checkpoints, block roots, pubkeys), and the
    # sweep preserves sequential flag/reward semantics within the batch.
    atts = list(body.attestations)
    if atts:
        rows = [_validate_attestation(state, op) for op in atts]
        from pos_evolution_tpu.backend import get_backend
        get_backend().block_sweep(state, rows)
    for op in body.deposits:
        process_deposit(state, op)
    for op in body.voluntary_exits:
        process_voluntary_exit(state, op)


# --- attestations (pos-evolution.md:722-755) ----------------------------------

def _validate_attestation(state: BeaconState, attestation: Attestation):
    """Everything ``process_attestation`` checks before its first mutation.

    Returns the validated row ``(attesting_indices int64[k], flag_indices,
    is_current)`` consumed by the fused sweep
    (``ops/transition.apply_attestation_rows_*``).
    """
    c = cfg()
    data = attestation.data
    assert int(data.target.epoch) in (get_previous_epoch(state), get_current_epoch(state))
    assert int(data.target.epoch) == compute_epoch_at_slot(int(data.slot))
    assert (int(data.slot) + c.min_attestation_inclusion_delay <= int(state.slot)
            <= int(data.slot) + c.slots_per_epoch)
    assert int(data.index) < get_committee_count_per_slot(state, int(data.target.epoch))

    committee = get_beacon_committee(state, int(data.slot), int(data.index))
    bits = np.asarray(attestation.aggregation_bits, dtype=bool)
    assert bits.shape[0] == committee.shape[0], "aggregation bits length mismatch"

    participation_flag_indices = get_attestation_participation_flag_indices(
        state, data, int(state.slot) - int(data.slot))

    assert is_valid_indexed_attestation(
        state, get_indexed_attestation(state, attestation)), "bad attestation signature"

    attesting = get_attesting_indices(state, data, bits).astype(np.int64)
    is_current = int(data.target.epoch) == get_current_epoch(state)
    return attesting, participation_flag_indices, is_current


def process_attestation(state: BeaconState, attestation: Attestation) -> None:
    """Reference-shaped single-attestation entry (validation + host apply).

    Hot paths batch through ``process_operations``; this keeps the spec
    signature for tests and one-off call sites, applying via the NumPy
    oracle sweep (bit-identical to the reference loop :744-749 — the
    per-attester ``get_base_reward`` collapses to the hoisted
    per-increment constant, same integer arithmetic)."""
    row = _validate_attestation(state, attestation)
    from pos_evolution_tpu.ops.transition import apply_attestation_rows_host
    apply_attestation_rows_host(state, [row])


# --- deposits (pos-evolution.md:139-175) --------------------------------------

def process_deposit(state: BeaconState, deposit: Deposit) -> None:
    c = cfg()
    assert is_valid_merkle_branch(
        leaf=hash_tree_root(deposit.data),
        branch=[deposit.proof[i].tobytes() for i in range(deposit.proof.shape[0])],
        depth=c.deposit_contract_tree_depth + 1,  # +1 for the length mix-in
        index=int(state.eth1_deposit_index),
        root=bytes(state.eth1_data.deposit_root),
    ), "invalid deposit proof"

    state.eth1_deposit_index = int(state.eth1_deposit_index) + 1

    pubkey = bytes(deposit.data.pubkey)
    amount = int(deposit.data.amount)
    existing = state.validators.find_pubkey(pubkey)
    if existing is None:
        deposit_message = DepositMessage(
            pubkey=deposit.data.pubkey,
            withdrawal_credentials=deposit.data.withdrawal_credentials,
            amount=deposit.data.amount,
        )
        domain = compute_domain(DOMAIN_DEPOSIT)  # fork-agnostic
        signing_root = compute_signing_root(deposit_message, domain)
        if bls.Verify(pubkey, signing_root, deposit.data.signature):
            state.validators.append(get_validator_from_deposit(state, deposit.data))
            state.balances = np.append(state.balances, np.uint64(amount))
            state.previous_epoch_participation = np.append(
                state.previous_epoch_participation, np.uint8(0))
            state.current_epoch_participation = np.append(
                state.current_epoch_participation, np.uint8(0))
            state.inactivity_scores = np.append(state.inactivity_scores, np.uint64(0))
    else:
        increase_balance(state, existing, amount)


# --- slashings ----------------------------------------------------------------

def process_proposer_slashing(state: BeaconState, slashing: ProposerSlashing) -> None:
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    assert int(h1.slot) == int(h2.slot), "headers from different slots"
    assert int(h1.proposer_index) == int(h2.proposer_index), "different proposers"
    assert h1 != h2, "headers identical"
    proposer_index = int(h1.proposer_index)
    proposer = state.validators[proposer_index]
    assert is_slashable_validator(proposer, get_current_epoch(state))
    for signed_header in (slashing.signed_header_1, slashing.signed_header_2):
        domain = get_domain(state, DOMAIN_BEACON_PROPOSER,
                            compute_epoch_at_slot(int(signed_header.message.slot)))
        signing_root = compute_signing_root(signed_header.message, domain)
        assert bls.Verify(bytes(proposer.pubkey), signing_root, signed_header.signature)
    slash_validator(state, proposer_index)


def process_attester_slashing(state: BeaconState, slashing: AttesterSlashing) -> None:
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    assert is_slashable_attestation_data(a1.data, a2.data), "not slashable"
    assert is_valid_indexed_attestation(state, a1)
    assert is_valid_indexed_attestation(state, a2)
    slashed_any = False
    common = sorted(set(int(i) for i in np.asarray(a1.attesting_indices))
                    & set(int(i) for i in np.asarray(a2.attesting_indices)))
    for index in common:
        if is_slashable_validator(state.validators[index], get_current_epoch(state)):
            slash_validator(state, index)
            slashed_any = True
    assert slashed_any, "no slashable intersection"


def process_voluntary_exit(state: BeaconState, signed_exit: SignedVoluntaryExit) -> None:
    c = cfg()
    exit_msg = signed_exit.message
    index = int(exit_msg.validator_index)
    validator = state.validators[index]
    assert is_active_validator(validator, get_current_epoch(state))
    assert validator.exit_epoch == FAR_FUTURE_EPOCH
    assert get_current_epoch(state) >= int(exit_msg.epoch)
    assert get_current_epoch(state) >= validator.activation_epoch + c.shard_committee_period
    domain = get_domain(state, DOMAIN_VOLUNTARY_EXIT, int(exit_msg.epoch))
    signing_root = compute_signing_root(exit_msg, domain)
    assert bls.Verify(bytes(validator.pubkey), signing_root, signed_exit.signature)
    from pos_evolution_tpu.specs.helpers import initiate_validator_exit
    initiate_validator_exit(state, index)


# --- sync aggregate (pos-evolution.md:642, 548-557) ---------------------------

def process_sync_aggregate(state: BeaconState, aggregate: SyncAggregate) -> None:
    c = cfg()
    bits = np.asarray(aggregate.sync_committee_bits, dtype=bool)
    committee_pubkeys = [bytes(pk) for pk in state.current_sync_committee.pubkeys]
    if bits.shape[0] != len(committee_pubkeys):
        bits = bits[: len(committee_pubkeys)]
    participant_pubkeys = [pk for pk, b in zip(committee_pubkeys, bits) if b]

    previous_slot = max(int(state.slot), 1) - 1
    domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(previous_slot))
    signing_root = compute_signing_root_bytes(
        get_block_root_at_slot(state, previous_slot), domain)
    if participant_pubkeys:
        assert bls.FastAggregateVerify(
            participant_pubkeys, signing_root,
            aggregate.sync_committee_signature), "bad sync aggregate"

    # Rewards: participants and proposer.
    total_active_increments = (get_total_active_balance(state)
                               // c.effective_balance_increment)
    total_base_rewards = get_base_reward_per_increment(state) * total_active_increments
    max_participant_rewards = (total_base_rewards * SYNC_REWARD_WEIGHT
                               // WEIGHT_DENOMINATOR // c.slots_per_epoch)
    committee_size = max(len(committee_pubkeys), 1)
    participant_reward = max_participant_rewards // committee_size
    proposer_reward = (participant_reward * PROPOSER_WEIGHT
                       // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT))
    proposer_index = get_beacon_proposer_index(state)
    for pk, participated in zip(committee_pubkeys, bits):
        idx = state.validators.find_pubkey(pk)
        if idx is None:
            continue
        if participated:
            increase_balance(state, idx, participant_reward)
            increase_balance(state, proposer_index, proposer_reward)
        else:
            decrease_balance(state, idx, participant_reward)


def compute_signing_root_bytes(root: bytes, domain: bytes) -> bytes:
    """Signing root where the object is already a 32-byte root."""
    from pos_evolution_tpu.specs.helpers import SigningData
    return hash_tree_root(SigningData(object_root=root, domain=domain))


def process_execution_payload(state: BeaconState, body) -> None:
    """Simulated execution layer (pos-evolution.md:374, 644): record the
    payload header; consensus-only simulation performs no EL validation."""
    payload = body.execution_payload
    from pos_evolution_tpu.specs.containers import ExecutionPayloadHeader
    tx_sedes = type(payload)._fields["transactions"]
    state.latest_execution_payload_header = ExecutionPayloadHeader(
        parent_hash=payload.parent_hash,
        fee_recipient=payload.fee_recipient,
        state_root=payload.state_root,
        receipts_root=payload.receipts_root,
        logs_bloom=payload.logs_bloom,
        prev_randao=payload.prev_randao,
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=payload.extra_data,
        base_fee_per_gas=payload.base_fee_per_gas,
        block_hash=payload.block_hash,
        transactions_root=hash_tree_root(payload.transactions, tx_sedes),
    )
