"""Slasher (watchtower): accountability made operational (SURVEY.md §2.5).

The reference defines the violations — double votes and surround votes
(pos-evolution.md:233-238, 1128-1143) and equivocating proposals
(:1154-1156) — and notes "the evidence of the violation can be observed"
(:238, 1148). This component does the observing: it ingests indexed
attestations and signed block headers, maintains per-validator vote
histories, and emits ready-to-include ``AttesterSlashing`` /
``ProposerSlashing`` evidence, closing the accountable-safety loop
(detected evidence -> ``process_attester_slashing`` /
``on_attester_slashing`` -> stake slashed + fork-choice discounting).
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from pos_evolution_tpu.specs.containers import (
    AttesterSlashing,
    IndexedAttestation,
    ProposerSlashing,
    SignedBeaconBlockHeader,
)
from pos_evolution_tpu.specs.helpers import is_slashable_attestation_data


class Slasher:
    """Ingests consensus messages, emits slashing evidence."""

    def __init__(self):
        # validator -> target epoch -> [(data_root, IndexedAttestation)]
        self._by_validator: dict[int, dict[int, list[tuple[bytes, IndexedAttestation]]]] = \
            defaultdict(lambda: defaultdict(list))
        # full history per validator for the surround scan
        self._spans: dict[int, list[tuple[int, int, bytes, IndexedAttestation]]] = \
            defaultdict(list)
        # (data_root, validator) pairs already ingested (replay dedup)
        self._seen: set[tuple[bytes, int]] = set()
        # (proposer, slot) -> first signed header seen
        self._headers: dict[tuple[int, int], SignedBeaconBlockHeader] = {}
        self._emitted: set = set()

    # -- attestations ---------------------------------------------------------
    def on_attestation(self, indexed: IndexedAttestation) -> list[AttesterSlashing]:
        """Record an indexed attestation; return any new evidence.

        Data roots are hashed once per ingest and cached with the history;
        replayed (data, validator) pairs are skipped outright.
        """
        out: list[AttesterSlashing] = []
        call_pairs: set = set()
        data = indexed.data
        src, tgt = int(data.source.epoch), int(data.target.epoch)
        data_root_new = self._root(data)

        for v in (int(i) for i in np.asarray(indexed.attesting_indices)):
            if (data_root_new, v) in self._seen:
                continue
            self._seen.add((data_root_new, v))
            # double vote: same target epoch, different data
            for prior_root, prior in self._by_validator[v][tgt]:
                if prior_root != data_root_new \
                        and is_slashable_attestation_data(prior.data, data):
                    out.extend(self._emit(v, prior_root, prior,
                                          data_root_new, indexed, call_pairs))
                    break
            # surround in either direction
            for (ps, pt, prior_root, prior) in self._spans[v]:
                if (ps < src and tgt < pt) or (src < ps and pt < tgt):
                    out.extend(self._emit(v, prior_root, prior,
                                          data_root_new, indexed, call_pairs))
                    break
            self._by_validator[v][tgt].append((data_root_new, indexed))
            self._spans[v].append((src, tgt, data_root_new, indexed))
        return out

    @staticmethod
    def _root(data) -> bytes:
        from pos_evolution_tpu.ssz import hash_tree_root
        return hash_tree_root(data)

    def _emit(self, validator: int, root1: bytes, a1: IndexedAttestation,
              root2: bytes, a2: IndexedAttestation,
              call_pairs: set) -> list[AttesterSlashing]:
        # Keyed per implicated validator, so a *later* equivocator covered
        # by an already-reported data pair still yields evidence. Within one
        # ingest, the *exact aggregate pair* is emitted at most once: a
        # suppressed validator is then necessarily in the emitted pair's
        # intersection (it sits in both aggregates), so no evidence is lost
        # — aggregates that merely share a data root get their own emission.
        key = (validator,) + tuple(sorted((root1, root2)))
        if key in self._emitted:
            return []
        self._emitted.add(key)
        from pos_evolution_tpu.ssz import hash_tree_root
        pair = tuple(sorted((hash_tree_root(a1), hash_tree_root(a2))))
        if pair in call_pairs:
            return []
        call_pairs.add(pair)
        # order so attestation_1 is the surrounding/earlier vote
        if is_slashable_attestation_data(a1.data, a2.data):
            return [AttesterSlashing(attestation_1=a1, attestation_2=a2)]
        return [AttesterSlashing(attestation_1=a2, attestation_2=a1)]

    # -- block headers --------------------------------------------------------
    def on_block_header(self, signed: SignedBeaconBlockHeader) -> ProposerSlashing | None:
        """Record a signed header; equivocating proposals yield evidence."""
        h = signed.message
        key = (int(h.proposer_index), int(h.slot))
        prior = self._headers.get(key)
        if prior is None:
            self._headers[key] = signed
            return None
        if prior.message == h:
            return None
        ekey = ("hdr", key)
        if ekey in self._emitted:
            return None
        self._emitted.add(ekey)
        return ProposerSlashing(signed_header_1=prior, signed_header_2=signed)

    # -- introspection --------------------------------------------------------
    def tracked_validators(self) -> int:
        return len(self._spans)
