"""Weak subjectivity (cross-cutting layer LX; pos-evolution.md:1198-1317).

Long-range attacks rewrite history with old keys (pos-evolution.md:1200);
the mitigation is weak-subjectivity checkpoints that act as new genesis
(:1216): clients reject blocks conflicting with the checkpoint and must
sync from a checkpoint no older than the weak subjectivity period.
"""

from __future__ import annotations

from pos_evolution_tpu.config import ETH_TO_GWEI, cfg
from pos_evolution_tpu.specs.containers import BeaconState, Checkpoint
from pos_evolution_tpu.specs.helpers import (
    compute_epoch_at_slot,
    get_active_validator_indices,
    get_current_epoch,
    get_total_active_balance,
    get_validator_churn_limit,
)


def get_latest_weak_subjectivity_checkpoint_epoch(state: BeaconState,
                                                  safety_decay: float = 0.1) -> int:
    """Latest WS checkpoint epoch for ``state`` (pos-evolution.md:1225-1242)."""
    c = cfg()
    weak_subjectivity_mod = c.min_validator_withdrawability_delay
    val_count = len(get_active_validator_indices(state, get_current_epoch(state)))
    if val_count >= c.min_per_epoch_churn_limit * c.churn_limit_quotient:
        weak_subjectivity_mod += 256 * int((safety_decay * c.churn_limit_quotient / 2) // 256)
    else:
        weak_subjectivity_mod += 256 * int(
            (safety_decay * val_count / (2 * c.min_per_epoch_churn_limit)) // 256)
    finalized = int(state.finalized_checkpoint.epoch)
    return finalized - (finalized % weak_subjectivity_mod)


def compute_weak_subjectivity_period(state: BeaconState) -> int:
    """WS period from churn + top-up bounds (pos-evolution.md:1257-1288).

    E.g. 3,277 epochs (~2 weeks) at >=262,144 validators with D=10%
    (pos-evolution.md:1307-1313).
    """
    c = cfg()
    ws_period = c.min_validator_withdrawability_delay
    N = len(get_active_validator_indices(state, get_current_epoch(state)))
    t = get_total_active_balance(state) // N // ETH_TO_GWEI
    T = c.max_effective_balance // ETH_TO_GWEI
    delta = get_validator_churn_limit(state)
    Delta = c.max_deposits * c.slots_per_epoch
    D = c.safety_decay

    if T * (200 + 3 * D) < t * (200 + 12 * D):
        epochs_for_validator_set_churn = (
            N * (t * (200 + 12 * D) - T * (200 + 3 * D)) // (600 * delta * (2 * t + T)))
        epochs_for_balance_top_ups = N * (200 + 3 * D) // (600 * Delta)
        ws_period += max(epochs_for_validator_set_churn, epochs_for_balance_top_ups)
    else:
        ws_period += 3 * N * D * t // (200 * Delta * (T - t))
    return int(ws_period)


def is_within_weak_subjectivity_period(store, ws_state: BeaconState,
                                       ws_checkpoint: Checkpoint) -> bool:
    """Client-side sync check (pos-evolution.md:1293-1302)."""
    from pos_evolution_tpu.specs.forkchoice import get_current_slot
    assert bytes(ws_state.latest_block_header.state_root) == bytes(ws_checkpoint.root)
    assert compute_epoch_at_slot(int(ws_state.slot)) == int(ws_checkpoint.epoch)
    ws_period = compute_weak_subjectivity_period(ws_state)
    ws_state_epoch = compute_epoch_at_slot(int(ws_state.slot))
    current_epoch = compute_epoch_at_slot(get_current_slot(store))
    return current_epoch <= ws_state_epoch + ws_period


def checkpoint_for_state(ws_state: BeaconState):
    """(state', checkpoint) pair satisfying the sync-gate contract for a
    raw anchor state — the client-side half of checkpoint sync. A state
    fresh off a transition has an EMPTY header state-root cache (it is
    filled by the next ``process_slot``, pos-evolution.md's state-root
    deferral); mimic that here (hash first, then fill) so the gate's
    ``header.state_root == checkpoint.root`` assert (:1295) holds."""
    from pos_evolution_tpu.ssz import hash_tree_root
    if bytes(ws_state.latest_block_header.state_root) == b"\x00" * 32:
        root = hash_tree_root(ws_state)
        ws_state = ws_state.copy()
        ws_state.latest_block_header.state_root = root
    return ws_state, Checkpoint(
        epoch=compute_epoch_at_slot(int(ws_state.slot)),
        root=bytes(ws_state.latest_block_header.state_root))
