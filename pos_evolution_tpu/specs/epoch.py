"""Epoch processing (L2): the full-registry sweeps of SURVEY.md §2.2.

``process_epoch`` umbrella implied by the BeaconState fields
(pos-evolution.md:338-374; SURVEY.md §2.6): justification/finalization
(:793-852), inactivity scores (:369), rewards/penalties (participation
flags :361-362), registry updates (churn :1270), slashings vector (:359),
hysteresis effective-balance updates (:122-133), RANDAO rotation (:357),
participation rotation, sync-committee rotation (:542).

Every sweep is a vectorized pass over the dense registry columns — the
NumPy form of the pmapped/shard_map epoch pass (north-star config #4).
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import (
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    cfg,
)
from pos_evolution_tpu.specs.containers import BeaconState, Checkpoint
from pos_evolution_tpu.specs.helpers import (
    active_validator_mask,
    compute_activation_exit_epoch,
    get_block_root,
    get_current_epoch,
    get_base_reward_per_increment,
    get_previous_epoch,
    get_randao_mix,
    get_total_active_balance,
    get_next_sync_committee,
    initiate_validator_exit,
    is_in_inactivity_leak,
)
from pos_evolution_tpu.ssz.merkle import merkleize_chunks


def process_epoch(state: BeaconState) -> None:
    from pos_evolution_tpu.backend import get_backend
    if getattr(get_backend(), "accelerated_epoch", False):
        _process_epoch_accelerated(state)
        return
    process_justification_and_finalization(state)
    process_inactivity_updates(state)
    process_rewards_and_penalties(state)
    process_registry_updates(state)
    process_slashings(state)
    process_eth1_data_reset(state)
    process_effective_balance_updates(state)
    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_roots_update(state)
    process_participation_flag_updates(state)
    process_sync_committee_updates(state)


def _process_epoch_accelerated(state: BeaconState) -> None:
    """Epoch boundary via the fused device sweep (ops/epoch.py), with exact
    host write-back — bit-identical to the NumPy pipeline above.

    The device kernel covers the O(n) sweeps (justification tallies,
    inactivity, rewards, slashings penalties, hysteresis, flag rotation);
    the host keeps the O(changes) bookkeeping: checkpoint roots, registry
    churn (run against pre-hysteresis effective balances, preserving the
    reference ordering), and the per-epoch resets/rotations.
    """
    from pos_evolution_tpu.backend import get_backend
    import numpy as np

    current_epoch = get_current_epoch(state)
    old_prev_justified = state.previous_justified_checkpoint
    old_cur_justified = state.current_justified_checkpoint

    # Stage the registry on device once; both the sweep and the churn
    # kernel read it (epoch columns and pre-hysteresis effective balances
    # are unchanged between the two).
    from pos_evolution_tpu.ops.epoch import densify
    dense_pre = densify(state)
    out = get_backend().epoch_sweep(state, cfg(), dense=dense_pre)

    # --- justification / finalization bookkeeping (roots live host-side) ---
    if current_epoch > GENESIS_EPOCH + 1:
        state.previous_justified_checkpoint = state.current_justified_checkpoint
        if bool(out.justify_prev):
            state.current_justified_checkpoint = Checkpoint(
                epoch=get_previous_epoch(state),
                root=get_block_root(state, get_previous_epoch(state)))
        if bool(out.justify_cur):
            state.current_justified_checkpoint = Checkpoint(
                epoch=current_epoch, root=get_block_root(state, current_epoch))
        state.justification_bits = np.array(out.new_justification_bits)
        fin = int(out.finalize_epoch)
        if fin >= 0:
            # later finalization cases (which win in the spec) use the old
            # *current* justified checkpoint — check it first
            if fin == int(old_cur_justified.epoch):
                state.finalized_checkpoint = old_cur_justified
            elif fin == int(old_prev_justified.epoch):
                state.finalized_checkpoint = old_prev_justified

    # --- write back sweeps; effective balances AFTER churn (spec order) ---
    reg = out.registry
    state.balances = np.array(reg.balance).astype(np.uint64)
    state.inactivity_scores = np.array(reg.inactivity_scores).astype(np.uint64)
    new_eff = np.array(reg.effective_balance).astype(np.uint64)

    # Registry churn on device too (reads pre-hysteresis effective balances
    # and the *post-sweep* finalized checkpoint, matching the spec order).
    from pos_evolution_tpu.ops.epoch import (
        densify_eligibility, i64_to_epochs, registry_churn_dense,
    )
    churn = registry_churn_dense(
        dense_pre, densify_eligibility(state), current_epoch,
        int(state.finalized_checkpoint.epoch), cfg())

    v = state.validators
    v.activation_eligibility_epoch = i64_to_epochs(churn.activation_eligibility_epoch)
    v.activation_epoch = i64_to_epochs(churn.activation_epoch)
    v.exit_epoch = i64_to_epochs(churn.exit_epoch)
    v.withdrawable_epoch = i64_to_epochs(churn.withdrawable_epoch)
    process_eth1_data_reset(state)
    state.validators.effective_balance = new_eff
    state.previous_epoch_participation = np.array(reg.prev_flags)
    state.current_epoch_participation = np.array(reg.cur_flags)

    process_slashings_reset(state)
    process_randao_mixes_reset(state)
    process_historical_roots_update(state)
    process_sync_committee_updates(state)


# --- justification & finalization (pos-evolution.md:793-852) ------------------

def _unslashed_target_balance(state: BeaconState, epoch: int) -> int:
    """Total effective balance of unslashed TIMELY_TARGET participants."""
    participation = (state.current_epoch_participation
                     if epoch == get_current_epoch(state)
                     else state.previous_epoch_participation)
    mask = (active_validator_mask(state, epoch)
            & (((participation >> np.uint8(TIMELY_TARGET_FLAG_INDEX)) & np.uint8(1)).astype(bool))
            & ~state.validators.slashed)
    total = int(state.validators.effective_balance[mask].sum())
    return max(cfg().effective_balance_increment, total)


def process_justification_and_finalization(state: BeaconState) -> None:
    """pos-evolution.md:793-803 — skip the first two epochs, then weigh."""
    if get_current_epoch(state) <= GENESIS_EPOCH + 1:
        return
    previous_target_balance = _unslashed_target_balance(state, get_previous_epoch(state))
    current_target_balance = _unslashed_target_balance(state, get_current_epoch(state))
    weigh_justification_and_finalization(
        state, get_total_active_balance(state),
        previous_target_balance, current_target_balance)


def weigh_justification_and_finalization(state: BeaconState,
                                         total_active_balance: int,
                                         previous_epoch_target_balance: int,
                                         current_epoch_target_balance: int) -> None:
    """The Casper FFG core (pos-evolution.md:817-852).

    Shift the justification bits, justify prev/current epoch on the
    2/3-stake rule (:830-837), then apply the 4-case 2-finalization rule
    (:842-851).
    """
    previous_epoch = get_previous_epoch(state)
    current_epoch = get_current_epoch(state)
    old_previous_justified = state.previous_justified_checkpoint
    old_current_justified = state.current_justified_checkpoint

    # Shift bits: bit[0] is the current epoch.
    bits = state.justification_bits
    bits[1:] = bits[:-1].copy()
    bits[0] = False
    state.previous_justified_checkpoint = state.current_justified_checkpoint

    if previous_epoch_target_balance * 3 >= total_active_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=previous_epoch, root=get_block_root(state, previous_epoch))
        bits[1] = True
    if current_epoch_target_balance * 3 >= total_active_balance * 2:
        state.current_justified_checkpoint = Checkpoint(
            epoch=current_epoch, root=get_block_root(state, current_epoch))
        bits[0] = True

    # 2-finalization, 4 cases (pos-evolution.md:842-851).
    if bits[1:4].all() and int(old_previous_justified.epoch) + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if bits[1:3].all() and int(old_previous_justified.epoch) + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if bits[0:3].all() and int(old_current_justified.epoch) + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if bits[0:2].all() and int(old_current_justified.epoch) + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


# --- inactivity scores (pos-evolution.md:369) ---------------------------------

def _eligible_mask(state: BeaconState) -> np.ndarray:
    """Active in previous epoch, or slashed and not yet withdrawable."""
    reg = state.validators
    prev = get_previous_epoch(state)
    return active_validator_mask(state, prev) | (
        reg.slashed & (np.uint64(prev + 1) < reg.withdrawable_epoch))


def _target_participating_prev(state: BeaconState) -> np.ndarray:
    prev = get_previous_epoch(state)
    flags = state.previous_epoch_participation
    return (active_validator_mask(state, prev)
            & (((flags >> np.uint8(TIMELY_TARGET_FLAG_INDEX)) & np.uint8(1)).astype(bool))
            & ~state.validators.slashed)


def process_inactivity_updates(state: BeaconState) -> None:
    if get_current_epoch(state) == GENESIS_EPOCH:
        return
    c = cfg()
    eligible = _eligible_mask(state)
    participating = _target_participating_prev(state)
    scores = state.inactivity_scores.astype(np.int64)
    scores = np.where(eligible & participating, np.maximum(scores - 1, 0), scores)
    scores = np.where(eligible & ~participating, scores + c.inactivity_score_bias, scores)
    if not is_in_inactivity_leak(state):
        scores = np.where(eligible,
                          scores - np.minimum(scores, c.inactivity_score_recovery_rate),
                          scores)
    state.inactivity_scores = scores.astype(np.uint64)


# --- rewards & penalties (Altair flag deltas, vectorized) ---------------------

def process_rewards_and_penalties(state: BeaconState) -> None:
    if get_current_epoch(state) == GENESIS_EPOCH:
        return
    c = cfg()
    reg = state.validators
    n = len(reg)
    eligible = _eligible_mask(state)
    prev = get_previous_epoch(state)
    eff = reg.effective_balance.astype(np.int64)
    base_reward = (eff // c.effective_balance_increment) * get_base_reward_per_increment(state)

    total_active = get_total_active_balance(state)
    active_increments = total_active // c.effective_balance_increment
    in_leak = is_in_inactivity_leak(state)

    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    from pos_evolution_tpu.config import WEIGHT_DENOMINATOR
    flags = state.previous_epoch_participation
    active_prev = active_validator_mask(state, prev)

    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        participating = (active_prev
                         & (((flags >> np.uint8(flag_index)) & np.uint8(1)).astype(bool))
                         & ~reg.slashed)
        participating_increments = int(
            reg.effective_balance[participating].sum()) // c.effective_balance_increment
        gets_reward = eligible & participating
        if not in_leak:
            numer = base_reward * weight * participating_increments
            denom = active_increments * WEIGHT_DENOMINATOR
            rewards += np.where(gets_reward, numer // denom, 0)
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties += np.where(eligible & ~participating,
                                  base_reward * weight // WEIGHT_DENOMINATOR, 0)

    # Inactivity penalties (quadratic leak) for non-target-participants.
    target_participating = _target_participating_prev(state)
    scores = state.inactivity_scores.astype(np.int64)
    inactivity_penalty = (eff * scores
                          // (c.inactivity_score_bias * c.inactivity_penalty_quotient))
    penalties += np.where(eligible & ~target_participating, inactivity_penalty, 0)

    balances = state.balances.astype(np.int64)
    balances = np.maximum(balances + rewards - penalties, 0)
    state.balances = balances.astype(np.uint64)


# --- registry updates ---------------------------------------------------------

def process_registry_updates(state: BeaconState) -> None:
    c = cfg()
    reg = state.validators
    current_epoch = get_current_epoch(state)

    # Eligibility: fresh validators at max effective balance join the queue.
    newly_eligible = ((reg.activation_eligibility_epoch == np.uint64(FAR_FUTURE_EPOCH))
                      & (reg.effective_balance == np.uint64(c.max_effective_balance)))
    reg.activation_eligibility_epoch[newly_eligible] = current_epoch + 1

    # Ejections: active validators that fell to the ejection balance.
    ejectable = (active_validator_mask(state, current_epoch)
                 & (reg.effective_balance <= np.uint64(c.ejection_balance)))
    for idx in np.nonzero(ejectable)[0]:
        initiate_validator_exit(state, int(idx))

    # Dequeue up to churn limit, ordered by (eligibility epoch, index).
    finalized = int(state.finalized_checkpoint.epoch)
    queued = np.nonzero(
        (reg.activation_eligibility_epoch <= np.uint64(finalized))
        & (reg.activation_epoch == np.uint64(FAR_FUTURE_EPOCH)))[0]
    if queued.size:
        order = np.lexsort((queued, reg.activation_eligibility_epoch[queued]))
        from pos_evolution_tpu.specs.helpers import get_validator_churn_limit
        dequeued = queued[order][: get_validator_churn_limit(state)]
        reg.activation_epoch[dequeued] = compute_activation_exit_epoch(current_epoch)


# --- slashings sweep ----------------------------------------------------------

def process_slashings(state: BeaconState) -> None:
    c = cfg()
    reg = state.validators
    epoch = get_current_epoch(state)
    total_balance = get_total_active_balance(state)
    adjusted_total = min(int(state.slashings.sum()) * c.proportional_slashing_multiplier,
                         total_balance)
    vector_len = state.slashings.shape[0]
    hit = reg.slashed & (np.uint64(epoch + vector_len // 2) == reg.withdrawable_epoch)
    if not hit.any():
        return
    increment = c.effective_balance_increment
    eff = reg.effective_balance.astype(np.int64)
    penalty = (eff // increment * adjusted_total) // total_balance * increment
    balances = state.balances.astype(np.int64)
    state.balances = np.maximum(balances - np.where(hit, penalty, 0), 0).astype(np.uint64)


# --- resets / rotations -------------------------------------------------------

def process_eth1_data_reset(state: BeaconState) -> None:
    c = cfg()
    next_epoch = get_current_epoch(state) + 1
    if next_epoch % c.epochs_per_eth1_voting_period == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(state: BeaconState) -> None:
    """Hysteresis sweep (pos-evolution.md:122-133), fully vectorized."""
    c = cfg()
    reg = state.validators
    hysteresis_increment = c.effective_balance_increment // c.hysteresis_quotient
    downward = hysteresis_increment * c.hysteresis_downward_multiplier
    upward = hysteresis_increment * c.hysteresis_upward_multiplier
    balance = state.balances.astype(np.int64)
    eff = reg.effective_balance.astype(np.int64)
    needs_update = ((balance + downward < eff) | (eff + upward < balance))
    new_eff = np.minimum(balance - balance % c.effective_balance_increment,
                         c.max_effective_balance)
    reg.effective_balance = np.where(needs_update, new_eff, eff).astype(np.uint64)


def process_slashings_reset(state: BeaconState) -> None:
    next_epoch = get_current_epoch(state) + 1
    state.slashings[next_epoch % state.slashings.shape[0]] = 0


def process_randao_mixes_reset(state: BeaconState) -> None:
    vector_len = state.randao_mixes.shape[0]
    current_epoch = get_current_epoch(state)
    next_epoch = current_epoch + 1
    state.randao_mixes[next_epoch % vector_len] = np.frombuffer(
        get_randao_mix(state, current_epoch), dtype=np.uint8)


def process_historical_roots_update(state: BeaconState) -> None:
    c = cfg()
    next_epoch = get_current_epoch(state) + 1
    if next_epoch % (c.slots_per_historical_root // c.slots_per_epoch) == 0:
        block_root = merkleize_chunks(state.block_roots, state.block_roots.shape[0])
        state_root = merkleize_chunks(state.state_roots, state.state_roots.shape[0])
        batch_root = merkleize_chunks(
            np.frombuffer(block_root + state_root, dtype=np.uint8).reshape(2, 32))
        state.historical_roots = np.vstack(
            [state.historical_roots,
             np.frombuffer(batch_root, dtype=np.uint8).reshape(1, 32)])


def process_participation_flag_updates(state: BeaconState) -> None:
    state.previous_epoch_participation = state.current_epoch_participation
    state.current_epoch_participation = np.zeros(len(state.validators), dtype=np.uint8)


def process_sync_committee_updates(state: BeaconState) -> None:
    c = cfg()
    next_epoch = get_current_epoch(state) + 1
    if next_epoch % c.epochs_per_sync_committee_period == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(state)
