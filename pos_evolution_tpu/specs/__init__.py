"""Spec layer: containers, helpers, state transition, fork choice."""
