"""Merge-transition validation (Bellatrix fork-choice additions).

The reference's ``on_block`` consults two helpers when a block crosses the
PoW→PoS boundary (pos-evolution.md:1011-1013)::

    # [New in Bellatrix]
    if is_merge_transition_block(pre_state, block.body):
        validate_merge_block(block)

The document references but does not inline them; this module supplies the
standard Bellatrix semantics. ``validate_merge_block`` needs a view of the
PoW chain to check the terminal block's total difficulty; a real client asks
its execution engine, so the simulator exposes the same seam as a pluggable
provider (default: an in-process registry the tests/scenarios populate).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.specs.containers import (
    BeaconBlock,
    BeaconState,
    ExecutionPayload,
    ExecutionPayloadHeader,
)
from pos_evolution_tpu.specs.helpers import compute_epoch_at_slot

__all__ = [
    "PowBlock",
    "PowChainView",
    "DEFAULT_POW_CHAIN",
    "get_pow_block",
    "set_pow_block_provider",
    "register_pow_block",
    "clear_pow_chain",
    "is_merge_transition_complete",
    "is_merge_transition_block",
    "is_valid_terminal_pow_block",
    "validate_merge_block",
]


@dataclasses.dataclass(frozen=True)
class PowBlock:
    """Minimal PoW-chain view needed for terminal-block validation."""

    block_hash: bytes
    parent_hash: bytes
    total_difficulty: int


# --- pluggable PoW chain provider -------------------------------------------
# ``get_pow_block(hash) -> PowBlock | None`` mirrors the engine-API lookup a
# real client performs. Each ``Store`` may carry its own ``PowChainView``
# (``Simulation`` creates one per instance, so concurrent or sequential sims
# never share PoW state); stores without one fall back to the module-level
# default view that ``register_pow_block``/``set_pow_block_provider`` manage.


class PowChainView:
    """An isolated PoW-chain lookup: a block registry plus an optional
    engine-API-style provider that overrides it."""

    def __init__(self) -> None:
        self.blocks: Dict[bytes, PowBlock] = {}
        self.provider: Optional[Callable[[bytes], Optional[PowBlock]]] = None

    def register(self, block: PowBlock) -> None:
        self.blocks[bytes(block.block_hash)] = block

    def clear(self) -> None:
        self.blocks.clear()

    def set_provider(
        self, provider: Optional[Callable[[bytes], Optional[PowBlock]]]
    ) -> None:
        self.provider = provider

    def get(self, block_hash: bytes) -> Optional[PowBlock]:
        if self.provider is not None:
            return self.provider(bytes(block_hash))
        return self.blocks.get(bytes(block_hash))


DEFAULT_POW_CHAIN = PowChainView()


def register_pow_block(block: PowBlock) -> None:
    DEFAULT_POW_CHAIN.register(block)


def clear_pow_chain() -> None:
    DEFAULT_POW_CHAIN.clear()


def set_pow_block_provider(
    provider: Optional[Callable[[bytes], Optional[PowBlock]]]
) -> None:
    """Install a custom PoW lookup on the default view (None restores the
    registry default)."""
    DEFAULT_POW_CHAIN.set_provider(provider)


def get_pow_block(block_hash: bytes,
                  view: Optional[PowChainView] = None) -> Optional[PowBlock]:
    return (view or DEFAULT_POW_CHAIN).get(bytes(block_hash))


# --- transition predicates ---------------------------------------------------

def is_merge_transition_complete(state: BeaconState) -> bool:
    """True once the state has recorded any non-default payload header."""
    return state.latest_execution_payload_header != ExecutionPayloadHeader()


def is_merge_transition_block(state: BeaconState, body) -> bool:
    """True for the first block carrying a real execution payload
    (pos-evolution.md:1012): pre-state is still pre-merge AND the body's
    payload is non-default."""
    return (not is_merge_transition_complete(state)
            and body.execution_payload != ExecutionPayload())


def is_valid_terminal_pow_block(block: PowBlock, parent: PowBlock) -> bool:
    """The terminal PoW block is the first to reach terminal total
    difficulty: the block is at/over the threshold, its parent under."""
    c = cfg()
    is_total_difficulty_reached = (
        block.total_difficulty >= c.terminal_total_difficulty)
    is_parent_total_difficulty_valid = (
        parent.total_difficulty < c.terminal_total_difficulty)
    return is_total_difficulty_reached and is_parent_total_difficulty_valid


def validate_merge_block(block: BeaconBlock,
                         pow_view: Optional[PowChainView] = None) -> None:
    """Validate the merge-transition block's PoW parent
    (pos-evolution.md:1013).

    With a terminal-block-hash override configured, only the hash and the
    activation epoch are checked; otherwise the PoW parent and grandparent
    must exist and straddle the terminal total difficulty. AssertionError
    on failure, like every other ``on_block`` check — note the reference's
    caveat that a block failing only for an *unavailable* PoW block may
    become valid later (pos-evolution.md:988-990), which the simulator
    surfaces as the distinct message below.
    """
    c = cfg()
    if c.terminal_block_hash != b"\x00" * 32:
        assert (compute_epoch_at_slot(int(block.slot))
                >= c.terminal_block_hash_activation_epoch), \
            "merge block before terminal-block-hash activation epoch"
        assert (bytes(block.body.execution_payload.parent_hash)
                == c.terminal_block_hash), \
            "payload parent is not the configured terminal block"
        return

    pow_block = get_pow_block(bytes(block.body.execution_payload.parent_hash),
                              pow_view)
    assert pow_block is not None, "terminal PoW block unavailable"
    pow_parent = get_pow_block(bytes(pow_block.parent_hash), pow_view)
    assert pow_parent is not None, "terminal PoW parent unavailable"
    assert is_valid_terminal_pow_block(pow_block, pow_parent), \
        "PoW block does not straddle terminal total difficulty"
