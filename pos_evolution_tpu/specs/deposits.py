"""Deposit-tree construction (pos-evolution.md:81-107).

Builds the Merkle-proved deposits the state transition verifies in
``process_deposit`` (pos-evolution.md:139-147): a depth-32 incremental tree
of ``hash_tree_root(DepositData)`` leaves with the list-length mix-in as the
33rd proof element.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import DOMAIN_DEPOSIT, cfg
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import Deposit, DepositData, DepositMessage
from pos_evolution_tpu.specs.helpers import compute_domain, compute_signing_root
from pos_evolution_tpu.ssz import hash_tree_root
from pos_evolution_tpu.ssz.merkle import merkle_tree_branch, merkleize_chunks, mix_in_length


def build_deposit_data(sk: int, withdrawal_credentials: bytes, amount: int) -> DepositData:
    """Signed deposit (proof of possession, pos-evolution.md:156-163)."""
    pubkey = bls.SkToPk(sk)
    message = DepositMessage(pubkey=pubkey,
                             withdrawal_credentials=withdrawal_credentials,
                             amount=amount)
    signing_root = compute_signing_root(message, compute_domain(DOMAIN_DEPOSIT))
    return DepositData(pubkey=pubkey,
                       withdrawal_credentials=withdrawal_credentials,
                       amount=amount,
                       signature=bls.Sign(sk, signing_root))


def build_deposit_tree(deposit_datas: list[DepositData]):
    """Return (deposit_root, [Deposit]) for a batch of deposit data.

    ``deposit_root`` is ``hash_tree_root(List[DepositData, 2**32])`` — the
    eth1 contract root the state checks against; each proof is the depth-32
    branch plus the length chunk (pos-evolution.md:144).
    """
    depth = cfg().deposit_contract_tree_depth
    n = len(deposit_datas)
    leaves = np.frombuffer(
        b"".join(hash_tree_root(d) for d in deposit_datas), dtype=np.uint8
    ).reshape(n, 32) if n else np.empty((0, 32), dtype=np.uint8)
    tree_root = merkleize_chunks(leaves, 2**depth)
    deposit_root = mix_in_length(tree_root, n)
    length_chunk = n.to_bytes(32, "little")
    deposits = []
    for i, data in enumerate(deposit_datas):
        branch = merkle_tree_branch(leaves, i, depth) + [length_chunk]
        proof = np.frombuffer(b"".join(branch), dtype=np.uint8).reshape(depth + 1, 32)
        deposits.append(Deposit(proof=proof, data=data))
    return deposit_root, deposits
