"""Genesis state construction and deterministic test keypairs.

The reference's system model starts from a genesis block at slot 0
(pos-evolution.md:193) with a known validator set (:31). This module builds
a config-sized ``BeaconState`` + anchor ``BeaconBlock`` the way pyspec
genesis tooling does, with all history vectors sized from the active config.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.crypto.bls import bls
from pos_evolution_tpu.specs.containers import (
    BeaconBlock,
    BeaconBlockBody,
    BeaconBlockHeader,
    BeaconState,
    Checkpoint,
    Eth1Data,
    Fork,
    SyncCommittee,
    ValidatorRegistry,
)
from pos_evolution_tpu.ssz import hash_tree_root


def validator_secret_key(index: int) -> int:
    return index + 1


def validator_pubkey(index: int) -> bytes:
    return bls.SkToPk(validator_secret_key(index))


def make_genesis_state(n_validators: int, genesis_time: int = 0) -> BeaconState:
    """Build a genesis BeaconState with ``n_validators`` active at epoch 0."""
    c = cfg()
    reg = ValidatorRegistry(n_validators)
    all_pks = np.zeros((n_validators, 48), dtype=np.uint8)
    for i in range(n_validators):
        all_pks[i] = np.frombuffer(validator_pubkey(i), dtype=np.uint8)
    reg.set_pubkeys(all_pks)
    wc = bytes([0x00]) + bytes(31)  # placeholder withdrawal credentials
    reg.withdrawal_credentials[:] = np.frombuffer(wc, dtype=np.uint8)
    reg.effective_balance[:] = c.max_effective_balance
    reg.activation_eligibility_epoch[:] = 0
    reg.activation_epoch[:] = 0

    state = BeaconState(
        genesis_time=genesis_time,
        slot=0,
        fork=Fork(previous_version=b"\x00" * 4, current_version=b"\x00" * 4, epoch=0),
        latest_block_header=BeaconBlockHeader(
            body_root=hash_tree_root(BeaconBlockBody())),
        block_roots=np.zeros((c.slots_per_historical_root, 32), dtype=np.uint8),
        state_roots=np.zeros((c.slots_per_historical_root, 32), dtype=np.uint8),
        historical_roots=np.zeros((0, 32), dtype=np.uint8),
        eth1_data=Eth1Data(deposit_count=n_validators),
        eth1_deposit_index=n_validators,
        validators=reg,
        balances=np.full(n_validators, c.max_effective_balance, dtype=np.uint64),
        randao_mixes=np.zeros((c.epochs_per_historical_vector, 32), dtype=np.uint8),
        slashings=np.zeros(c.epochs_per_slashings_vector, dtype=np.uint64),
        previous_epoch_participation=np.zeros(n_validators, dtype=np.uint8),
        current_epoch_participation=np.zeros(n_validators, dtype=np.uint8),
        justification_bits=np.zeros(c.justification_bits_length, dtype=bool),
        previous_justified_checkpoint=Checkpoint(),
        current_justified_checkpoint=Checkpoint(),
        finalized_checkpoint=Checkpoint(),
        inactivity_scores=np.zeros(n_validators, dtype=np.uint64),
    )
    state.genesis_validators_root = state.validators.__ssz_root__()

    # Seed the sync committees from the genesis registry (pos-evolution.md:542).
    if n_validators > 0:
        from pos_evolution_tpu.specs.helpers import get_next_sync_committee
        committee = get_next_sync_committee(state)
        state.current_sync_committee = committee
        state.next_sync_committee = get_next_sync_committee(state)
    return state


def make_genesis(n_validators: int, genesis_time: int = 0):
    """Return (genesis_state, anchor_block) consistent for the fork-choice
    store init contract ``anchor_block.state_root == hash_tree_root(state)``
    (pos-evolution.md:1078)."""
    state = make_genesis_state(n_validators, genesis_time)
    anchor = BeaconBlock(
        slot=0,
        proposer_index=0,
        parent_root=b"\x00" * 32,
        state_root=hash_tree_root(state),
        body=BeaconBlockBody(),
    )
    return state, anchor
