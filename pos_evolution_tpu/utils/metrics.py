"""Observability: per-handler tracing + structured per-slot metrics
(SURVEY.md §5 tracing/metrics; absent in the reference, which is prose).

- ``HandlerTimer``: wall-clock tracing of ``on_block`` / ``on_attestation``
  / ``get_head`` with percentile summaries — the north-star fork-choice p50
  metric comes from here.
- ``SlotLog``: the structured per-slot record mirroring the quantities the
  spec itself tracks in state (justification bits pos-evolution.md:364,
  participation flags :361-362, equivocator set :897).
- ``StoreInvariantChecker``: the concurrency-adjacent contract of
  pos-evolution.md:1041 (failed handlers must not modify the store),
  enforced by snapshot/compare around handler calls — the framework's
  "race detector" analogue (the handlers are the only mutation sites).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import numpy as np


class HandlerTimer:
    """Collects wall-clock samples per named handler."""

    def __init__(self):
        self.samples: dict[str, list[float]] = defaultdict(list)

    @contextmanager
    def track(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.samples[name].append(time.perf_counter() - t0)

    def wrap(self, name: str, fn):
        def wrapped(*a, **kw):
            with self.track(name):
                return fn(*a, **kw)
        return wrapped

    def percentile(self, name: str, q: float) -> float:
        """The q-th percentile of ``name``'s samples in SECONDS (NaN when
        empty) — the one accessor every consumer (benches, the profiling
        exporters, ``summary()`` itself) derives p50/p95 from, instead of
        re-implementing percentile math over raw sample lists."""
        xs = self.samples.get(name, [])
        return float(np.percentile(xs, q)) if xs else float("nan")

    def reset(self) -> None:
        """Drop all samples — benches call this after warm-up/compile
        iterations so measured percentiles cover only the steady state."""
        self.samples.clear()

    def summary(self) -> dict:
        # an empty sample list (a handler registered but never hit, or a
        # summary taken right after reset()) is NaN/0, never a crash
        return {
            name: {
                "count": len(xs),
                "p50_ms": round(self.percentile(name, 50) * 1e3, 4),
                "p95_ms": round(self.percentile(name, 95) * 1e3, 4),
                "total_s": round(float(np.sum(xs)), 4) if xs else 0.0,
            }
            for name, xs in self.samples.items()
        }


def slot_record(store, slot: int, head: bytes | None = None) -> dict:
    """Structured per-slot log entry (SURVEY.md §5 metrics).

    ``head`` lets a caller that already ran the head query (the sim
    driver, whose accelerated path answers from the device-resident
    store) pass it in instead of paying a second spec walk."""
    if head is None:
        from pos_evolution_tpu.specs.forkchoice import get_head
        head = get_head(store)
    head_state = store.block_states[head]
    n = len(head_state.validators)
    participation = (
        float((head_state.current_epoch_participation > 0).sum()) / n if n else 0.0)
    return {
        "slot": slot,
        "head_root": head.hex()[:16],
        "head_slot": int(store.blocks[head].slot),
        "justified_epoch": int(store.justified_checkpoint.epoch),
        "finalized_epoch": int(store.finalized_checkpoint.epoch),
        "justification_bits": head_state.justification_bits.astype(int).tolist(),
        "participation": round(participation, 4),
        "n_blocks": len(store.blocks),
        "n_latest_messages": len(store.latest_messages),
        "equivocators": len(store.equivocating_indices),
    }


def light_client_lag_record(lc_store, slot: int, full_head_slot: int,
                            full_finalized_epoch: int) -> dict:
    """Per-slot lag of a light client behind the full node it follows:
    ``head_lag`` in slots (full head vs optimistic header) and
    ``finality_lag`` in epochs (full finalized epoch vs the epoch of the
    client's finalized header). The structured complement of ``slot_record``
    for the thin-client side of the sync protocol."""
    from pos_evolution_tpu.config import cfg
    spe = cfg().slots_per_epoch
    head_slot = int(lc_store.optimistic_header.slot)
    # A checkpoint's block can sit BEFORE its epoch boundary (skipped
    # boundary slot), so round the block slot UP to the epoch it anchors —
    # floor division would report a phantom one-epoch lag. Force-updated
    # headers are arbitrary mid-epoch attested headers, for which the
    # rounding over-credits by at most one epoch; clamp at zero so the lag
    # never goes negative in exactly those lossy scenarios.
    finalized_epoch = (int(lc_store.finalized_header.slot) + spe - 1) // spe
    return {
        "slot": int(slot),
        "lc_head_slot": head_slot,
        "lc_finalized_slot": int(lc_store.finalized_header.slot),
        "head_lag": int(full_head_slot) - head_slot,
        "finality_lag": max(int(full_finalized_epoch) - finalized_epoch, 0),
    }


class StoreInvariantChecker:
    """Wraps fork-choice handlers; on handler exception, verifies the store
    is unchanged (pos-evolution.md:1041) and re-raises."""

    def __init__(self, store):
        self.store = store
        self.violations: list[str] = []

    def _fingerprint(self):
        s = self.store
        return (
            s.time,
            tuple(sorted(s.blocks.keys())),
            tuple(sorted((v, m.epoch, m.root) for v, m in s.latest_messages.items())),
            (int(s.justified_checkpoint.epoch), bytes(s.justified_checkpoint.root)),
            (int(s.finalized_checkpoint.epoch), bytes(s.finalized_checkpoint.root)),
            (int(s.best_justified_checkpoint.epoch),
             bytes(s.best_justified_checkpoint.root)),
            bytes(s.proposer_boost_root),
            frozenset(s.equivocating_indices),
            tuple(sorted(s.checkpoint_states.keys())),
        )

    def call(self, handler, *args, **kwargs):
        before = self._fingerprint()
        try:
            return handler(self.store, *args, **kwargs)
        except AssertionError:
            if getattr(handler, "commits_prefix", False):
                # batch handlers (forkchoice.on_block_batch) document
                # prefix-commit semantics: a mid-run reject leaves every
                # earlier item fully committed — each through the same
                # per-item asserts the atomic handler enforces — so a
                # changed store here is the contract, not a torn write.
                raise
            after = self._fingerprint()
            if before != after:
                self.violations.append(
                    f"{getattr(handler, '__name__', handler)} mutated the store "
                    f"on a failed call")
            raise


@contextmanager
def device_trace(log_dir, annotation: str | None = None):
    """``jax.profiler`` device trace around a code region (SURVEY.md §5:
    per-handler tracing "via jax.profiler traces + host-side counters").

    Writes a TensorBoard/XProf-loadable trace (xplane protobuf) under
    ``log_dir`` covering every device op dispatched inside the region —
    the device-timeline complement to ``HandlerTimer``'s host wall-clock.
    Optionally wraps the region in a named ``TraceAnnotation`` so it is
    findable on the trace timeline.
    """
    import jax

    with jax.profiler.trace(str(log_dir)):
        if annotation is not None:
            with jax.profiler.TraceAnnotation(annotation):
                yield
        else:
            yield


@contextmanager
def trace_region(name: str):
    """Named ``jax.profiler.TraceAnnotation`` region (e.g. per handler:
    ``with trace_region("on_block"): ...``) — visible in any enclosing
    ``device_trace`` timeline; free when no trace is active."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def percentile_ms(xs, q: float) -> float:
    """q-th percentile of a raw sample list in SECONDS, reported in
    MILLISECONDS (NaN when empty) — the free-function twin of
    ``HandlerTimer.percentile`` for consumers that hold their own
    sample lists (the serving tier's latency reservoirs), so percentile
    math isn't re-implemented with subtly different interpolation at
    every call site."""
    if not xs:
        return float("nan")
    return round(float(np.percentile(xs, q)) * 1e3, 4)
