"""Supervised execution with commit-on-arrival partial results.

Long device benches die in ways an in-process try/except cannot always
catch (the round-5 casualty: an LLVM compile OOM killed config #3 chunk 4
and took every completed chunk's number with it). The watchdog's answer is
twofold:

- **commit-on-arrival**: every completed step's result is atomically
  written to a JSON file *immediately*, so whatever kills the process
  later cannot un-measure what already finished;
- **supervision**: each step runs under an optional wall-clock timeout
  (SIGALRM, main-thread only) with bounded retries + exponential backoff;
  a step that still fails is recorded as an *incident* in the same JSON
  and the harness moves on — benches exit 0 with partial results instead
  of dying with none.

Timeout honesty: SIGALRM handlers run between Python bytecodes, so the
timeout interrupts host-side Python hangs but NOT a hang inside native
code (an XLA/LLVM compile loop never yields to the handler until it
returns). For that class of death — OOM kills included — the defense is
commit-on-arrival plus an *external* supervisor (the shell's `timeout`,
a CI step limit): whatever kills the process, the JSON survives.

JSON format (documented in BUILD_NOTES.md):

    {"tag": "...", "started_unix": ..., "updated_unix": ...,
     "completed": {"<step>": <result>, ...},
     "incidents": [{"step", "attempt", "error", "elapsed_s", "unix"}, ...]}
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time


class WatchdogTimeout(Exception):
    """A supervised step exceeded its wall-clock budget."""


# -- run-loop heartbeats (ISSUE 10) --------------------------------------------
#
# The SIGALRM supervision above wraps bench STEPS; a long simulation run
# needs liveness visible from OUTSIDE the process (a hang inside native
# code never returns to any in-process handler, and an OOM-killed
# process answers nothing). The heartbeat is the watchdog's file-based
# leg: the run loop beats once per slot, and the resilience supervisor
# (pos_evolution_tpu/resilience/supervisor.py) kills + resumes a child
# whose heartbeat file stops advancing.

class Heartbeat:
    """Atomic single-file heartbeat: each ``beat`` replaces the file
    with ``{"unix": <now>, ...fields}`` via write + rename, so a reader
    never sees a torn payload and the previous beat survives a kill
    mid-write (the same posture as ``Watchdog.commit``)."""

    def __init__(self, path: str):
        self.path = os.fspath(path)
        self.beats = 0

    def beat(self, **fields) -> None:
        payload = {"unix": round(time.time(), 3), "pid": os.getpid(),
                   **fields}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.write("\n")
        os.replace(tmp, self.path)
        self.beats += 1


def read_heartbeat(path: str) -> dict | None:
    """``{"age_s": <seconds since the last beat>, "payload": {...}}``,
    or None when the file does not exist yet (a child that has not
    reached its run loop is not hung — the supervisor falls back to
    time-since-launch). A torn/unparseable file reads as None too: the
    writer is atomic, so that means no beat has landed."""
    try:
        with open(os.fspath(path)) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return {"age_s": max(time.time() - float(payload.get("unix", 0.0)), 0.0),
            "payload": payload}


def _can_arm(timeout_s) -> bool:
    """Whether a step timeout can actually be armed here: a timeout was
    requested, the platform has SIGALRM, we are on the main thread, and
    no OUTER supervision timer is already running (a nested Watchdog —
    bench_all's config3b step calls bench_config3_real.run(), which has
    its own — must defer to the enclosing timer, not clobber it)."""
    return (bool(timeout_s) and hasattr(signal, "SIGALRM")
            and threading.current_thread() is threading.main_thread()
            and signal.getitimer(signal.ITIMER_REAL)[0] == 0)


def _call_with_timeout(fn, args, kwargs, timeout_s):
    """Run ``fn`` under SIGALRM. Falls back to an unsupervised call when
    no timeout is requested, off the main thread, on platforms without
    SIGALRM, or under an enclosing timer — supervision degrades, it never
    blocks the work."""
    if not _can_arm(timeout_s):
        return fn(*args, **kwargs)

    def _alarm(signum, frame):
        raise WatchdogTimeout(f"step exceeded {timeout_s}s")

    old_handler = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, timeout_s)
    try:
        return fn(*args, **kwargs)
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old_handler)


class Watchdog:
    """Commit-on-arrival step runner for bench harnesses.

    ``path=None`` keeps everything in memory (tests, ad-hoc runs); with a
    path every state change lands on disk via atomic rename, so a crash at
    ANY point leaves a parseable JSON of what completed before it."""

    def __init__(self, path: str | None = None, tag: str = "",
                 timeout_s: float | None = None, retries: int = 0,
                 backoff_s: float = 1.0):
        self.path = path
        self.tag = tag
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.completed: dict[str, object] = {}
        self.incidents: list[dict] = []
        self._started = time.time()
        # NO commit here: the previous run's partial file is exactly the
        # evidence commit-on-arrival exists to preserve — clobbering it
        # with an empty summary before this run completes anything would
        # re-lose it on a retry that dies early. First write happens at
        # the first step completion or incident.

    @classmethod
    def from_env(cls, tag: str, default_path: str,
                 timeout_s: float | None = None) -> "Watchdog":
        """The bench harnesses' shared env contract in one place:
        ``POS_BENCH_PARTIAL`` overrides the partial-results path and
        ``POS_BENCH_STEP_TIMEOUT`` (seconds; 0/unset = off) arms the
        per-step timeout unless the caller passes an explicit one."""
        if timeout_s is None:
            timeout_s = float(os.environ.get("POS_BENCH_STEP_TIMEOUT",
                                             "0")) or None
        return cls(path=os.environ.get("POS_BENCH_PARTIAL", default_path),
                   tag=tag, timeout_s=timeout_s)

    # -- steps -----------------------------------------------------------------

    def step(self, name: str, fn, *args, timeout_s: float | None = None,
             retries: int | None = None, default=None, **kwargs):
        """Run one supervised step. On success the result is recorded
        under ``name`` and committed. On failure (exception or timeout)
        the attempt is retried up to ``retries`` times with exponential
        backoff; if all attempts fail the incident is recorded, committed,
        and ``default`` is returned — the caller keeps going."""
        timeout = self.timeout_s if timeout_s is None else timeout_s
        attempts = (self.retries if retries is None else retries) + 1
        for attempt in range(attempts):
            t0 = time.time()
            armed = _can_arm(timeout)
            try:
                value = _call_with_timeout(fn, args, kwargs, timeout)
            except Exception as e:
                if isinstance(e, WatchdogTimeout) and not armed:
                    raise   # an ENCLOSING supervisor's alarm, not ours —
                            # let it unwind to the step that owns it
                incident = {
                    "step": name,
                    "attempt": attempt,
                    "error": f"{type(e).__name__}: {e}"[:400],
                    "elapsed_s": round(time.time() - t0, 3),
                    "unix": round(time.time(), 3),
                }
                self.incidents.append(incident)
                self.commit()
                from pos_evolution_tpu.telemetry import emit_global
                emit_global("watchdog_incident", tag=self.tag,
                            retries_left=attempts - attempt - 1, **incident)
                if attempt + 1 < attempts:
                    time.sleep(self.backoff_s * 2 ** attempt)
                continue
            self.completed[name] = value
            self.commit()
            return value
        return default

    def failed(self, name: str) -> bool:
        return name not in self.completed and any(
            i["step"] == name for i in self.incidents)

    # -- persistence -----------------------------------------------------------

    def summary(self) -> dict:
        return {
            "tag": self.tag,
            "started_unix": round(self._started, 3),
            "updated_unix": round(time.time(), 3),
            "completed": self.completed,
            "incidents": self.incidents,
        }

    def commit(self) -> None:
        """Atomically persist the current summary (write + rename, so a
        kill mid-commit leaves the previous consistent file in place)."""
        if self.path is None:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.summary(), f, indent=1, default=repr)
            f.write("\n")
        os.replace(tmp, self.path)
