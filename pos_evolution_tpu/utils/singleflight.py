"""Single-flight: concurrent cache misses for one key build ONCE.

The stampede the serving tier must survive: a new block is published,
every sampling client's next request misses the proof-path cache for the
same (block, blob), and — without suppression — each concurrent requester
re-runs the same backing-scheme branch build. ``SingleFlight.do`` lets
the FIRST caller per key run the build while every concurrent caller
blocks on the leader's result (value or exception, shared either way).

The flight entry is removed once the leader finishes, so a LATER call
with the same key builds again — single-flight is stampede suppression,
not a cache; pair it with one (the leader's job is to populate it).

Lives in ``utils/`` (not ``serve/``) on purpose: ``das/server.py`` needs
it too, and ``serve/`` already imports from ``das/`` — this is the
neutral ground that keeps the dependency one-directional.
"""

from __future__ import annotations

import threading
import time

__all__ = ["SingleFlight", "ProcessFlight"]


class _Flight:
    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error: BaseException | None = None


class SingleFlight:
    """Per-key call deduplication for concurrent builders."""

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: dict = {}
        # leaders actually ran the build; waits piggybacked on one
        self.leads = 0
        self.waits = 0

    def do(self, key, fn):
        """Run ``fn()`` once per concurrent set of callers of ``key``;
        every caller gets the leader's result (or its exception)."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
                self.leads += 1
            else:
                leader = False
                self.waits += 1
        if leader:
            try:
                flight.value = fn()
            except BaseException as e:  # share failures too: every
                flight.error = e        # waiter must see the same verdict
                raise
            finally:
                flight.done.set()
                with self._lock:
                    self._flights.pop(key, None)
        else:
            flight.done.wait()
            if flight.error is not None:
                raise flight.error
        return flight.value


class ProcessFlight:
    """Single-flight across PROCESSES: thread dedupe in front of a
    cross-process build lease (``serve/shm.ShmViewBoard``'s lease
    table + proof spools).

    The two-layer shape mirrors the cache story (one per-process LRU,
    one shared build): within a process, concurrent callers of one key
    collapse through a plain ``SingleFlight``; the surviving caller then
    claims the key's lease in the shared segment. Exactly one process
    per concurrent set becomes the **leader** and runs ``fn()`` (the
    real backing build); every other process **waits** on the lease's
    4-byte state word and absorbs the leader's spooled result instead
    of rebuilding — which is what keeps the global build count at one
    per (block, blob) however many processes stampede.

    Failure posture: a leader that dies mid-build (SIGKILL included)
    never wedges waiters — the lease's owner pid goes dead, the next
    claimant takes the build over. A waiter that outlives
    ``timeout_s`` falls back to building locally: duplicate work over
    a wedged request, correctness over dedupe.
    """

    def __init__(self, board, poll_s: float = 0.002,
                 timeout_s: float = 10.0):
        self.board = board
        self.poll_s = float(poll_s)
        self.timeout_s = float(timeout_s)
        self._local = SingleFlight()
        self.leads = 0          # builds this process actually ran
        self.cross_waits = 0    # builds absorbed from another process
        self.takeovers = 0      # dead-leader leases taken over
        self.fallbacks = 0      # waits that timed out into local builds
    # DasServer passes the cache-absorb callback only to flights that
    # can return another process's build
    wants_absorb = True

    @property
    def waits(self) -> int:
        return self._local.waits + self.cross_waits

    def _lead(self, fn, digest, slot):
        self.leads += 1
        try:
            built = fn()
        except BaseException:
            # free the lease: the NEXT miss elects a fresh leader
            # instead of waiting on this failure
            self.board.lease_abort(slot, digest)
            raise
        if slot >= 0:
            self.board.spool_write(digest, built)
            self.board.lease_done(slot, digest)
        return built

    def do(self, key, fn, absorb=None):
        """Run ``fn()`` once per concurrent set of callers of ``key``
        ACROSS processes. ``absorb(built)`` is called (when given) on a
        result that arrived from another process's spool, so the caller
        can populate its per-process cache without counting a build."""
        from pos_evolution_tpu.serve.shm import (
            LEASE_BUILDING,
            LEASE_DONE,
            lease_digest,
        )

        def _cross():
            digest = lease_digest(key)
            deadline = time.monotonic() + self.timeout_s
            while True:
                role, slot = self.board.lease_acquire(digest)
                if role == "lead":
                    return self._lead(fn, digest, slot)
                if role == "done":
                    built = self.board.spool_read(digest)
                    if built is None:
                        # spool GC'd under a stale DONE lease: build
                        # locally rather than loop on a ghost
                        return self._lead(fn, digest, -1)
                    self.cross_waits += 1
                    if absorb is not None:
                        absorb(built)
                    return built
                # role == "wait": poll the lease's state word
                while True:
                    state, pid = self.board.lease_state(slot, digest)
                    if state == LEASE_DONE:
                        break
                    if state != LEASE_BUILDING \
                            or not self.board._alive(pid):
                        self.takeovers += 1
                        break  # freed or dead leader: re-acquire
                    if time.monotonic() > deadline:
                        self.fallbacks += 1
                        return self._lead(fn, digest, -1)
                    time.sleep(self.poll_s)
                if state == LEASE_DONE:
                    built = self.board.spool_read(digest)
                    if built is not None:
                        self.cross_waits += 1
                        if absorb is not None:
                            absorb(built)
                        return built
                # fell out without a result: re-acquire (takeover path)

        return self._local.do(key, _cross)
