"""Single-flight: concurrent cache misses for one key build ONCE.

The stampede the serving tier must survive: a new block is published,
every sampling client's next request misses the proof-path cache for the
same (block, blob), and — without suppression — each concurrent requester
re-runs the same backing-scheme branch build. ``SingleFlight.do`` lets
the FIRST caller per key run the build while every concurrent caller
blocks on the leader's result (value or exception, shared either way).

The flight entry is removed once the leader finishes, so a LATER call
with the same key builds again — single-flight is stampede suppression,
not a cache; pair it with one (the leader's job is to populate it).

Lives in ``utils/`` (not ``serve/``) on purpose: ``das/server.py`` needs
it too, and ``serve/`` already imports from ``das/`` — this is the
neutral ground that keeps the dependency one-directional.
"""

from __future__ import annotations

import threading

__all__ = ["SingleFlight"]


class _Flight:
    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.error: BaseException | None = None


class SingleFlight:
    """Per-key call deduplication for concurrent builders."""

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: dict = {}
        # leaders actually ran the build; waits piggybacked on one
        self.leads = 0
        self.waits = 0

    def do(self, key, fn):
        """Run ``fn()`` once per concurrent set of callers of ``key``;
        every caller gets the leader's result (or its exception)."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
                self.leads += 1
            else:
                leader = False
                self.waits += 1
        if leader:
            try:
                flight.value = fn()
            except BaseException as e:  # share failures too: every
                flight.error = e        # waiter must see the same verdict
                raise
            finally:
                flight.done.set()
                with self._lock:
                    self._flights.pop(key, None)
        else:
            flight.done.wait()
            if flight.error is not None:
                raise flight.error
        return flight.value
