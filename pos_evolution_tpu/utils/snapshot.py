"""Checkpoint / resume (SURVEY.md §5).

The reference's own resume mechanism is the anchor-state store init
(pos-evolution.md:1077-1095) from a finalized or weak-subjectivity
checkpoint — "checkpoints that act as new genesis" (:1216). Simulator
snapshots therefore are SSZ-serialized ``BeaconState`` + anchor
``BeaconBlock`` pairs (optionally the full Store), and resume goes through
``get_forkchoice_store`` exactly like a syncing client.

Dense device arrays (the TPU array level) snapshot via host offload to
``.npz`` — the orbax-style path for registry-scale state.
"""

from __future__ import annotations

import io
import json
import os
import struct

import numpy as np

from pos_evolution_tpu.specs.containers import (
    BeaconBlock,
    BeaconState,
    Checkpoint,
    LatestMessage,
)
from pos_evolution_tpu.ssz import deserialize, hash_tree_root, serialize


def atomic_write_bytes(path: str | os.PathLike, data: bytes,
                       fsync: bool = True) -> str:
    """Tmp + (fsync) + rename, so a kill at ANY point leaves either the
    previous complete file or the new complete file — never a torn one
    that a later ``resume``/``load`` half-parses. Every checkpoint
    write in the repo (manual snapshot files, the dense driver's npz,
    chaos repro bundles, the resilience manager) goes through this or
    its directory-level sibling in ``resilience/manager.py``."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(data)
        if fsync:
            fh.flush()
            os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def _frame(out: io.BytesIO, payload: bytes) -> None:
    out.write(struct.pack("<Q", len(payload)))
    out.write(payload)


def _unframe(buf: io.BytesIO) -> bytes:
    (n,) = struct.unpack("<Q", buf.read(8))
    return buf.read(n)


# --- anchor snapshots (the spec's own mechanism) ------------------------------

def save_anchor(state: BeaconState, block: BeaconBlock) -> bytes:
    """Snapshot = SSZ(state) + SSZ(block); the pair satisfies the store-init
    contract ``block.state_root == hash_tree_root(state)``."""
    assert bytes(block.state_root) == hash_tree_root(state), \
        "anchor block/state inconsistent"
    out = io.BytesIO()
    _frame(out, serialize(state))
    _frame(out, serialize(block))
    return out.getvalue()


def load_anchor(data: bytes) -> tuple[BeaconState, BeaconBlock]:
    buf = io.BytesIO(data)
    state = deserialize(_unframe(buf), BeaconState)
    block = deserialize(_unframe(buf), BeaconBlock)
    return state, block


def resume_store(data: bytes, pow_chain=None):
    """Rebuild a fork-choice store from a snapshot — the weak-subjectivity
    sync flow (pos-evolution.md:1221, 1293). ``pow_chain`` reattaches an
    isolated PoW view (see ``load_store``)."""
    from pos_evolution_tpu.specs.forkchoice import get_forkchoice_store
    state, block = load_anchor(data)
    return get_forkchoice_store(state, block, pow_chain=pow_chain)


def snapshot_head(store) -> bytes:
    """Snapshot the current head block + post-state of a running store."""
    from pos_evolution_tpu.specs.forkchoice import get_head
    head = get_head(store)
    return save_anchor(store.block_states[head], store.blocks[head])


# --- full-store snapshots -----------------------------------------------------

def save_store(store) -> bytes:
    """Serialize an entire Store (view) for exact-resume debugging."""
    out = io.BytesIO()
    meta = {
        "time": store.time,
        "genesis_time": store.genesis_time,
        "justified": [int(store.justified_checkpoint.epoch),
                      bytes(store.justified_checkpoint.root).hex()],
        "finalized": [int(store.finalized_checkpoint.epoch),
                      bytes(store.finalized_checkpoint.root).hex()],
        "best_justified": [int(store.best_justified_checkpoint.epoch),
                           bytes(store.best_justified_checkpoint.root).hex()],
        "proposer_boost_root": bytes(store.proposer_boost_root).hex(),
        "equivocating": sorted(store.equivocating_indices),
        "latest_messages": {str(v): [m.epoch, m.root.hex()]
                            for v, m in store.latest_messages.items()},
        "block_order": [r.hex() for r in store.blocks],
        "checkpoint_keys": [[e, r.hex()] for (e, r) in store.checkpoint_states],
    }
    _frame(out, json.dumps(meta).encode())
    for root in store.blocks:
        _frame(out, serialize(store.blocks[root]))
        _frame(out, serialize(store.block_states[root]))
    for key in store.checkpoint_states:
        _frame(out, serialize(store.checkpoint_states[key]))
    return out.getvalue()


def load_store(data: bytes, pow_chain=None):
    """Rebuild a Store from ``save_store`` bytes.

    ``pow_chain`` reattaches a PoW-chain view (specs.merge.PowChainView):
    the view can hold a live callable provider, so it is not serialized —
    a resumed store that must re-validate a merge-transition block needs
    the caller to pass the view back in (None falls back to the module
    default registry, as everywhere else).
    """
    from pos_evolution_tpu.specs.forkchoice import Store
    buf = io.BytesIO(data)
    meta = json.loads(_unframe(buf).decode())
    blocks, block_states = {}, {}
    for root_hex in meta["block_order"]:
        block = deserialize(_unframe(buf), BeaconBlock)
        state = deserialize(_unframe(buf), BeaconState)
        blocks[bytes.fromhex(root_hex)] = block
        block_states[bytes.fromhex(root_hex)] = state
    checkpoint_states = {}
    for epoch, root_hex in meta["checkpoint_keys"]:
        checkpoint_states[(epoch, bytes.fromhex(root_hex))] = \
            deserialize(_unframe(buf), BeaconState)

    def cp(pair):
        return Checkpoint(epoch=pair[0], root=bytes.fromhex(pair[1]))

    return Store(
        time=meta["time"],
        genesis_time=meta["genesis_time"],
        justified_checkpoint=cp(meta["justified"]),
        finalized_checkpoint=cp(meta["finalized"]),
        best_justified_checkpoint=cp(meta["best_justified"]),
        proposer_boost_root=bytes.fromhex(meta["proposer_boost_root"]),
        equivocating_indices=set(meta["equivocating"]),
        blocks=blocks,
        block_states=block_states,
        checkpoint_states=checkpoint_states,
        latest_messages={int(v): LatestMessage(epoch=m[0], root=bytes.fromhex(m[1]))
                         for v, m in meta["latest_messages"].items()},
        pow_chain=pow_chain,
    )


# --- whole-simulation snapshots (sim/driver.py checkpoint/resume) -------------

# message kind -> SSZ payload class for queue/pool serialization
def _payload_class(kind: str):
    from pos_evolution_tpu.specs.containers import (
        Attestation,
        AttesterSlashing,
        SignedBeaconBlock,
    )
    if kind == "blob":
        from pos_evolution_tpu.das.containers import BlobSidecar
        return BlobSidecar
    return {"block": SignedBeaconBlock, "attestation": Attestation,
            "slashing": AttesterSlashing}[kind]


def save_simulation(sim, path: str | os.PathLike | None = None) -> bytes:
    """Serialize a running ``sim.driver.Simulation`` so that ``resume``
    continues it bit-identically: per group the full Store
    (``save_store``), the pending message queue (times + arrival sequence
    + SSZ payloads), the attestation pool, and the per-block inclusion
    index; plus the slot cursor and recorded per-slot metrics.
    ``path`` additionally lands the bytes on disk ATOMICALLY
    (``atomic_write_bytes``): a kill mid-write can never leave a torn
    file that a later manual ``resume()`` half-loads.

    Not serialized, by design: the Schedule/FaultPlan (callables — the
    caller passes the same one to ``resume``; fault decisions are
    stateless hashes so they replay identically), the PoW-chain view
    (``load_store`` contract), and wall-clock handler timings."""
    out = io.BytesIO()
    meta = {
        "version": 1,
        "n_validators": sim.n_validators,
        "genesis_time": sim.genesis_time,
        "slot": sim.slot,
        "accelerated": sim.accelerated_forkchoice,
        # Sharded mode (ISSUE 9): only the mesh SHAPE is simulation
        # state. Resident device arrays are never serialized — they
        # rebuild from the restored stores, placed per the partition
        # rules on whatever mesh is active at resume time, so a
        # checkpoint taken on a 2x4 mesh resumes bit-identically on 4x2,
        # 1x8, or a single device (pinned in tests/test_sharded_e2e.py).
        "sharded": getattr(sim, "sharded", None),
        "metrics": sim.metrics,
        "archive_roots": [r.hex() for r in sim.block_archive],
        # DAS (das/, DESIGN.md §15): sidecar CONTENT is a seeded pure
        # function of the chain, so only availability bookkeeping is
        # recorded — which (block, blob) pairs each view had verified —
        # plus the engine parameters, so ``resume(das=engine)`` can
        # refuse a mismatched engine loudly (a wrong seed/scheme would
        # regenerate self-consistent sidecars whose commitments never
        # match any block's graffiti: the chain stalls silently forever).
        "das": (sim.das.describe()
                if getattr(sim, "das", None) is not None else None),
        # Protocol variant (variants/, DESIGN.md §16): the describe()
        # fingerprint plus the full variant state (per-view vote
        # overlays, fast/kappa confirmations, per-slot FFG checkpoints
        # and evidence logs), so a resumed run — including a chaos repro
        # bundle — replays under the variant that produced it. Absent on
        # pre-seam checkpoints, which resume as Gasper.
        "variant": sim.variant.describe(),
        "variant_state": sim.variant.state_blob(sim),
        "groups": [{
            "id": g.id,
            "seq": g._seq,
            "queue": [[m.time, m.seq, m.kind] for m in sorted(g.queue)],
            "n_pool": len(g.pool),
            "blob_keys": [[r.hex(), i] for (r, i) in
                          getattr(g, "blob_store", None).sidecars]
            if getattr(g, "blob_store", None) is not None else [],
            "block_atts": {r.hex(): [a.hex() for a in atts]
                           for r, atts in g.block_atts.items()},
            # resident mirror supervision state: a degradation must
            # survive resume (the uninterrupted run answers from the host
            # path after one; a resurrected device path would break the
            # bit-identical contract in exactly the diverging case)
            "resident": None if g.resident is None else {
                "degraded": g.resident.degraded,
                "incidents": list(g.resident.incidents),
                "selfcheck_every": g.resident.selfcheck_every,
                "head_queries": g.resident._head_queries,
                "min_capacity": g.resident._min_capacity,
            },
        } for g in sim.groups],
    }
    _frame(out, json.dumps(meta).encode())
    for sb in sim.block_archive.values():
        _frame(out, serialize(sb))
    for g in sim.groups:
        _frame(out, save_store(g.store))
        for m in sorted(g.queue):
            _frame(out, serialize(m.payload))
        for att in g.pool.values():
            _frame(out, serialize(att))
    data = out.getvalue()
    if path is not None:
        atomic_write_bytes(path, data)
    return data


def load_simulation(data: bytes, schedule=None, telemetry=None,
                    adversaries=(), monitors=(), das=None, variant=None,
                    sharded=None):
    """Rebuild a ``save_simulation`` checkpoint into a live Simulation.
    ``schedule`` must be the run's original Schedule (with its FaultPlan)
    for faithful replay; crash flags re-derive from the plan + slot.
    ``telemetry`` re-attaches an event bus (not sim state; queue span ids
    are not serialized, so pre-checkpoint deliveries re-emitted after a
    resume carry no parent lineage). ``adversaries``/``monitors``
    re-attach in-loop strategies and property monitors; they bind AFTER
    the restore so their handles see the checkpointed stores, not the
    skeleton's."""
    from pos_evolution_tpu.sim.driver import Simulation, _QueuedMessage
    buf = io.BytesIO(data)
    meta = json.loads(_unframe(buf).decode())
    assert meta["version"] == 1, f"unknown snapshot version {meta['version']}"
    # build the skeleton WITHOUT residents: __init__ would densify every
    # genesis store only for the mirrors to be rebuilt from the restored
    # stores below — at registry scale that doubles resume latency.
    # Telemetry attaches AFTER the restore (below), not here: __init__
    # would emit a run_start describing the skeleton (accelerated=False,
    # slot 0) instead of the checkpointed run.
    # Re-enable (or override) the sharded backend mode BEFORE residents
    # rebuild, so the restored message columns land sharded on the
    # current mesh (resume-across-mesh-shapes: the mesh shape is policy,
    # not layout — a different shape or device count re-shards).
    if sharded is None:
        meta_sharded = meta.get("sharded")
        sharded = (tuple(meta_sharded[a] for a in ("pods", "shard"))
                   if meta_sharded else None)
    sim = Simulation(meta["n_validators"], schedule=schedule,
                     genesis_time=meta["genesis_time"],
                     accelerated_forkchoice=False, sharded=sharded)
    sim.accelerated_forkchoice = meta["accelerated"]
    assert len(sim.groups) == len(meta["groups"]), \
        "schedule shape does not match the checkpointed run"
    sim.slot = meta["slot"]
    sim.metrics = list(meta["metrics"])
    sim.block_archive = {}
    for root_hex in meta["archive_roots"]:
        sb = deserialize(_unframe(buf), _payload_class("block"))
        sim.block_archive[bytes.fromhex(root_hex)] = sb
    plan = sim.schedule.faults
    for g, gm in zip(sim.groups, meta["groups"]):
        g.store = load_store(_unframe(buf), pow_chain=sim.pow_chain)
        g._seq = gm["seq"]
        g.queue = []
        for time_, seq, kind in gm["queue"]:
            payload = deserialize(_unframe(buf), _payload_class(kind))
            g.queue.append(_QueuedMessage(time_, seq, kind, payload))
        # entries were framed in sorted order, which is already heap order
        g.pool = {}
        for _ in range(gm["n_pool"]):
            att = deserialize(_unframe(buf), _payload_class("attestation"))
            g.pool[hash_tree_root(att)] = att
        g.block_atts = {bytes.fromhex(r): [bytes.fromhex(a) for a in atts]
                        for r, atts in gm["block_atts"].items()}
        g.crashed = bool(plan.crashed(g.id, sim.slot)) if plan else False
        if meta["accelerated"]:
            from pos_evolution_tpu.ops.resident import ResidentForkChoice
            rm = gm.get("resident") or {}
            g.resident = ResidentForkChoice(
                g.store,
                capacity=rm.get("min_capacity", 64),
                selfcheck_every=rm.get("selfcheck_every", 64))
            # merge saved supervision state with anything the rebuild
            # itself just recorded (a still-broken device stays degraded)
            g.resident.degraded = g.resident.degraded or rm.get("degraded",
                                                                False)
            g.resident.incidents = (list(rm.get("incidents", []))
                                    + g.resident.incidents)
            g.resident._head_queries = rm.get("head_queries", 0)
    # Protocol variant: rebuild from the checkpoint's fingerprint when the
    # caller passes none (describe() round-trips via variant_from_config);
    # an explicit variant must match — a silently different rule would
    # replay a different protocol under the same evidence.
    from pos_evolution_tpu.variants import variant_from_config
    meta_variant = meta.get("variant")
    if variant is None:
        variant = variant_from_config(meta_variant)
    elif meta_variant is not None and variant.describe() != meta_variant:
        raise ValueError(
            f"resumed variant {variant.describe()} does not match the "
            f"checkpointed variant {meta_variant}")
    sim.variant = variant
    variant.bind(sim)
    if variant.needs_view:
        for g in sim.groups:
            view = variant.make_view(g.id)
            g.variant_view = view
            g.store.variant_view = view
        variant.restore_blob(sim, meta.get("variant_state", {}))
    if telemetry is not None:
        # attach to the fully restored run: groups get the bus, the debug
        # checker anchors on the RESTORED stores, the fault sink is
        # claimed for this run, and run_start describes the checkpointed
        # state (not the skeleton)
        sim.telemetry = telemetry
        for g in sim.groups:
            g.telemetry = telemetry
            if telemetry.debug:
                from pos_evolution_tpu.utils.metrics import (
                    StoreInvariantChecker,
                )
                g.invariants = StoreInvariantChecker(g.store)
        if sim.schedule.faults is not None:
            sim.schedule.faults.sink = telemetry.bus
        telemetry.bus.emit(
            "run_start", n_validators=sim.n_validators,
            n_groups=sim.schedule.n_groups, genesis_time=sim.genesis_time,
            accelerated_forkchoice=sim.accelerated_forkchoice,
            debug=telemetry.debug, resumed_at_slot=sim.slot)
    if adversaries or monitors:
        sim.adversaries = list(adversaries)
        sim.monitors = list(monitors)
        sim._bind_adversaries_and_monitors()
    if meta.get("das") and das is not None:
        _restore_das(sim, meta, das)
    return sim


def _restore_das(sim, meta: dict, das) -> None:
    """Reattach a DAS engine to a resumed run: regenerate every archived
    block's sidecars from the seed (bit-identical by construction),
    rebuild per-group blob stores, and replay exactly the sidecars each
    view had verified at checkpoint time. Queued ``blob`` messages were
    serialized with the rest of the queue and deliver normally."""
    from pos_evolution_tpu.das import BlobStore
    from pos_evolution_tpu.das.containers import parse_das_graffiti
    if das.describe() != meta["das"]:
        raise ValueError(
            f"resumed DAS engine {das.describe()} does not match the "
            f"checkpointed engine {meta['das']} — regenerated sidecars "
            f"would never satisfy the availability gate")
    sim.das = das
    sim.blob_archive = {}
    for root, sb in sim.block_archive.items():
        if parse_das_graffiti(bytes(sb.message.body.graffiti)) is not None:
            sim.blob_archive[root] = das.regenerate(sb, root)
    registry = (sim.telemetry.registry if sim.telemetry is not None else None)
    for g, gm in zip(sim.groups, meta["groups"]):
        g.blob_store = BlobStore(das, registry=registry, group=g.id)
        g.store.blob_store = g.blob_store
        # insert directly: these sidecars were just regenerated from the
        # trusted seed (bit-identical by construction), so re-running the
        # full commitment + erasure verification per (group, block, blob)
        # would only multiply resume latency and double-count the
        # ``das_sidecars_accepted_total`` metric on the resumed registry
        for root_hex, idx in gm.get("blob_keys", []):
            root = bytes.fromhex(root_hex)
            for sc in sim.blob_archive.get(root, ()):
                if int(sc.blob_index) == int(idx):
                    g.blob_store.sidecars.setdefault(
                        (root, int(idx)), {})[bytes(sc.commitment)] = sc


# --- dense-array host offload -------------------------------------------------

def save_dense(path: str, registry) -> None:
    """Host-offload a DenseRegistry pytree to .npz, atomically (the
    compressed stream lands in memory first, then tmp + fsync + rename
    — a preempted offload can never leave a torn npz)."""
    buf = io.BytesIO()
    np.savez_compressed(buf, **{f: np.asarray(getattr(registry, f))
                                for f in registry._fields})
    atomic_write_bytes(path, buf.getvalue())


def load_dense(path: str):
    from pos_evolution_tpu.ops.epoch import DenseRegistry
    import jax.numpy as jnp
    with np.load(path) as z:
        return DenseRegistry(**{f: jnp.asarray(z[f]) for f in DenseRegistry._fields})


def save_dense_orbax(path: str, registry) -> None:
    """Checkpoint the dense registry pytree with orbax (device->host
    offload of possibly mesh-sharded arrays)."""
    import orbax.checkpoint as ocp
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, registry._asdict(), force=True)


def load_dense_orbax(path: str, mesh=None):
    """Restore a DenseRegistry checkpoint.

    With ``mesh``, arrays are re-placed sharded over the validator axes of
    the *current* topology (safe across topology changes); otherwise they
    come back as single-device jnp arrays (matching ``load_dense``).
    """
    import jax.numpy as jnp
    import orbax.checkpoint as ocp
    from pos_evolution_tpu.ops.epoch import DenseRegistry
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    reg = DenseRegistry(**{f: jnp.asarray(tree[f]) for f in DenseRegistry._fields})
    if mesh is not None:
        from pos_evolution_tpu.parallel.sharded import shard_registry
        reg = shard_registry(mesh, reg)
    return reg
