"""Honest device timing on the axon relay (and any async JAX backend).

Discovered in round 3: ``jax.block_until_ready`` does NOT synchronize
through the axon relay in its default mode — it returns at enqueue, so
naive timings measure dispatch latency regardless of workload. Only a
device->host transfer truly syncs, and the first transfer switches the
process into a synchronous mode with a ~70-90 ms round-trip per dispatch.

The one honest recipe, shared by ``bench.py`` and
``scripts/pallas_tpu_evidence.py`` so it cannot drift:

- fuse K iterations of the workload into ONE jitted ``lax.fori_loop``
  whose body folds a per-iteration salt into the inputs (the relay's
  execution cache persists across processes, so callers must pass
  per-invocation ``os.urandom`` entropy);
- every timed call ends in a transfer of an i32 checksum that every
  output feeds (full reductions, not element picks — XLA's simplifier
  moves slices through elementwise ops and would shrink the work);
- report the work-difference ``(t(K_hi) - t(1)) / (K_hi - 1)``, which
  cancels the fixed per-call round-trip out of the number.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from pos_evolution_tpu.telemetry import jaxrt


def checksum_tree(out) -> jax.Array:
    """i32 checksum covering EVERY element of every leaf (wraparound sums:
    a slice-through-elementwise rewrite cannot eliminate the work)."""
    acc = jnp.int32(0)
    for leaf in jax.tree_util.tree_leaves(out):
        # sum(dtype=...) keeps the accumulator i32 even under x64's
        # numpy-style promotion (wraparound is fine for a checksum)
        acc = acc + leaf.ravel().sum(dtype=jnp.int32)
    return acc


def fused_measure(body, *, k_hi: int = 4, entropy: int | None = None,
                  tag: str = "", reps: int = 2, captures=None) -> float:
    """Per-iteration seconds for ``body(salt_i32, acc_i32) -> acc_i32``.

    ``body`` must fold ``salt`` into its inputs and fold all its outputs
    into the returned accumulator (use ``checksum_tree``).

    ``captures``: an optional pytree of arrays passed to ``body`` as a
    third argument, **traced** through the jitted loop. Pass the big
    lookup tables here instead of closing over them: a closed-over array
    becomes an HLO *constant*, and XLA's constant-folding pass will
    happily evaluate a whole scatter/reduce chain over it at compile
    time — the ``s64[65]`` scatter-add in ``head_and_weights`` cost >1 s
    per compile in BENCH_r05 exactly this way (the message table and
    weights were closures, so the per-block vote reduction was a
    compile-time constant). Traced captures keep compilation
    O(program), and the workload they feed is measured, not folded.
    """
    ent = entropy if entropy is not None else \
        int.from_bytes(os.urandom(3), "little")

    @jax.jit
    def run(k, salt0, cap):
        def step(i, acc):
            if captures is None:
                return body(salt0 + i, acc)
            return body(salt0 + i, acc, cap)
        return jax.lax.fori_loop(0, k, step, jnp.int32(0))

    def t_of(k: int, salt0: int) -> float:
        t0 = time.perf_counter()
        out = np.asarray(run(jnp.int32(k), jnp.int32(salt0),
                             captures))  # transfer = sync
        elapsed = time.perf_counter() - t0
        # runtime telemetry (no-ops unless a registry is installed): one
        # dispatch + one d2h checksum transfer per timed call
        jaxrt.record_dispatch(site="fused_measure")
        jaxrt.record_transfer(out.nbytes, direction="d2h",
                              site="fused_measure")
        return elapsed

    t_of(1, ent)                                         # compile + warm
    t1 = min(t_of(1, ent + 11 + r) for r in range(reps))
    thi = min(t_of(k_hi, ent + 21 + r) for r in range(reps))
    per = (thi - t1) / (k_hi - 1)
    if per <= 0:
        # Jitter swamped the added work: fall back to the conservative
        # upper bound (includes the round-trip) and say so loudly rather
        # than report a bogus sub-nanosecond number.
        print(f"# benchtime WARNING [{tag}]: non-positive work-difference "
              f"(t1={t1*1e3:.1f}ms t{k_hi}={thi*1e3:.1f}ms); reporting the "
              f"round-trip-inclusive upper bound", file=sys.stderr)
        return thi / k_hi
    if tag:
        print(f"# {tag}: t1={t1*1e3:.1f}ms t{k_hi}={thi*1e3:.1f}ms "
              f"-> {per*1e3:.2f}ms/iter", file=sys.stderr)
    return per
