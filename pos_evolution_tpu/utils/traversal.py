"""Shared iterative tree traversal (no recursion-depth limits).

Both fork-choice implementations walk block trees that can grow far past
Python's ~1000-frame recursion limit in long simulations; every tree walk
in the package uses this explicit-stack post-order instead.
"""

from __future__ import annotations

from typing import Iterable, Iterator


def postorder(children: dict, root) -> Iterator:
    """Yield nodes of the tree under ``root`` in post-order (children
    before parents), iteratively."""
    stack = [(root, False)]
    while stack:
        node, expanded = stack.pop()
        kids = children.get(node, ())
        if expanded or not kids:
            yield node
        else:
            stack.append((node, True))
            stack.extend((k, False) for k in kids)
