"""Virtual host-device re-exec for sharded demo/CLI entry points.

XLA fixes the CPU device count at jax import time
(``--xla_force_host_platform_device_count``), so a script that wants an
N-device virtual mesh must set the flag BEFORE importing jax — which
means restarting itself once with the right environment. Three scripts
grew identical copies of this dance (multichip_demo, dense_chaos_demo,
chaos_fuzz --dense); this is the one shared implementation, with the
child-guard env var as the only per-caller knob.
"""

from __future__ import annotations

import os
import sys

__all__ = ["reexec_with_host_devices"]


def reexec_with_host_devices(n_devices: int, guard_env: str) -> None:
    """Re-exec the current process pinned to CPU with ``n_devices``
    virtual host devices, unless ``guard_env`` marks us as the child
    already. Never returns in the parent (``os.execve`` replaces it)."""
    if os.environ.get(guard_env) == "1":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={n_devices}"
                 ).strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags)
    env[guard_env] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)
