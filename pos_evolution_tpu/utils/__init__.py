"""Auxiliary subsystems: snapshots, metrics/tracing, invariants, watchdog."""

from pos_evolution_tpu.utils.metrics import (
    HandlerTimer,
    StoreInvariantChecker,
    slot_record,
)
from pos_evolution_tpu.utils.snapshot import (
    load_anchor,
    load_dense,
    load_simulation,
    load_store,
    resume_store,
    save_anchor,
    save_dense,
    save_simulation,
    save_store,
    snapshot_head,
)
from pos_evolution_tpu.utils.watchdog import Watchdog, WatchdogTimeout
