"""Auxiliary subsystems: snapshots, metrics/tracing, invariants."""

from pos_evolution_tpu.utils.metrics import (
    HandlerTimer,
    StoreInvariantChecker,
    slot_record,
)
from pos_evolution_tpu.utils.snapshot import (
    load_anchor,
    load_dense,
    load_store,
    resume_store,
    save_anchor,
    save_dense,
    save_store,
    snapshot_head,
)
