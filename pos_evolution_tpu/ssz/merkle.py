"""SSZ merkleization: chunking, padded binary merkle trees, branch proofs.

Implements the merkleization half of the SSZ standard referenced at
pos-evolution.md:9 — ``merkleize(chunks, limit)``, length mix-in for lists,
and ``is_valid_merkle_branch`` (pos-evolution.md:141-147). All tree levels
are hashed with the batched NumPy SHA-256 (ssz/hash.py), so merkleizing a
1M-leaf balances array is ~20 batched compression sweeps, not 2M Python
hashlib calls — the "<32 MB rehashed per epoch" bound of pos-evolution.md:114.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.ssz.hash import sha256, sha256_batch, sha256_pairs

__all__ = [
    "ZERO_HASHES",
    "merkleize",
    "merkleize_chunks",
    "mix_in_length",
    "is_valid_merkle_branch",
    "merkle_tree_branch",
    "next_pow_of_two",
]

MAX_DEPTH = 64


def _compute_zero_hashes() -> np.ndarray:
    z = np.zeros((MAX_DEPTH + 1, 32), dtype=np.uint8)
    for i in range(MAX_DEPTH):
        z[i + 1] = np.frombuffer(sha256(z[i].tobytes() * 2), dtype=np.uint8)
    return z


# ZERO_HASHES[d] = root of an all-zero subtree of depth d.
ZERO_HASHES = _compute_zero_hashes()


def next_pow_of_two(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _depth_for(limit: int) -> int:
    return (next_pow_of_two(limit) - 1).bit_length() if limit > 1 else 0


def merkleize_chunks(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """Merkleize (N, 32) uint8 chunk array, virtually padded to ``limit``.

    ``limit=None`` pads to the next power of two of N (SSZ vector rule).
    Returns the 32-byte root.
    """
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    if chunks.ndim == 1:
        chunks = chunks.reshape(-1, 32)
    count = chunks.shape[0]
    if limit is None:
        limit = max(count, 1)
    if count > limit:
        raise ValueError(f"{count} chunks exceed limit {limit}")
    depth = _depth_for(limit)
    if count == 0:
        return ZERO_HASHES[depth].tobytes()
    if count >= 32:
        # Whole-tree merkleization in one native call (component N2).
        try:
            from pos_evolution_tpu import native
            if native.available():
                return native.merkleize_chunks(chunks, limit)
        except Exception:
            pass
    layer = chunks
    for level in range(depth):
        if layer.shape[0] % 2 == 1:
            layer = np.concatenate([layer, ZERO_HASHES[level][None, :]], axis=0)
        layer = sha256_pairs(layer[0::2], layer[1::2])
    return layer[0].tobytes()


def merkleize(chunks, limit: int | None = None) -> bytes:
    """Accepts a list of 32-byte chunks or an (N, 32) array."""
    if isinstance(chunks, np.ndarray):
        return merkleize_chunks(chunks, limit)
    if len(chunks) == 0:
        return merkleize_chunks(np.empty((0, 32), dtype=np.uint8), limit)
    arr = np.frombuffer(b"".join(bytes(c) for c in chunks), dtype=np.uint8).reshape(-1, 32)
    return merkleize_chunks(arr, limit)


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little"))


def is_valid_merkle_branch(leaf: bytes, branch, depth: int, index: int, root: bytes) -> bool:
    """Verify a merkle inclusion proof (pos-evolution.md:141-147 contract)."""
    value = bytes(leaf)
    for i in range(depth):
        sibling = bytes(branch[i])
        if (index >> i) & 1:
            value = sha256(sibling + value)
        else:
            value = sha256(value + sibling)
    return value == bytes(root)


def merkle_tree_branch(leaves: np.ndarray, index: int, depth: int) -> list[bytes]:
    """Build the merkle proof for ``leaves[index]`` in a depth-``depth`` tree.

    Used by the deposit-tree test fixtures (pos-evolution.md:105-107).
    """
    layer = np.ascontiguousarray(leaves, dtype=np.uint8).reshape(-1, 32)
    branch: list[bytes] = []
    idx = index
    for level in range(depth):
        sib = idx ^ 1
        if sib < layer.shape[0]:
            branch.append(layer[sib].tobytes())
        else:
            branch.append(ZERO_HASHES[level].tobytes())
        if layer.shape[0] % 2 == 1:
            layer = np.concatenate([layer, ZERO_HASHES[level][None, :]], axis=0)
        layer = sha256_pairs(layer[0::2], layer[1::2])
        idx //= 2
    return branch
