"""SSZ merkleization: chunking, padded binary merkle trees, branch proofs.

Implements the merkleization half of the SSZ standard referenced at
pos-evolution.md:9 — ``merkleize(chunks, limit)``, length mix-in for lists,
and ``is_valid_merkle_branch`` (pos-evolution.md:141-147). All tree levels
are hashed with the batched NumPy SHA-256 (ssz/hash.py), so merkleizing a
1M-leaf balances array is ~20 batched compression sweeps, not 2M Python
hashlib calls — the "<32 MB rehashed per epoch" bound of pos-evolution.md:114.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.ssz.hash import sha256, sha256_batch, sha256_pairs

__all__ = [
    "ZERO_HASHES",
    "merkleize",
    "merkleize_chunks",
    "mix_in_length",
    "is_valid_merkle_branch",
    "merkle_tree_branch",
    "multiproof_helper_gindices",
    "build_multiproof",
    "verify_multiproof",
    "next_pow_of_two",
]

MAX_DEPTH = 64


def _compute_zero_hashes() -> np.ndarray:
    z = np.zeros((MAX_DEPTH + 1, 32), dtype=np.uint8)
    for i in range(MAX_DEPTH):
        z[i + 1] = np.frombuffer(sha256(z[i].tobytes() * 2), dtype=np.uint8)
    return z


# ZERO_HASHES[d] = root of an all-zero subtree of depth d.
ZERO_HASHES = _compute_zero_hashes()


def next_pow_of_two(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _depth_for(limit: int) -> int:
    return (next_pow_of_two(limit) - 1).bit_length() if limit > 1 else 0


def merkleize_chunks(chunks: np.ndarray, limit: int | None = None,
                     combine=sha256_pairs) -> bytes:
    """Merkleize (N, 32) uint8 chunk array, virtually padded to ``limit``.

    ``limit=None`` pads to the next power of two of N (SSZ vector rule).
    Returns the 32-byte root. ``combine`` is the level combiner —
    ``ops/merkle_device.merkleize`` passes its dispatching ``pair_hash``
    so this stays the one copy of the padded walk; the native whole-tree
    fast path only applies to the default host combiner.
    """
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    if chunks.ndim == 1:
        chunks = chunks.reshape(-1, 32)
    count = chunks.shape[0]
    if limit is None:
        limit = max(count, 1)
    if count > limit:
        raise ValueError(f"{count} chunks exceed limit {limit}")
    depth = _depth_for(limit)
    if count == 0:
        return ZERO_HASHES[depth].tobytes()
    if count >= 32 and combine is sha256_pairs:
        # Whole-tree merkleization in one native call (component N2).
        try:
            from pos_evolution_tpu import native
            if native.available():
                return native.merkleize_chunks(chunks, limit)
        except Exception:
            pass
    layer = chunks
    for level in range(depth):
        if layer.shape[0] % 2 == 1:
            layer = np.concatenate([layer, ZERO_HASHES[level][None, :]], axis=0)
        layer = combine(layer[0::2], layer[1::2])
    return layer[0].tobytes()


def merkleize(chunks, limit: int | None = None) -> bytes:
    """Accepts a list of 32-byte chunks or an (N, 32) array."""
    if isinstance(chunks, np.ndarray):
        return merkleize_chunks(chunks, limit)
    if len(chunks) == 0:
        return merkleize_chunks(np.empty((0, 32), dtype=np.uint8), limit)
    arr = np.frombuffer(b"".join(bytes(c) for c in chunks), dtype=np.uint8).reshape(-1, 32)
    return merkleize_chunks(arr, limit)


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little"))


def is_valid_merkle_branch(leaf: bytes, branch, depth: int, index: int, root: bytes) -> bool:
    """Verify a merkle inclusion proof (pos-evolution.md:141-147 contract)."""
    value = bytes(leaf)
    for i in range(depth):
        sibling = bytes(branch[i])
        if (index >> i) & 1:
            value = sha256(sibling + value)
        else:
            value = sha256(value + sibling)
    return value == bytes(root)


# --- generalized-index multiproofs --------------------------------------------
# The SSZ multiproof dialect: node 1 is the root, node ``g``'s children are
# ``2g`` and ``2g+1``, leaf ``i`` of a depth-``d`` tree is ``2**d + i``. One
# proof covers MANY leaves by shipping only the siblings off the union of
# their root paths — the commitment-side analogue of the polynomial
# multiproofs in arxiv 2604.16559 (das/commitment.py serves these for
# batched DAS cell samples).


def multiproof_helper_gindices(leaf_indices, depth: int) -> list[int]:
    """Sibling generalized indices a multiproof over ``leaf_indices`` must
    carry, sorted descending (deepest first — the canonical SSZ order)."""
    on_path: set[int] = {1}
    for i in leaf_indices:
        g = (1 << depth) + int(i)
        while g > 1:
            on_path.add(g)
            g >>= 1
    helpers = {g ^ 1 for g in on_path if g > 1 and (g ^ 1) not in on_path}
    return sorted(helpers, reverse=True)


def _tree_levels(leaves: np.ndarray, depth: int,
                 combine=sha256_pairs) -> list[np.ndarray]:
    """All levels of the padded tree, leaves first (virtual zero padding
    stays virtual: out-of-range nodes read from ``ZERO_HASHES``).
    ``combine`` is the level combiner — ``ops/merkle_device.tree_levels``
    passes its dispatching ``pair_hash`` so THIS stays the one copy of
    the padded-tree walk."""
    layer = np.ascontiguousarray(leaves, dtype=np.uint8).reshape(-1, 32)
    levels = [layer]
    for level in range(depth):
        if layer.shape[0] % 2 == 1:
            layer = np.concatenate([layer, ZERO_HASHES[level][None, :]], axis=0)
        layer = combine(np.ascontiguousarray(layer[0::2]),
                        np.ascontiguousarray(layer[1::2]))
        levels.append(layer)
    return levels


def _node_value(levels: list[np.ndarray], gindex: int, depth: int) -> bytes:
    level = depth - (gindex.bit_length() - 1)
    idx = gindex - (1 << (gindex.bit_length() - 1))
    layer = levels[level]
    if idx < layer.shape[0]:
        return layer[idx].tobytes()
    return ZERO_HASHES[level].tobytes()


def build_multiproof(leaves: np.ndarray, leaf_indices, depth: int,
                     combine=sha256_pairs) -> list[bytes]:
    """One proof for all ``leaf_indices`` of a depth-``depth`` tree over
    ``leaves``: the helper-sibling values in ``multiproof_helper_gindices``
    order. Shared path prefixes are shipped once, so proving c cells costs
    ~c*(depth - log2 c) siblings instead of c*depth."""
    levels = _tree_levels(leaves, depth, combine)
    return [_node_value(levels, g, depth)
            for g in multiproof_helper_gindices(leaf_indices, depth)]


def verify_multiproof(leaf_values, leaf_indices, proof, depth: int,
                      root: bytes) -> bool:
    """Recompute the root from leaves + helper siblings; level-by-level so
    each sweep is ONE batched ``sha256_pairs`` call (the MTU tree-unit
    shape of arxiv 2507.16793) rather than per-node scalar hashing."""
    leaf_indices = [int(i) for i in leaf_indices]
    helpers = multiproof_helper_gindices(leaf_indices, depth)
    if len(proof) != len(helpers) or len(leaf_values) != len(leaf_indices):
        return False
    # duplicate gindices must agree — a dict would silently keep only the
    # LAST value, letting a corrupted (index, value) pair verify whenever
    # the same index also appears with the honest value (samplers draw
    # cells with replacement, so duplicates are normal inputs here)
    objects: dict[int, bytes] = {}
    for g, v in zip(
            ((1 << depth) + i for i in leaf_indices), leaf_values):
        if objects.setdefault(g, bytes(v)) != bytes(v):
            return False
    for g, v in zip(helpers, proof):
        if objects.setdefault(g, bytes(v)) != bytes(v):
            return False
    for length in range(depth + 1, 1, -1):  # bit_length of gindices, deep->shallow
        parents, lefts, rights = [], [], []
        for g in [g for g in objects if g.bit_length() == length]:
            p = g >> 1
            if p in objects or p in parents:
                continue
            left, right = objects.get(p << 1), objects.get((p << 1) | 1)
            if left is None or right is None:
                return False  # malformed proof: a needed sibling is absent
            parents.append(p)
            lefts.append(left)
            rights.append(right)
        if parents:
            la = np.frombuffer(b"".join(lefts), dtype=np.uint8).reshape(-1, 32)
            ra = np.frombuffer(b"".join(rights), dtype=np.uint8).reshape(-1, 32)
            for p, digest in zip(parents, sha256_pairs(la, ra)):
                objects[p] = digest.tobytes()
    return objects.get(1) == bytes(root)


def merkle_tree_branch(leaves: np.ndarray, index: int, depth: int) -> list[bytes]:
    """Build the merkle proof for ``leaves[index]`` in a depth-``depth`` tree.

    Used by the deposit-tree test fixtures (pos-evolution.md:105-107).
    """
    layer = np.ascontiguousarray(leaves, dtype=np.uint8).reshape(-1, 32)
    branch: list[bytes] = []
    idx = index
    for level in range(depth):
        sib = idx ^ 1
        if sib < layer.shape[0]:
            branch.append(layer[sib].tobytes())
        else:
            branch.append(ZERO_HASHES[level].tobytes())
        if layer.shape[0] % 2 == 1:
            layer = np.concatenate([layer, ZERO_HASHES[level][None, :]], axis=0)
        layer = sha256_pairs(layer[0::2], layer[1::2])
        idx //= 2
    return branch
