"""Incremental SSZ merkleization: persistent hash trees + dirty-subtree rehash.

``hash_tree_root(state)`` used to re-merkleize every chunk of every field on
every call — at 64K validators that is ~1.5M SHA-256 compressions per root,
and the simulator asks for a state root several times per slot
(``process_slot``, the post-block state-root check, head-state advances).
SCALE_DEMO_r05 measured the consequence: ``on_block`` p50 of 1.39s with the
state transition — not fork choice — as the wall.

This module keeps a **persistent hash tree per big field** and re-hashes only
the O(dirty · log n) paths above mutated chunks:

- ``ChunkTree``      — one padded SSZ merkle tree over (N, 32) chunks with
                       diff-based dirty detection (the spec layer mutates
                       numpy columns in place, so mutations are *detected*
                       by comparing against the last-seen leaves — a memcmp,
                       not a hash — and never need explicit invalidation
                       hooks). Dirty subtrees re-hash in batched
                       ``sha256_pairs`` level sweeps, the level-sweep kernel
                       shape of the MTU tree-unit paper (arxiv 2507.16793).
- ``RegistryTree``   — the validator registry: column-level compares find
                       dirty rows, only those rows re-run the 8-leaf
                       validator merkleization, then the roots feed a
                       ``ChunkTree`` capped at VALIDATOR_REGISTRY_LIMIT.
- ``ContainerTreeCache`` — per-container orchestration: registry/list/vector
                       fields get trees, small fields get serialize-compare
                       root memos, and the field roots themselves sit in one
                       more ``ChunkTree``.

Correctness contract: **bit-identical to full re-merkleization** — the trees
reproduce ``merkleize_chunks(chunks, limit)`` (+ ``mix_in_length``) exactly,
including virtual zero-subtree padding to the type limit and list
grow/shrink; ``tests/test_incremental_ssz.py`` pins this property under
randomized mutation. A cache is an *optimization handle*, never a source of
truth: a state that has never seen a cache (deserialized snapshots, copies
from before the wiring) simply rebuilds on first use.

Sharing contract: ``BeaconState.copy()`` hands the copy the *same* cache
object. Diff-based detection makes that safe — whichever state asks for its
root next diffs against whatever the cache last hashed, so fork siblings and
parent/child states share one ~O(state) cache per lineage instead of one per
stored state. (Single-threaded simulation; the cache is not locked.)

Where the hashes RUN (ISSUE 15): every level sweep goes through
``ops/merkle_device.pair_hash`` — host SHA-256 below the measured
crossover, the batched device kernel above it — and a container-root
computation drives all of its field trees in LOCKSTEP through one
``LevelSweeper``: the tree updates are generators that yield their
per-level pair blocks, and each level of every dirty field hashes in ONE
kernel launch instead of one ``sha256_pairs`` call per level per field.
Bit-identical on every path; ``tests/test_merkle_device.py`` pins it.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.ops.merkle_device import LevelSweeper, drive, pair_hash
from pos_evolution_tpu.ssz.hash import sha256
from pos_evolution_tpu.ssz.merkle import ZERO_HASHES, mix_in_length

__all__ = [
    "ChunkTree", "RegistryTree", "ContainerTreeCache",
    "state_root", "stats", "reset_stats", "set_enabled",
]


# --- telemetry ----------------------------------------------------------------
# Module-level cumulative counters; the sim driver snapshots deltas into its
# MetricsRegistry each slot and run_report.py renders them as the
# merkleization section.

_STATS = {
    "htr_calls": 0,        # incremental container-root computations
    "htr_cache_hit": 0,    # field roots served without any re-hashing
    "htr_cache_miss": 0,   # field roots that needed (partial) re-hashing
    "dirty_chunks": 0,     # leaf chunks re-hashed across all trees
    "rebuilds": 0,         # full tree (re)builds (first use / shrink / limit change)
}

_ENABLED = True


def stats() -> dict:
    return dict(_STATS)


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0


def set_enabled(flag: bool) -> bool:
    """Global switch (tests / A-B benches): when False, ``state_root``
    falls back to full re-merkleization. Returns the previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    return prev


# --- persistent chunk tree ----------------------------------------------------

def _depth_for(limit: int) -> int:
    if limit <= 1:
        return 0
    return (limit - 1).bit_length()


class ChunkTree:
    """Persistent merkle tree over an (N, 32) uint8 chunk array.

    ``limit`` is the chunk limit of the SSZ type (virtual zero padding up to
    ``2**ceil(log2(limit))`` leaves); ``limit=None`` is the vector rule (pad
    to the next power of two of the runtime count). ``root(chunks)`` diffs
    the chunks against the last-seen leaves and re-hashes only the dirty
    paths; a shrink or a limit change rebuilds from scratch (lists shrink
    only at rare resets — eth1 vote clearing — so rebuilds stay off the hot
    path).
    """

    __slots__ = ("limit", "count", "levels", "_root", "_pending")

    def __init__(self, limit: int | None = None):
        self.limit = limit
        self.count = -1
        self.levels: list[np.ndarray] | None = None
        self._root = b""
        # an update generator is in flight: leaves may be written before
        # the internal nodes hash, so an ABANDONED sweep (exception
        # between sweeper registration and run) must not leave the tree
        # claiming a clean diff against a stale root — the next query
        # rebuilds instead
        self._pending = False

    # -- public ---------------------------------------------------------------

    def root(self, chunks: np.ndarray, sweeper: LevelSweeper | None = None):
        """Incremental root. Without ``sweeper``: returns the 32-byte
        root, hashing dirty paths immediately. With one: registers this
        tree's level sweeps on the lockstep batcher and returns a
        zero-arg finisher to call AFTER ``sweeper.run()`` — that is how a
        ``ContainerTreeCache`` turns a whole-container rehash into one
        kernel launch per level across every dirty field."""
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        if chunks.ndim == 1:
            chunks = chunks.reshape(-1, 32)
        n = chunks.shape[0]
        if self.limit is not None and n > self.limit:
            raise ValueError(f"{n} chunks exceed limit {self.limit}")
        if self.levels is None or n < self.count or self._pending:
            return self._launch(self._rebuild_steps(chunks), sweeper)
        if n == self.count and np.array_equal(self.levels[0], chunks):
            _STATS["htr_cache_hit"] += 1
            return self._done(sweeper)
        m = self.count
        diff = (self.levels[0][: min(m, n)] != chunks[: min(m, n)]).any(axis=1)
        dirty = np.nonzero(diff)[0]
        if n > m:
            dirty = np.concatenate(
                [dirty, np.arange(m, n, dtype=np.int64)]).astype(np.int64)
        if dirty.size == 0:
            # pure equality (count unchanged) was handled above; reaching
            # here with an empty dirty set means nothing changed
            _STATS["htr_cache_hit"] += 1
            return self._done(sweeper)
        _STATS["htr_cache_miss"] += 1
        _STATS["dirty_chunks"] += int(dirty.size)
        return self._launch(self._update_steps(chunks, dirty, n), sweeper)

    def update_rows(self, chunks: np.ndarray, dirty: np.ndarray,
                    sweeper: LevelSweeper | None = None):
        """Like ``root`` but with the dirty leaf set supplied by the caller
        (``RegistryTree`` already knows which validator rows changed, so the
        chunk-level compare would be redundant work). ``dirty`` must be a
        superset of the changed rows; shrink/first-use still rebuilds."""
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8).reshape(-1, 32)
        n = chunks.shape[0]
        if self.limit is not None and n > self.limit:
            raise ValueError(f"{n} chunks exceed limit {self.limit}")
        if self.levels is None or n < self.count or self._pending:
            return self._launch(self._rebuild_steps(chunks), sweeper)
        dirty = np.asarray(dirty, dtype=np.int64)
        if n > self.count:
            dirty = np.concatenate(
                [dirty, np.arange(self.count, n, dtype=np.int64)])
        dirty = np.unique(dirty)
        if dirty.size == 0 and n == self.count:
            _STATS["htr_cache_hit"] += 1
            return self._done(sweeper)
        _STATS["htr_cache_miss"] += 1
        _STATS["dirty_chunks"] += int(dirty.size)
        return self._launch(self._update_steps(chunks, dirty, n), sweeper)

    # -- internals ------------------------------------------------------------

    def _done(self, sweeper):
        """Root already known (cache hit): bytes, or a finisher in
        deferred mode — same contract either way."""
        if sweeper is None:
            return self._root
        root = self._root
        return lambda: root

    def _launch(self, gen, sweeper):
        """Run one update generator — immediately (standalone) or on the
        caller's lockstep batcher (deferred)."""
        if sweeper is None:
            drive(gen)
            return self._root
        sweeper.add(gen)
        return lambda: self._root

    def _effective_depth(self, n: int) -> int:
        limit = self.limit if self.limit is not None else max(n, 1)
        return _depth_for(limit)

    def _rebuild_steps(self, chunks: np.ndarray):
        """Full-tree rebuild as a level-sweep generator: yields each
        level's (left, right) pair block, receives the digests."""
        n = chunks.shape[0]
        _STATS["rebuilds"] += 1
        _STATS["htr_cache_miss"] += 1
        _STATS["dirty_chunks"] += n
        self._pending = True
        self.count = n
        if n == 0:
            self.levels = [np.empty((0, 32), dtype=np.uint8)]
            self._root = ZERO_HASHES[self._effective_depth(0)].tobytes()
            self._pending = False
            return
        levels = [chunks.copy()]
        layer = levels[0]
        level = 0
        while layer.shape[0] > 1:
            if layer.shape[0] % 2 == 1:
                layer = np.concatenate(
                    [layer, ZERO_HASHES[level][None, :]], axis=0)
            layer = yield (np.ascontiguousarray(layer[0::2]),
                           np.ascontiguousarray(layer[1::2]))
            levels.append(layer)
            level += 1
        self.levels = levels
        self._root = self._cap(levels[-1][0], level)
        self._pending = False

    def _update_steps(self, chunks: np.ndarray, dirty: np.ndarray, n: int):
        """Dirty-path rehash as a level-sweep generator (the lockstep
        form of the old ``_update`` — identical writes, identical
        digests; leaf writes happen when the generator is primed)."""
        self._pending = True
        levels = self.levels
        if n != self.count:
            levels[0] = chunks.copy()
        else:
            levels[0][dirty] = chunks[dirty]
        self.count = n
        size = n
        k = 0
        while size > 1:
            parents = np.unique(dirty >> 1)
            next_size = (size + 1) // 2
            if len(levels) <= k + 1:
                levels.append(np.zeros((next_size, 32), dtype=np.uint8))
            elif levels[k + 1].shape[0] != next_size:
                grown = np.zeros((next_size, 32), dtype=np.uint8)
                keep = min(levels[k + 1].shape[0], next_size)
                grown[:keep] = levels[k + 1][:keep]
                levels[k + 1] = grown
            child = levels[k]
            left = child[2 * parents]
            right_idx = 2 * parents + 1
            in_range = right_idx < size
            right = np.empty((parents.shape[0], 32), dtype=np.uint8)
            if in_range.any():
                right[in_range] = child[right_idx[in_range]]
            if (~in_range).any():
                right[~in_range] = ZERO_HASHES[k]
            digests = yield (np.ascontiguousarray(left), right)
            levels[k + 1][parents] = digests
            dirty = parents
            size = next_size
            k += 1
        del levels[k + 1:]
        self._root = self._cap(levels[k][0], k)
        self._pending = False

    def _cap(self, top: np.ndarray, k: int) -> bytes:
        """Combine the top of the occupied subtree with virtual zero
        subtrees up to the type-limit depth (the SSZ padding rule)."""
        root = top.tobytes()
        for level in range(k, self._effective_depth(self.count)):
            root = sha256(root + ZERO_HASHES[level].tobytes())
        return root


# --- validator registry -------------------------------------------------------

_SCALAR_COLS = ("effective_balance", "slashed", "activation_eligibility_epoch",
                "activation_epoch", "exit_epoch", "withdrawable_epoch")
_ROW_COLS = ("pubkeys", "withdrawal_credentials")


def _validator_roots_rows(reg, idx: np.ndarray) -> np.ndarray:
    """``ValidatorRegistry.validator_roots`` restricted to rows ``idx``
    (same batched 8-leaf merkleization, bit-identical per row)."""
    k = idx.shape[0]
    leaves = np.zeros((k, 8, 32), dtype=np.uint8)
    pk = reg.pubkeys[idx]
    pk_hi = np.zeros((k, 32), dtype=np.uint8)
    pk_hi[:, :16] = pk[:, 32:]
    leaves[:, 0] = pair_hash(np.ascontiguousarray(pk[:, :32]), pk_hi)
    leaves[:, 1] = reg.withdrawal_credentials[idx]
    leaves[:, 2, :8] = reg.effective_balance[idx].astype(
        "<u8").view(np.uint8).reshape(k, 8)
    leaves[:, 3, 0] = reg.slashed[idx].astype(np.uint8)
    for j, f in enumerate(("activation_eligibility_epoch", "activation_epoch",
                           "exit_epoch", "withdrawable_epoch")):
        leaves[:, 4 + j, :8] = getattr(reg, f)[idx].astype(
            "<u8").view(np.uint8).reshape(k, 8)
    layer = leaves.reshape(k * 8, 32)
    for _ in range(3):
        layer = pair_hash(layer[0::2], layer[1::2])
    return layer.reshape(k, 32)


class RegistryTree:
    """Incremental ``List[Validator, VALIDATOR_REGISTRY_LIMIT]`` root.

    Keeps a copy of every registry column plus the per-validator roots;
    ``root(reg)`` finds dirty rows by column compare (``np.array_equal``
    fast path per column — most blocks touch no registry column at all),
    re-merkleizes only those validators, and pushes the changed roots into
    a limit-capped ``ChunkTree``.
    """

    __slots__ = ("_cols", "_roots", "_tree", "_limit")

    def __init__(self):
        self._cols: dict | None = None
        self._roots: np.ndarray | None = None
        self._tree: ChunkTree | None = None
        self._limit = -1

    def root(self, reg, limit: int, sweeper: LevelSweeper | None = None):
        """Incremental registry root; same deferred contract as
        ``ChunkTree.root`` (the dirty-row re-merkleization runs eagerly
        — it is itself one batched ``pair_hash`` cascade — and the
        chunk-tree update joins the caller's lockstep sweep)."""
        n = len(reg)
        if self._tree is None or limit != self._limit:
            self._limit = limit
            self._tree = ChunkTree(limit)
            self._cols = None
        if self._cols is None or n < self._roots.shape[0]:
            self._roots = reg.validator_roots()
            self._snapshot(reg, np.arange(n, dtype=np.int64), n)
            fin = self._tree.update_rows(
                self._roots, np.arange(n, dtype=np.int64), sweeper)
            if sweeper is None:
                return mix_in_length(fin, n)
            return lambda: mix_in_length(fin(), n)

        old_n = self._roots.shape[0]
        m = min(old_n, n)
        dirty_mask = None
        for f in _SCALAR_COLS + _ROW_COLS:
            new_col = getattr(reg, f)
            old_col = self._cols[f]
            if new_col.shape[0] == old_col.shape[0] and \
                    np.array_equal(new_col, old_col):
                continue
            d = new_col[:m] != old_col[:m]
            if d.ndim == 2:
                d = d.any(axis=1)
            dirty_mask = d if dirty_mask is None else (dirty_mask | d)
        dirty = (np.nonzero(dirty_mask)[0].astype(np.int64)
                 if dirty_mask is not None else np.empty(0, dtype=np.int64))
        if n > old_n:
            dirty = np.concatenate(
                [dirty, np.arange(old_n, n, dtype=np.int64)])
        if dirty.size:
            new_roots = _validator_roots_rows(reg, dirty)
            if n > old_n:
                grown = np.zeros((n, 32), dtype=np.uint8)
                grown[:old_n] = self._roots
                self._roots = grown
            self._roots[dirty] = new_roots
            self._snapshot(reg, dirty, n)
        fin = self._tree.update_rows(self._roots, dirty, sweeper)
        if sweeper is None:
            return mix_in_length(fin, n)
        return lambda: mix_in_length(fin(), n)

    def _snapshot(self, reg, dirty: np.ndarray, n: int) -> None:
        """Refresh the column copies for the rows just re-hashed."""
        if self._cols is None or n != self._cols["effective_balance"].shape[0]:
            self._cols = {f: getattr(reg, f).copy()
                          for f in _SCALAR_COLS + _ROW_COLS}
            return
        for f in _SCALAR_COLS + _ROW_COLS:
            self._cols[f][dirty] = getattr(reg, f)[dirty]


# --- per-container orchestration ----------------------------------------------

def _pack_uint_chunks(arr: np.ndarray, byte_len: int) -> np.ndarray:
    """Basic-uint list/vector -> (ceil(bytes/32), 32) zero-padded chunks."""
    raw = np.ascontiguousarray(arr).astype(f"<u{byte_len}").view(np.uint8)
    n_bytes = raw.size
    if n_bytes == 0:
        return np.empty((0, 32), dtype=np.uint8)
    padded = np.zeros(((n_bytes + 31) // 32) * 32, dtype=np.uint8)
    padded[:n_bytes] = raw.reshape(-1)
    return padded.reshape(-1, 32)


class _TreeField:
    """A field backed by a ``ChunkTree`` (+ optional length mix-in)."""

    __slots__ = ("chunker", "mix", "length_of", "tree")

    def __init__(self, chunker, mix: bool, length_of, limit: int | None):
        self.chunker = chunker
        self.mix = mix
        self.length_of = length_of
        self.tree = ChunkTree(limit)

    def root(self, value) -> bytes:
        return self.root_deferred(value, None)()

    def root_deferred(self, value, sweeper):
        fin = self.tree.root(self.chunker(value), sweeper)
        if sweeper is None:
            root = fin
            fin = lambda: root  # noqa: E731 — uniform finisher shape
        if not self.mix:
            return fin
        length = self.length_of(value)
        return lambda: mix_in_length(fin(), length)


class _SmallField:
    """Serialize-compare memo for cheap fields: identical serialization
    implies identical root (SSZ serialization is injective per sedes)."""

    __slots__ = ("sedes", "_blob", "_root")

    def __init__(self, sedes):
        self.sedes = sedes
        self._blob = None
        self._root = b""

    def root(self, value) -> bytes:
        blob = self.sedes.serialize(value)
        if blob == self._blob:
            _STATS["htr_cache_hit"] += 1
            return self._root
        _STATS["htr_cache_miss"] += 1
        self._blob = blob
        self._root = self.sedes.htr(value)
        return self._root

    def root_deferred(self, value, sweeper):
        root = self.root(value)  # cheap fields never defer
        return lambda: root


class _RegistryField:
    __slots__ = ("reg_tree",)

    def __init__(self):
        self.reg_tree = RegistryTree()

    def root(self, value) -> bytes:
        from pos_evolution_tpu.config import cfg
        return self.reg_tree.root(value, cfg().validator_registry_limit)

    def root_deferred(self, value, sweeper):
        from pos_evolution_tpu.config import cfg
        fin = self.reg_tree.root(value, cfg().validator_registry_limit,
                                 sweeper)
        if sweeper is None:
            root = fin
            return lambda: root
        return fin


class ContainerTreeCache:
    """Incremental ``hash_tree_root`` for one container lineage.

    Field handlers are derived from the container's sedes inventory: the
    dense registry, root-row vectors/lists and packed uint lists/vectors
    get persistent trees; everything else gets a serialize-compare memo.
    """

    def __init__(self, cls):
        from pos_evolution_tpu.specs import containers as _c
        from pos_evolution_tpu.ssz.core import _sedes_of
        self.cls = cls
        self.fields = {}
        for fname, s in cls._fields.items():
            sedes = _sedes_of(s)
            if isinstance(sedes, _c._RegistrySedes):
                self.fields[fname] = _RegistryField()
            elif isinstance(sedes, _c.Bytes32Rows):
                self.fields[fname] = _TreeField(
                    chunker=lambda v: v,
                    mix=sedes.is_list,
                    length_of=lambda v: np.ascontiguousarray(
                        v, dtype=np.uint8).reshape(-1, 32).shape[0],
                    limit=sedes.limit if sedes.is_list else None)
            elif isinstance(sedes, _c._U64ListSedes):
                per_chunk = 32 // sedes.byte_len
                limit_chunks = (sedes.limit + per_chunk - 1) // per_chunk
                self.fields[fname] = _TreeField(
                    chunker=(lambda bl: lambda v: _pack_uint_chunks(v, bl))(
                        sedes.byte_len),
                    mix=True,
                    length_of=lambda v: np.asarray(v).shape[0],
                    limit=limit_chunks)
            elif isinstance(sedes, _c._U64VectorSedes):
                self.fields[fname] = _TreeField(
                    chunker=lambda v: _pack_uint_chunks(v, 8),
                    mix=False, length_of=None, limit=None)
            else:
                self.fields[fname] = _SmallField(sedes)
        self.top = ChunkTree(None)

    def root(self, value) -> bytes:
        """One container root = one lockstep sweep: every dirty field
        tree registers its level generators on a shared ``LevelSweeper``,
        so level k of ALL fields hashes in one kernel launch (and one
        device dispatch decision) instead of one call per field. The top
        field-roots tree depends on every finisher, so it runs after."""
        _STATS["htr_calls"] += 1
        sweeper = LevelSweeper()
        finishers = [self.fields[f].root_deferred(getattr(value, f), sweeper)
                     for f in self.cls._fields]
        sweeper.run()
        roots = b"".join(fin() for fin in finishers)
        chunks = np.frombuffer(roots, dtype=np.uint8).reshape(-1, 32)
        return self.top.root(chunks)


# --- BeaconState entry point --------------------------------------------------

def state_root(state) -> bytes:
    """Incremental ``hash_tree_root`` for a BeaconState: attach (or reuse)
    the lineage cache and fold in only the dirty subtrees. Falls back to
    full re-merkleization when disabled via ``set_enabled(False)``."""
    if not _ENABLED:
        return type(state).htr(state)
    cache = state.__dict__.get("_htr_cache")
    if cache is None or cache.cls is not type(state):
        cache = ContainerTreeCache(type(state))
        state._htr_cache = cache
    return cache.root(state)
