"""SHA-256: scalar (hashlib) and batched vectorized (NumPy) implementations.

This is native component N2 of the build (SURVEY.md §2.7): SHA-256 is the hot
primitive behind the swap-or-not shuffle (2 hashes x rounds x position-blocks,
pos-evolution.md:522-530), seed derivation (:486), and all SSZ merkleization
(:423, :9). The batched NumPy path processes N independent equal-length
messages as uint32 lane arithmetic — the same formulation the JAX/Pallas
kernel in ``ops/sha256.py`` uses on TPU.
"""

from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["hash_eth2", "sha256", "sha256_batch", "sha256_batch_lanes",
           "sha256_pairs", "sha256_pairs_lanes"]


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# The spec's `hash` function is SHA-256 (pos-evolution.md:9, :486).
hash_eth2 = sha256


_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """One SHA-256 compression round over a batch.

    state: (N, 8) uint32; blocks: (N, 16) uint32 big-endian words.
    """
    w = np.empty(blocks.shape[:-1] + (64,), dtype=np.uint32)
    w[..., :16] = blocks
    for t in range(16, 64):
        s0 = _rotr(w[..., t - 15], 7) ^ _rotr(w[..., t - 15], 18) ^ (w[..., t - 15] >> np.uint32(3))
        s1 = _rotr(w[..., t - 2], 17) ^ _rotr(w[..., t - 2], 19) ^ (w[..., t - 2] >> np.uint32(10))
        w[..., t] = w[..., t - 16] + s0 + w[..., t - 7] + s1

    a, b, c, d, e, f, g, h = (state[..., i].copy() for i in range(8))
    for t in range(64):
        big_s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + big_s1 + ch + _K[t] + w[..., t]
        big_s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = big_s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2

    out = np.stack([a, b, c, d, e, f, g, h], axis=-1)
    return (state + out).astype(np.uint32)


def _pad_messages(msgs: np.ndarray) -> np.ndarray:
    """Apply SHA-256 padding to a batch of equal-length messages.

    msgs: (N, L) uint8 -> (N, n_blocks*16) uint32 big-endian words.
    """
    n, length = msgs.shape
    bit_len = length * 8
    # message + 0x80 + zeros + 8-byte length, to a multiple of 64
    total = ((length + 1 + 8 + 63) // 64) * 64
    padded = np.zeros((n, total), dtype=np.uint8)
    padded[:, :length] = msgs
    padded[:, length] = 0x80
    padded[:, -8:] = np.frombuffer(bit_len.to_bytes(8, "big"), dtype=np.uint8)
    return padded.reshape(n, -1, 4).view(">u4")[..., 0].astype(np.uint32).reshape(n, -1)


# Below this batch size the fixed Python overhead of the lane kernel
# (~300 numpy dispatches) loses to a C hashlib loop.
_LANE_THRESHOLD = 1024
# Above this size, dispatch to the native C++ core (component N2) when built.
_NATIVE_THRESHOLD = 64


def _native():
    try:
        from pos_evolution_tpu import native
        return native if native.available() else None
    except Exception:
        return None


def sha256_batch(msgs: np.ndarray) -> np.ndarray:
    """SHA-256 of N equal-length messages at once.

    msgs: (N, L) uint8 array. Returns (N, 32) uint8 digests. Dispatch:
    tiny batches -> hashlib loop; medium/large -> native C++ core (N2)
    when built; fallback -> vectorized uint32-lane kernel (the same
    formulation as the TPU kernel).
    """
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    if msgs.ndim != 2:
        raise ValueError("sha256_batch expects a (N, L) uint8 array")
    n = msgs.shape[0]
    if n == 0:
        return np.empty((0, 32), dtype=np.uint8)
    if n >= _NATIVE_THRESHOLD:
        native = _native()
        if native is not None:
            return native.sha256_batch(msgs)
    if n < _LANE_THRESHOLD:
        out = np.empty((n, 32), dtype=np.uint8)
        raw = msgs.tobytes()
        length = msgs.shape[1]
        for i in range(n):
            out[i] = np.frombuffer(
                hashlib.sha256(raw[i * length:(i + 1) * length]).digest(), dtype=np.uint8)
        return out
    return sha256_batch_lanes(msgs)


def sha256_batch_lanes(msgs: np.ndarray) -> np.ndarray:
    """The vectorized uint32-lane kernel, undispatched: (N, L) uint8 ->
    (N, 32) digests on pure NumPy regardless of batch size or the
    native core. This is the "host NumPy sweep" that
    ``scripts/bench_merkle.py`` baselines the device kernel against,
    and the bottom rung of the ops/merkle_device fallback ladder's
    bit-identity tests."""
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n = msgs.shape[0]
    if n == 0:
        return np.empty((0, 32), dtype=np.uint8)
    words = _pad_messages(msgs)  # (N, n_blocks*16)
    state = np.broadcast_to(_H0, (n, 8)).copy()
    for blk in range(words.shape[1] // 16):
        state = _compress(state, words[:, blk * 16:(blk + 1) * 16])
    # big-endian state words -> bytes
    return state.astype(">u4").view(np.uint8).reshape(n, 32)


def sha256_pairs(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """Hash N 64-byte concatenations: sha256(left[i] || right[i]).

    left, right: (N, 32) uint8. Returns (N, 32) uint8. This is the merkle
    tree combiner used by ``ssz.merkle.merkleize``.
    """
    return sha256_batch(np.concatenate([left, right], axis=1))


def sha256_pairs_lanes(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """``sha256_pairs`` pinned to the pure-NumPy lane kernel (no native
    core, no hashlib loop) — the bench baseline / ladder oracle."""
    return sha256_batch_lanes(np.concatenate([left, right], axis=1))
