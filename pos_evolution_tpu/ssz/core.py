"""SSZ type system: basic types, vectors/lists/bitfields, containers.

A from-scratch implementation of the SSZ serialization + merkleization
standard that the reference's containers are written in (pos-evolution.md:9).
Values are plain Python/NumPy data — ints, bytes, numpy arrays for
registry-scale uint lists, Python lists for composite lists — while *sedes*
(schema) objects drive serialization and hashing. Registry-scale fields hash
through the vectorized chunk path in ``ssz/merkle.py``.
"""

from __future__ import annotations

import copy as _copy
from functools import lru_cache

import numpy as np

from pos_evolution_tpu.ssz.hash import sha256
from pos_evolution_tpu.ssz.merkle import merkleize_chunks, mix_in_length

__all__ = [
    "Sedes", "uint8", "uint16", "uint32", "uint64", "boolean",
    "ByteVector", "ByteList", "Bytes4", "Bytes20", "Bytes32", "Bytes48", "Bytes96",
    "Vector", "List", "Bitvector", "Bitlist", "Container",
    "hash_tree_root", "cached_root", "serialize", "deserialize",
]

OFFSET_SIZE = 4


def _pack_bytes_to_chunks(data: bytes) -> np.ndarray:
    """Right-pad bytes with zeros to a multiple of 32 and view as (N,32)."""
    n = len(data)
    padded_len = max(((n + 31) // 32) * 32, 32)
    buf = np.zeros(padded_len, dtype=np.uint8)
    if n:
        buf[:n] = np.frombuffer(data, dtype=np.uint8)
    return buf.reshape(-1, 32)


class Sedes:
    """Base schema object. Subclasses implement the SSZ type rules."""

    def is_fixed(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def htr(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


# --- basic types -------------------------------------------------------------

class _UInt(Sedes):
    def __init__(self, byte_len: int):
        self.byte_len = byte_len

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.byte_len

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.byte_len, "little")

    def deserialize(self, data: bytes) -> int:
        return int.from_bytes(data, "little")

    def htr(self, value) -> bytes:
        return int(value).to_bytes(self.byte_len, "little").ljust(32, b"\x00")

    def default(self) -> int:
        return 0

    @property
    def np_dtype(self):
        return {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[self.byte_len]

    def __repr__(self):
        return f"uint{self.byte_len * 8}"


class _Boolean(Sedes):
    def is_fixed(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        return data != b"\x00"

    def htr(self, value) -> bytes:
        return (b"\x01" if value else b"\x00").ljust(32, b"\x00")

    def default(self) -> bool:
        return False

    def __repr__(self):
        return "boolean"


uint8 = _UInt(1)
uint16 = _UInt(2)
uint32 = _UInt(4)
uint64 = _UInt(8)
boolean = _Boolean()


class _ByteVector(Sedes):
    def __init__(self, length: int):
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, value) -> bytes:
        b = bytes(value)
        if len(b) != self.length:
            raise ValueError(f"ByteVector[{self.length}] got {len(b)} bytes")
        return b

    def deserialize(self, data: bytes) -> bytes:
        return bytes(data)

    def htr(self, value) -> bytes:
        return merkleize_chunks(_pack_bytes_to_chunks(bytes(value)))

    def default(self) -> bytes:
        return b"\x00" * self.length

    def __repr__(self):
        return f"ByteVector[{self.length}]"


class _ByteList(Sedes):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        return bytes(value)

    def deserialize(self, data: bytes) -> bytes:
        return bytes(data)

    def htr(self, value) -> bytes:
        b = bytes(value)
        chunk_limit = (self.limit + 31) // 32
        chunks = _pack_bytes_to_chunks(b) if b else np.empty((0, 32), dtype=np.uint8)
        return mix_in_length(merkleize_chunks(chunks, max(chunk_limit, 1)), len(b))

    def default(self) -> bytes:
        return b""

    def __repr__(self):
        return f"ByteList[{self.limit}]"


@lru_cache(maxsize=None)
def ByteVector(length: int) -> _ByteVector:
    return _ByteVector(length)


@lru_cache(maxsize=None)
def ByteList(limit: int) -> _ByteList:
    return _ByteList(limit)


Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)


# --- homogeneous collections -------------------------------------------------

def _pack_basic_array(elem: _UInt, value) -> np.ndarray:
    """Pack a sequence of basic uints into (N, 32) chunks, vectorized."""
    arr = np.asarray(value, dtype=elem.np_dtype)
    if arr.ndim != 1:
        raise ValueError("expected 1-D array of basic elements")
    raw = arr.astype(f"<u{elem.byte_len}").view(np.uint8)
    return _pack_bytes_to_chunks(raw.tobytes()) if raw.size else np.empty((0, 32), dtype=np.uint8)


def _composite_roots(elem: Sedes, values) -> np.ndarray:
    roots = [elem.htr(v) for v in values]
    if not roots:
        return np.empty((0, 32), dtype=np.uint8)
    return np.frombuffer(b"".join(roots), dtype=np.uint8).reshape(-1, 32)


class _Vector(Sedes):
    def __init__(self, elem: Sedes, length: int):
        self.elem = elem
        self.length = length

    def is_fixed(self):
        return self.elem.is_fixed()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"Vector[{self.length}] got {len(value)} elements")
        if isinstance(self.elem, _UInt):
            return np.asarray(value, dtype=self.elem.np_dtype).astype(
                f"<u{self.elem.byte_len}").tobytes()
        return _serialize_sequence(self.elem, list(value))

    def deserialize(self, data: bytes):
        if isinstance(self.elem, _UInt):
            return np.frombuffer(data, dtype=f"<u{self.elem.byte_len}").astype(
                self.elem.np_dtype).copy()
        return _deserialize_sequence(self.elem, data)

    def htr(self, value) -> bytes:
        if isinstance(self.elem, _UInt):
            chunks = _pack_basic_array(self.elem, value)
            return merkleize_chunks(chunks, chunks.shape[0])
        return merkleize_chunks(_composite_roots(self.elem, value))

    def default(self):
        if isinstance(self.elem, _UInt):
            return np.zeros(self.length, dtype=self.elem.np_dtype)
        return [self.elem.default() for _ in range(self.length)]

    def __repr__(self):
        return f"Vector[{self.elem!r}, {self.length}]"


class _List(Sedes):
    def __init__(self, elem: Sedes, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        if isinstance(self.elem, _UInt):
            return np.asarray(value, dtype=self.elem.np_dtype).astype(
                f"<u{self.elem.byte_len}").tobytes()
        return _serialize_sequence(self.elem, list(value))

    def deserialize(self, data: bytes):
        if isinstance(self.elem, _UInt):
            return np.frombuffer(data, dtype=f"<u{self.elem.byte_len}").astype(
                self.elem.np_dtype).copy()
        return _deserialize_sequence(self.elem, data)

    def htr(self, value) -> bytes:
        n = len(value)
        if isinstance(self.elem, _UInt):
            chunks = _pack_basic_array(self.elem, value)
            per_chunk = 32 // self.elem.byte_len
            limit_chunks = (self.limit + per_chunk - 1) // per_chunk
            root = merkleize_chunks(chunks, max(limit_chunks, 1))
        else:
            root = merkleize_chunks(_composite_roots(self.elem, value), self.limit)
        return mix_in_length(root, n)

    def default(self):
        if isinstance(self.elem, _UInt):
            return np.zeros(0, dtype=self.elem.np_dtype)
        return []

    def __repr__(self):
        return f"List[{self.elem!r}, {self.limit}]"


class _Bitvector(Sedes):
    def __init__(self, length: int):
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def _bits(self, value) -> np.ndarray:
        bits = np.asarray(value, dtype=bool)
        if bits.shape[0] != self.length:
            raise ValueError(f"Bitvector[{self.length}] got {bits.shape[0]} bits")
        return bits

    def serialize(self, value) -> bytes:
        return np.packbits(self._bits(value), bitorder="little").tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        raw = np.frombuffer(data, dtype=np.uint8)
        return np.unpackbits(raw, bitorder="little")[: self.length].astype(bool)

    def htr(self, value) -> bytes:
        packed = np.packbits(self._bits(value), bitorder="little").tobytes()
        return merkleize_chunks(_pack_bytes_to_chunks(packed))

    def default(self) -> np.ndarray:
        return np.zeros(self.length, dtype=bool)

    def __repr__(self):
        return f"Bitvector[{self.length}]"


class _Bitlist(Sedes):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        bits = np.asarray(value, dtype=bool)
        # trailing delimiter bit marks the length
        with_delim = np.concatenate([bits, np.ones(1, dtype=bool)])
        return np.packbits(with_delim, bitorder="little").tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        raw = np.unpackbits(np.frombuffer(data, dtype=np.uint8), bitorder="little")
        # strip everything from the highest set (delimiter) bit
        idx = np.nonzero(raw)[0]
        if idx.size == 0:
            raise ValueError("malformed bitlist: no delimiter bit")
        return raw[: idx[-1]].astype(bool)

    def htr(self, value) -> bytes:
        bits = np.asarray(value, dtype=bool)
        packed = np.packbits(bits, bitorder="little").tobytes() if bits.size else b""
        chunk_limit = ((self.limit + 7) // 8 + 31) // 32
        chunks = _pack_bytes_to_chunks(packed) if packed else np.empty((0, 32), dtype=np.uint8)
        return mix_in_length(merkleize_chunks(chunks, max(chunk_limit, 1)), int(bits.size))

    def default(self) -> np.ndarray:
        return np.zeros(0, dtype=bool)

    def __repr__(self):
        return f"Bitlist[{self.limit}]"


@lru_cache(maxsize=None)
def Vector(elem: Sedes, length: int) -> _Vector:
    return _Vector(elem, length)


@lru_cache(maxsize=None)
def List(elem: Sedes, limit: int) -> _List:
    return _List(elem, limit)


@lru_cache(maxsize=None)
def Bitvector(length: int) -> _Bitvector:
    return _Bitvector(length)


@lru_cache(maxsize=None)
def Bitlist(limit: int) -> _Bitlist:
    return _Bitlist(limit)


# --- variable-size sequence framing ------------------------------------------

def _serialize_sequence(elem: Sedes, values: list) -> bytes:
    if elem.is_fixed():
        return b"".join(elem.serialize(v) for v in values)
    parts = [elem.serialize(v) for v in values]
    offset = OFFSET_SIZE * len(parts)
    head = b""
    for p in parts:
        head += offset.to_bytes(OFFSET_SIZE, "little")
        offset += len(p)
    return head + b"".join(parts)


def _deserialize_sequence(elem: Sedes, data: bytes) -> list:
    if not data:
        return []
    if elem.is_fixed():
        size = elem.fixed_size()
        if len(data) % size:
            raise ValueError("sequence length not a multiple of element size")
        return [elem.deserialize(data[i:i + size]) for i in range(0, len(data), size)]
    first = int.from_bytes(data[:OFFSET_SIZE], "little")
    count = first // OFFSET_SIZE
    offsets = [int.from_bytes(data[i * OFFSET_SIZE:(i + 1) * OFFSET_SIZE], "little")
               for i in range(count)] + [len(data)]
    return [elem.deserialize(data[offsets[i]:offsets[i + 1]]) for i in range(count)]


# --- containers --------------------------------------------------------------

class ContainerMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields: dict[str, Sedes] = {}
        for base in reversed(bases):
            fields.update(getattr(base, "_fields", {}))
        module_globals = vars(__import__("sys").modules.get(ns.get("__module__", ""), None)) \
            if ns.get("__module__") in __import__("sys").modules else {}
        for fname, sedes in ns.get("__annotations__", {}).items():
            if isinstance(sedes, str):
                # `from __future__ import annotations` stringifies annotations;
                # resolve sedes expressions in the defining module's namespace.
                try:
                    sedes = eval(sedes, module_globals, dict(ns))  # noqa: S307
                except Exception:
                    continue
            if isinstance(sedes, (Sedes, ContainerMeta)):
                fields[fname] = sedes
        cls._fields = fields
        return cls


class Container(metaclass=ContainerMeta):
    """Base class for SSZ containers; the class doubles as its own sedes."""

    _fields: dict[str, Sedes] = {}

    def __init__(self, **kwargs):
        for fname, sedes in self._fields.items():
            if fname in kwargs:
                setattr(self, fname, kwargs.pop(fname))
            else:
                setattr(self, fname, _sedes_of(sedes).default())
        if kwargs:
            raise TypeError(f"unknown fields for {type(self).__name__}: {list(kwargs)}")

    # -- sedes protocol (classmethods so the class is usable as a schema) --
    @classmethod
    def is_fixed(cls) -> bool:
        return all(_sedes_of(s).is_fixed() for s in cls._fields.values())

    @classmethod
    def fixed_size(cls) -> int:
        return sum(_sedes_of(s).fixed_size() for s in cls._fields.values())

    @classmethod
    def serialize(cls, value: "Container") -> bytes:
        fixed_parts: list[bytes | None] = []
        var_parts: list[bytes] = []
        for fname, s in cls._fields.items():
            sedes = _sedes_of(s)
            v = getattr(value, fname)
            if sedes.is_fixed():
                fixed_parts.append(sedes.serialize(v))
            else:
                fixed_parts.append(None)
                var_parts.append(sedes.serialize(v))
        fixed_len = sum(OFFSET_SIZE if p is None else len(p) for p in fixed_parts)
        out, var_out, offset = [], [], fixed_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out.append(offset.to_bytes(OFFSET_SIZE, "little"))
                var_out.append(var_parts[vi])
                offset += len(var_parts[vi])
                vi += 1
            else:
                out.append(p)
        return b"".join(out) + b"".join(var_out)

    @classmethod
    def deserialize(cls, data: bytes) -> "Container":
        values: dict[str, object] = {}
        pos = 0
        var_fields: list[tuple[str, Sedes, int]] = []
        for fname, s in cls._fields.items():
            sedes = _sedes_of(s)
            if sedes.is_fixed():
                size = sedes.fixed_size()
                values[fname] = sedes.deserialize(data[pos:pos + size])
                pos += size
            else:
                off = int.from_bytes(data[pos:pos + OFFSET_SIZE], "little")
                var_fields.append((fname, sedes, off))
                pos += OFFSET_SIZE
        bounds = [off for (_, _, off) in var_fields] + [len(data)]
        for i, (fname, sedes, off) in enumerate(var_fields):
            values[fname] = sedes.deserialize(data[off:bounds[i + 1]])
        return cls(**values)

    @classmethod
    def _field_chunks(cls, value: "Container") -> np.ndarray:
        """Zero-copy (n_fields, 32) view over the per-field chunk roots."""
        roots = b"".join(_sedes_of(s).htr(getattr(value, f)) for f, s in cls._fields.items())
        return np.frombuffer(roots, dtype=np.uint8).reshape(-1, 32)

    @classmethod
    def field_roots(cls, value: "Container") -> np.ndarray:
        """(n_fields, 32) per-field chunk roots — the leaves of ``htr``.

        Exposed so merkle *proofs into a container's field tree* (light-client
        finality / sync-committee branches) can be built from the same chunks
        the root hashes over. Returns a writable copy; ``htr`` itself stays
        on the zero-copy view (it is the hottest path in the codebase).
        """
        return cls._field_chunks(value).copy()

    @classmethod
    def htr(cls, value: "Container") -> bytes:
        return merkleize_chunks(cls._field_chunks(value))

    @classmethod
    def default(cls) -> "Container":
        return cls()

    # -- instance conveniences --
    def hash_tree_root(self) -> bytes:
        return type(self).htr(self)

    def copy(self) -> "Container":
        out = _copy.deepcopy(self)
        # a memoized root (cached_root) or an incremental-merkleization
        # cache must not ride into a copy that may be mutated
        out.__dict__.pop("_htr_memo", None)
        out.__dict__.pop("_htr_cache", None)
        return out

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        for f in self._fields:
            a, b = getattr(self, f), getattr(other, f)
            if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    return False
            elif a != b:
                return False
        return True

    def __hash__(self):
        return hash(self.hash_tree_root())

    def __repr__(self):
        inner = ", ".join(f"{f}={getattr(self, f)!r}" for f in list(self._fields)[:4])
        more = "..." if len(self._fields) > 4 else ""
        return f"{type(self).__name__}({inner}{more})"


class _ContainerSedes(Sedes):
    """Adapter making a Container class usable where a Sedes instance is."""

    def __init__(self, cls):
        self.cls = cls

    def is_fixed(self):
        return self.cls.is_fixed()

    def fixed_size(self):
        return self.cls.fixed_size()

    def serialize(self, v):
        return self.cls.serialize(v)

    def deserialize(self, data):
        return self.cls.deserialize(data)

    def htr(self, v):
        return self.cls.htr(v)

    def default(self):
        return self.cls()


@lru_cache(maxsize=None)
def _container_sedes(cls) -> _ContainerSedes:
    return _ContainerSedes(cls)


def _sedes_of(s) -> Sedes:
    if isinstance(s, Sedes):
        return s
    if isinstance(s, ContainerMeta):
        return _container_sedes(s)
    raise TypeError(f"not an SSZ schema: {s!r}")


# --- top-level API ------------------------------------------------------------

def hash_tree_root(value, sedes=None) -> bytes:
    """SSZ hash_tree_root (pos-evolution.md:142, 423, 1016-1024).

    Objects that define ``__ssz_root__`` (e.g. the dense validator registry)
    hash themselves; containers know their own schema; anything else needs an
    explicit ``sedes``. A root memoized with ``cached_root`` (immutable
    gossip objects: blocks, attestations) is honored first.
    """
    if sedes is None:
        d = getattr(value, "__dict__", None)
        if d is not None:
            memo = d.get("_htr_memo")
            if memo is not None:
                return memo
    custom = getattr(value, "__ssz_root__", None)
    if custom is not None and sedes is None:
        return custom()
    if sedes is None:
        if isinstance(value, Container):
            return type(value).htr(value)
        raise TypeError("hash_tree_root of a bare value requires a sedes")
    return _sedes_of(sedes).htr(value)


def cached_root(value) -> bytes:
    """``hash_tree_root`` memoized on the object (``_htr_memo``).

    Only for objects that are immutable once rooted — the driver's gossip
    payloads (signed blocks, attestations), whose roots were being
    recomputed at origination, gossip delivery, pool insert, and backfill.
    ``Container.copy()`` strips the memo, so copy-then-mutate flows
    (adversarial equivocation builders) cannot observe a stale root.
    """
    d = value.__dict__
    memo = d.get("_htr_memo")
    if memo is None:
        memo = hash_tree_root(value)
        d["_htr_memo"] = memo
    return memo


def serialize(value, sedes=None) -> bytes:
    if sedes is None:
        if isinstance(value, Container):
            return type(value).serialize(value)
        raise TypeError("serialize of a bare value requires a sedes")
    return _sedes_of(sedes).serialize(value)


def deserialize(data: bytes, sedes) -> object:
    return _sedes_of(sedes).deserialize(data)
