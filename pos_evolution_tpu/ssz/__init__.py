"""SSZ type system and merkleization (L1/L0 of SURVEY.md §1)."""

from pos_evolution_tpu.ssz.core import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Sedes,
    Vector,
    boolean,
    cached_root,
    deserialize,
    hash_tree_root,
    serialize,
    uint8,
    uint16,
    uint32,
    uint64,
)
from pos_evolution_tpu.ssz.hash import hash_eth2, sha256, sha256_batch, sha256_pairs
from pos_evolution_tpu.ssz.merkle import (
    ZERO_HASHES,
    is_valid_merkle_branch,
    merkle_tree_branch,
    merkleize,
    merkleize_chunks,
    mix_in_length,
)
