"""``MetricsRegistry``: named counters / gauges / histograms with label
support and Prometheus-text + JSON export (SURVEY.md §5 "structured
metrics"; the prose reference has none).

Design constraints, in order:

- **host-side and allocation-light** — metrics are updated from the sim
  driver's per-message hot loop and from ``ops/resident.py`` device-call
  sites, so one update must be a dict lookup + integer add, never I/O
  (export is pull-based: ``to_prometheus()`` / ``to_json()`` walk the
  registry when asked);
- **labels as sorted key-tuples** — the Prometheus data model
  (``name{k="v"}``) without a client-library dependency (nothing may be
  pip-installed in this image);
- **counts are the contract** — ``scripts/perf_gate.py`` gates on count
  metrics (recompiles, handler calls, dispatches) because counts are
  deterministic on CPU CI where timings are not. ``counts()`` flattens
  every counter into one {name[;labels]: int} dict for exactly that
  consumer.
"""

from __future__ import annotations

import json
import threading
from bisect import bisect_left

# Cross-process snapshot format (``snapshot()`` / ``merge_snapshot()``):
# bumped only when the shape changes incompatibly — readers refuse
# unknown versions instead of misfolding a future format.
SNAPSHOT_VERSION = 1


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _label_text(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self.series: dict[tuple, object] = {}
        # updates are read-modify-writes: the serving tier
        # (serve/server.py) increments one registry from N worker and
        # reader threads, where an unlocked `get + set` silently drops
        # counts — and the perf gate gates on those counts. One
        # uncontended lock acquisition is ~100 ns; the driver hot loop
        # doesn't notice.
        self._lock = threading.Lock()

    def _prom_header(self) -> list[str]:
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        return out

    def to_prometheus(self) -> list[str]:
        """One scalar sample per labelled series (Histogram overrides)."""
        out = self._prom_header()
        for key in sorted(self.series):
            out.append(f"{self.name}{_label_text(key)} {self.series[key]}")
        return out


class Counter(_Metric):
    """Monotonically increasing count, optionally labelled."""

    kind = "counter"

    def inc(self, amount: int | float = 1, **labels) -> None:
        assert amount >= 0, "counters only go up"
        key = _label_key(labels)
        with self._lock:
            self.series[key] = self.series.get(key, 0) + amount

    def value(self, **labels):
        return self.series.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Last-write-wins scalar (queue depths, capacities, lag)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        # last-write-wins is the gauge semantic, but the first touch of a
        # key races dict insertion against concurrent inc() resizes —
        # same discipline as every other series update
        with self._lock:
            self.series[_label_key(labels)] = value

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self.series[key] = self.series.get(key, 0) + amount

    def value(self, **labels):
        return self.series.get(_label_key(labels), 0)


# Default bounds sized for handler latencies in seconds: 0.1 ms .. ~13 s.
_DEFAULT_BUCKETS = tuple(0.0001 * 2 ** i for i in range(18))


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf is implicit)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str = "",
                 buckets: tuple = _DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        self.observe_n(value, 1, **labels)

    def observe_n(self, value: float, n: int, **labels) -> None:
        """``n`` observations of ``value`` in one bucket update — hot
        paths that tally identical sub-bucket samples batch them here
        instead of paying the label-key encode + lock per sample."""
        key = _label_key(labels)
        with self._lock:
            row = self.series.get(key)
            if row is None:
                row = {"bucket_counts": [0] * len(self.buckets),
                       "sum": 0.0, "count": 0}
                self.series[key] = row
            i = bisect_left(self.buckets, value)
            if i < len(self.buckets):
                row["bucket_counts"][i] += n
            row["sum"] += value * n
            row["count"] += n

    def value(self, **labels) -> dict | None:
        return self.series.get(_label_key(labels))

    def to_prometheus(self) -> list[str]:
        out = self._prom_header()
        for key in sorted(self.series):
            row = self.series[key]
            cum = 0
            for le, c in zip(self.buckets, row["bucket_counts"]):
                cum += c
                bkey = key + (("le", repr(float(le))),)
                out.append(f"{self.name}_bucket{_label_text(bkey)} {cum}")
            bkey = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_label_text(bkey)} {row['count']}")
            out.append(f"{self.name}_sum{_label_text(key)} {row['sum']}")
            out.append(f"{self.name}_count{_label_text(key)} {row['count']}")
        return out


class MetricsRegistry:
    """One namespace of metrics; get-or-create accessors so call sites
    never need registration order."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help_: str, **kw):
        # get-or-create under the lock: two threads first touching the
        # same metric name concurrently must share ONE object, or the
        # loser's updates land on an orphan and vanish
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, **kw)
                self._metrics[name] = m
        assert isinstance(m, cls), \
            f"metric {name!r} already registered as {m.kind}"
        return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(Counter, name, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(Gauge, name, help_)

    def histogram(self, name: str, help_: str = "",
                  buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_, buckets=buckets)

    # -- export ----------------------------------------------------------------

    def to_prometheus(self) -> str:
        lines = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].to_prometheus())
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> dict:
        out = {}
        for name, m in sorted(self._metrics.items()):
            series = []
            for key, val in sorted(m.series.items()):
                entry = {"labels": dict(key)}
                if m.kind == "histogram":
                    entry.update(val)
                    entry["buckets"] = list(m.buckets)
                else:
                    entry["value"] = val
                series.append(entry)
            out[name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def dumps(self) -> str:
        return json.dumps(self.to_json(), indent=1, sort_keys=True)

    # -- cross-process snapshot / merge (ISSUE 18 fleet pipeline) --------------

    def snapshot(self) -> dict:
        """Schema-versioned, JSON-serializable copy of every series —
        the unit a worker process flushes beside its heartbeat file and
        a ``FleetAggregator`` merges back. Unlike ``to_json`` this holds
        each metric's lock while copying, so a concurrent ``observe_n``
        can never leave a torn histogram row (bucket counts from one
        batch, ``count`` from another) in the snapshot."""
        metrics: dict[str, dict] = {}
        for name, m in sorted(self._metrics.items()):
            with m._lock:
                if m.kind == "histogram":
                    series = [{"labels": dict(key),
                               "bucket_counts": list(row["bucket_counts"]),
                               "sum": row["sum"], "count": row["count"]}
                              for key, row in sorted(m.series.items())]
                else:
                    series = [{"labels": dict(key), "value": val}
                              for key, val in sorted(m.series.items())]
            entry = {"kind": m.kind, "help": m.help, "series": series}
            if m.kind == "histogram":
                entry["buckets"] = list(m.buckets)
            metrics[name] = entry
        return {"v": SNAPSHOT_VERSION, "metrics": metrics}

    def merge_snapshot(self, snap: dict, extra_labels: dict | None = None
                       ) -> None:
        """Fold one ``snapshot()`` emission into this registry,
        optionally tagging every series with ``extra_labels`` (the fleet
        aggregator passes ``{"worker": "<id>"}`` so per-worker series
        stay distinguishable after the merge). Counters and histogram
        rows ADD — merging two snapshots of the same worker double
        counts, by design the caller's problem; gauges are last-write-
        wins, matching their single-registry semantics."""
        if snap.get("v") != SNAPSHOT_VERSION:
            raise ValueError(
                f"unknown metrics snapshot version {snap.get('v')!r} "
                f"(this reader understands v{SNAPSHOT_VERSION})")
        extra = extra_labels or {}
        for name, entry in sorted((snap.get("metrics") or {}).items()):
            kind = entry.get("kind")
            if kind == "counter":
                c = self.counter(name, entry.get("help", ""))
                for row in entry.get("series", ()):
                    c.inc(row.get("value", 0),
                          **{**row.get("labels", {}), **extra})
            elif kind == "gauge":
                g = self.gauge(name, entry.get("help", ""))
                for row in entry.get("series", ()):
                    g.set(row.get("value", 0),
                          **{**row.get("labels", {}), **extra})
            elif kind == "histogram":
                bounds = tuple(entry.get("buckets", _DEFAULT_BUCKETS))
                h = self.histogram(name, entry.get("help", ""),
                                   buckets=bounds)
                for row in entry.get("series", ()):
                    labels = {**row.get("labels", {}), **extra}
                    if h.buckets == tuple(sorted(bounds)):
                        key = _label_key(labels)
                        with h._lock:
                            dst = h.series.get(key)
                            if dst is None:
                                dst = {"bucket_counts":
                                       [0] * len(h.buckets),
                                       "sum": 0.0, "count": 0}
                                h.series[key] = dst
                            src = row.get("bucket_counts", ())
                            for i, n in enumerate(src[:len(h.buckets)]):
                                dst["bucket_counts"][i] += n
                            dst["sum"] += row.get("sum", 0.0)
                            dst["count"] += row.get("count", 0)
                    else:
                        # bucket bounds drifted between emitter and
                        # merger (mixed code versions): degrade to
                        # re-observing each bucket at its upper bound —
                        # totals stay exact, bucket placement approximate
                        srcb = sorted(bounds)
                        counts = list(row.get("bucket_counts", ()))
                        for le, n in zip(srcb, counts):
                            if n:
                                h.observe_n(le, n, **labels)
                        over = row.get("count", 0) - sum(counts)
                        if over > 0 and srcb:  # +Inf-bucket residue
                            h.observe_n(srcb[-1] * 2, over, **labels)

    def counts(self) -> dict[str, int | float]:
        """Flatten all counters (and histogram counts) into one
        {name[;k=v;...]: value} dict — the count-based emission
        ``scripts/perf_gate.py`` gates on."""
        out: dict[str, int | float] = {}
        for name, m in sorted(self._metrics.items()):
            for key, val in sorted(m.series.items()):
                suffix = "".join(f";{k}={v}" for k, v in key)
                if m.kind == "counter":
                    out[name + suffix] = val
                elif m.kind == "histogram":
                    out[name + suffix + ";stat=count"] = val["count"]
        return out
