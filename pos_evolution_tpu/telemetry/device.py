"""Device flight recorder: HBM watermarks, shard-skew probes, and the
compile-provenance ledger in one armable bundle (ISSUE 19).

ROADMAP item 5's levers (donated epoch step, gather collapse, compile
pre-seeding) are device-level phenomena. This module is the device-side
counterpart of the fleet observability plane:

- **memory watermarks** — :class:`DeviceMemorySampler` reads
  ``device.memory_stats()`` per device and turns it into
  ``device_memory_bytes{device,stat}`` gauges, ``device_memory`` events,
  and an in-memory headroom curve. On CPU jax returns ``memory_stats()
  = None`` (jax 0.4.37, probed), so the sampler falls back to a pure
  host RSS estimate from ``/proc/self/statm`` — labelled
  ``platform=host_rss`` because it measures the *process*, not an
  accelerator: it includes Python, numpy, caches; it proves the
  sampling plumbing and gives a CPU headroom proxy, nothing more.
- **shard-skew probes** — :func:`shard_completion_times` walks an
  output array's ``addressable_shards`` and records, per device, when
  that device's shard became ready. Blocking is one-pass in shard
  order, so each row is "time until *this* shard AND every
  earlier-polled shard finished" — cumulative and monotone, which still
  bounds the straggler (the max row is exact; earlier rows are upper
  bounds only for devices polled after the straggler). One row on a
  single-device run.
- **flight recorder** — :class:`FlightRecorder` bundles the sampler, a
  ``profiling/ledger.CompileLedger`` and the skew accumulator behind
  one ``install()``/cadence policy, so the dense driver arms all four
  ISSUE-19 legs with a single kwarg. Probes run every
  ``sample_every``-th slot (the phase profiler's fencing policy), which
  is what keeps the fully-armed steady state within the +3% bench_obs
  budget.

Everything degrades silently: telemetry must never be the reason a
NumPy-only run dies.
"""

from __future__ import annotations

import json
import os
import time

__all__ = [
    "DeviceMemorySampler",
    "FlightRecorder",
    "host_rss_bytes",
    "shard_completion_times",
]

#: retained headroom-curve points before decimation (keeps artifacts and
#: memory bounded on 1M-validator-scale runs)
CURVE_CAP = 4096


def host_rss_bytes() -> int | None:
    """Resident-set bytes of this process from ``/proc/self/statm``
    (field 2 = resident pages). None off-Linux — the caller then simply
    has no fallback row. No psutil: nothing pip-installable here."""
    try:
        with open("/proc/self/statm") as fh:
            rss_pages = int(fh.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return None  # pev: ignore[PEV005] — estimator is best-effort


class DeviceMemorySampler:
    """Per-device memory watermarks with a host-RSS fallback.

    ``sample()`` never raises; each call appends one point (per device)
    to the in-memory curve, updates peak watermarks, sets
    ``device_memory_bytes`` gauges, and emits one ``device_memory``
    event when a bus is attached.
    """

    def __init__(self, registry=None, bus=None, curve_cap: int = CURVE_CAP):
        self.registry = registry
        self.bus = bus
        self.curve_cap = max(int(curve_cap), 2)
        self.samples = 0
        self.source: str | None = None
        self.curve: list[dict] = []
        self._curve_stride = 1  # decimation factor after cap overflows
        self.peak: dict[str, int] = {}

    def _rows(self) -> list[dict]:
        rows: list[dict] = []
        try:
            import jax
            for d in jax.devices():
                stats = d.memory_stats()
                if not stats:
                    continue  # CPU backend: memory_stats() is None
                row = {"device": f"{d.platform}:{d.id}",
                       "platform": d.platform,
                       "bytes_in_use": int(stats.get("bytes_in_use", 0))}
                for src, dst in (("peak_bytes_in_use", "peak_bytes_in_use"),
                                 ("bytes_limit", "limit_bytes")):
                    if stats.get(src) is not None:
                        row[dst] = int(stats[src])
                rows.append(row)
        except Exception:
            pass  # pev: ignore[PEV005] — sampling must never kill a run
        if rows:
            self.source = "memory_stats"
            return rows
        rss = host_rss_bytes()
        if rss is not None:
            self.source = "host_rss"
            return [{"device": "host", "platform": "host_rss",
                     "bytes_in_use": rss}]
        self.source = "unavailable"
        return []

    def sample(self, *, site: str = "slot", slot=None) -> list[dict]:
        rows = self._rows()
        if not rows:
            return rows
        self.samples += 1
        for row in rows:
            dev = row["device"]
            in_use = row["bytes_in_use"]
            if in_use > self.peak.get(dev, -1):
                self.peak[dev] = in_use
        reg = self.registry
        if reg is not None:
            try:
                g = reg.gauge("device_memory_bytes",
                              "per-device memory watermark samples")
                for row in rows:
                    g.set(row["bytes_in_use"], device=row["device"],
                          stat="bytes_in_use")
                    g.set(self.peak[row["device"]], device=row["device"],
                          stat="peak_bytes_in_use")
                    if row.get("limit_bytes") is not None:
                        g.set(row["limit_bytes"], device=row["device"],
                              stat="limit_bytes")
            except Exception:
                pass  # pev: ignore[PEV005] — gauges are best-effort
        point = {"unix": time.time(), "site": site, "slot": slot,
                 "rows": rows}
        if self.bus is not None:
            try:
                self.bus.emit("device_memory", **point)
            except Exception:
                pass  # pev: ignore[PEV005] — a closed bus must not kill us
        # bounded curve: on overflow drop every other retained point and
        # double the stride — spacing coarsens, endpoints survive
        if self.samples % self._curve_stride == 0:
            self.curve.append(point)
            if len(self.curve) >= self.curve_cap:
                del self.curve[1::2]
                self._curve_stride *= 2
        return rows

    def watermark(self) -> dict:
        return {"samples": self.samples, "source": self.source,
                "peak_bytes": dict(self.peak),
                "curve_points": len(self.curve),
                "curve_stride": self._curve_stride}


def shard_completion_times(array) -> list[dict]:
    """Per-device readiness of one (possibly sharded) array, ms since
    the probe started. Rows come back in shard-poll order; see module
    docstring for the cumulative-monotone caveat. Empty list when the
    value has no pollable shards (host arrays, no jax)."""
    t0 = time.perf_counter()
    rows: list[dict] = []
    try:
        shards = getattr(array, "addressable_shards", None)
        if shards:
            for sh in shards:
                sh.data.block_until_ready()
                rows.append({
                    "device": str(getattr(sh, "device", "?")),
                    "ms": round((time.perf_counter() - t0) * 1e3, 4)})
        elif hasattr(array, "block_until_ready"):
            array.block_until_ready()
            rows.append({"device": "0",
                         "ms": round((time.perf_counter() - t0) * 1e3, 4)})
    except Exception:
        return []  # pev: ignore[PEV005] — probing is best-effort
    return rows


class FlightRecorder:
    """Arms the device flight recorder for one run.

    >>> fr = FlightRecorder(telemetry=tel, sample_every=16)
    >>> sim = DenseSimulation(n, telemetry=tel, flight_recorder=fr)
    >>> sim.run_epochs(4)
    >>> fr.summary()["compile_ledger"]["attribution"]["named_pct"]

    The dense driver calls ``install()`` (idempotent) when handed a
    recorder, then ``should_probe``/``on_slot``/``on_epoch``/
    ``probe_skew``/``sample_memory`` at the cadence sites. Construction
    order matters for the >=95% attribution bar: arm *after* building
    the sim (warm-up compiles outside any phase would otherwise land
    unattributed) and before running it.
    """

    def __init__(self, telemetry=None, *, registry=None, bus=None,
                 sample_every: int = 16, skew: bool = True,
                 ledger: bool = True, memory: bool = True):
        if telemetry is not None:
            registry = registry if registry is not None else telemetry.registry
            bus = bus if bus is not None else telemetry.bus
        self.registry = registry
        self.bus = bus
        self.sample_every = max(int(sample_every), 1)
        self.memory = (DeviceMemorySampler(registry=registry, bus=bus)
                       if memory else None)
        if ledger:
            from pos_evolution_tpu.profiling.ledger import CompileLedger
            self.ledger = CompileLedger(registry=registry)
        else:
            self.ledger = None
        self.skew_enabled = bool(skew)
        self.skew_probes = 0
        # (phase, device) -> [total_ms, count, max_ms]
        self._skew: dict[tuple[str, str], list] = {}
        self._installed = False

    # -- lifecycle -------------------------------------------------------------

    @property
    def installed(self) -> bool:
        return self._installed

    def install(self) -> "FlightRecorder":
        """Point jax runtime telemetry at this recorder's registry and
        attach the compile ledger. Idempotent; last install wins (same
        semantics as ``jaxrt.install``)."""
        from pos_evolution_tpu.telemetry import jaxrt
        if self.registry is not None:
            jaxrt.install(self.registry)
        if self.ledger is not None:
            jaxrt.attach_ledger(self.ledger)
        self._installed = True
        return self

    def detach(self) -> None:
        from pos_evolution_tpu.telemetry import jaxrt
        if self.ledger is not None and jaxrt.current_ledger() is self.ledger:
            jaxrt.attach_ledger(None)
        self._installed = False

    # -- cadence sites (called by the drivers) ---------------------------------

    def should_probe(self, slot: int) -> bool:
        return (slot % self.sample_every) == 0

    def on_slot(self, slot: int) -> None:
        if self.memory is not None and self.should_probe(slot):
            self.memory.sample(site="slot", slot=slot)

    def on_epoch(self, slot: int) -> None:
        if self.memory is not None:
            self.memory.sample(site="epoch", slot=slot)

    def sample_memory(self, *, site: str, slot=None) -> None:
        if self.memory is not None:
            self.memory.sample(site=site, slot=slot)

    def probe_skew(self, phase: str, array, slot=None) -> list[dict]:
        """Record per-device completion of ``array`` under ``phase``.
        Call only at fenced/sampled slots — this blocks."""
        if not self.skew_enabled:
            return []
        rows = shard_completion_times(array)
        if not rows:
            return rows
        self.skew_probes += 1
        for row in rows:
            cell = self._skew.setdefault((phase, row["device"]),
                                         [0.0, 0, 0.0])
            cell[0] += row["ms"]
            cell[1] += 1
            cell[2] = max(cell[2], row["ms"])
        spread = round(max(r["ms"] for r in rows)
                       - min(r["ms"] for r in rows), 4)
        if self.bus is not None:
            try:
                self.bus.emit("shard_skew", phase=phase, slot=slot,
                              spread_ms=spread, rows=rows)
            except Exception:
                pass  # pev: ignore[PEV005] — probing is best-effort
        if self.registry is not None:
            try:
                self.registry.gauge(
                    "shard_skew_ms",
                    "straggler spread (max-min shard readiness) at the "
                    "last probed slot").set(spread, phase=phase)
            except Exception:
                pass  # pev: ignore[PEV005] — gauges are best-effort
        return rows

    # -- reporting -------------------------------------------------------------

    def skew_table(self) -> list[dict]:
        rows = [{"phase": k[0], "device": k[1],
                 "mean_ms": round(v[0] / v[1], 4), "max_ms": round(v[2], 4),
                 "probes": v[1]}
                for k, v in self._skew.items()]
        rows.sort(key=lambda r: (r["phase"], -r["max_ms"], r["device"]))
        return rows

    def summary(self) -> dict:
        out: dict = {"sample_every": self.sample_every,
                     "installed": self._installed}
        if self.memory is not None:
            out["memory"] = self.memory.watermark()
        if self.ledger is not None:
            out["compile_ledger"] = self.ledger.summary()
        if self.skew_enabled:
            out["shard_skew"] = {"probes": self.skew_probes,
                                 "table": self.skew_table()}
        return out

    def write_artifact(self, path: str) -> dict:
        """Write the device-ledger artifact ``run_report.py``
        auto-discovers beside an event log (``*device_ledger.json``):
        summary + the full memory curve."""
        doc = {"v": 1, "flight_recorder": self.summary()}
        if self.memory is not None:
            doc["memory_curve"] = self.memory.curve
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
        return doc
