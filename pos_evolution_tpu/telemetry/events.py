"""Structured event bus: schema-versioned JSONL with span lineage.

Every event is one JSON object per line, self-describing enough that
``scripts/run_report.py`` can reconstruct a run — finality timeline,
per-handler percentiles, fault attribution — **without access to the live
``Simulation``** (the acceptance contract of ISSUE 3).

Envelope (schema v1):

    {"v": 1, "seq": <int>, "type": "<event type>", ...payload...}

- ``seq`` is a per-bus monotonic ordinal: JSONL has no transactional
  ordering guarantee across writers, so consumers sort by ``seq``;
- span events additionally carry ``span`` (this event's id) and
  ``parent`` (the id of the causally preceding span, or null at the
  root). Span ids are **deterministic message identities**
  (``blk-<slot>-<proposer>``, ``att-<slot>-g<group>-c<committee>``, and
  per-edge ``…/g<dst>`` suffixes), not random uuids — the same run
  always produces the same lineage, which is what lets tests pin
  parent/child integrity across checkpoint/resume;
- ``t`` is SIMULATION time where the emitter has one (delivery events).
  The bus itself never stamps absolute wall-clock onto the envelope;
  emitters may still include measured fields (``duration_ms`` on
  deliveries, ``unix``/``elapsed_s`` on watchdog incidents), so golden
  JSONL fixtures are hand-authored, not regenerated from live runs.

The bus is deliberately not simulation state: ``Simulation.checkpoint``
excludes it (like wall-clock handler timings), and a resumed run records
only post-resume events.
"""

from __future__ import annotations

import io
import json
import os

SCHEMA_VERSION = 1


class EventBus:
    """Append-only event sink: in-memory list + optional JSONL file.

    ``path=None`` keeps events in memory only (tests, ad-hoc runs); with a
    path every ``emit`` writes one line immediately (line-buffered), so a
    crashed run still leaves a parseable prefix — the commit-on-arrival
    posture of ``utils/watchdog.py`` applied to events.
    """

    def __init__(self, path: str | os.PathLike | None = None,
                 keep_in_memory: bool = True, append: bool = False):
        self.path = os.fspath(path) if path is not None else None
        self.keep_in_memory = keep_in_memory
        self.events: list[dict] = []
        self._seq = 0
        self._fh: io.TextIOBase | None = None
        if self.path is not None:
            # append mode (ISSUE 10): a resumed attempt extends the
            # previous attempt's log instead of truncating it, and
            # continues the seq ordinal past the existing maximum so the
            # sort-by-seq contract keeps the attempts in order (a torn
            # final line from the killed writer is tolerated, exactly as
            # read_jsonl would)
            if append and os.path.exists(self.path):
                try:
                    prior = read_versioned_jsonl(self.path, SCHEMA_VERSION)
                    self._seq = 1 + max(
                        (e.get("seq", -1) for e in prior), default=-1)
                except ValueError:
                    pass  # mid-log corruption: emit from 0, report sorts
                # a killed writer can leave the final line without its
                # newline. Appending straight onto it would corrupt BOTH
                # events, and newline-terminating it would be worse: the
                # fragment would become a NON-final unparseable line,
                # which read_jsonl treats as fatal mid-log corruption.
                # Readers already drop a torn tail, so TRUNCATE it.
                with open(self.path, "rb+") as prev:
                    prev.seek(0, os.SEEK_END)
                    size = prev.tell()
                    if size > 0:
                        prev.seek(-1, os.SEEK_END)
                        if prev.read(1) != b"\n":
                            prev.seek(0)
                            data = prev.read(size)
                            keep = data.rfind(b"\n") + 1
                            prev.truncate(keep)
            self._fh = open(self.path, "a" if append else "w", buffering=1)

    # -- emission --------------------------------------------------------------

    def emit(self, type_: str, *, span: str | None = None,
             parent: str | None = None, **fields) -> dict:
        """Record one event; returns the envelope (callers chain span ids
        off it). Payload values must be JSON-serializable."""
        ev = {"v": SCHEMA_VERSION, "seq": self._seq, "type": type_}
        self._seq += 1
        if span is not None:
            ev["span"] = span
        if parent is not None:
            ev["parent"] = parent
        ev.update(fields)
        if self.keep_in_memory:
            self.events.append(ev)
        if self._fh is not None:
            self._fh.write(json.dumps(ev, sort_keys=True) + "\n")
        return ev

    # -- queries (test/report convenience on the in-memory view) ---------------

    def of_type(self, type_: str) -> list[dict]:
        return [e for e in self.events if e["type"] == type_]

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "EventBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_versioned_jsonl(path: str | os.PathLike, expected_version: int,
                         label: str = "event") -> list[dict]:
    """The one torn-tail-tolerant, schema-versioned JSONL reader — shared
    by the telemetry event log and the bench history
    (``profiling/history.py``), so the subtle semantics cannot drift
    between them.

    Tolerates a torn FINAL line (a run killed mid-write) — everything
    before it is still usable, which is the point of line-at-a-time
    commit. A decode error anywhere EARLIER is corruption, not a torn
    tail, and raises with the line number: silently dropping the suffix
    would present a truncated log as a complete one. Also raises on an
    unknown ``"v"``: consumers must not misread future formats.
    """
    with open(path) as fh:
        lines = [(i + 1, line.strip()) for i, line in enumerate(fh)]
    lines = [(ln, text) for ln, text in lines if text]
    out = []
    for pos, (ln, text) in enumerate(lines):
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            if pos == len(lines) - 1:
                break  # torn tail from a killed writer
            raise ValueError(
                f"{os.fspath(path)}:{ln}: corrupt {label} line mid-log "
                f"(only the final line may be torn)")
        v = obj.get("v")
        if v != expected_version:
            raise ValueError(
                f"unknown {label} schema version {v!r} "
                f"(this reader understands v{expected_version})")
        out.append(obj)
    return out


def read_jsonl(path: str | os.PathLike) -> list[dict]:
    """Load a JSONL event log back into memory, sorted by ``seq``
    (see ``read_versioned_jsonl`` for the torn-tail/corruption/schema
    contract)."""
    events = read_versioned_jsonl(path, SCHEMA_VERSION, label="event")
    events.sort(key=lambda e: e.get("seq", 0))
    return events


# -- multi-process event logs (ISSUE 18) ---------------------------------------
#
# Two processes appending to ONE EventBus file interleave partial lines
# whenever a write straddles a pipe buffer — the old plane only survived
# because workers reopened the file per emission and wrote short lines.
# The supported shape is one file per process: ``per_process_path``
# derives ``events.<pid>.jsonl`` from the logical log path, each process
# owns its file exclusively, and ``merge_event_files`` re-sequences the
# union for the offline consumers.

def per_process_path(path: str | os.PathLike,
                     pid: int | None = None) -> str:
    """``/run/events.jsonl`` -> ``/run/events.<pid>.jsonl``. Appending
    the pid BEFORE the final suffix keeps the ``.jsonl`` extension so
    every existing glob/tooling convention still matches."""
    path = os.fspath(path)
    pid = os.getpid() if pid is None else int(pid)
    root, ext = os.path.splitext(path)
    return f"{root}.{pid}{ext or '.jsonl'}"


def discover_per_process(path: str | os.PathLike) -> list[str]:
    """Sibling ``events.<pid>.jsonl`` files of a logical log path,
    sorted by pid — what ``scripts/run_report.py`` auto-merges."""
    path = os.fspath(path)
    directory = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    root, ext = os.path.splitext(base)
    found = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if not (name.startswith(root + ".") and name.endswith(ext)):
            continue
        middle = name[len(root) + 1:len(name) - len(ext)]
        if middle.isdigit():
            found.append((int(middle), os.path.join(directory, name)))
    return [p for _, p in sorted(found)]


def merge_event_files(paths, out_path: str | os.PathLike | None = None
                      ) -> list[dict]:
    """Merge per-process event logs into one stream, re-sequenced by
    ``(wall, seq, source order)`` — wall when the emitter stamped one
    (cross-process ordering needs a shared clock; per-bus ``seq`` only
    orders within one process), falling back to ``seq`` so single-file
    merges keep their original order. The merged events get fresh
    contiguous ``seq`` ordinals; the original ordinal survives as
    ``src_seq`` and the source pid (parsed from the filename) as
    ``src_pid``, so lineage back to the per-process file is never lost.

    ``out_path`` additionally writes the merged stream as JSONL (the
    shape every existing consumer reads)."""
    rows = []
    for order, path in enumerate(paths):
        pid = None
        root = os.path.splitext(os.path.basename(os.fspath(path)))[0]
        tail = root.rsplit(".", 1)[-1]
        if tail.isdigit():
            pid = int(tail)
        # events between wall-stamped ones inherit the last stamp seen
        # (carry-forward): per-file seq order is preserved exactly, and
        # cross-file interleave happens at wall-clock granularity
        last_wall = 0.0
        for ev in read_versioned_jsonl(path, SCHEMA_VERSION,
                                       label="event"):
            wall = ev.get("wall")
            if wall is not None:
                last_wall = max(last_wall, float(wall))
            rows.append(((last_wall, order, ev.get("seq", 0)), pid, ev))
    rows.sort(key=lambda r: r[0])
    merged = []
    for seq, (_, pid, ev) in enumerate(rows):
        ev = dict(ev)
        ev["src_seq"] = ev.get("seq", 0)
        if pid is not None:
            ev["src_pid"] = pid
        ev["seq"] = seq
        merged.append(ev)
    if out_path is not None:
        with open(os.fspath(out_path), "w") as fh:
            for ev in merged:
                fh.write(json.dumps(ev, sort_keys=True) + "\n")
    return merged
