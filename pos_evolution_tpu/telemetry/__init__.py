"""Unified telemetry subsystem (ISSUE 3; SURVEY.md §5).

One bundle, three legs:

- ``MetricsRegistry`` (``registry.py``): counters / gauges / histograms
  with labels, Prometheus-text + JSON export, and the flattened
  ``counts()`` emission ``scripts/perf_gate.py`` gates on;
- ``EventBus`` (``events.py``): schema-versioned JSONL — message
  lifecycle spans from ``sim/driver.py``, fault attribution from
  ``sim/faults.py``, degradation/fallback from ``ops/resident.py`` and
  ``utils/watchdog.py``; consumed offline by ``scripts/run_report.py``;
- JAX runtime telemetry (``jaxrt.py``): recompile/trace/lowering counts,
  compile-duration histograms, dispatch + transfer-byte counters, folded
  into the same registry.

Two attachment modes:

- **scoped**: pass a ``Telemetry`` to ``Simulation(telemetry=...)`` — the
  driver emits spans/slot records to that bus only (parallel sims don't
  interleave);
- **global sink**: components with no natural handle to a bus
  (``ops/resident.py`` degradation, ``utils/watchdog.py`` incidents)
  call ``emit_global``, a no-op until some harness calls
  ``set_global``/``Telemetry.install_global``.

Telemetry is **not simulation state**: ``Simulation.checkpoint`` excludes
it (exactly like wall-clock handler timings), and a resumed run records
only post-resume events.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from pos_evolution_tpu.telemetry.device import (
    DeviceMemorySampler,
    FlightRecorder,
)
from pos_evolution_tpu.telemetry.events import (
    SCHEMA_VERSION,
    EventBus,
    discover_per_process,
    merge_event_files,
    per_process_path,
    read_jsonl,
)
from pos_evolution_tpu.telemetry.fleet import FleetAggregator
from pos_evolution_tpu.telemetry.registry import (
    SNAPSHOT_VERSION,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

__all__ = [
    "SCHEMA_VERSION", "SNAPSHOT_VERSION", "EventBus", "read_jsonl",
    "per_process_path", "discover_per_process", "merge_event_files",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "FleetAggregator", "DeviceMemorySampler", "FlightRecorder",
    "Telemetry", "set_global", "get_global", "emit_global",
]


@dataclass
class Telemetry:
    """The bundle a harness threads through a run: one bus, one registry,
    and the debug flag that arms ``StoreInvariantChecker`` in the driver
    (snapshot/compare around every handler call — too slow for benches,
    exactly right for fault hunts)."""

    bus: EventBus = field(default_factory=EventBus)
    registry: MetricsRegistry = field(default_factory=MetricsRegistry)
    debug: bool = False

    @classmethod
    def to_file(cls, path, debug: bool = False,
                keep_in_memory: bool = True,
                append: bool = False) -> "Telemetry":
        """``append=True`` extends an existing log instead of truncating
        it — the resumed-attempt contract of ISSUE 10 (seq ordinals
        continue past the previous attempt's maximum)."""
        return cls(bus=EventBus(path, keep_in_memory=keep_in_memory,
                                append=append),
                   debug=debug)

    def install_jax_runtime(self) -> bool:
        """Fold JAX compiler/dispatch/transfer telemetry into this
        bundle's registry (process-global listeners; last install wins)."""
        from pos_evolution_tpu.telemetry import jaxrt
        return jaxrt.install(self.registry)

    def install_global(self) -> "Telemetry":
        """Also make this bundle the global sink for bus-less emitters
        (resident degradation, watchdog incidents)."""
        set_global(self)
        return self

    def close(self) -> None:
        self.bus.close()


_GLOBAL: list = [None]


def set_global(telemetry: Telemetry | None) -> None:
    _GLOBAL[0] = telemetry


def get_global() -> Telemetry | None:
    return _GLOBAL[0]


def emit_global(type_: str, **fields) -> dict | None:
    """Emit onto the global bus if one is installed; no-op otherwise.
    The call sites (degradation paths, watchdog incidents) must never
    fail because telemetry is absent or broken."""
    t = _GLOBAL[0]
    if t is None:
        return None
    try:
        return t.bus.emit(type_, **fields)
    except Exception:
        return None
