"""Cross-process metrics pipeline (ISSUE 18 leg a).

Each ``WorkerPool`` worker process owns a private ``MetricsRegistry``;
nothing in the plane shares memory for metrics (the shared-memory board
carries health rows, not series). The pipeline that makes the fleet
observable as ONE registry:

- the worker's beat thread calls ``write_snapshot`` every beat — an
  atomic tmp+rename JSON dump of ``MetricsRegistry.snapshot()`` beside
  its heartbeat file, named ``worker<id>.pid<pid>.metrics.json``. The
  pid in the name is load-bearing: a respawned incarnation writes a NEW
  file instead of overwriting its predecessor's, so a SIGKILLed
  worker's last-flushed counts survive into the fleet view (only the
  final beat-interval of updates is lost);
- ``FleetAggregator`` scans a directory for those snapshots and merges
  them into one registry, tagging every series with a ``worker=<id>``
  label (incarnations of the same worker id fold into one labelled
  series — counters add, which is exactly right across a respawn);
- the merged registry is served live by the admission-exempt
  ``metrics`` RPC on every ``ServeFront`` (Prometheus text + JSON),
  consumed by the balancer's health bias, asserted by
  ``run_mp_scenario``'s verdict (per-worker request counts must sum to
  the loadgen's sent count ± resends), and rendered by
  ``scripts/run_report.py``.

Snapshot files are self-describing: the registry snapshot rides under
``"registry"`` next to a small meta header (worker id, pid, front,
generation, wall). Readers tolerate a torn/absent file — a snapshot
mid-rename or a worker that died before its first beat must never fail
the scrape.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import time

from pos_evolution_tpu.telemetry.registry import (
    SNAPSHOT_VERSION,
    MetricsRegistry,
)

__all__ = ["FleetAggregator", "write_snapshot", "load_snapshot",
           "snapshot_path", "discover_snapshots"]

_SNAP_RE = re.compile(r"^worker(\d+)\.pid(\d+)\.metrics\.json$")


def snapshot_path(directory: str | os.PathLike, worker: int,
                  pid: int) -> str:
    return os.path.join(os.fspath(directory),
                        f"worker{worker}.pid{pid}.metrics.json")


def write_snapshot(path: str | os.PathLike, registry: MetricsRegistry,
                   worker: int, pid: int, front: int | None = None,
                   generation: int | None = None) -> None:
    """Atomic tmp+rename dump — a reader never sees a half-written
    snapshot, same discipline as the worker stats/heartbeat files."""
    path = os.fspath(path)
    blob = {
        "v": SNAPSHOT_VERSION,
        "worker": int(worker),
        "pid": int(pid),
        "front": front,
        "generation": generation,
        "wall": time.time(),
        "registry": registry.snapshot(),
    }
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".metrics_")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(blob, fh)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_snapshot(path: str | os.PathLike) -> dict | None:
    """One snapshot blob, or None when the file is absent/torn — a
    worker killed mid-rename must never fail the whole scrape."""
    try:
        with open(path) as fh:
            blob = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(blob, dict) \
            or blob.get("v") != SNAPSHOT_VERSION \
            or not isinstance(blob.get("registry"), dict):
        return None
    return blob


def discover_snapshots(directory: str | os.PathLike) -> list[str]:
    """Every ``worker<id>.pid<pid>.metrics.json`` under ``directory``,
    sorted by (worker, pid) for deterministic merge order."""
    directory = os.fspath(directory)
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for name in names:
        m = _SNAP_RE.match(name)
        if m:
            found.append((int(m.group(1)), int(m.group(2)), name))
    return [os.path.join(directory, name)
            for _, _, name in sorted(found)]


class FleetAggregator:
    """Merge per-worker registry snapshots into one fleet registry.

    >>> agg = FleetAggregator.from_dir(run_dir)
    >>> agg.registry.to_prometheus()     # every series worker-labelled
    >>> agg.worker_totals("serve_requests_total")
    {'0': 812, '1': 790, ...}
    """

    def __init__(self):
        self.registry = MetricsRegistry()
        self.workers: dict[str, dict] = {}  # worker id -> freshest meta
        self.snapshots_merged = 0
        self.snapshots_skipped = 0

    @classmethod
    def from_dir(cls, directory: str | os.PathLike,
                 extra: tuple = ()) -> "FleetAggregator":
        """Aggregate every snapshot in ``directory``; ``extra`` holds
        already-loaded blobs to fold in on top (the serving front passes
        its own in-memory registry this way so the live process is never
        a beat-interval stale in its own scrape)."""
        agg = cls()
        for path in discover_snapshots(directory):
            agg.add(load_snapshot(path))
        for blob in extra:
            agg.add(blob)
        return agg

    def add(self, blob: dict | None) -> bool:
        """Fold one snapshot blob in; False when the blob was unusable
        (torn file, schema drift) — counted, never raised."""
        if blob is None:
            self.snapshots_skipped += 1
            return False
        worker = str(blob.get("worker", "?"))
        try:
            self.registry.merge_snapshot(blob["registry"],
                                         extra_labels={"worker": worker})
        except (ValueError, KeyError, TypeError):
            self.snapshots_skipped += 1
            return False
        meta = self.workers.get(worker)
        if meta is None or (blob.get("wall") or 0) >= (meta.get("wall")
                                                       or 0):
            new = {
                "pid": blob.get("pid"), "front": blob.get("front"),
                "generation": blob.get("generation"),
                "wall": blob.get("wall"),
            }
            if meta is not None:
                # a live-registry blob carries no front/generation —
                # don't let it blank out what the beat snapshot knew
                for k in ("front", "generation"):
                    if new[k] is None:
                        new[k] = meta.get(k)
            self.workers[worker] = new
        self.snapshots_merged += 1
        return True

    # -- fleet views -----------------------------------------------------------

    def worker_totals(self, metric: str) -> dict[str, float]:
        """Per-worker total of one counter (all non-worker labels
        summed out): the shape the harness verdict and the balancer
        health bias consume."""
        m = self.registry._metrics.get(metric)
        out: dict[str, float] = {}
        if m is None or m.kind != "counter":
            return out
        for key, val in m.series.items():
            labels = dict(key)
            w = labels.get("worker")
            if w is not None:
                out[w] = out.get(w, 0) + val
        return out

    def fleet_total(self, metric: str) -> float:
        return sum(self.worker_totals(metric).values())

    def worker_status_totals(self, metric: str
                             ) -> dict[str, dict[str, float]]:
        """Per-worker counts split by ``status`` label — the balancer's
        health-bias input (error fraction per worker)."""
        m = self.registry._metrics.get(metric)
        out: dict[str, dict[str, float]] = {}
        if m is None or m.kind != "counter":
            return out
        for key, val in m.series.items():
            labels = dict(key)
            w = labels.get("worker")
            if w is None:
                continue
            by = out.setdefault(w, {})
            st = labels.get("status", "?")
            by[st] = by.get(st, 0) + val
        return out

    def summary(self) -> dict:
        """The JSON shape the ``metrics`` RPC returns next to the
        Prometheus text: merge provenance + per-worker request totals."""
        return {
            "v": SNAPSHOT_VERSION,
            "workers": {w: dict(meta)
                        for w, meta in sorted(self.workers.items())},
            "snapshots_merged": self.snapshots_merged,
            "snapshots_skipped": self.snapshots_skipped,
            "requests_by_worker":
                self.worker_totals("serve_requests_total"),
        }
