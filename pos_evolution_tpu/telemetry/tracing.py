"""End-to-end request tracing (ISSUE 18 leg b).

One logical request — hedged, resent, breaker-probed — becomes one
visible timeline across process boundaries:

- **sampling is a seeded stateless decision** (PEV002 decision-scope
  contract): ``sample(seed, index, rate)`` hashes the request identity
  with blake2b, exactly the ``sim/faults.stateless_unit`` discipline.
  No wall clock, no RNG cursor — the same (seed, index) always samples
  the same way, so a replayed load schedule traces the same requests;
- **trace ids are deterministic**: ``trace_id(seed, index)`` is a hash
  of the identity, not a uuid, so client- and server-side spans of the
  same request agree on the id without coordination;
- the id + sample decision ride the frame protocol's optional ``trace``
  field (``{"id": "...", "s": 1}``) — absent for unsampled traffic,
  which keeps the byte-template and byte-scan fast paths byte-identical
  to the untraced plane;
- each process buffers its spans in a ``SpanBuffer`` and flushes them
  (append-only JSONL, one file per pid: ``spans.<pid>.jsonl``) on its
  own cadence; ``scripts/trace_merge.py`` merges the per-process set
  into one Chrome trace with one pid lane per process.

Span record (one JSON object per line):

    {"trace": <id>, "name": "service", "ph": "span",
     "t0": <unix seconds>, "dur_ms": <float>, "pid": <os pid>,
     "proc": "<label>", "tid": <int>, ...free-form args...}

``t0`` is wall-clock epoch seconds on purpose — it is the only clock
processes on one host share, and the merge tool re-bases everything to
the earliest span so Chrome renders microsecond offsets. Span emission
must never fail the request it observes: every buffer operation
swallows into a dropped-span counter rather than raising.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import time

__all__ = ["sample", "trace_id", "SpanBuffer", "span_filename",
           "install_buffer", "get_buffer", "record_span"]

_TRACE_TAG = 0x7452_6163  # "tRac": domain-separates trace draws from
# fault/adversary draws sharing a run seed


def _unit(seed: int, *key: int) -> float:
    """blake2b -> uniform [0,1): the ``sim/faults.stateless_unit``
    discipline, inlined so telemetry never imports the sim tier."""
    h = hashlib.blake2b(
        struct.pack(f"<{len(key) + 1}q", seed, *key),
        digest_size=8).digest()
    return int.from_bytes(h, "little") / 2.0**64


def sample(seed: int, index: int, rate: float) -> bool:
    """Seeded per-request sample decision. ``rate`` is the sampled
    fraction (0 disables tracing entirely, 1 traces everything)."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return _unit(seed, _TRACE_TAG, index) < rate


def trace_id(seed: int, index: int) -> str:
    """Deterministic 16-hex-digit trace id for request ``index``."""
    h = hashlib.blake2b(
        struct.pack("<3q", seed, _TRACE_TAG ^ 0x1D, index),
        digest_size=8).hexdigest()
    return h


def span_filename(pid: int | None = None) -> str:
    return f"spans.{os.getpid() if pid is None else pid}.jsonl"


class SpanBuffer:
    """Per-process span sink: bounded in-memory list + incremental
    append-only JSONL flush.

    ``flush()`` appends every span recorded since the previous flush to
    ``<directory>/spans.<pid>.jsonl`` — append-only because the worker's
    beat thread calls it on a cadence and a crash between flushes must
    keep everything already written (the same commit-on-arrival posture
    as the event bus). A full buffer drops new spans and counts them:
    tracing is an observer, backpressure on the observed path would be
    a measurement artifact worse than a gap."""

    def __init__(self, directory: str | os.PathLike | None = None,
                 proc: str = "", max_spans: int = 100_000):
        self.directory = (os.fspath(directory)
                          if directory is not None else None)
        self.proc = proc or f"pid{os.getpid()}"
        self.max_spans = int(max_spans)
        self.spans: list[dict] = []
        self.dropped = 0
        self._flushed = 0
        self._lock = threading.Lock()

    def add(self, trace: str, name: str, t0: float, dur_ms: float,
            tid: int = 0, **args) -> None:
        span = {"trace": trace, "name": name,
                "t0": round(float(t0), 6),
                "dur_ms": round(float(dur_ms), 4),
                "pid": os.getpid(), "proc": self.proc, "tid": int(tid)}
        for k, v in args.items():
            if v is not None:
                span[k] = v
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return
            self.spans.append(span)

    def mark(self, trace: str, name: str, tid: int = 0, **args) -> None:
        """Zero-duration instant (breaker probe, resend decision)."""
        self.add(trace, name, time.time(), 0.0, tid=tid, **args)

    def flush(self) -> int:
        """Append unflushed spans to this process's span file; returns
        the number written. No directory -> in-memory only (tests)."""
        with self._lock:
            pending = self.spans[self._flushed:]
            self._flushed = len(self.spans)
        if not pending or self.directory is None:
            return 0
        path = os.path.join(self.directory, span_filename())
        try:
            with open(path, "a") as fh:
                for span in pending:
                    fh.write(json.dumps(span, sort_keys=True) + "\n")
        except OSError:
            # the trace file is an observer artifact — a full disk must
            # not take the serving plane down with it
            return 0
        return len(pending)

    def summary(self) -> dict:
        with self._lock:
            return {"spans": len(self.spans), "dropped": self.dropped,
                    "flushed": self._flushed}


# -- per-process singleton -------------------------------------------------
#
# The serving tier's span emitters (client pool, front worker loops, the
# das backing path) have no natural constructor handle to thread a
# buffer through, exactly like the global telemetry sink: install once
# per process, no-op when absent.

_BUFFER: list[SpanBuffer | None] = [None]


def install_buffer(directory: str | os.PathLike | None,
                   proc: str = "") -> SpanBuffer:
    buf = SpanBuffer(directory, proc=proc)
    _BUFFER[0] = buf
    return buf


def get_buffer() -> SpanBuffer | None:
    return _BUFFER[0]


def record_span(trace: str | None, name: str, t0: float, dur_ms: float,
                tid: int = 0, **args) -> None:
    """Module-level convenience: record onto the installed buffer if
    tracing is on AND this request carried a sampled trace id."""
    if trace is None:
        return
    buf = _BUFFER[0]
    if buf is not None:
        buf.add(trace, name, t0, dur_ms, tid=tid, **args)
