"""JAX runtime telemetry folded into a ``MetricsRegistry``.

Three sources, one namespace:

- **compiler events** via ``jax.monitoring`` listeners:
  ``jax_backend_compiles_total`` (one per XLA backend compile — the
  "recompile count" the perf gate pins, since an unexpected recompile is
  the classic silent TPU perf regression), ``jax_traces_total`` /
  ``jax_lowerings_total`` (jaxpr trace / MLIR lowering passes), the
  generic ``jax_events_total{event=...}``, and a
  ``jax_compile_seconds`` histogram;
- **dispatches**: ``record_dispatch()`` called from the call sites this
  repo controls — ``utils/benchtime.fused_measure`` timed calls and the
  ``ops/resident.py`` device paths (flush scatter batches, bucket head
  queries). JAX exposes no public dispatch-count hook, so we count where
  we dispatch rather than guessing at internals;
- **host↔device transfers**: ``record_transfer(nbytes, direction)`` from
  the same sites (the fused-measure checksum read-back, the resident
  head index read-back).

``jax.monitoring`` listener registration is process-global and
irrevocable (``clear_event_listeners`` nukes everyone's), so ``install``
registers ONE forwarding pair on first use and points it at the active
registry; ``install(None)`` detaches without touching other listeners.
Everything degrades to a no-op when jax or the monitoring module is
absent — telemetry must never be the reason a NumPy-only run dies.

The flight recorder (ISSUE 19) adds a fourth source: a
``profiling/ledger.CompileLedger`` attached via ``attach_ledger``
receives every compile-pipeline duration event *with span context*
(function / phase), decomposing ``jax_backend_compiles_total`` into a
per-(function, phase) table. ``record_transfer`` additionally charges
bytes to the active phase (``jax_transfer_bytes_by_phase_total``) when
one is set, and ``record_donation`` tracks donated-buffer bytes so the
donation-efficacy lever of ROADMAP item 5 has a number.
"""

from __future__ import annotations

_STATE: dict = {"registry": None, "listeners_registered": False,
                "ledger": None}

# monitoring key -> counter name for the compile-pipeline stages the perf
# gate cares about (everything else lands in jax_events_total{event=...})
_DURATION_COUNTERS = {
    "/jax/core/compile/backend_compile_duration": "jax_backend_compiles_total",
    "/jax/core/compile/jaxpr_trace_duration": "jax_traces_total",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "jax_lowerings_total",
}


def current():
    """The registry runtime events currently feed (None = detached)."""
    return _STATE["registry"]


def attach_ledger(ledger) -> None:
    """Point compile-pipeline duration events at a ``CompileLedger``
    (None detaches). Independent of the registry: a ledger without a
    registry still accumulates rows in memory."""
    _STATE["ledger"] = ledger


def current_ledger():
    return _STATE["ledger"]


def _on_event(event: str, **kw) -> None:
    reg = _STATE["registry"]
    if reg is not None:
        reg.counter("jax_events_total",
                    "jax.monitoring events by key").inc(event=event)


def _on_duration(event: str, duration: float, **kw) -> None:
    led = _STATE["ledger"]
    if led is not None and event in _DURATION_COUNTERS:
        try:
            led.on_duration(event, duration)
        except Exception:
            pass  # pev: ignore[PEV005] — ledger must never kill a run
    reg = _STATE["registry"]
    if reg is None:
        return
    name = _DURATION_COUNTERS.get(event)
    if name is not None:
        reg.counter(name, f"count of {event}").inc()
        reg.histogram("jax_compile_seconds",
                      "compile-pipeline stage durations").observe(
            duration, stage=event.rsplit("/", 1)[-1])
    else:
        reg.counter("jax_events_total",
                    "jax.monitoring events by key").inc(event=event)


def install(registry) -> bool:
    """Point JAX runtime telemetry at ``registry`` (None detaches).
    Returns True when the monitoring listeners are live."""
    _STATE["registry"] = registry
    if registry is None or _STATE["listeners_registered"]:
        return _STATE["listeners_registered"]
    try:
        import jax.monitoring as monitoring
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
        _STATE["listeners_registered"] = True
    except Exception:
        # no jax / exotic build: counters still work via the explicit
        # record_* helpers, compiler events just stay at zero
        pass
    return _STATE["listeners_registered"]


# -- explicit hooks for the call sites this repo controls ----------------------

def record_dispatch(n: int = 1, *, site: str = "unknown") -> None:
    reg = _STATE["registry"]
    if reg is not None:
        reg.counter("jax_dispatches_total",
                    "device computations dispatched from "
                    "instrumented call sites").inc(n, site=site)


def record_transfer(nbytes: int, *, direction: str = "d2h",
                    site: str = "unknown") -> None:
    reg = _STATE["registry"]
    if reg is not None:
        reg.counter("jax_transfer_bytes_total",
                    "host<->device bytes moved by instrumented call "
                    "sites").inc(int(nbytes), direction=direction, site=site)
        # charge to the active phase taxonomy when a phase block is open
        # (separate counter: the site-keyed one above is a pinned
        # contract, and adding a label would rename its count keys)
        from pos_evolution_tpu.profiling import ledger as _ledger
        phase = _ledger.current_phase()
        if phase is not None:
            reg.counter("jax_transfer_bytes_by_phase_total",
                        "host<->device bytes charged to the dense phase "
                        "active at transfer time").inc(
                int(nbytes), direction=direction, phase=phase)


def record_donation(nbytes: int, *, site: str = "unknown",
                    armed: bool = True) -> None:
    """Account bytes offered for buffer donation at an instrumented call
    site. ``armed=False`` records the same bytes on the undonated path
    (e.g. the CPU epoch step, where donation is off), so the efficacy
    ratio donated/(donated+undonated) is computable from the counter
    pair alone."""
    reg = _STATE["registry"]
    if reg is not None:
        reg.counter("jax_donation_bytes_total",
                    "bytes offered for XLA buffer donation (armed) vs "
                    "moved undonated (armed=0) at instrumented call "
                    "sites").inc(int(nbytes), site=site,
                                 armed="1" if armed else "0")
