"""pos_evolution_tpu — a TPU-native executable consensus framework.

A brand-new implementation of the capability surface of
``ethereum/pos-evolution`` (the Gasper consensus spec monograph and its
research successors): SSZ containers, beacon state transition, committee
shuffling, HLMD-GHOST fork choice, slashing, weak subjectivity, the
adversarial network simulator, and the protocol variants (proposer boost,
equivocation discounting, view-merge, Goldfish, RLMD-GHOST, SSF).

Architecture (see SURVEY.md §7): a spec-faithful *object level* keeps the
reference function signatures intact, while all validator-set hot loops run
on a dense *array level* dispatched through a pluggable ``ExecutionBackend``
(pure NumPy reference, or JAX/XLA/Pallas on TPU).
"""

__version__ = "0.1.0"

from pos_evolution_tpu.config import Config, mainnet_config, minimal_config, cfg, use_config
