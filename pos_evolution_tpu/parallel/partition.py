"""Named-pytree partition rules (ISSUE 9 tentpole, layer 1).

The resident hot-loop state — ``DenseRegistry`` epoch columns, the
``ResidentForkChoice`` latest-message table, ``ops/transition.py``'s
session columns — becomes *registered pytrees with explicit partition
rules*: every leaf gets a ``/``-joined name, a regex rule table maps
names to ``PartitionSpec``s (the fmengine/pjit idiom of SNIPPETS.md
[1]/[3]), and shard/gather functions place leaves on the ``(pods,
shard)`` mesh of ``parallel/sharded.py``.

The long-context analogue (SURVEY.md §5) is literal here: the validator
axis is the sequence-parallel axis, so every ``[N]`` registry column
shards over ``(pods, shard)`` like a long sequence, while the O(B)
block-tree columns and scalars replicate — reductions instead of ring
attention.

Shard-resident construction: ``build_sharded`` fills each shard's slice
through a callback, so a mainnet-scale (1M-validator) column is *never
materialized as one unsharded device buffer* — each device holds only
its ``N / mesh.size`` slice from the start. ``shard_leaf`` places an
existing host array the same way (per-shard slices, no full-array
device_put); ``gather_tree`` is the inverse host offload used by
checkpoint/resume (``utils/snapshot.py``), which re-shards on the
*current* mesh — resume across mesh shapes is a gather + re-place, not
a layout contract.
"""

from __future__ import annotations

import re

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pos_evolution_tpu.parallel.collectives import POD_AXIS, SHARD_AXIS

__all__ = [
    "VALIDATOR_SPEC",
    "REPLICATED",
    "PARTITION_RULES",
    "named_tree_map",
    "match_partition_rules",
    "shard_leaf",
    "build_sharded",
    "shard_tree",
    "gather_tree",
    "pad_rows",
]

# the validator (sequence-parallel) axis spans both mesh axes
VALIDATOR_SPEC = P((POD_AXIS, SHARD_AXIS))
REPLICATED = P()

# Default rule table for this repo's resident pytrees. First match wins;
# scalars always replicate regardless of rules (nothing to shard).
PARTITION_RULES: tuple[tuple[str, P], ...] = (
    # DenseRegistry / epoch-sweep columns: int64/uint8/bool [N]
    (r"registry/.*", VALIDATOR_SPEC),
    # resident fork-choice latest-message table + the dense driver's
    # committee-assignment, vote-delivery-mask (faults/adversary, ISSUE
    # 13), evidence and genesis-stake columns: [N] over validators
    (r"messages/(msg_block|msg_epoch|msg_slot|weight|ok|assigned"
     r"|allow|evidence|stake)", VALIDATOR_SPEC),
    # fused-transition session columns: [N] over validators
    (r"session/(balances|prev_flags|cur_flags|eff_units)", VALIDATOR_SPEC),
    # block-tree columns are O(B), replicated for the descent pass
    (r"(store|tree)/.*", REPLICATED),
    (r".*", REPLICATED),
)


def named_tree_map(fn, tree, sep: str = "/", _prefix: str = ""):
    """Map ``fn(name, leaf)`` over a pytree of dicts / NamedTuples /
    lists / tuples, where ``name`` is the ``sep``-joined path. NamedTuple
    fields contribute their field names (the reason this walker exists:
    ``jax.tree_util`` key paths name NamedTuple leaves by index)."""
    if isinstance(tree, dict):
        return {k: named_tree_map(fn, v, sep, f"{_prefix}{k}{sep}")
                for k, v in tree.items()}
    if hasattr(tree, "_fields"):  # NamedTuple
        return type(tree)(*(
            named_tree_map(fn, getattr(tree, f), sep, f"{_prefix}{f}{sep}")
            for f in tree._fields))
    if isinstance(tree, (list, tuple)):
        mapped = [named_tree_map(fn, v, sep, f"{_prefix}{i}{sep}")
                  for i, v in enumerate(tree)]
        return type(tree)(mapped) if isinstance(tree, list) else tuple(mapped)
    return fn(_prefix[: -len(sep)] if _prefix else _prefix, tree)


def match_partition_rules(rules, tree):
    """Pytree of ``PartitionSpec`` for ``tree`` by regex-matching leaf
    names against ``rules`` (first ``re.search`` hit wins). Scalar /
    single-element leaves never partition."""
    def get_spec(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return REPLICATED
        return _match(rules, name)
    return named_tree_map(get_spec, tree)


def _match(rules, name: str) -> P:
    for rule, spec in rules:
        if re.search(rule, name) is not None:
            return spec
    raise ValueError(f"no partition rule matched leaf {name!r}")


def spec_for(name: str) -> P:
    """Rule-table lookup for one named leaf — the entry point every live
    placement site uses (`registry/*` in ``parallel/sharded.py``,
    `messages/*` in ``ops/resident.py``, `session/*` in
    ``ops/transition.py``, plus the dense driver), so editing
    ``PARTITION_RULES`` actually changes runtime placement."""
    return _match(PARTITION_RULES, name)


def _shard_slices(mesh: Mesh, spec: P, shape) -> int:
    """Number of distinct row-slices ``spec`` induces on axis 0."""
    if not spec or spec[0] is None:
        return 1
    axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def shard_leaf(mesh: Mesh, spec: P, x):
    """Place one host array on the mesh under ``spec`` without creating
    a full-size single-device buffer: each addressable device receives
    only its slice via ``make_array_from_callback``."""
    x = np.asarray(x)
    sharding = NamedSharding(mesh, spec)
    if x.ndim == 0:
        return jax.device_put(x, sharding)
    n_slices = _shard_slices(mesh, spec, x.shape)
    if x.shape[0] % n_slices != 0:
        raise ValueError(
            f"axis 0 ({x.shape[0]}) must divide by the {n_slices}-way "
            f"shard count; pad with pad_rows first")
    return jax.make_array_from_callback(
        x.shape, sharding, lambda idx: np.ascontiguousarray(x[idx]))


def build_sharded(mesh: Mesh, spec: P, shape, dtype, fill):
    """Build a sharded array whose slices come straight from
    ``fill(start, stop) -> np.ndarray`` — the shard-resident-from-the-
    start constructor: nothing of global ``shape`` ever exists, on host
    or device (used by the dense 1M-validator driver's genesis)."""
    sharding = NamedSharding(mesh, spec)
    n_slices = _shard_slices(mesh, spec, shape)
    if shape[0] % n_slices != 0:
        raise ValueError(f"shape[0]={shape[0]} must divide by {n_slices}")

    def cb(idx):
        s = idx[0]
        start = 0 if s.start is None else s.start
        stop = shape[0] if s.stop is None else s.stop
        out = np.asarray(fill(int(start), int(stop)), dtype=dtype)
        assert out.shape[0] == stop - start, "fill returned a wrong slice"
        return np.ascontiguousarray(out)

    return jax.make_array_from_callback(tuple(shape), sharding, cb)


def shard_tree(mesh: Mesh, tree, rules=PARTITION_RULES):
    """Shard every leaf of a named pytree per the rule table."""
    specs = match_partition_rules(rules, tree)
    flat_specs = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, P))
    flat = jax.tree_util.tree_leaves(tree)
    placed = [shard_leaf(mesh, s, x) for s, x in zip(flat_specs, flat)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(tree), placed)


def gather_tree(tree):
    """Host-offload every leaf (gathers sharded arrays) — the
    checkpoint side of resume-across-mesh-shapes."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def pad_rows(x: np.ndarray, n_to: int, fill) -> np.ndarray:
    """Pad axis 0 to ``n_to`` rows with ``fill`` (inert-row values are
    the caller's contract — see ``ops/epoch.pad_registry``)."""
    x = np.asarray(x)
    if x.shape[0] == n_to:
        return x
    pad = np.full((n_to - x.shape[0],) + x.shape[1:], fill, x.dtype)
    return np.concatenate([x, pad])
