"""Parallelism: device mesh, collectives, sharded registry passes."""

from pos_evolution_tpu.parallel.collectives import (
    POD_AXIS,
    SHARD_AXIS,
    JaxCollectives,
    NumpyCollectives,
)
