"""Multi-chip sharded passes: epoch sweep, SSF tallies, gossip fabric.

Scale-out step of SURVEY.md §7 (step 5): the validator registry is sharded
over a 2-D device mesh (``pods`` x ``shard``) and the epoch sweep of
``ops/epoch.py`` runs as a ``shard_map`` with ``psum`` allreduce for the
registry-wide balances/tallies — ICI within a pod, DCN across pods
(north-star config #4). The SSF supermajority vote tally (config #5)
reduces over the ICI axis first, then the DCN axis.

Long-context analogue (SURVEY.md §5): the registry axis IS the
sequence-parallel axis — 1M+ validators sharded like a long sequence, with
reductions instead of ring attention (no attention exists to ring).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax

from pos_evolution_tpu.backend.jax_init import ensure_x64
ensure_x64()

import jax.numpy as jnp  # noqa: E402
from jax.experimental import mesh_utils  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402
try:
    from jax import shard_map  # noqa: E402
except ImportError:
    # pre-0.6 jax: only the experimental spelling exists, and the
    # replication check is still called check_rep (renamed check_vma
    # upstream); everything else about the call sites is identical
    from jax.experimental.shard_map import (  # noqa: E402
        shard_map as _shard_map_experimental,
    )

    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_experimental(f, **kwargs)

from pos_evolution_tpu.config import Config  # noqa: E402
from pos_evolution_tpu.ops.epoch import (  # noqa: E402
    DenseRegistry,
    EpochResult,
    epoch_core,
)
from pos_evolution_tpu.parallel.collectives import (  # noqa: E402
    POD_AXIS,
    SHARD_AXIS,
    JaxCollectives,
)
from pos_evolution_tpu.profiling import ledger  # noqa: E402


def make_mesh(n_devices: int | None = None, n_pods: int | None = None) -> Mesh:
    """A (pods, shard) mesh over the available devices.

    On real hardware ``pods`` maps to the DCN-connected axis and ``shard``
    to ICI; under the CPU 8-device override both are virtual.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_pods is None:
        n_pods = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    dev_mesh = mesh_utils.create_device_mesh(
        (n_pods, n_devices // n_pods), devices=devices[:n_devices])
    return Mesh(dev_mesh, (POD_AXIS, SHARD_AXIS))


def _replicated(mesh):
    return NamedSharding(mesh, P())


def host_gather(tree):
    """Gather a (possibly mesh-sharded) array pytree to host numpy —
    the cheap, device-synchronous half of an async checkpoint (ISSUE
    10): the caller keeps only this host copy on the critical path and
    hands compression/serialization to the background writer. Works on
    plain jnp/np arrays too, so call sites need no mesh conditional.

    Every gather is charged to ``jax_transfer_bytes_total{site=
    host_gather}`` (and to the active phase) — the baseline number for
    ROADMAP item 5's "collapse the per-slot gather" lever."""
    gathered = jax.tree_util.tree_map(lambda a: np.asarray(a), tree)
    try:
        from pos_evolution_tpu.telemetry import jaxrt
        nbytes = sum(a.nbytes for a in jax.tree_util.tree_leaves(gathered)
                     if hasattr(a, "nbytes"))
        jaxrt.record_transfer(nbytes, direction="d2h", site="host_gather")
    except Exception:
        pass  # pev: ignore[PEV005] — accounting must never kill a gather
    return gathered


def shard_registry(mesh: Mesh, reg: DenseRegistry) -> DenseRegistry:
    """Place registry columns per the partition rules (``registry/*`` ->
    validator axes; per-shard slice placement — no full-size
    single-device buffer)."""
    from pos_evolution_tpu.parallel.partition import shard_leaf, spec_for
    return DenseRegistry(*(
        shard_leaf(mesh, spec_for(f"registry/{f}"), np.asarray(a))
        for f, a in zip(DenseRegistry._fields, reg)))


def sharded_epoch_step(mesh: Mesh, cfg: Config):
    """Build the jitted multi-chip epoch boundary function.

    Same semantics as ``process_epoch_dense`` — every global tally becomes a
    two-axis ``psum`` (ICI then DCN) — so differential tests can compare the
    sharded result against the single-chip kernel exactly.
    """
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)
    scalar = P()

    def psum_both(x):
        return jax.lax.psum(x, both)

    reg_specs = DenseRegistry(*([vspec] * len(DenseRegistry._fields)))
    out_specs = EpochResult(
        registry=reg_specs, total_active_balance=scalar,
        prev_target_balance=scalar, cur_target_balance=scalar,
        justify_prev=scalar, justify_cur=scalar,
        new_justification_bits=scalar, finalize_epoch=scalar)

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(reg_specs, scalar, scalar, scalar, scalar, scalar, scalar),
             out_specs=out_specs)
    def step(reg, current_epoch, finalized_epoch, justification_bits,
             prev_justified_epoch, cur_justified_epoch, slashings_sum):
        return epoch_core(reg, current_epoch, finalized_epoch,
                          justification_bits, prev_justified_epoch,
                          cur_justified_epoch, slashings_sum, cfg,
                          reduce_fn=psum_both)

    return step


# --- cached live-path kernels (ISSUE 9: the sharded backend mode) -------------
#
# The dry-run builders above construct a fresh jitted shard_map per call;
# the live dispatch path (backend/jax_backend.py's ``sharded`` mode) goes
# through these memoized builders instead, so per-slot hot loops reuse
# one compiled executable per (mesh, static-shape) pair.

_KERNEL_CACHE: dict = {}


def _cached(key, build):
    kern = _KERNEL_CACHE.get(key)
    if kern is None:
        # wrap the built kernel in a compile-provenance scope named by
        # the cache key's leading element ("epoch", "votes", ...): any
        # (re)compile the call triggers lands on a named ledger row.
        # One context-manager enter/exit per call — noise next to a
        # device dispatch, and the wrapper is cached with the kernel.
        raw = build()
        name = key[0] if isinstance(key, tuple) and key else str(key)

        def kern(*a, _raw=raw, _name=f"sharded:{name}", **kw):
            with ledger.function_scope(_name):
                return _raw(*a, **kw)

        kern.__wrapped__ = raw
        _KERNEL_CACHE[key] = kern
    return kern


def clear_kernel_cache() -> None:
    """Drop memoized sharded kernels (tests; mesh teardown)."""
    _KERNEL_CACHE.clear()


def epoch_step_for(mesh: Mesh, cfg: Config, donate: bool = False):
    """Memoized ``sharded_epoch_step`` with optional registry-buffer
    donation (off-CPU only — XLA:CPU does not implement donation and
    would warn per epoch; the epoch result rewrites the registry in
    place on real devices, so HBM never holds two copies)."""
    def build():
        step = _sharded_epoch_core(mesh, cfg, donate)
        return step
    return _cached(("epoch", mesh, cfg, donate), build)


def _sharded_epoch_core(mesh: Mesh, cfg: Config, donate: bool):
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)
    scalar = P()

    def psum_ici_dcn(x):
        # ICI allreduce within the pod first, DCN across pods second —
        # the collectives ordering of north-star config #4
        return JaxCollectives.psum_two_level(x)

    reg_specs = DenseRegistry(*([vspec] * len(DenseRegistry._fields)))
    out_specs = EpochResult(
        registry=reg_specs, total_active_balance=scalar,
        prev_target_balance=scalar, cur_target_balance=scalar,
        justify_prev=scalar, justify_cur=scalar,
        new_justification_bits=scalar, finalize_epoch=scalar)

    @partial(shard_map, mesh=mesh,
             in_specs=(reg_specs, scalar, scalar, scalar, scalar, scalar,
                       scalar),
             out_specs=out_specs)
    def step(reg, current_epoch, finalized_epoch, justification_bits,
             prev_justified_epoch, cur_justified_epoch, slashings_sum):
        return epoch_core(reg, current_epoch, finalized_epoch,
                          justification_bits, prev_justified_epoch,
                          cur_justified_epoch, slashings_sum, cfg,
                          reduce_fn=psum_ici_dcn)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def vote_weights_for(mesh: Mesh, capacity: int):
    """Memoized validator-sharded fork-choice vote pass (config #1):
    identical collective shape to ``sharded_vote_weights`` but reused
    across every head query of a run."""
    return _cached(("votes", mesh, capacity),
                   lambda: sharded_vote_weights(mesh, capacity))


def link_tally_for(mesh: Mesh, n_links: int):
    """Memoized sharded SSF supermajority-link / acknowledgment tally
    (north-star config #5): the vote batch is sharded over the validator
    mesh axes, each shard segment-sums its local slice, and the partial
    per-link tallies allreduce ICI-first then DCN — the live-``SsfVariant``
    fold of ``ssf_supermajority_tally``'s dry run. Bit-identical to
    ``ops/variant_tally.link_tally_host`` (int64 adds reassociate
    exactly). Batches must be padded to a multiple of ``mesh.size`` with
    ``active=False`` rows (``backend/jax_backend.py`` does this)."""
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=(vspec, vspec, vspec),
                 out_specs=P())
        def tally(link_idx, weight, active):
            ok = active & (link_idx >= 0) & (link_idx < n_links)
            seg = jnp.where(ok, link_idx, n_links)
            local = jax.ops.segment_sum(
                jnp.where(ok, weight, 0), seg,
                num_segments=n_links + 1)[:n_links]
            return JaxCollectives.psum_two_level(local)  # ICI, then DCN
        return tally
    return _cached(("link", mesh, n_links), build)


def windowed_tally_for(mesh: Mesh, n_blocks: int):
    """Memoized sharded expiry-windowed vote tally (the Goldfish / RLMD /
    SSF head-vote reduction of ``ops/variant_tally.py``), same ICI-first
    DCN-second allreduce as ``link_tally_for``."""
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(vspec, vspec, vspec, vspec, P(), P()),
                 out_specs=P())
        def tally(block_idx, vote_slot, weight, active, lo, hi):
            ok = (active & (block_idx >= 0) & (block_idx < n_blocks)
                  & (vote_slot >= lo) & (vote_slot <= hi))
            seg = jnp.where(ok, block_idx, n_blocks)
            local = jax.ops.segment_sum(
                jnp.where(ok, weight, 0), seg,
                num_segments=n_blocks + 1)[:n_blocks]
            return JaxCollectives.psum_two_level(local)  # ICI, then DCN
        return tally
    return _cached(("windowed", mesh, n_blocks), build)


def vote_apply_for(mesh: Mesh):
    """Memoized masked vote application INSIDE a ``shard_map`` over the
    validator axes (ISSUE 13): the dense driver's per-slot vote landing
    — latest-message table + participation flags updated where the
    delivery mask is True. The mask is the composition of duty
    (committee selector), view membership, and the ``DenseFaultPlan``
    drop/delay/crash masks, computed replicated on host and placed
    sharded; elementwise, zero collectives, so faulted == unfaulted-
    with-all-pass-masks bit-for-bit on every mesh shape (and identical
    to the single-device jitted twin in sim/dense_driver.py). The
    ``msg_slot`` column (ISSUE 20) stamps each landed vote with its
    origination slot — the expiry-window input of the variant plane."""
    vspec = P((POD_AXIS, SHARD_AXIS))

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(vspec, vspec, vspec, vspec, vspec,
                           P(), P(), P(), P()),
                 out_specs=(vspec, vspec, vspec, vspec))
        def apply(msg_block, msg_epoch, msg_slot, cur_flags, mask,
                  idx, ep, vslot, flag_on):
            return (jnp.where(mask, idx, msg_block),
                    jnp.where(mask, ep, msg_epoch),
                    jnp.where(mask, vslot, msg_slot),
                    jnp.where(mask & flag_on,
                              cur_flags | np.uint8(7), cur_flags))
        return apply
    return _cached(("vote_apply", mesh), build)


def expiry_mask_for(mesh: Mesh):
    """Memoized expiry-window message filter (ISSUE 20): the Goldfish /
    RLMD / SSF head query counts only votes whose origination slot falls
    inside ``[lo, hi]`` — elementwise over the sharded latest-message
    columns (expired rows become the no-vote sentinel -1), zero
    collectives, feeding the unchanged ``vote_weights_for`` reduction.
    Identical math to the single-device twin in sim/dense_variants.py."""
    vspec = P((POD_AXIS, SHARD_AXIS))

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(vspec, vspec, P(), P()), out_specs=vspec)
        def mask(msg_block, msg_slot, lo, hi):
            live = (msg_slot >= lo) & (msg_slot <= hi)
            return jnp.where(live, msg_block, jnp.int32(-1))
        return mask
    return _cached(("expiry_mask", mesh), build)


def masked_stake_for(mesh: Mesh):
    """Memoized masked-stake tally (ISSUE 13): summed effective balance
    where ``mask`` — the gathered per-slot tally the dense monitors read
    (double-vote evidence stake, per-view target participation). Each
    shard sums its local slice, partials allreduce ICI-first then DCN;
    int64 adds reassociate exactly, so the result is bit-identical to
    the host twin ``ops/epoch.masked_stake_host``."""
    vspec = P((POD_AXIS, SHARD_AXIS))

    def build():
        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=(vspec, vspec),
                 out_specs=P())
        def tally(mask, weight):
            local = jnp.sum(jnp.where(mask, weight, 0))
            return JaxCollectives.psum_two_level(local)  # ICI, then DCN
        return tally
    return _cached(("masked_stake", mesh), build)


def shuffle_for(mesh: Mesh, n: int, rounds: int):
    """Memoized ``sharded_shuffle`` (config #2) — the dense driver runs
    one shuffle per epoch over an identical (mesh, n, rounds) signature;
    without the cache each epoch would rebuild and recompile the
    shard_map closure."""
    return _cached(("shuffle", mesh, n, rounds),
                   lambda: sharded_shuffle(mesh, n, rounds))


def aggregation_verify_for(mesh: Mesh):
    """Memoized ``sharded_aggregation_verify`` (config #3) for the live
    per-slot sweep: the committee/batch axis shards over (pods, shard),
    the pk-midstate table stays replicated, verdicts merge with one
    tiled all_gather. The batch axis must be padded to a multiple of
    ``mesh.size`` (callers pad with all-False bit rows and slice)."""
    return _cached(("aggverify", mesh),
                   lambda: sharded_aggregation_verify(mesh))


def pad_batch_to_mesh(mesh: Mesh, arrays, fills, pow2: bool = True):
    """Pad 1-D vote batches to a shard-able length: next power of two
    (compile-storm discipline of ops/variant_tally.py) that divides by
    ``mesh.size``, filled with inert rows; returns (padded jnp arrays
    placed sharded, original length)."""
    from pos_evolution_tpu.parallel.partition import (
        VALIDATOR_SPEC,
        pad_rows,
        shard_leaf,
    )
    k = len(np.asarray(arrays[0]))
    kp = max(k, 1)
    if pow2:
        kp = max(int(2 ** np.ceil(np.log2(max(kp, 2)))), 2)
    if kp % mesh.size != 0:
        kp = ((kp + mesh.size - 1) // mesh.size) * mesh.size
    out = tuple(
        shard_leaf(mesh, VALIDATOR_SPEC,
                   pad_rows(np.asarray(a), kp, fill))
        for a, fill in zip(arrays, fills))
    return out, k


def ssf_supermajority_tally(mesh: Mesh):
    """SSF per-slot FFG vote tally (north-star config #5;
    pos-evolution.md:1624-1637): sharded vote masks reduce over the ICI
    axis, then across pods over DCN, against the 2/3 supermajority line."""

    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(vspec, vspec, P()),
             out_specs=(P(), P()))
    def tally(vote_mask, effective_balance, total_active):
        local = jnp.sum(jnp.where(vote_mask, effective_balance, 0))
        intra_pod = jax.lax.psum(local, SHARD_AXIS)   # ICI allreduce
        global_sum = jax.lax.psum(intra_pod, POD_AXIS)  # DCN allreduce
        return global_sum, global_sum * 3 >= total_active * 2

    return tally


def ring_allreduce_tally(mesh: Mesh):
    """Epoch tally via an explicit ``ppermute`` ring instead of ``psum``.

    The ring form of the validator-shard reduction (the ring-collective
    analogue this framework has instead of ring attention, SURVEY.md §5):
    each step every shard passes its partial sum to its ICI ring neighbor
    and accumulates, completing the allreduce in |shard|-1 hops; the pod
    axis then folds with one DCN psum. Numerically identical to the fused
    ``psum`` path (int64 addition is associative/commutative) — XLA's psum
    is normally the right choice; this exists to exercise and document the
    explicit-ring pattern.
    """
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)

    # varying-manual-axes check off: the ring leaves every shard holding
    # the same total, but that replication is not statically inferable
    # from ppermute.
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(vspec, vspec), out_specs=P(),
             check_vma=False)
    def tally(mask, values):
        local = jnp.sum(jnp.where(mask, values, 0))
        n_shard = mesh.shape[SHARD_AXIS]
        perm = [(i, (i + 1) % n_shard) for i in range(n_shard)]

        def hop(_, carry):
            acc, moving = carry
            moving = jax.lax.ppermute(moving, SHARD_AXIS, perm)
            return acc + moving, moving

        acc, _ = jax.lax.fori_loop(0, n_shard - 1, hop, (local, local))
        return jax.lax.psum(acc, POD_AXIS)  # fold pods over DCN

    return tally


def sharded_vote_weights(mesh: Mesh, capacity: int):
    """Fork-choice latest-message accumulation sharded over validators
    (north-star config #1; pos-evolution.md:905-931's latest_messages →
    weights): each shard segment-sums its local (msg_block, weight) votes
    into a full block-indexed weight vector, then a two-axis ``psum``
    (ICI then DCN) merges the partial tallies. Bit-identical to the
    single-chip ``segment_sum`` — int64 addition reassociates exactly —
    so the dense subtree/head pass can run replicated on the result.

    msg_block int32[N] (validator-sharded; <0 = no vote), weight int64[N]
    → vote_weight int64[capacity] (replicated).
    """
    both = (POD_AXIS, SHARD_AXIS)
    vspec = P(both)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(vspec, vspec), out_specs=P())
    def votes(msg_block, weight):
        valid = msg_block >= 0
        seg = jnp.where(valid, msg_block, capacity)
        local = jax.ops.segment_sum(
            jnp.where(valid, weight, 0), seg,
            num_segments=capacity + 1)[:capacity]
        return jax.lax.psum(jax.lax.psum(local, SHARD_AXIS), POD_AXIS)

    return votes


def sharded_aggregation_verify(mesh: Mesh):
    """Attestation-aggregate verification sharded over committees
    (north-star config #3): the committee/batch axis is embarrassingly
    parallel (pos-evolution.md:472-475 — committees partition the slot's
    validators), so the pk-midstate table is replicated, the per-aggregate
    inputs are sharded on axis 0, every shard verifies its slice with the
    single-chip kernel, and one tiled ``all_gather`` merges the verdicts.

    pk_states (N, 8) u32 replicated; committees (A, C) i32, bits (A, C)
    bool, msg_words (A, 8) u32, signatures (A, 24) u32 all sharded on A.
    A must divide by the device count. Returns bool[A] (replicated).
    """
    both = (POD_AXIS, SHARD_AXIS)
    aspec = P(both)

    from pos_evolution_tpu.ops.aggregation import aggregate_verify_batch

    # check_vma off: the SHA-256 fori_loop carry mixes the replicated
    # message schedule with shard-varying lane states, which the static
    # varying-axes inference cannot type (it would need per-carry pcasts
    # inside the shared kernel); correctness is pinned by the differential
    # test against the single-chip kernel instead.
    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(P(), aspec, aspec, aspec, aspec), out_specs=P(),
             check_vma=False)
    def verify(pk_states, committees, bits, msg_words, signatures):
        ok = aggregate_verify_batch(
            pk_states, committees, bits, msg_words, signatures)
        return jax.lax.all_gather(ok, both, axis=0, tiled=True)

    def checked_verify(pk_states, committees, bits, msg_words, signatures):
        a = committees.shape[0]
        if a % mesh.size != 0:
            raise ValueError(
                f"sharded_aggregation_verify: batch axis A={a} (committees"
                f".shape[0]) must be divisible by the mesh device count "
                f"{mesh.size} (aggregates are sharded evenly)")
        return verify(pk_states, committees, bits, msg_words, signatures)

    return checked_verify


def sharded_shuffle(mesh: Mesh, n: int, rounds: int):
    """Swap-or-not committee shuffle sharded over validator indices
    (north-star config #2; pos-evolution.md:513-535): every index's
    swap-or-not trajectory is independent, so each shard runs the full
    fixed round schedule on its local index slice against the replicated
    seed/pivot data — zero collectives, the embarrassingly-parallel ideal.
    The per-round digest table spans the FULL position space (positions
    mix across shards), which is why ``_shuffle_rounds`` takes ``n``
    globally rather than per-shard.

    Call with idx = arange(n) sharded over validators; n must divide by
    the device count. Returns the permutation, validator-sharded.
    """
    if n % mesh.size != 0:
        raise ValueError(
            f"sharded_shuffle: n={n} must be divisible by the mesh device "
            f"count {mesh.size} (the index axis is sharded evenly)")
    vspec = P((POD_AXIS, SHARD_AXIS))

    from pos_evolution_tpu.ops.shuffle import _shuffle_rounds

    # check_vma off: same SHA-256 carry-typing limitation as
    # ``sharded_aggregation_verify`` (differentially pinned instead).
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(), P(), vspec),
             out_specs=vspec, check_vma=False)
    def shuf(seed_words, pivots, idx):
        return _shuffle_rounds(seed_words, pivots, idx, n, rounds)

    return shuf


def gossip_all_gather(mesh: Mesh):
    """Simulated gossip round (pos-evolution.md:187-189): every shard's
    message vector is gathered everywhere (the broadcast primitive), then
    each recipient applies its own delivery mask row — adversarial
    partitions/delays are data, not control flow (SURVEY.md §2.8).

    messages: f/i array sharded over validators (senders);
    delivery_mask: (recipients_local x senders_global) bool, recipient-sharded.
    Returns per-recipient combined view (here: masked sum of messages).
    """
    vspec = P((POD_AXIS, SHARD_AXIS))

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(vspec, vspec), out_specs=vspec)
    def gossip(messages, delivery_mask):
        everyone = jax.lax.all_gather(
            messages, (POD_AXIS, SHARD_AXIS), axis=0, tiled=True)
        return jnp.where(delivery_mask, everyone[None, :], 0).sum(axis=1)

    return gossip


def gossip_factored(mesh: Mesh):
    """The gossip fabric that SURVIVES 1M validators (VERDICT r4 item 8):
    the dense per-(recipient, sender) mask of ``gossip_all_gather`` is
    O(n^2) — a correctness probe, not a fabric. Real adversarial delivery
    patterns in the reference are STRUCTURED (pos-evolution.md:187-189:
    per-validator outages and network partitions chosen by the adversary;
    sim/schedule.py expresses them as awake masks and partition sets), so
    the fabric factors the mask:

        M[r, s] = recv_up[r] & link[device(r), device(s)] & send_up[s]

    with send_up/recv_up validator-sharded O(n) and ``link`` a tiny
    replicated D x D device-reachability matrix (the partition). Delivery
    then needs only each shard's LOCAL masked partial sum and one O(D)
    ``all_gather`` of those scalars — nothing n x n ever exists, and the
    cross-device traffic drops from O(n) gathered messages to O(D):

        out[r] = recv_up[r] * dot(link[device(r), :], partials)

    Single-edge exceptions (one lost message) stay with the dense probe
    at toy n; epochs of faults compose by calling this per round with
    schedule-driven masks. Differential-pinned against the dense mask in
    ``tests/test_parallel.py`` and executed in ``dryrun_multichip``.
    """
    vspec = P((POD_AXIS, SHARD_AXIS))
    n_dev = mesh.size

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(vspec, vspec, vspec, P()), out_specs=vspec)
    def gossip(messages, send_up, recv_up, link):
        local = jnp.where(send_up, messages, 0).sum()            # O(n/D)
        partials = jax.lax.all_gather(                           # O(D)
            local[None], (POD_AXIS, SHARD_AXIS), axis=0, tiled=True)
        me = (jax.lax.axis_index(POD_AXIS) * (n_dev // mesh.shape[POD_AXIS])
              + jax.lax.axis_index(SHARD_AXIS))
        heard = jnp.where(link[me], partials, 0).sum()
        return jnp.where(recv_up, heard, 0)

    return gossip
