"""Distributed communication backend (SURVEY.md §2.8, §5).

The reference's only communication primitive is abstract best-effort gossip
(pos-evolution.md:187-189); its parallelism is committee-based
(:472-475). The TPU-native equivalent is a thin collectives abstraction
over named mesh axes:

- ``validators`` axes (``pods`` x ``shard``): the registry is sharded here;
  epoch sweeps reduce with ``psum`` over ICI within a pod and DCN across
  pods (north-star configs #4/#5);
- simulated gossip = ``all_gather`` of message tensors with delivery masks
  (partitions are masks, so adversarial scheduling stays jittable);
- SSF supermajority tallies = cross-pod allreduce (config #5).

The ``numpy`` implementation of the same five primitives is the
single-process fallback, so every collective code path also runs without
JAX (SURVEY.md §2.8 "CPU backend implements the same interface").
"""

from __future__ import annotations

import numpy as np

__all__ = ["JaxCollectives", "NumpyCollectives", "POD_AXIS", "SHARD_AXIS"]

POD_AXIS = "pods"     # DCN-class axis (across pods / hosts)
SHARD_AXIS = "shard"  # ICI-class axis (within a pod)


class JaxCollectives:
    """Named-axis collectives inside ``shard_map``/``pjit`` traces."""

    name = "jax"

    @staticmethod
    def psum(x, axis):
        import jax
        return jax.lax.psum(x, axis)

    @staticmethod
    def psum_two_level(x, ici_axis=SHARD_AXIS, dcn_axis=POD_AXIS):
        """Hierarchical allreduce: ICI within the pod first, DCN across
        pods second — the reduction ordering every ISSUE-9 sharded
        kernel uses (numerically identical to a fused two-axis psum for
        the integer Gwei sums; the ordering matters for the network, not
        the value)."""
        import jax
        return jax.lax.psum(jax.lax.psum(x, ici_axis), dcn_axis)

    @staticmethod
    def pmax(x, axis):
        import jax
        return jax.lax.pmax(x, axis)

    @staticmethod
    def all_gather(x, axis, axis_index=0, tiled=False):
        import jax
        return jax.lax.all_gather(x, axis, axis=axis_index, tiled=tiled)

    @staticmethod
    def ppermute(x, axis, perm):
        import jax
        return jax.lax.ppermute(x, axis, perm)

    @staticmethod
    def broadcast(x, axis, src=0):
        # broadcast = select src shard then all-gather; on a mesh axis the
        # cheapest form is psum of a masked value
        import jax
        idx = jax.lax.axis_index(axis)
        contrib = jax.numpy.where(idx == src, x, jax.numpy.zeros_like(x))
        return jax.lax.psum(contrib, axis)

    @staticmethod
    def axis_index(axis):
        import jax
        return jax.lax.axis_index(axis)


class NumpyCollectives:
    """Single-process reference semantics: one shard holds everything, so
    reductions are identities over the lone participant."""

    name = "numpy"

    @staticmethod
    def psum(x, axis):
        return x

    @staticmethod
    def psum_two_level(x, ici_axis=SHARD_AXIS, dcn_axis=POD_AXIS):
        return x

    @staticmethod
    def pmax(x, axis):
        return x

    @staticmethod
    def all_gather(x, axis, axis_index=0, tiled=False):
        x = np.asarray(x)
        return x if tiled else x[None, ...]

    @staticmethod
    def ppermute(x, axis, perm):
        # single participant: only the self-loop (0 -> 0) delivers
        return x if any(s == 0 and d == 0 for s, d in perm) else np.zeros_like(x)

    @staticmethod
    def broadcast(x, axis, src=0):
        return x

    @staticmethod
    def axis_index(axis):
        return 0
