"""Lockset thread-safety analyzer: PEV101 (unlocked read-modify-write)
and PEV102 (inconsistent locking discipline).

The PR 12 review found the same race twice in one afternoon:
``MetricsRegistry._get`` created two metric objects for one name under
concurrent first touch, and the admission queue's shed counters lost
increments — both the shape ``self.x = f(self.x, ...)`` executed from N
threads with no lock. This analyzer mechanizes exactly that class for
the codebase's locking idiom, which is deliberately narrow:

- every thread-shared class owns one ``threading.Lock``/``Condition``
  stored on ``self`` (name contains ``lock`` or ``cond``);
- critical sections are lexical ``with self._lock:`` blocks (no bare
  ``acquire``/``release`` pairs);
- a class that owns a lock is *declaring itself thread-shared*: every
  public method may run on any thread (the registry's callers are in
  other modules — worker threads the intra-package call graph cannot
  see), so consistency is demanded class-wide, not only on paths from
  discovered ``Thread(target=...)`` entry points;
- a class with **no** lock is analyzed only if one of its methods is a
  discovered thread entry point (``threading.Thread(target=self._x)``,
  ``Timer``, ``executor.submit``) — then every reachable
  read-modify-write is by definition unlocked.

Soundness boundary (DESIGN.md §21): callers that hold the lock while
calling a private helper are credited via a fixed-point "always called
locked" pass over in-class call sites; methods named ``*_locked`` are
trusted by convention. What the analyzer does NOT try to prove: aliasing
through locals, multi-lock protocols, or happens-before through queues —
none of which the codebase uses on purpose.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .core import Rule, register_rule
from .rules_hygiene import _MUTATING_METHODS

_LOCKISH_ATTR_RE = re.compile(r"(lock|cond|mutex)", re.IGNORECASE)
_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore")
_THREAD_FACTORIES = frozenset({
    "threading.Thread", "Thread", "threading.Timer", "Timer",
})
# read-only / publish-only attrs by convention: not state
_IGNORED_ATTRS_RE = re.compile(r"^(__|_abc_)")


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


@dataclass
class _Access:
    method: str
    line: int
    node: ast.AST
    kind: str       # "read" | "store" | "rmw"
    locked: bool


@dataclass
class _ClassInfo:
    node: ast.ClassDef
    methods: dict = field(default_factory=dict)   # name -> FunctionDef
    lock_attrs: set = field(default_factory=set)
    thread_targets: set = field(default_factory=set)
    accesses: dict = field(default_factory=dict)  # attr -> [_Access]
    init_only: set = field(default_factory=set)


def _collect_classes(ctx) -> list[_ClassInfo]:
    """Classes with same-module single-inheritance flattening: a subclass
    sees its base's methods and lock attrs (``Gauge(_Metric)`` inherits
    ``_Metric._lock``), overrides winning by name."""
    by_name: dict[str, ast.ClassDef] = {}
    for node in ctx.walk(ast.ClassDef):
        by_name[node.name] = node

    def own_methods(cls: ast.ClassDef) -> dict:
        return {n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    infos = []
    for cls in by_name.values():
        info = _ClassInfo(node=cls)
        chain, cur = [], cls
        while cur is not None and cur not in chain:
            chain.append(cur)
            base = next((ctx.dotted(b) for b in cur.bases
                         if ctx.dotted(b) in by_name), None)
            cur = by_name.get(base) if base else None
        for klass in reversed(chain):  # base first, overrides win
            info.methods.update(own_methods(klass))
        infos.append(info)
    return infos


def _lock_attrs_of(info: _ClassInfo, ctx) -> set:
    attrs = set()
    for fn in info.methods.values():
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    name = _self_attr(t)
                    if name and isinstance(node.value, ast.Call):
                        callee = ctx.dotted(node.value.func)
                        if callee.rsplit(".", 1)[-1] in _LOCK_CTORS:
                            attrs.add(name)
            elif isinstance(node, ast.With):
                for item in node.items:
                    name = _self_attr(item.context_expr)
                    if name and _LOCKISH_ATTR_RE.search(name):
                        attrs.add(name)  # used as a lock = is a lock
    return attrs


def _thread_targets_of(ctx) -> set:
    """Bare method/function names handed to Thread/Timer/submit anywhere
    in the module (the spawn may live in another class)."""
    targets = set()
    for node in ctx.walk(ast.Call):
        callee = ctx.dotted(node.func)
        cand = None
        if callee in _THREAD_FACTORIES:
            kw = next((k for k in node.keywords if k.arg == "target"), None)
            if kw is not None:
                cand = kw.value
            elif callee.endswith("Timer") and len(node.args) >= 2:
                cand = node.args[1]
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            cand = node.args[0]
        if cand is not None:
            dotted = ctx.dotted(cand)
            if dotted:
                targets.add(dotted.rsplit(".", 1)[-1])
    return targets


def _local_lock_aliases(method: ast.AST, lock_attrs: set) -> set:
    """Local names bound from the class's own lock (`lock = self._lock`)
    — the one-hop alias a drain loop uses. Only a VERIFIED alias counts:
    crediting any lockish-looking name would let `with other_lock:`
    (the wrong lock — the classic race) pass silently."""
    aliases = set()
    for node in ast.walk(method):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _self_attr(node.value) in lock_attrs:
            aliases.add(node.targets[0].id)
    return aliases


def _is_locked_at(ctx, node: ast.AST, lock_attrs: set,
                  method: ast.AST) -> bool:
    """Lexically dominated by ``with self.<lock>`` (or a verified local
    alias of it) within ``method``."""
    aliases = _local_lock_aliases(method, lock_attrs)
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = _self_attr(item.context_expr)
                if name in lock_attrs:
                    return True
                if isinstance(item.context_expr, ast.Name) \
                        and item.context_expr.id in aliases:
                    return True
        if anc is method:
            break
    return False


def _rhs_reads_attr(node: ast.AST, attr: str) -> bool:
    for sub in ast.walk(node):
        if _self_attr(sub) == attr and isinstance(
                getattr(sub, "ctx", None), ast.Load):
            return True
    return False


def _classify_accesses(ctx, info: _ClassInfo) -> None:
    for mname, fn in info.methods.items():
        for node in ast.walk(fn):
            # a chained assignment (`self.a = self.b = ...`) records EVERY
            # target — collect (attr, kind) pairs, not a single slot
            hits: list[tuple[str, str]] = []
            attr, kind = None, None
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        hits.append((a, "rmw" if _rhs_reads_attr(
                            node.value, a) else "store"))
                    elif isinstance(t, ast.Subscript):
                        a = _self_attr(t.value)
                        if a:
                            hits.append((a, "rmw"))  # container write
            elif isinstance(node, ast.AugAssign):
                a = _self_attr(node.target)
                if a is None and isinstance(node.target, ast.Subscript):
                    a = _self_attr(node.target.value)
                if a:
                    attr, kind = a, "rmw"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_METHODS:
                a = _self_attr(node.func.value)
                if a:
                    attr, kind = a, "rmw"
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                a = _self_attr(node)
                if a:
                    attr, kind = a, "read"
            if attr is not None:
                hits.append((attr, kind))
            for attr, kind in hits:
                if attr in info.lock_attrs or _IGNORED_ATTRS_RE.match(attr):
                    continue
                info.accesses.setdefault(attr, []).append(_Access(
                    method=mname, line=node.lineno, node=node, kind=kind,
                    locked=_is_locked_at(ctx, node, info.lock_attrs, fn)))


def _always_locked_methods(ctx, info: _ClassInfo) -> set:
    """Fixed point over in-class call sites: a leading-underscore method
    every one of whose ``self._m(...)`` call sites is lock-dominated (or
    inside an already always-locked method) is credited as locked.
    ``*_locked`` names are trusted by convention."""
    locked = {m for m in info.methods if m.endswith("_locked")}
    call_sites: dict[str, list] = {}
    for mname, fn in info.methods.items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in info.methods:
                    call_sites.setdefault(callee, []).append((mname, fn, node))
    for _ in range(4):  # tiny graphs; fixpoint in <= depth iterations
        grew = False
        for mname in info.methods:
            if mname in locked or not mname.startswith("_") \
                    or mname.startswith("__"):
                continue
            sites = call_sites.get(mname)
            if not sites:
                continue
            if all(caller in locked
                   or _is_locked_at(ctx, node, info.lock_attrs, fn)
                   for caller, fn, node in sites):
                locked.add(mname)
                grew = True
        if not grew:
            break
    return locked


def _reachable_from_targets(info: _ClassInfo) -> set:
    """Closure of the class's thread entry points over self-calls."""
    edges: dict[str, set] = {m: set() for m in info.methods}
    for mname, fn in info.methods.items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in info.methods:
                    edges[mname].add(callee)
    seen, frontier = set(), [t for t in info.thread_targets
                            if t in info.methods]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        frontier.extend(edges.get(m, ()))
    return seen


@register_rule
class LocksetRule(Rule):
    """PEV101/PEV102: lockset analysis over the multithreaded tiers."""

    code = "PEV101"
    codes = ("PEV101", "PEV102")
    name = "lockset"
    rationale = ("unlocked read-modify-writes from thread-reachable code "
                 "lose updates (the PR 12 MetricsRegistry._get and "
                 "shed-counter races); inconsistent discipline means the "
                 "lock protects nothing")

    def run(self, ctx):
        if not ctx.in_threaded_module():
            return
        module_targets = _thread_targets_of(ctx)
        for info in _collect_classes(ctx):
            info.lock_attrs = _lock_attrs_of(info, ctx)
            info.thread_targets = {t for t in module_targets
                                   if t in info.methods}
            if not info.lock_attrs and not info.thread_targets:
                continue
            _classify_accesses(ctx, info)
            locked_methods = _always_locked_methods(ctx, info)
            if info.lock_attrs:
                shared_methods = set(info.methods)  # lock declares sharing
            else:
                shared_methods = _reachable_from_targets(info)
            yield from self._judge(ctx, info, shared_methods,
                                   locked_methods)

    def _judge(self, ctx, info, shared_methods, locked_methods):
        for attr, accesses in sorted(info.accesses.items()):
            writes = [a for a in accesses if a.kind in ("store", "rmw")
                      and a.method not in ("__init__", "__new__")]
            if not writes:
                continue
            protected = [a for a in accesses
                         if a.locked or a.method in locked_methods]
            exposed = [a for a in writes
                       if a.method in shared_methods
                       and not a.locked and a.method not in locked_methods]
            cls = info.node.name
            for a in exposed:
                if a.kind == "rmw":
                    yield self._as("PEV101").finding(
                        ctx, a.node,
                        f"unlocked read-modify-write of 'self.{attr}' in "
                        f"{cls}.{a.method} — concurrent callers lose "
                        f"updates; wrap in `with "
                        f"self.{self._lock_name(info)}:`")
                elif protected:
                    yield self._as("PEV102").finding(
                        ctx, a.node,
                        f"'self.{attr}' is written without the lock in "
                        f"{cls}.{a.method} but accessed under it elsewhere "
                        f"— inconsistent discipline; lock it or document "
                        f"the atomic-publish intent with a suppression")

    @staticmethod
    def _lock_name(info: _ClassInfo) -> str:
        return sorted(info.lock_attrs)[0] if info.lock_attrs else "_lock"

    def _as(self, code: str):
        """A lightweight view of this rule reporting under ``code``
        (PEV101 and PEV102 share one analysis)."""
        view = object.__new__(LocksetRule)
        view.__dict__ = dict(self.__dict__)
        view.code = code
        return view
