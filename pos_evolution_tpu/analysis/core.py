"""Framework core: findings, the rule registry, suppressions, baseline.

Design decisions that matter:

- **Stable codes.** Every rule owns one ``PEV###`` code forever; codes are
  never renumbered or reused (the baseline and per-line suppressions key
  on them, and both outlive any refactor of the rule's internals).
- **Line-independent baseline identity.** A baseline entry matches on
  ``(code, path, enclosing-context, normalized source line)`` — NOT on
  the line number — so unrelated edits above a recorded finding don't
  invalidate the baseline. Each entry carries a mandatory one-line
  ``justification``: the baseline is documentation of deliberate
  patterns, not a dumping ground (``--strict`` also fails on *stale*
  entries so the file can only shrink as true positives get fixed).
- **Honest suppression.** ``# pev: ignore[PEV001]`` on the offending line
  (or a standalone comment on the line above) suppresses exactly the
  named codes; a bare ``# pev: ignore`` suppresses everything on that
  line. Suppressions are counted and reported so they stay visible.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field


@dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a specific location.

    ``context`` is the enclosing qualname (``Class.method``, ``func``, or
    ``""`` at module level); ``key`` is the stripped source line — the
    pair gives the baseline a line-number-independent identity.
    """

    path: str
    line: int
    code: str
    message: str
    context: str = ""
    key: str = ""
    col: int = 0

    @property
    def identity(self) -> tuple:
        return (self.code, self.path, self.context, self.key)

    def location(self) -> str:
        return f"{self.path}:{self.line}"


class Rule:
    """Base class: subclass, set ``code``/``name``/``rationale``, implement
    ``run(ctx)`` yielding ``Finding``s. ``ctx`` is an
    ``engine.ModuleContext`` (parsed tree + source + config + helpers)."""

    code: str = "PEV000"
    codes: tuple = ()  # multi-code rules (lockset) list every code here
    name: str = ""
    rationale: str = ""

    @property
    def all_codes(self) -> tuple:
        return self.codes or (self.code,)

    def run(self, ctx):  # pragma: no cover - interface
        raise NotImplementedError

    def finding(self, ctx, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            path=ctx.relpath, line=line, code=self.code, message=message,
            context=ctx.qualname_at(node), key=ctx.line_key(line),
            col=getattr(node, "col_offset", 0))


_RULES: dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and index by code. Codes are unique —
    a collision is a programming error, not a configuration one."""
    inst = cls()
    assert inst.code not in _RULES, f"duplicate rule code {inst.code}"
    assert re.fullmatch(r"PEV\d{3}", inst.code), inst.code
    _RULES[inst.code] = inst
    return cls


def all_rules() -> dict[str, Rule]:
    # import for side effect: rule modules self-register on first use
    from . import (lockset, rules_determinism, rules_hygiene,  # noqa: F401
                   rules_jax, rules_mp)
    return dict(sorted(_RULES.items()))


# --- suppressions -------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*pev:\s*ignore(\[[^\]\n]*\]?)?")
_CODE_RE = re.compile(r"PEV\d{3}")


def parse_suppressions(source: str) -> dict[int, frozenset | None]:
    """{1-based line: frozenset of codes, or None meaning all codes}.

    A standalone ``# pev: ignore...`` comment line covers the next
    non-comment line too (decorated defs and long calls put the
    interesting token on a line with no room for a trailing comment).

    Fail-closed on malformed code lists: ``ignore[pev001]`` or an
    unclosed ``ignore[PEV001`` suppresses NOTHING (the alternative —
    falling back to suppress-everything — would silently disable the
    whole gate for that line on a typo).
    """
    out: dict[int, frozenset | None] = {}
    lines = source.splitlines()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        if m.group(1) is not None:
            raw = m.group(1)
            if not raw.endswith("]"):
                continue  # unclosed bracket: malformed, suppress nothing
            tokens = [t.strip() for t in raw[1:-1].split(",")]
            if not tokens or any(not _CODE_RE.fullmatch(t) for t in tokens):
                continue  # bad code spelling: malformed, suppress nothing
            codes = frozenset(tokens)
        else:
            codes = None

        def merge(lineno: int) -> None:
            prev = out.get(lineno, frozenset())
            if codes is None or prev is None:
                out[lineno] = None
            else:
                out[lineno] = prev | codes

        merge(i)
        if text.lstrip().startswith("#"):  # standalone comment: cover below
            j = i + 1
            # skip further comments AND blank lines down to the next code
            # line — a suppression separated from its target by a blank
            # line must still land on the target
            while j <= len(lines) and (
                    lines[j - 1].lstrip().startswith("#")
                    or not lines[j - 1].strip()):
                j += 1
            if j <= len(lines):
                merge(j)
    return out


def is_suppressed(finding: Finding,
                  suppressions: dict[int, frozenset | None]) -> bool:
    codes = suppressions.get(finding.line, frozenset())
    return codes is None or finding.code in codes


# --- baseline -----------------------------------------------------------------

BASELINE_VERSION = 1


@dataclass
class Baseline:
    """Checked-in ledger of pre-existing / deliberate findings.

    ``match(findings)`` partitions into (new, absorbed) and records which
    entries went unused (stale). Every entry must carry a justification —
    ``load`` refuses a baseline that tries to silence findings without
    saying why.
    """

    entries: list[dict] = field(default_factory=list)
    path: str = ""

    @classmethod
    def load(cls, path) -> "Baseline":
        with open(path, encoding="utf-8") as fh:
            blob = json.load(fh)
        assert blob.get("version") == BASELINE_VERSION, \
            f"unknown baseline version {blob.get('version')!r}"
        entries = blob.get("entries", [])
        for e in entries:
            missing = {"code", "path", "context", "key",
                       "justification"} - set(e)
            assert not missing, f"baseline entry missing {sorted(missing)}: {e}"
            assert str(e["justification"]).strip(), \
                f"baseline entry needs a non-empty justification: {e}"
            e.setdefault("count", 1)
        return cls(entries=entries, path=str(path))

    @staticmethod
    def entry_for(finding: Finding, justification: str) -> dict:
        return {"code": finding.code, "path": finding.path,
                "context": finding.context, "key": finding.key,
                "count": 1, "justification": justification}

    def match(self, findings: list[Finding]) -> tuple[list[Finding],
                                                      list[Finding],
                                                      list[dict]]:
        """-> (new_findings, absorbed_findings, stale_entries)."""
        budget: dict[tuple, int] = {}
        for e in self.entries:
            ident = (e["code"], e["path"], e["context"], e["key"])
            budget[ident] = budget.get(ident, 0) + int(e["count"])
        used: dict[tuple, int] = {k: 0 for k in budget}
        new, absorbed = [], []
        for f in sorted(findings):
            ident = f.identity
            if used.get(ident, 0) < budget.get(ident, -1):
                used[ident] += 1
                absorbed.append(f)
            else:
                new.append(f)
        stale = [e for e in self.entries
                 if used.get((e["code"], e["path"], e["context"], e["key"]),
                             0) == 0]
        # multi-count entries partially used still have headroom; an entry
        # is stale only when NOTHING matched its identity (above), so a
        # count that merely shrank keeps the entry alive until hand-pruned.
        return new, absorbed, stale

    def dump(self) -> str:
        return json.dumps(
            {"version": BASELINE_VERSION,
             "entries": sorted(self.entries, key=lambda e: (
                 e["code"], e["path"], e["context"], e["key"]))},
            indent=1, sort_keys=True) + "\n"
