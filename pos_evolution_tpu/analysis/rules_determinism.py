"""PEV002: nondeterminism reachable from the seeded stateless paths.

PR 13's contract: every fault / adversary / monitor decision in the dense
tier is a **pure function of its identity** — ``stateless_unit(seed,
*key)`` over blake2b, no RNG cursor, no wall clock — which is what makes
runs byte-stable across backends, mesh shapes, and checkpoint/resume.
One ``time.time()`` or global-``random`` draw inside those paths breaks
replayable chaos bundles, the bit-identical-resume pins, and the
cross-mesh twin matrix all at once, usually in a way no single test
catches (the run is still *plausible*, just no longer reproducible).

Scope is configured per module class (``engine.AnalysisConfig``):

- **strict** modules (``sim/faults.py``, ``sim/dense_adversary.py``, …)
  host only decision logic: any wall-clock, RNG-cursor, hash-seed, or
  set-iteration-order dependence is flagged;
- **decision** modules (the drivers, specs, ops) legitimately measure
  wall time for telemetry, so clocks pass but RNG cursors / ``os.urandom``
  / unseeded generators are still flagged.
"""

from __future__ import annotations

import ast

from .core import Rule, register_rule

_CLOCK_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today", "date.today",
})

_ENTROPY_CALLS = frozenset({
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
})
_ENTROPY_PREFIXES = ("secrets.",)

# the global-cursor RNG surfaces; seeded Generators are fine.
# jax.random is deliberately ABSENT: it is functional (every draw takes
# an explicit key, there is no global cursor to ride), so keyed
# jax.random.* in ops/ and the drivers is the idiomatic deterministic
# pattern, not a violation.
_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_RNG_SEEDED_OK = frozenset({
    "np.random.default_rng", "numpy.random.default_rng",
    "np.random.Generator", "numpy.random.Generator",
    "np.random.SeedSequence", "numpy.random.SeedSequence",
    "np.random.RandomState", "numpy.random.RandomState",
})


def _rng_violation(name: str, node: ast.Call) -> str | None:
    if name in _ENTROPY_CALLS or name.startswith(_ENTROPY_PREFIXES):
        return f"{name}() draws OS entropy"
    if name in _RNG_SEEDED_OK:
        if not node.args and not node.keywords:
            return (f"{name}() without a seed falls back to OS entropy — "
                    f"thread the run seed through")
        return None
    if name.startswith(_RNG_PREFIXES):
        # random.Random(seed) is a seeded instance; bare module-level
        # draws (random.random, np.random.rand, ...) ride the global
        # cursor whose state depends on call order across the process
        if name in ("random.Random",) and (node.args or node.keywords):
            return None
        return f"{name}() rides a global RNG cursor (call-order dependent)"
    return None


@register_rule
class NondeterminismRule(Rule):
    """PEV002: wall-clock / RNG-cursor / iteration-order nondeterminism
    in modules bound by the seeded stateless contract."""

    code = "PEV002"
    name = "stateless-path-nondeterminism"
    rationale = ("seeded stateless paths must be byte-stable across "
                 "backends, mesh shapes, and resume (PR 13 "
                 "stateless_unit_array contract); a clock or RNG cursor "
                 "breaks replayable chaos bundles silently")

    def run(self, ctx):
        strict = ctx.in_stateless_strict()
        decision = ctx.in_stateless_decision()
        if not (strict or decision):
            return
        for node in ctx.walk(ast.Call):
            name = ctx.dotted(node.func)
            if not name:
                continue
            # match on the raw AND the alias-resolved spelling so
            # `import time as _t; _t.time()` cannot evade the contract
            resolved = ctx.resolved(node.func)
            rng = _rng_violation(name, node)
            if rng is None and resolved != name:
                rng = _rng_violation(resolved, node)
            if rng is not None:
                yield self.finding(
                    ctx, node,
                    f"{rng} — use sim.faults.stateless_unit/"
                    f"stateless_unit_array keyed on the decision identity")
            elif strict and (name in _CLOCK_CALLS
                             or resolved in _CLOCK_CALLS):
                yield self.finding(
                    ctx, node,
                    f"{name}() reads the wall clock in a stateless "
                    f"decision module — decisions must be pure functions "
                    f"of (seed, identity)")
            elif strict and name == "hash" and node.args:
                yield self.finding(
                    ctx, node,
                    "builtin hash() is salted per process "
                    "(PYTHONHASHSEED) — use hashlib.blake2b via "
                    "stateless_word for stable digests")
        if strict:
            yield from self._set_iteration(ctx)

    def _set_iteration(self, ctx):
        """Iterating a set feeds its (hash-salted for str keys) order into
        whatever consumes the loop — message ordering, digest input."""
        def is_set_expr(node):
            return isinstance(node, (ast.Set, ast.SetComp)) or (
                isinstance(node, ast.Call)
                and ctx.dotted(node.func) in ("set", "frozenset"))

        for node in ctx.walk((ast.For, ast.comprehension)):
            if is_set_expr(node.iter):
                yield self.finding(
                    ctx, node.iter,
                    "iteration over a set in a stateless decision module — "
                    "order is hash-salted for str elements; sort or use a "
                    "list/dict")
