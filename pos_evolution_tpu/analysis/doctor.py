"""``--doctor``: the analyzer's own CI negative.

A synthesized source file contains exactly one instance of every bug
class the pass exists to catch — each one a distilled copy of a bug this
repo actually shipped and reviewed out (the PR 7 per-call jit closure,
the PR 12 unlocked counter, the PR 13 determinism contract). Running the
analyzer over it must produce **exactly** the expected ``PEV###`` codes:

- produced exactly as expected -> exit ``DOCTOR_FINDINGS`` (1): the
  analyzer works, and the doctored file fails the lint, which is the
  CI-negative contract (mirrors the chaos / perf-gate doctor pattern —
  CI asserts ``rc == 1``);
- nothing found -> exit 0: a clean pass on a file full of bugs means the
  analyzer is broken, and CI's ``rc == 1`` assert fails loudly;
- wrong set found -> exit ``DOCTOR_MISMATCH`` (2) with a diff.
"""

from __future__ import annotations

from .engine import AnalysisConfig, analyze_source

DOCTOR_OK_NONE = 0        # found nothing: analyzer broken
DOCTOR_FINDINGS = 1       # found exactly the expected set
DOCTOR_MISMATCH = 2       # found the wrong set: analyzer broken differently

DOCTOR_RELPATH = "doctor_synthetic.py"

# One bug per class. Never imported or executed — parsed only.
DOCTOR_SOURCE = '''\
"""Synthesized bug zoo for the static-analysis doctor (never executed)."""
import multiprocessing
import threading
import time

import jax
import jax.numpy as jnp


def scale_batch(xs):
    # PR 7 class: a fresh closure per call recompiles per call
    fn = jax.jit(lambda v: v * 2)
    return fn(xs)


donated_step = jax.jit(lambda c, x: c + x, donate_argnums=(0,))


def drop_decision(seed, slot):
    # PR 13 class: a wall clock inside a seeded stateless decision
    return time.time() % 2.0 < 1.0


def drain_batches(batches):
    total = 0.0
    for b in batches:
        total += jnp.sum(b).item()
    return total


def collect(item, acc=[]):
    acc.append(item)
    return acc


class PumpWorker:
    def __init__(self, work):
        self.work = work
        self.thread = threading.Thread(target=self._pump_loop, daemon=True)

    def _pump_loop(self):
        while True:
            try:
                self.work()
            except Exception:
                continue


_pump_registry_lock = threading.Lock()


def pump_child(work):
    # PR 16 class: parent-created lock referenced on the child side
    with _pump_registry_lock:
        work()


def launch_pump(work):
    # PR 16 class: fork-start in a module that also runs threads
    ctx = multiprocessing.get_context("fork")
    return ctx.Process(target=pump_child, args=(work,))


class SharedCounters:
    """PR 12 class: a locked class with an unlocked read-modify-write."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}
        self.generation = 0

    def inc(self, key):
        self.counts[key] = self.counts.get(key, 0) + 1

    def snapshot(self):
        with self._lock:
            return dict(self.counts), self.generation

    def reset(self):
        self.generation = 0
'''

EXPECTED = {
    "PEV001": 1,   # scale_batch's per-call jax.jit
    "PEV002": 1,   # time.time in drop_decision
    "PEV003": 1,   # .item() in drain_batches' loop
    "PEV004": 1,   # donated_step without an off-CPU guard
    "PEV005": 1,   # PumpWorker._pump_loop swallows silently
    "PEV006": 1,   # collect's mutable default
    "PEV007": 2,   # launch_pump's fork context + pump_child's lock
    "PEV101": 1,   # SharedCounters.inc: the PR 12 unlocked counter
    "PEV102": 1,   # SharedCounters.reset: blind store, locked elsewhere
}


def doctor_config() -> AnalysisConfig:
    """Every scope active on the synthesized file, so one file exercises
    every rule."""
    return AnalysisConfig(
        stateless_strict=(DOCTOR_RELPATH,),
        stateless_decision=(),
        hot_modules=(DOCTOR_RELPATH,),
        threaded_modules=(DOCTOR_RELPATH,),
    )


def run_doctor(out=print) -> int:
    result = analyze_source(DOCTOR_SOURCE, DOCTOR_RELPATH, doctor_config())
    got: dict[str, int] = {}
    for f in result.findings:
        got[f.code] = got.get(f.code, 0) + 1
    for f in result.findings:
        out(f"{f.location()}: {f.code} {f.message}")
    expected = {c: n for c, n in EXPECTED.items() if n}
    if not result.findings:
        out("DOCTOR BROKEN: clean pass on the doctored file — the "
            "analyzer found none of the synthesized bugs")
        return DOCTOR_OK_NONE
    if got != expected:
        out(f"DOCTOR MISMATCH: expected {expected} got {got}")
        return DOCTOR_MISMATCH
    out(f"doctor: all {sum(expected.values())} expected findings across "
        f"{len(expected)} codes produced — the doctored file fails the "
        f"lint, as it must")
    return DOCTOR_FINDINGS
