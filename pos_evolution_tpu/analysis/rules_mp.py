"""PEV007: fork-unsafety across the process boundary.

The multi-process serving plane (PR 16) made process workers a
first-class part of the runtime, and the bug class that comes with them
is *fork inheriting a threaded parent's synchronization state*:

- **fork-start in a thread-running module.** ``fork`` duplicates the
  parent's memory image but only the calling thread survives in the
  child. Any lock held by another thread at fork time is copied *locked
  forever* — the child deadlocks the first time it touches it. A module
  that starts threads AND uses fork-start multiprocessing (explicitly
  via ``get_context("fork")`` / ``set_start_method("fork")``, or
  implicitly via bare ``multiprocessing.Process`` — the POSIX default)
  is exactly that trap. The fix is an explicit spawn context, which is
  what ``serve.workers`` uses.
- **pre-fork state referenced by a child entry point.** A
  ``threading.Lock`` / ``Condition`` (or a mutable registry) created in
  the parent and then touched from a ``Process(target=...)`` entry
  function is state that silently crossed the process boundary: under
  spawn it is a *different object* in the child (the "shared" registry
  shares nothing), under fork it may arrive already held. Either way
  the code reads as shared and is not. A deliberate, documented handoff
  opts out with ``# pev: ignore[PEV007]`` on the reference (or a
  justified baseline entry).
"""

from __future__ import annotations

import ast

from .core import Rule, register_rule

_THREAD_CTORS = frozenset({
    "threading.Thread", "threading.Timer",
    "concurrent.futures.ThreadPoolExecutor",
})
_FORK_PICKERS = frozenset({
    "multiprocessing.get_context", "multiprocessing.set_start_method",
})
# bare uses inherit the platform default start method (fork on POSIX)
_DEFAULT_START_CTORS = frozenset({
    "multiprocessing.Process", "multiprocessing.Pool",
})
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
})


def _child_entry_names(ctx) -> set[str]:
    """Bare names of functions handed to a ``*Process(target=...)``
    call — the code that will run on the child side of the boundary."""
    names: set[str] = set()
    for node in ctx.walk(ast.Call):
        dotted = ctx.dotted(node.func)
        if not (dotted == "Process" or dotted.endswith(".Process")):
            continue
        kw = next((k for k in node.keywords if k.arg == "target"), None)
        if kw is not None:
            target = ctx.dotted(kw.value)
            if target:
                names.add(target.rsplit(".", 1)[-1])
    return names


@register_rule
class ForkUnsafetyRule(Rule):
    """PEV007: fork-start multiprocessing in thread-running modules;
    parent-created locks/registries referenced from child entries."""

    code = "PEV007"
    name = "fork-unsafety"
    rationale = ("fork in a threaded parent copies locks in whatever "
                 "state some other thread held them — the child "
                 "deadlocks on first acquire; and parent-created "
                 "locks/registries referenced from a Process target are "
                 "state that silently crossed the process boundary")

    def run(self, ctx):
        starts_threads = any(
            ctx.resolved(node.func) in _THREAD_CTORS
            for node in ctx.walk(ast.Call))
        yield from self._fork_starts(ctx, starts_threads)
        yield from self._boundary_crossings(ctx)

    # -- shape 1: fork-start where threads run ---------------------------------

    def _fork_starts(self, ctx, starts_threads: bool):
        if not starts_threads:
            return
        for node in ctx.walk(ast.Call):
            resolved = ctx.resolved(node.func)
            if resolved in _FORK_PICKERS and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value == "fork":
                yield self.finding(
                    ctx, node,
                    "fork-start multiprocessing in a module that starts "
                    "threads — fork copies other threads' held locks "
                    "into the child locked forever; use "
                    "get_context(\"spawn\")")
            elif resolved in _DEFAULT_START_CTORS:
                yield self.finding(
                    ctx, node,
                    f"bare {resolved.rsplit('.', 1)[-1]}() in a module "
                    f"that starts threads inherits the platform default "
                    f"start method (fork on POSIX) — take an explicit "
                    f"spawn context instead")

    # -- shape 2: parent state referenced from a child entry -------------------

    def _boundary_crossings(self, ctx):
        entries = _child_entry_names(ctx)
        if not entries:
            return
        module_locks = self._module_lock_names(ctx)
        for fn in ctx.walk((ast.FunctionDef, ast.AsyncFunctionDef)):
            if fn.name not in entries:
                continue
            attr_locks = self._class_lock_attrs(ctx, fn)
            reported: set[str] = set()
            for node in ast.walk(fn):
                name = None
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in module_locks:
                    name = node.id
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in attr_locks:
                    name = f"self.{node.attr}"
                if name is None or name in reported:
                    continue
                reported.add(name)
                yield self.finding(
                    ctx, node,
                    f"child entry '{fn.name}' references parent-created "
                    f"lock '{name}' across the process boundary — under "
                    f"spawn it is a different object, under fork it may "
                    f"arrive held; create it in the child or document "
                    f"the handoff")

    @staticmethod
    def _module_lock_names(ctx) -> set[str]:
        names: set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            if isinstance(value, ast.Call) \
                    and ctx.resolved(value.func) in _LOCK_CTORS:
                names.update(t.id for t in targets
                             if isinstance(t, ast.Name))
        return names

    @staticmethod
    def _class_lock_attrs(ctx, fn) -> set[str]:
        """Lock-valued ``self.X`` attributes assigned anywhere in the
        class that owns ``fn`` (``__init__`` runs in the parent; the
        child entry method sees the copies)."""
        cls = next((a for a in ctx.ancestors(fn)
                    if isinstance(a, ast.ClassDef)), None)
        if cls is None:
            return set()
        attrs: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call) \
                    and ctx.resolved(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        attrs.add(t.attr)
        return attrs
