"""Analysis driver: file walking, parsed-module context, rule dispatch.

One ``ModuleContext`` per file carries everything every rule needs —
the parse tree with parent links, enclosing-scope qualnames, dotted-name
resolution through module aliases, and the per-module scope knobs from
``AnalysisConfig`` — so each rule stays a small, testable visitor.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field

from .core import Finding, all_rules, is_suppressed, parse_suppressions

# Scope tables: which modules are held to which contract. Patterns are
# fnmatch globs over repo-relative posix paths. These encode the
# codebase's own architecture (DESIGN.md §21) — they are configuration,
# not policy baked into the rules.

# PEV002: the seeded stateless decision paths. "strict" modules may not
# touch wall clocks at all (every decision is a pure function of the
# identity); "decision" modules host telemetry timing legitimately, so
# only RNG-cursor / hash-seed nondeterminism is flagged there.
STATELESS_STRICT = (
    "pos_evolution_tpu/sim/faults.py",
    "pos_evolution_tpu/sim/dense_adversary.py",
    "pos_evolution_tpu/sim/adversary.py",
    "pos_evolution_tpu/sim/schedule.py",
    "pos_evolution_tpu/sim/dense_monitors.py",
)
STATELESS_DECISION = (
    "pos_evolution_tpu/sim/driver.py",
    "pos_evolution_tpu/sim/dense_driver.py",
    "pos_evolution_tpu/sim/monitors.py",
    "pos_evolution_tpu/specs/*.py",
    "pos_evolution_tpu/ops/*.py",
    "pos_evolution_tpu/variants/*.py",
    "pos_evolution_tpu/ssz/*.py",
    # ISSUE 18: the trace-sampling decision (sample/trace_id) must be a
    # pure function of (seed, request ordinal) — a wall-clock or RNG
    # leak here would desynchronize client and server span identities.
    # Span *recording* timestamps legitimately read the clock, which is
    # exactly the "decision" (not "strict") contract.
    "pos_evolution_tpu/telemetry/tracing.py",
)

# PEV003: modules whose loops are per-slot / per-message hot paths where
# an accidental device->host sync stalls the pipeline.
HOT_MODULES = (
    "pos_evolution_tpu/ops/*.py",
    "pos_evolution_tpu/parallel/*.py",
    "pos_evolution_tpu/sim/dense_driver.py",
    "pos_evolution_tpu/backend/jax_backend.py",
)

# Lockset scope: the multithreaded tiers (threads are created here or the
# classes are called from them).
THREADED_MODULES = (
    "pos_evolution_tpu/serve/*.py",
    "pos_evolution_tpu/telemetry/*.py",
    "pos_evolution_tpu/resilience/*.py",
    "pos_evolution_tpu/das/server.py",
    "pos_evolution_tpu/utils/watchdog.py",
    "pos_evolution_tpu/utils/singleflight.py",
)

DEFAULT_PATHS = ("pos_evolution_tpu", "scripts", "examples",
                 "bench.py", "bench_all.py")

SKIP_DIRS = {"__pycache__", ".git", "bench_trace", "node_modules"}


@dataclass
class AnalysisConfig:
    rules: frozenset | None = None      # None = all registered
    stateless_strict: tuple = STATELESS_STRICT
    stateless_decision: tuple = STATELESS_DECISION
    hot_modules: tuple = HOT_MODULES
    threaded_modules: tuple = THREADED_MODULES
    # tests are analyzed with a narrowed rule set (see __main__)
    extra: dict = field(default_factory=dict)

    def rule_enabled(self, code: str) -> bool:
        return self.rules is None or code in self.rules


def _matches(relpath: str, patterns: tuple) -> bool:
    return any(fnmatch.fnmatch(relpath, p) for p in patterns)


class ModuleContext:
    """Parsed module + the navigation helpers rules share."""

    def __init__(self, source: str, relpath: str,
                 config: AnalysisConfig | None = None):
        self.source = source
        self.relpath = relpath.replace(os.sep, "/")
        self.config = config or AnalysisConfig()
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.suppressions = parse_suppressions(source)
        self._parents: dict[int, ast.AST] = {}
        self._qualnames: dict[int, str] = {}
        self._index(self.tree, None, ())
        self.aliases = self._import_aliases()

    def _import_aliases(self) -> dict[str, str]:
        """Local binding -> canonical dotted origin, from import
        statements: ``import time as _t`` maps ``_t`` -> ``time``,
        ``from jax import jit as J`` maps ``J`` -> ``jax.jit``. Rules
        match on *resolved* names so aliasing can't evade them."""
        out: dict[str, str] = {}
        for node in self.walk((ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = a.name
                    else:
                        out[a.name.split(".")[0]] = a.name.split(".")[0]
            elif node.module and not node.level:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def _index(self, node: ast.AST, parent, scope: tuple) -> None:
        if parent is not None:
            self._parents[id(node)] = parent
        self._qualnames[id(node)] = ".".join(scope)
        child_scope = scope
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            child_scope = scope + (node.name,)
        for child in ast.iter_child_nodes(node):
            # the def/class NODE itself belongs to the outer scope; its
            # children (including decorators, which run outside) get the
            # inner qualname — close enough for reporting purposes
            self._index(child, node, child_scope)

    # -- navigation ------------------------------------------------------------

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def qualname_at(self, node: ast.AST) -> str:
        return self._qualnames.get(id(node), "")

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    _LOOP_TYPES = (ast.For, ast.While, ast.ListComp, ast.SetComp,
                   ast.DictComp, ast.GeneratorExp)

    def enclosing_loop(self, node: ast.AST, stop_at_function: bool = True):
        """Nearest enclosing per-iteration context: for/while loops AND
        comprehensions (a `.item()` in a listcomp syncs per element just
        the same)."""
        for anc in self.ancestors(node):
            if isinstance(anc, self._LOOP_TYPES):
                return anc
            if stop_at_function and isinstance(
                    anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return None
        return None

    def line_key(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # -- name resolution -------------------------------------------------------

    @staticmethod
    def dotted(node: ast.AST) -> str:
        """'jax.jit' for Attribute/Name chains, '' when not a plain chain."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return ""

    def resolved(self, node: ast.AST) -> str:
        """``dotted`` with the head segment mapped through this module's
        import aliases: ``_t.time`` -> ``time.time``, ``J`` ->
        ``jax.jit``, ``np.random.rand`` -> ``numpy.random.rand``."""
        name = self.dotted(node)
        if not name:
            return name
        head, _, rest = name.partition(".")
        origin = self.aliases.get(head)
        if origin is None:
            return name
        return f"{origin}.{rest}" if rest else origin

    def walk(self, types=None):
        for node in ast.walk(self.tree):
            if types is None or isinstance(node, types):
                yield node

    # -- scope queries ---------------------------------------------------------

    def in_stateless_strict(self) -> bool:
        return _matches(self.relpath, self.config.stateless_strict)

    def in_stateless_decision(self) -> bool:
        return _matches(self.relpath, self.config.stateless_decision)

    def in_hot_module(self) -> bool:
        return _matches(self.relpath, self.config.hot_modules)

    def in_threaded_module(self) -> bool:
        return _matches(self.relpath, self.config.threaded_modules)


@dataclass
class ModuleResult:
    relpath: str
    findings: list[Finding]
    suppressed: int = 0
    parse_error: str | None = None


def analyze_source(source: str, relpath: str,
                   config: AnalysisConfig | None = None) -> ModuleResult:
    config = config or AnalysisConfig()
    try:
        ctx = ModuleContext(source, relpath, config)
    except SyntaxError as e:  # a file the pass cannot read is a finding
        return ModuleResult(relpath, [Finding(
            path=relpath, line=e.lineno or 1, code="PEV000",
            message=f"syntax error: {e.msg}")], parse_error=str(e))
    raw: list[Finding] = []
    for _code, rule in all_rules().items():
        if any(config.rule_enabled(c) for c in rule.all_codes):
            raw.extend(f for f in rule.run(ctx)
                       if config.rule_enabled(f.code))
    kept, suppressed = [], 0
    for f in sorted(raw):
        if is_suppressed(f, ctx.suppressions):
            suppressed += 1
        else:
            kept.append(f)
    return ModuleResult(ctx.relpath, kept, suppressed=suppressed)


def iter_py_files(paths, root: str = "."):
    for p in paths:
        full = os.path.join(root, p) if not os.path.isabs(p) else p
        if not os.path.exists(full):
            # a typo'ed path must never become a silent '0 files, rc 0'
            # pass — the gate would be a permanent no-op
            raise FileNotFoundError(f"analysis path does not exist: {p!r}")
        if os.path.isfile(full):
            yield full
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in SKIP_DIRS)
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def analyze_paths(paths=DEFAULT_PATHS, root: str = ".",
                  config: AnalysisConfig | None = None) -> list[ModuleResult]:
    config = config or AnalysisConfig()
    results = []
    for path in iter_py_files(paths, root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        results.append(analyze_source(source, relpath, config))
    return results
