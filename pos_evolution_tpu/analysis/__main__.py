"""CLI: ``python -m pos_evolution_tpu.analysis``.

Exit codes (gate semantics, pinned in tests/test_analysis.py):

- ``0`` — no new findings (and, under ``--strict``, no stale baseline
  entries); or report-only mode.
- ``1`` — new findings (``--strict`` / default gate), or ``--doctor``
  produced exactly the expected findings (the doctored file *fails* the
  lint — CI asserts rc == 1).
- ``2`` — the pass itself is unhealthy: stale baseline entries under
  ``--strict``, a doctor mismatch, or bad usage.
"""

from __future__ import annotations

import argparse
import sys
from dataclasses import dataclass, field

from .core import Baseline, Finding
from .doctor import run_doctor
from .engine import DEFAULT_PATHS, AnalysisConfig, analyze_paths
from .report import dumps as report_dumps
from .report import render_text


@dataclass
class Summary:
    files_scanned: int = 0
    new: list = field(default_factory=list)
    absorbed: int = 0
    suppressed: int = 0
    stale_baseline: list = field(default_factory=list)


def gate(paths, root=".", baseline: Baseline | None = None,
         config: AnalysisConfig | None = None) -> Summary:
    """Analyze ``paths`` and partition findings against the baseline."""
    results = analyze_paths(paths, root=root, config=config)
    findings: list[Finding] = []
    suppressed = 0
    for r in results:
        findings.extend(r.findings)
        suppressed += r.suppressed
    if baseline is not None:
        new, absorbed, stale = baseline.match(findings)
    else:
        new, absorbed, stale = findings, [], []
    return Summary(files_scanned=len(results), new=new,
                   absorbed=len(absorbed), suppressed=suppressed,
                   stale_baseline=stale)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pos_evolution_tpu.analysis",
        description="Consensus-grade static analysis: PEV lint + lockset "
                    "race detector (DESIGN.md §21)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to analyze (default: "
                         f"{' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--baseline", default="analysis_baseline.json",
                    help="checked-in baseline of justified pre-existing "
                         "findings (default: %(default)s; 'none' disables)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail (rc 2) on stale baseline entries")
    ap.add_argument("--json", metavar="FILE", default=None,
                    help="write the JSON report to FILE ('-' = stdout)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated PEV codes to run (default: all)")
    ap.add_argument("--assume-scope", choices=("strict", "decision"),
                    default=None,
                    help="treat EVERY analyzed file as a stateless-"
                         "contract module of the given class (used for "
                         "the tests/ flaky-prevention pass, where the "
                         "per-module scope tables don't apply)")
    ap.add_argument("--doctor", action="store_true",
                    help="self-test on the synthesized bug file; rc 1 = "
                         "healthy (the doctored file fails the lint)")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write current new findings as baseline entries "
                         "to FILE (justifications start as TODO and must "
                         "be hand-edited)")
    ap.add_argument("--root", default=".", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.doctor:
        return run_doctor()

    config = AnalysisConfig(
        rules=(frozenset(c.strip() for c in args.rules.split(",") if c.strip())
               if args.rules else None))
    if args.assume_scope == "strict":
        config.stateless_strict = ("*",)
    elif args.assume_scope == "decision":
        config.stateless_decision = ("*",)
    baseline = None
    if args.baseline and args.baseline != "none":
        try:
            baseline = Baseline.load(args.baseline)
        except FileNotFoundError:
            print(f"note: baseline {args.baseline!r} not found — every "
                  f"finding counts as new", file=sys.stderr)
    paths = args.paths or DEFAULT_PATHS
    try:
        summary = gate(paths, root=args.root, baseline=baseline,
                       config=config)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        entries = list(baseline.entries) if baseline else []
        for f in summary.new:
            entries.append(Baseline.entry_for(
                f, "TODO: one-line justification (deliberate pattern? "
                   "fix instead?)"))
        merged = Baseline(entries=entries)
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            fh.write(merged.dump())
        print(f"wrote {len(entries)} baseline entries to "
              f"{args.write_baseline}", file=sys.stderr)

    if args.json:
        blob = report_dumps(summary)
        if args.json == "-":
            sys.stdout.write(blob)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(blob)
    if args.json != "-":
        print(render_text(summary))

    if summary.new:
        return 1
    if args.strict and summary.stale_baseline:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
