"""Text and JSON reporters. The JSON schema is versioned and pinned by
tests/test_analysis.py — downstream tooling (CI greps, dashboards) may
rely on every key listed in ``SCHEMA_KEYS``."""

from __future__ import annotations

import json

REPORT_VERSION = 1

SCHEMA_KEYS = ("version", "files_scanned", "findings", "absorbed",
               "suppressed", "stale_baseline", "by_code", "rules")
FINDING_KEYS = ("code", "path", "line", "col", "message", "context", "key")


def render_text(summary) -> str:
    """Human-facing report: one `file:line  CODE  message` per finding,
    grouped stats at the end."""
    lines = []
    for f in summary.new:
        lines.append(f"{f.location()}: {f.code} [{f.context or '<module>'}] "
                     f"{f.message}")
    if summary.stale_baseline:
        lines.append("")
        lines.append("stale baseline entries (no longer match anything — "
                     "prune them):")
        for e in summary.stale_baseline:
            lines.append(f"  {e['code']} {e['path']} [{e['context']}] "
                         f"{e['key'][:60]}")
    lines.append("")
    by_code: dict[str, int] = {}
    for f in summary.new:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    tally = " ".join(f"{c}={n}" for c, n in sorted(by_code.items())) or "none"
    lines.append(
        f"{summary.files_scanned} files: {len(summary.new)} new finding(s) "
        f"[{tally}], {summary.absorbed} baselined, "
        f"{summary.suppressed} suppressed"
        + (f", {len(summary.stale_baseline)} stale baseline entr"
           f"{'y' if len(summary.stale_baseline) == 1 else 'ies'}"
           if summary.stale_baseline else ""))
    return "\n".join(lines)


def render_json(summary) -> dict:
    from .core import all_rules
    return {
        "version": REPORT_VERSION,
        "files_scanned": summary.files_scanned,
        "findings": [
            {"code": f.code, "path": f.path, "line": f.line, "col": f.col,
             "message": f.message, "context": f.context, "key": f.key}
            for f in summary.new],
        "absorbed": summary.absorbed,
        "suppressed": summary.suppressed,
        "stale_baseline": list(summary.stale_baseline),
        "by_code": _by_code(summary.new),
        "rules": {c: {"name": r.name, "rationale": r.rationale}
                  for r in all_rules().values()
                  for c in r.all_codes},
    }


def _by_code(findings) -> dict:
    out: dict[str, int] = {}
    for f in findings:
        out[f.code] = out.get(f.code, 0) + 1
    return dict(sorted(out.items()))


def dumps(summary) -> str:
    return json.dumps(render_json(summary), indent=1, sort_keys=True) + "\n"
