"""JAX compilation-discipline rules: PEV001, PEV003, PEV004.

These mechanize three review findings that each cost real wall-clock:

- **PEV001** — PR 7's ``reconstruct_check_device``: a fresh ``@jax.jit``
  closure built per call hits the compile cache never (each closure is a
  new Python callable), so every invocation recompiles. The demo went
  24.8s -> 7.6s when the jit was hoisted to a module singleton. The
  codebase's two blessed idioms are module-level construction and the
  memoized ``*_for`` builder (``parallel/sharded.epoch_step_for``).
- **PEV003** — a ``.item()`` / ``device_get`` / ``float(jnp...)`` inside
  a per-slot hot loop forces a device->host sync per iteration, serializing
  the dispatch pipeline the sharded driver lives on.
- **PEV004** — ``donate_argnums`` is a no-op that *warns per call* on
  XLA:CPU; the codebase standardizes on guarding donation off-CPU
  (``ops/transition._sweep_fn``, ``epoch_step_for(donate=...)``).
"""

from __future__ import annotations

import ast

from .core import Rule, register_rule

# callables whose *call* constructs a compiled-function closure
_JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
})
_JIT_BARE_NAMES = frozenset(n.rsplit(".", 1)[-1] for n in _JIT_NAMES)
_MEMO_SUFFIXES = ("_for",)
_CACHE_DECORATORS = frozenset({
    "lru_cache", "cache", "functools.lru_cache", "functools.cache",
    "cached_property", "functools.cached_property",
})


def _names_of(ctx, node) -> set:
    """Raw and alias-resolved spellings — matching both defeats
    ``from jax import jit as J`` style aliasing."""
    return {ctx.dotted(node), ctx.resolved(node)} - {""}


def _is_jit_constructor(ctx, node: ast.AST) -> bool:
    """True for ``jax.jit(...)``, ``shard_map(...)``, bare ``@jax.jit``
    decorator references, and ``partial(jax.jit, ...)`` forms."""
    if isinstance(node, ast.Call):
        names = _names_of(ctx, node.func)
        if names & _JIT_NAMES:
            return True
        if names & {"partial", "functools.partial"} and node.args:
            return bool(_names_of(ctx, node.args[0]) & _JIT_NAMES)
        return False
    return bool(_names_of(ctx, node) & _JIT_NAMES)


def _in_decorators(fn, node) -> bool:
    return any(node is d or any(node is sub for sub in ast.walk(d))
               for d in fn.decorator_list)


def _func_chain(ctx, node):
    """Enclosing (non-lambda) function defs, innermost first. A node
    inside a def's decorator list executes in the ENCLOSING scope —
    ``@jax.jit`` on a module-level def is the module-level idiom, not a
    per-call construction — so that def is excluded from its own
    decorators' chain."""
    chain = []
    for a in ctx.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if not chain and _in_decorators(a, node):
                continue
            chain.append(a)
    return chain


def _has_cache_decorator(fn) -> bool:
    from .engine import ModuleContext
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if ModuleContext.dotted(target) in _CACHE_DECORATORS:
            return True
    return False


def _declares_singleton_global(fn) -> bool:
    """The ``ops/transition._device`` idiom: ``global _DEVICE`` + write —
    the function IS the memo for a module singleton."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Global) and any(
                n.startswith("_") for n in node.names):
            return True
    return False


def _memo_exempt_chain(ctx, chain) -> bool:
    for fn in chain:
        if fn.name.endswith(_MEMO_SUFFIXES):
            return True
        if _has_cache_decorator(fn) or _declares_singleton_global(fn):
            return True
    return False


def _references_only_memoized(ctx, fn_name: str, own_def) -> bool:
    """Exemption for the helper-builder idiom: ``_sharded_epoch_core``
    constructs the jit but is only ever *called* from inside a ``*_for``
    memo (or handed to ``_cached``). Every in-module reference outside the
    def itself must sit in a memoized context; zero references = not
    exempt (the caller is outside our view — make it a baseline entry)."""
    own_nodes = {id(n) for n in ast.walk(own_def)}
    refs = [n for n in ctx.walk(ast.Name)
            if n.id == fn_name and isinstance(n.ctx, ast.Load)
            and id(n) not in own_nodes]
    if not refs:
        return False
    for ref in refs:
        chain = _func_chain(ctx, ref)
        if _memo_exempt_chain(ctx, chain):
            continue
        in_cached_call = any(
            isinstance(a, ast.Call) and ctx.dotted(a.func).endswith("_cached")
            for a in ctx.ancestors(ref))
        if not in_cached_call:
            return False
    return True


@register_rule
class FreshJitClosureRule(Rule):
    """PEV001: ``jax.jit`` / ``shard_map`` / ``pjit`` closure constructed
    inside a function or loop body without memoization."""

    code = "PEV001"
    name = "fresh-jit-closure"
    rationale = ("a closure built per call is a new callable every time: "
                 "XLA's compile cache keys on it and recompiles on every "
                 "invocation (PR 7: 3.3x demo slowdown)")

    def run(self, ctx):
        seen = set()
        for node in ctx.walk((ast.Call, ast.Attribute, ast.Name)):
            if not _is_jit_constructor(ctx, node):
                continue
            # a bare Name/Attribute only matters as a decorator reference
            if not isinstance(node, ast.Call):
                parent = ctx.parent(node)
                if not (isinstance(parent, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))
                        and node in parent.decorator_list):
                    continue
            # skip the inner jax.jit of partial(...) double-hits: the
            # Call case already reports the partial itself
            parent = ctx.parent(node)
            if (isinstance(parent, ast.Call) and node in parent.args
                    and _is_jit_constructor(ctx, parent)):
                continue
            chain = _func_chain(ctx, node)
            # a compat shim DEFINING one of the constructor names (the
            # pre-0.6 `def shard_map(f, **kw): return _experimental(...)`
            # wrapper) is a pass-through: its CALLERS are the audit sites
            if chain and any(fn.name in _JIT_BARE_NAMES for fn in chain):
                continue
            if not chain:
                if ctx.enclosing_loop(node, stop_at_function=False) is None:
                    continue  # module level, outside any loop: the idiom
                outer = None
            else:
                outer = chain[-1]
            if chain and _memo_exempt_chain(ctx, chain):
                continue
            if outer is not None and _references_only_memoized(
                    ctx, outer.name, outer):
                continue
            # one finding per decorated def, not one per stacked decorator
            decorated = next(
                (a for a in ctx.ancestors(node)
                 if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and _in_decorators(a, node)), None)
            key = ("deco", id(decorated)) if decorated is not None \
                else ("line", node.lineno)
            if key in seen:
                continue
            seen.add(key)
            where = chain[0].name if chain else "module loop"
            yield self.finding(
                ctx, node,
                f"fresh jit/shard_map closure constructed in '{where}' — "
                f"hoist to module level or route through a memoized "
                f"'*_for' builder (recompiles per call otherwise)")


_SYNC_CALLS = frozenset({"jax.device_get", "jax.block_until_ready"})
_TRACED_HINTS = frozenset({"jnp", "lax", "jsp"})


def _mentions_traced(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Name) and n.id in _TRACED_HINTS
               for n in ast.walk(node))


@register_rule
class HostSyncInHotLoopRule(Rule):
    """PEV003: host-device synchronization inside a per-slot hot loop."""

    code = "PEV003"
    name = "host-sync-in-hot-loop"
    rationale = ("`.item()`/`device_get`/`float(jnp...)` inside a hot loop "
                 "blocks on the device every iteration — the async dispatch "
                 "pipeline the sharded driver depends on collapses to "
                 "lockstep round-trips")

    def run(self, ctx):
        if not ctx.in_hot_module():
            return
        for node in ctx.walk(ast.Call):
            if ctx.enclosing_loop(node) is None:
                continue
            name = ctx.dotted(node.func)
            names = _names_of(ctx, node.func)
            hit = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args:
                hit = ".item() sync"
            elif names & _SYNC_CALLS or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"):
                hit = f"{name or 'block_until_ready'} sync"
            elif name in ("float", "int", "bool") and node.args \
                    and _mentions_traced(node.args[0]):
                hit = f"{name}() on a traced/device expression"
            elif names & {"np.asarray", "numpy.asarray"} and node.args \
                    and _mentions_traced(node.args[0]):
                hit = "np.asarray of a device array"
            if hit:
                yield self.finding(
                    ctx, node,
                    f"{hit} inside a hot loop — pull the value once "
                    f"outside the loop or keep the reduction on device")


@register_rule
class UnguardedDonationRule(Rule):
    """PEV004: ``donate_argnums`` without the off-CPU guard."""

    code = "PEV004"
    name = "unguarded-donation"
    rationale = ("XLA:CPU does not implement buffer donation and warns on "
                 "every call; the codebase standardizes on guarding "
                 "donation off-CPU (transition._sweep_fn, "
                 "epoch_step_for(donate=...))")

    def run(self, ctx):
        # a real default_backend USE in code, not a docstring mention
        module_guarded = any(
            (isinstance(n, ast.Attribute) and n.attr == "default_backend")
            or (isinstance(n, ast.Name) and n.id == "default_backend")
            for n in ctx.walk((ast.Attribute, ast.Name)))
        for node in ctx.walk(ast.Call):
            kw = next((k for k in node.keywords
                       if k.arg == "donate_argnums"), None)
            if kw is None:
                continue
            if isinstance(kw.value, ast.IfExp):
                continue  # `(0,) if donate else ()` — the guard is inline
            if isinstance(kw.value, ast.Tuple) and not kw.value.elts:
                continue  # explicit no-donation
            chain = _func_chain(ctx, node)
            if any(a.arg == "donate"
                   for fn in chain
                   for a in (fn.args.args + fn.args.kwonlyargs)):
                continue  # caller decides, like epoch_step_for(donate=...)
            if module_guarded:
                continue  # module selects donated vs plain by backend
            yield self.finding(
                ctx, node,
                "donate_argnums without an off-CPU guard — gate on "
                "jax.default_backend() or take a `donate` flag the "
                "backend-aware caller sets")
