"""Hygiene rules: PEV005 (silent except in daemon loops), PEV006
(mutable defaults / lowercase module mutables).

- **PEV005** is PR 12's silent-dead-worker class: a worker thread's loop
  catches an exception and continues with *no* emission — the worker is
  effectively dead-or-degraded and nothing ever says so. The serving
  tier hardened every such loop to either emit telemetry or close/propagate
  loudly; this rule keeps it that way. Only handlers whose body performs
  **no call, no raise, no return, no break** are flagged — a handler that
  reports, closes a connection, or re-raises is doing its job.
- **PEV006** covers the two Python-footgun shapes of shared mutable
  state: a mutable default argument (one object shared across all calls),
  and a *lowercase* module-level mutable mutated from function bodies.
  The codebase's deliberate module singletons (``_KERNEL_CACHE``,
  ``_RULES``) are SCREAMING_SNAKE by convention — that spelling is the
  opt-in marker; a lowercase module global mutated from functions reads
  as local state and gets flagged.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, register_rule

_WORKER_NAME_RE = re.compile(
    r"(_loop|_worker|_drain|_forever|_heartbeat)$")
_THREAD_FACTORIES = frozenset({
    "threading.Thread", "Thread", "threading.Timer", "Timer",
})


def worker_functions(ctx) -> set[str]:
    """Names of functions that run on their own thread: ``Thread(target=
    X)`` / ``Timer(_, X)`` targets plus the ``*_loop``-style naming
    convention. Methods are tracked by bare name (``self._drain`` ->
    ``_drain``)."""
    names: set[str] = set()
    for node in ctx.walk(ast.Call):
        callee = ctx.dotted(node.func)
        target = None
        if callee in _THREAD_FACTORIES:
            kw = next((k for k in node.keywords if k.arg == "target"), None)
            if kw is not None:
                target = kw.value
            elif callee.endswith("Timer") and len(node.args) >= 2:
                target = node.args[1]
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            target = node.args[0]
        if target is not None:
            dotted = ctx.dotted(target)
            if dotted:
                names.add(dotted.rsplit(".", 1)[-1])
    for fn in ctx.walk((ast.FunctionDef, ast.AsyncFunctionDef)):
        if _WORKER_NAME_RE.search(fn.name):
            names.add(fn.name)
    return names


def _handler_is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break, ast.Call)):
            return False
        # `except ... as e: self._worker_error = e` captures the exception
        # for later surfacing (the CheckpointManager idiom) — not silent
        if handler.name and isinstance(node, ast.Name) \
                and node.id == handler.name \
                and isinstance(node.ctx, ast.Load):
            return False
    return True


@register_rule
class SilentWorkerExceptRule(Rule):
    """PEV005: except-and-continue in a daemon/worker loop that swallows
    the exception without emitting anything."""

    code = "PEV005"
    name = "silent-worker-except"
    rationale = ("a worker loop that eats exceptions silently is the "
                 "silent-dead-worker class PR 12 hardened against: the "
                 "tier degrades and no event, counter, or log says why")

    def run(self, ctx):
        workers = worker_functions(ctx)
        for fn in ctx.walk((ast.FunctionDef, ast.AsyncFunctionDef)):
            if fn.name not in workers:
                continue
            # a Try nested under several loops must report once, not once
            # per enclosing loop — collect distinct handlers first
            seen: set[int] = set()
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.While, ast.For)):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Try):
                        continue
                    for handler in node.handlers:
                        if id(handler) in seen:
                            continue
                        seen.add(id(handler))
                        if _handler_is_silent(handler):
                            yield self.finding(
                                ctx, handler,
                                f"worker loop '{fn.name}' swallows an "
                                f"exception with no emission — emit a "
                                f"telemetry event/counter or let it "
                                f"propagate to the supervisor")


_MUTABLE_CALLS = frozenset({
    "dict", "list", "set", "deque", "collections.deque",
    "defaultdict", "collections.defaultdict", "OrderedDict",
    "collections.OrderedDict", "bytearray",
})
_SINGLETON_NAME_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_MUTATING_METHODS = frozenset({
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "appendleft", "clear", "setdefault",
    "sort", "reverse",
})


def _is_mutable_ctor(ctx, node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    return (isinstance(node, ast.Call)
            and ctx.dotted(node.func) in _MUTABLE_CALLS)


@register_rule
class MutableSharedStateRule(Rule):
    """PEV006: mutable default arguments; lowercase module-level mutables
    mutated from function bodies."""

    code = "PEV006"
    name = "mutable-shared-state"
    rationale = ("a mutable default is one object shared by every call; "
                 "an undeclared module-level mutable is cross-call state "
                 "invisible to checkpoint/resume and to readers "
                 "(deliberate singletons are SCREAMING_SNAKE)")

    def run(self, ctx):
        yield from self._mutable_defaults(ctx)
        yield from self._module_mutables(ctx)

    def _mutable_defaults(self, ctx):
        for fn in ctx.walk((ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            args = fn.args
            for default in list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]:
                if _is_mutable_ctor(ctx, default):
                    name = getattr(fn, "name", "<lambda>")
                    yield self.finding(
                        ctx, default,
                        f"mutable default argument in '{name}' — one "
                        f"object is shared across every call; default to "
                        f"None and construct inside")

    def _module_mutables(self, ctx):
        mutables: dict[str, ast.AST] = {}
        for stmt in ctx.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and _is_mutable_ctor(ctx, value) \
                        and not _SINGLETON_NAME_RE.match(t.id):
                    mutables[t.id] = stmt
        if not mutables:
            return
        mutated: dict[str, int] = {}
        for fn in ctx.walk((ast.FunctionDef, ast.AsyncFunctionDef)):
            shadowed = self._locally_bound(fn)
            for node in ast.walk(fn):
                name, line = self._mutation_of(ctx, node)
                if name in shadowed:
                    continue  # the function's own local, not the global
                if name in mutables and name not in mutated:
                    mutated[name] = line
        for name, line in sorted(mutated.items()):
            stmt = mutables[name]
            yield self.finding(
                ctx, stmt,
                f"lowercase module-level mutable '{name}' is mutated from "
                f"a function (line {line}) — rename to SCREAMING_SNAKE to "
                f"declare the singleton, or move the state into a class")

    @staticmethod
    def _locally_bound(fn) -> set:
        """Names the function binds locally (params, plain-name
        assignments, for-targets, withitems) and does NOT declare
        ``global``: mutations of those are local, whatever the module
        defines under the same name."""
        bound: set[str] = set()
        args = fn.args
        for a in (args.args + args.kwonlyargs + args.posonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            bound.add(a.arg)
        globals_: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                globals_.update(node.names)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                bound.add(node.id)
        return bound - globals_

    @staticmethod
    def _mutation_of(ctx, node: ast.AST) -> tuple[str | None, int]:
        """(mutated module-global name, line) or (None, 0)."""
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATING_METHODS \
                and isinstance(node.func.value, ast.Name):
            return node.func.value.id, node.lineno
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = node.targets if isinstance(node, ast.Assign) else (
                [node.target] if isinstance(node, ast.AugAssign)
                else node.targets)
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name):
                    return t.value.id, node.lineno
        return None, 0
