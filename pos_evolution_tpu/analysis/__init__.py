"""Consensus-grade static analysis: the repo's recurring review findings
as a mechanical, CI-gated pass (DESIGN.md §21).

The paper's security claims (accountable safety at exactly-1/3 evidence,
liveness after GST) only hold if the implementation stays deterministic,
race-free, and recompile-stable — and the repo's review history shows
those properties regress in the same few ways every PR:

- a fresh ``@jax.jit`` closure built per call, silently recompiling on
  every invocation (PR 7 review fix: 3.3x demo slowdown);
- unlocked read-modify-writes on shared counters that the perf gate then
  gates on (PR 12 review fixes in ``telemetry/registry.py`` and
  ``serve/admission.py``);
- wall-clock / RNG-cursor nondeterminism leaking into seeded stateless
  paths that must be byte-stable across backends, mesh shapes, and
  resume (PR 13's ``stateless_unit_array`` contract).

This package turns each reviewed-out bug class into an AST rule with a
stable ``PEV###`` code, plus a lockset-based thread-safety analyzer over
the multithreaded tiers. Everything is pure stdlib ``ast`` — the pass
imports nothing from the analyzed tree and needs no jax/numpy, so CI can
run it before any heavy job.

Entry points::

    python -m pos_evolution_tpu.analysis --strict   # gate the tree
    python -m pos_evolution_tpu.analysis --doctor   # self-test negative
    python scripts/lint_deep.py                     # same, from scripts/

Rule index (full rationale per rule in its docstring):

==========  ==================================================================
PEV001      fresh ``jax.jit``/``shard_map``/``pjit`` closure per call
PEV002      nondeterminism reachable from seeded stateless paths
PEV003      host-device sync inside per-slot hot loops
PEV004      ``donate_argnums`` without the off-CPU guard
PEV005      except-and-continue that swallows errors in daemon loops
PEV006      mutable default args / lowercase module mutables
PEV007      fork-unsafety: fork-start amid threads; pre-fork locks in child entries
PEV101      unlocked read-modify-write on a shared instance attribute
PEV102      inconsistent locking discipline on a shared instance attribute
==========  ==================================================================
"""

from .core import (  # noqa: F401
    Baseline,
    Finding,
    Rule,
    all_rules,
    parse_suppressions,
    register_rule,
)
from .engine import AnalysisConfig, analyze_paths, analyze_source  # noqa: F401
from .report import render_json, render_text  # noqa: F401

__all__ = [
    "AnalysisConfig",
    "Baseline",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "parse_suppressions",
    "register_rule",
    "render_json",
    "render_text",
]
