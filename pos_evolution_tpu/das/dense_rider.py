"""DAS workload rider for the dense driver (ISSUE 20).

PR 17 built the sidecar plane (``das/engine.py``: deterministic blob
grids, merkle/kzg cell commitments, erasure-consistency verification)
and the sampling-client population (``das/sampler.py``) — but only the
spec driver ever drove them. This rider attaches both to
``DenseSimulation``: every per-view proposal gets its sidecars built,
verified through the full ``BlobStore.on_sidecar`` pipeline (commitment
recompute + the 50%-reconstruction check through the active
``ExecutionBackend`` — the kzg scheme runs the device-resident Fr/NTT
engine), and sampled by the seeded client population. The work is
charged to the driver's ``workload`` phase, so adversarial runs get the
same phase attribution as benign ones.

Everything is a pure function of (seed, slot, parent_root), so a
resumed episode rebuilds byte-identical sidecars — the rider's counters
are its only mutable state and ride the dense checkpoint.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DenseDasRider"]


class DenseDasRider:
    """Sidecar production + sampling + verification per dense proposal."""

    kind = "das"

    def __init__(self, scheme: str = "merkle", n_blobs: int = 1,
                 n_clients: int = 64, samples_per_client: int = 4,
                 seed: int = 0, verify_every: int = 1):
        self.scheme = str(scheme)
        self.n_blobs = int(n_blobs)
        self.n_clients = int(n_clients)
        self.samples_per_client = int(samples_per_client)
        self.seed = int(seed)
        # the erasure-reconstruction check is the expensive leg; mainnet
        # pins thin it to every N-th proposal (commitments + sampling
        # still run on every one)
        self.verify_every = max(int(verify_every), 1)
        self.sim = None
        self.sidecars_built = 0
        self.sidecars_verified = 0
        self.sidecar_failures = 0
        self.samples_drawn = 0
        self.sample_misses = 0
        self._proposals_seen = 0

    def bind(self, sim) -> None:
        from pos_evolution_tpu.das.engine import BlobEngine
        from pos_evolution_tpu.das.sampler import SamplingClientPopulation
        self.sim = sim
        self.engine = BlobEngine(n_blobs=self.n_blobs, scheme=self.scheme,
                                 seed=self.seed)
        self.clients = SamplingClientPopulation(
            self.n_clients, samples_per_client=self.samples_per_client,
            seed=self.seed)

    def on_proposals(self, sim, slot: int, new_idx) -> None:
        from pos_evolution_tpu.das.containers import BlobSidecar
        from pos_evolution_tpu.das.engine import BlobStore
        for idx in dict.fromkeys(int(i) for i in new_idx):
            self._proposals_seen += 1
            root = sim.roots[idx]
            parent_root = sim.roots[sim.parents[idx]]
            grids, commitments, _ = self.engine.build_for(slot, parent_root)
            self.sidecars_built += len(grids)
            if self._proposals_seen % self.verify_every == 0:
                # the receiving view's full verification: geometry,
                # commitment recompute, parity-half reconstruction
                store = BlobStore(self.engine)
                for i, (grid, com) in enumerate(zip(grids, commitments)):
                    sc = BlobSidecar(slot=slot, proposer_index=0,
                                     block_root=root, blob_index=i,
                                     n_blobs=len(grids), cells=grid,
                                     commitment=com)
                    if store.on_sidecar(sc):
                        self.sidecars_verified += 1
                    else:
                        self.sidecar_failures += 1
            blob_ids, cell_ids = self.clients.select_cells(
                root, len(grids), int(grids[0].shape[0]))
            self.samples_drawn += int(blob_ids.size)
            # availability sweep: every sampled (blob, cell) coordinate
            # must exist in the extended grids the proposer published
            ok = ((blob_ids >= 0) & (blob_ids < len(grids))
                  & (cell_ids >= 0) & (cell_ids < grids[0].shape[0]))
            self.sample_misses += int(np.size(ok) - np.count_nonzero(ok))

    def describe(self) -> dict:
        return {"kind": self.kind, "scheme": self.scheme,
                "n_blobs": self.n_blobs, "n_clients": self.n_clients,
                "samples_per_client": self.samples_per_client,
                "seed": self.seed, "verify_every": self.verify_every}

    @classmethod
    def from_config(cls, d: dict) -> "DenseDasRider":
        return cls(scheme=d.get("scheme", "merkle"),
                   n_blobs=int(d.get("n_blobs", 1)),
                   n_clients=int(d.get("n_clients", 64)),
                   samples_per_client=int(d.get("samples_per_client", 4)),
                   seed=int(d.get("seed", 0)),
                   verify_every=int(d.get("verify_every", 1)))

    def stats(self) -> dict:
        return {"scheme": self.scheme,
                "sidecars_built": self.sidecars_built,
                "sidecars_verified": self.sidecars_verified,
                "sidecar_failures": self.sidecar_failures,
                "samples_drawn": self.samples_drawn,
                "sample_misses": self.sample_misses,
                "blocks_sampled": self.clients.blocks_sampled}

    # -- checkpoint state (counters only; content is replay-from-seed) ---------

    def state_meta(self) -> dict:
        return {"sidecars_built": self.sidecars_built,
                "sidecars_verified": self.sidecars_verified,
                "sidecar_failures": self.sidecar_failures,
                "samples_drawn": self.samples_drawn,
                "sample_misses": self.sample_misses,
                "proposals_seen": self._proposals_seen,
                "blocks_sampled": self.clients.blocks_sampled,
                "client_samples_drawn": self.clients.samples_drawn}

    def state_arrays(self) -> dict:
        return {}

    def restore_state(self, meta: dict, arrays: dict) -> None:
        self.sidecars_built = int(meta.get("sidecars_built", 0))
        self.sidecars_verified = int(meta.get("sidecars_verified", 0))
        self.sidecar_failures = int(meta.get("sidecar_failures", 0))
        self.samples_drawn = int(meta.get("samples_drawn", 0))
        self.sample_misses = int(meta.get("sample_misses", 0))
        self._proposals_seen = int(meta.get("proposals_seen", 0))
        self.clients.blocks_sampled = int(meta.get("blocks_sampled", 0))
        self.clients.samples_drawn = int(meta.get("client_samples_drawn", 0))
