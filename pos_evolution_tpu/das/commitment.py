"""Pluggable cell-commitment schemes for the DAS grid.

A commitment scheme binds one blob's extended cell grid to a 32-byte
commitment and proves individual cells (or batches of cells) against it.
The default ``MerkleCellScheme`` is a padded binary merkle tree over the
per-cell SHA-256 leaves — every tree level is one batched sweep through
the ``ops/merkle_device.py`` dispatch layer (host SHA-256 below the
crossover, the batched device kernel above it; DESIGN.md §22), the
level-sweep kernel shape of the MTU tree-unit paper (arxiv 2507.16793)
— with generalized-index multiproofs standing in for the polynomial
multiproofs of arxiv 2604.16559. Proof branches come off ONE shared
tree build via vectorized sibling gathers
(``build_multiproof_paths``).

The scheme is a seam, not a constant: commitments travel as opaque
32-byte roots and every verifier goes through the scheme object, so a
pairing-based KZG scheme (ROADMAP item 3's device pairing) can register
under a new name and slot in without touching the sidecar containers,
the availability gate, or the serving layer.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.ops.merkle_device import (
    build_multiproof_paths,
    merkleize,
    multiproof,
)
from pos_evolution_tpu.ssz.hash import sha256_batch
from pos_evolution_tpu.ssz.merkle import merkle_tree_branch, verify_multiproof

__all__ = [
    "CellCommitmentScheme",
    "MerkleCellScheme",
    "register_scheme",
    "get_scheme",
]


class CellCommitmentScheme:
    """Contract every scheme implements over an (n_cells, cell_bytes) grid."""

    name = "abstract"
    # capability flag: schemes that fold a whole sampled set into ONE
    # opening proof (kzg/) set this True and additionally implement
    # ``prove_aggregate``/``verify_aggregate``; branch-based schemes
    # leave it False and serve per-cell branches
    aggregates = False

    def cell_leaves(self, cells: np.ndarray) -> np.ndarray:
        """(n, 32) leaf values the commitment tree/polynomial is built over."""
        raise NotImplementedError

    def commit(self, cells: np.ndarray) -> bytes:
        """32-byte commitment to the full extended grid."""
        raise NotImplementedError

    def branch(self, cells: np.ndarray, index: int) -> np.ndarray:
        """(depth, 32) single-cell inclusion proof for ``cells[index]``."""
        raise NotImplementedError

    def prove_cells(self, cells: np.ndarray, indices) -> list[bytes]:
        """One aggregated proof for a batch of cell indices."""
        raise NotImplementedError

    def verify_cells(self, commitment: bytes, cells: np.ndarray, indices,
                     proof: list[bytes]) -> bool:
        """Check a batch of (index, cell) pairs against ``commitment``."""
        raise NotImplementedError


class MerkleCellScheme(CellCommitmentScheme):
    """SHA-256 merkle commitment over per-cell leaves.

    The grid's 2k cell count is a power of two, so the tree is exactly
    depth log2(2k) with no virtual padding; single-cell branches feed the
    batched device walk in ``ops/das_verify.py`` and multi-cell proofs use
    the generalized-index multiproof (shared prefixes shipped once).
    """

    name = "merkle"

    @staticmethod
    def depth_for(n_cells: int) -> int:
        return max(int(n_cells - 1).bit_length(), 0)

    def cell_leaves(self, cells: np.ndarray) -> np.ndarray:
        return sha256_batch(np.ascontiguousarray(cells, dtype=np.uint8))

    def commit(self, cells: np.ndarray) -> bytes:
        # level sweeps through the device dispatch layer
        # (ops/merkle_device.py): host below the crossover, the batched
        # SHA-256 kernel above it — same bytes either way
        return merkleize(self.cell_leaves(cells))

    def branch(self, cells: np.ndarray, index: int) -> np.ndarray:
        leaves = self.cell_leaves(cells)
        sibs = merkle_tree_branch(leaves, int(index),
                                  self.depth_for(leaves.shape[0]))
        return np.frombuffer(b"".join(sibs), dtype=np.uint8).reshape(-1, 32)

    def branches(self, cells: np.ndarray, indices) -> tuple[np.ndarray, np.ndarray]:
        """(leaves[indices], (len(indices), depth, 32) branches) for the
        batched sample-verification kernel — leaves hashed once, every
        branch gathered VECTORIZED off one shared (device-built) tree."""
        leaves = self.cell_leaves(cells)
        return build_multiproof_paths(leaves, indices,
                                      self.depth_for(leaves.shape[0]))

    def prove_cells(self, cells: np.ndarray, indices) -> list[bytes]:
        leaves = self.cell_leaves(cells)
        return multiproof(leaves, [int(i) for i in indices],
                          self.depth_for(leaves.shape[0]))

    def verify_cells(self, commitment: bytes, cells: np.ndarray, indices,
                     proof: list[bytes]) -> bool:
        # hash only the sampled cells — the verifier never sees the grid
        leaves = sha256_batch(np.ascontiguousarray(cells, dtype=np.uint8))
        n_cells = 2 * cfg().das_cells_per_blob
        return verify_multiproof([leaves[j].tobytes()
                                  for j in range(leaves.shape[0])],
                                 [int(i) for i in indices], proof,
                                 self.depth_for(n_cells), commitment)


_SCHEMES: dict[str, type] = {}


def register_scheme(cls) -> type:
    """Register a ``CellCommitmentScheme`` subclass by its ``name`` —
    the hook a future pairing-based (KZG) scheme plugs into."""
    _SCHEMES[cls.name] = cls
    return cls


def get_scheme(name: str = "merkle") -> CellCommitmentScheme:
    if name == "kzg" and name not in _SCHEMES:
        # lazy self-registration: the kzg package costs import time
        # (field/curve constants), so it only loads when asked for
        import pos_evolution_tpu.kzg.scheme  # noqa: F401
    try:
        return _SCHEMES[name]()
    except KeyError:
        raise ValueError(f"unknown cell-commitment scheme {name!r}; "
                         f"registered: {sorted(_SCHEMES)}") from None


register_scheme(MerkleCellScheme)
