"""Blob production + per-view availability: the full-node side of DAS.

``BlobEngine`` is the deterministic blob workload: a blob's data cells
are a seeded pure function of (slot, parent_root, blob_index), so the
proposer, every verifying view group, and a resumed simulation all
regenerate byte-identical sidecars from the chain alone — the same
replay-from-seed posture as ``sim/faults.py``.

``BlobStore`` is one view group's availability state: sidecars arrive by
gossip (or req/resp backfill), get verified — commitment recomputed over
the full grid, then the erasure-consistency check from a 50% subset
through the ``ExecutionBackend`` (``ops/das_verify.reconstruct_check``)
— and ``is_available`` answers the fork-choice gate: a block whose
graffiti carries the DAS marker imports only once every committed
sidecar is held and verified (specs/forkchoice.on_block, gated exactly
like the merge payload validation).
"""

from __future__ import annotations

import itertools

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.das.commitment import CellCommitmentScheme, get_scheme
from pos_evolution_tpu.das.containers import (
    BlobSidecar,
    commitments_digest,
    das_graffiti,
    parse_das_graffiti,
    validate_das_config,
)
from pos_evolution_tpu.das.erasure import extend_blob
from pos_evolution_tpu.ssz.hash import sha256_batch

__all__ = ["BlobEngine", "BlobStore"]


class BlobEngine:
    """Deterministic blob workload + sidecar factory (one per Simulation)."""

    def __init__(self, n_blobs: int | None = None, scheme: str = "merkle",
                 seed: int = 0):
        validate_das_config()
        self.n_blobs = n_blobs
        self.scheme: CellCommitmentScheme = (
            scheme if isinstance(scheme, CellCommitmentScheme)
            else get_scheme(scheme))
        self.seed = int(seed)

    def blobs_per_block(self) -> int:
        return (cfg().das_max_blobs_per_block if self.n_blobs is None
                else self.n_blobs)

    def blob_data(self, slot: int, parent_root: bytes,
                  blob_index: int) -> np.ndarray:
        """(k, cell_bytes) seeded data cells — one SHA-256 counter stream
        per blob, batched across the whole grid."""
        c = cfg()
        total = c.das_cells_per_blob * c.das_cell_bytes
        n_hashes = (total + 31) // 32
        msgs = np.zeros((n_hashes, 52), dtype=np.uint8)
        msgs[:, :8] = np.frombuffer(
            self.seed.to_bytes(8, "little"), dtype=np.uint8)
        msgs[:, 8:16] = np.frombuffer(
            int(slot).to_bytes(8, "little"), dtype=np.uint8)
        msgs[:, 16:48] = np.frombuffer(bytes(parent_root), dtype=np.uint8)
        msgs[:, 48] = blob_index & 0xFF
        msgs[:, 49:52] = np.arange(n_hashes, dtype="<u4").view(
            np.uint8).reshape(n_hashes, 4)[:, :3]
        stream = sha256_batch(msgs).reshape(-1)[:total]
        return stream.reshape(c.das_cells_per_blob, c.das_cell_bytes)

    def build_for(self, slot: int, parent_root: bytes
                  ) -> tuple[list[np.ndarray], list[bytes], bytes]:
        """Everything a proposer needs BEFORE the block exists: the
        extended grids, their commitments, and the graffiti marker the
        block must carry (state_root covers graffiti, so the marker goes
        in at build time)."""
        grids, commitments = [], []
        for i in range(self.blobs_per_block()):
            grid = extend_blob(self.blob_data(slot, parent_root, i))
            grids.append(grid)
            commitments.append(self.scheme.commit(grid))
        return grids, commitments, das_graffiti(commitments)

    def sidecars_for(self, signed_block, block_root: bytes,
                     grids: list[np.ndarray],
                     commitments: list[bytes]) -> list[BlobSidecar]:
        block = signed_block.message
        return [BlobSidecar(slot=int(block.slot),
                            proposer_index=int(block.proposer_index),
                            block_root=bytes(block_root),
                            blob_index=i,
                            n_blobs=len(grids),
                            cells=grid,
                            commitment=commitments[i])
                for i, grid in enumerate(grids)]

    def regenerate(self, signed_block, block_root: bytes) -> list[BlobSidecar]:
        """Rebuild a block's sidecars from the block alone (resume path /
        late joiners): blob content is a pure function of the seed."""
        block = signed_block.message
        grids, commitments, _ = self.build_for(int(block.slot),
                                               bytes(block.parent_root))
        return self.sidecars_for(signed_block, block_root, grids, commitments)

    def describe(self) -> dict:
        return {"kind": "blob_engine", "scheme": self.scheme.name,
                "n_blobs": self.blobs_per_block(), "seed": self.seed}


class BlobStore:
    """One view group's DAS availability state (hangs off ``Store.blob_store``)."""

    def __init__(self, engine: BlobEngine, registry=None, group: int = -1):
        self.engine = engine
        self.registry = registry
        self.group = group
        # (block_root, blob_index) -> {commitment: verified BlobSidecar}.
        # Candidate SETS, not first-writer-wins: a sidecar that is
        # self-consistent under its own (wrong) commitment still verifies
        # here, and must not block the honest one for the same slot — the
        # block's graffiti digest picks the real set at gate time.
        self.sidecars: dict[tuple[bytes, int], dict[bytes, BlobSidecar]] = {}
        # block_root -> the candidate-per-index selection whose commitment
        # set matched the graffiti digest (memo filled by is_available)
        self._resolved: dict[bytes, list[BlobSidecar]] = {}

    # -- ingest ----------------------------------------------------------------

    def _count(self, name: str, help_: str) -> None:
        if self.registry is not None:
            self.registry.counter(name, help_).inc(group=self.group)

    def on_sidecar(self, sc: BlobSidecar) -> bool:
        """Gossip/backfill ingest: verify, then index. Verification =
        geometry + commitment recompute over the full grid + the
        50%-erasure consistency check through the ExecutionBackend (a
        corrupted or miscommitted sidecar is rejected, counted, and never
        feeds the availability gate)."""
        c = cfg()
        key = (bytes(sc.block_root), int(sc.blob_index))
        com = bytes(sc.commitment)
        if com in self.sidecars.get(key, ()):
            self._count("das_sidecar_duplicates_total",
                        "sidecar redeliveries ignored by the blob store")
            return True
        cells = np.ascontiguousarray(sc.cells, dtype=np.uint8)
        ok = (cells.shape == (2 * c.das_cells_per_blob, c.das_cell_bytes)
              and int(sc.blob_index) < int(sc.n_blobs))
        if ok:
            ok = self.engine.scheme.commit(cells) == bytes(sc.commitment)
        if ok:
            from pos_evolution_tpu.ops.das_verify import reconstruct_check
            # reconstruct from the PARITY half (a data-half mask would make
            # the interpolation matrix the identity — data compared to
            # itself): the k data cells interpolated back from the parity
            # evaluations must equal the claimed data half, and their
            # re-extension must reproduce the claimed parity half, so the
            # whole grid lies on one degree-<k polynomial
            half = np.zeros(cells.shape[0], dtype=bool)
            half[c.das_cells_per_blob:] = True
            recon_ok, data = reconstruct_check(cells, half)
            ok = recon_ok and bool(
                (data == cells[: c.das_cells_per_blob]).all())
        if not ok:
            self._count("das_sidecars_rejected_total",
                        "sidecars failing commitment/erasure verification")
            return False
        self.sidecars.setdefault(key, {})[com] = sc
        self._count("das_sidecars_accepted_total",
                    "sidecars verified and stored")
        return True

    # -- availability gate -----------------------------------------------------

    def is_available(self, block_root: bytes, block) -> bool:
        """The fork-choice data-availability predicate: for every blob the
        block's graffiti marker commits to, some verified candidate is
        held whose commitment set matches the marker digest. Blocks
        without the marker (no blobs, or a non-DAS proposer) gate
        vacuously."""
        meta = parse_das_graffiti(bytes(block.body.graffiti))
        if meta is None:
            return True
        n_blobs, digest = meta
        root = bytes(block_root)
        if root in self._resolved:
            return True
        candidates = [list(self.sidecars.get((root, i), {}).values())
                      for i in range(n_blobs)]
        if any(not held for held in candidates):
            return False
        # honest traffic has exactly one candidate per index; a Byzantine
        # flood is bounded rather than searched exhaustively
        for pick in itertools.islice(itertools.product(*candidates), 256):
            if commitments_digest(
                    [bytes(sc.commitment) for sc in pick]) == digest:
                self._resolved[root] = list(pick)
                return True
        return False

    def sidecars_for_block(self, block_root: bytes) -> list[BlobSidecar]:
        root = bytes(block_root)
        if root in self._resolved:
            return list(self._resolved[root])
        out = []
        i = 0
        while (root, i) in self.sidecars:
            held = self.sidecars[(root, i)]
            out.append(next(iter(held.values())))
            i += 1
        return out
