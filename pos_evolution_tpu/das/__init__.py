"""Data-availability-sampling subsystem (DESIGN.md §15).

Four layers, wired through every existing level of the stack:

- **data** — erasure-coded blobs (``das/erasure.py``), SSZ blob sidecars
  over the extended cell grid (``das/containers.py``), and pluggable
  cell commitments with generalized-index multiproofs
  (``das/commitment.py``; KZG slots in here when ROADMAP item 3 lands);
- **verification** — batched (client, cell) sample checks and the
  50%-erasure reconstruction check on both ``ExecutionBackend`` paths
  (``ops/das_verify.py``);
- **availability** — deterministic blob production + per-view stores
  feeding the fork-choice data-availability gate (``das/engine.py``,
  ``specs/forkchoice.on_block``);
- **serving** — a vectorized 10^5+ sampling-client population with
  request coalescing, LRU proof/update caches and p50/p95 latency
  metrics (``das/sampler.py``, ``das/server.py``), driven per slot by
  ``sim/driver.py`` and reported by ``scripts/run_report.py``.
"""

from pos_evolution_tpu.das.commitment import (
    CellCommitmentScheme,
    MerkleCellScheme,
    get_scheme,
    register_scheme,
)
from pos_evolution_tpu.das.containers import (
    MAX_EXTENDED_CELLS,
    BlobSidecar,
    CellRows,
    das_graffiti,
    parse_das_graffiti,
)
from pos_evolution_tpu.das.engine import BlobEngine, BlobStore
from pos_evolution_tpu.das.erasure import (
    extend_blob,
    extension_matrix,
    gf_matmul,
    reconstruct_blob,
)
from pos_evolution_tpu.das.sampler import SamplingClientPopulation
from pos_evolution_tpu.das.server import DasServer, LRUCache

__all__ = [
    "MAX_EXTENDED_CELLS",
    "BlobEngine",
    "BlobSidecar",
    "BlobStore",
    "CellCommitmentScheme",
    "CellRows",
    "DasServer",
    "LRUCache",
    "MerkleCellScheme",
    "SamplingClientPopulation",
    "das_graffiti",
    "extend_blob",
    "extension_matrix",
    "get_scheme",
    "gf_matmul",
    "parse_das_graffiti",
    "reconstruct_blob",
    "register_scheme",
]
