"""Sampling-client population: 10^5-10^6 DAS light clients as arrays.

A DAS client's behaviour is tiny — pick a few (blob, cell) coordinates
per block, request them, verify the proofs — so the population is
modelled the way the validator registry is: struct-of-arrays, no
per-client Python objects. Cell selection is a seeded stateless hash of
(seed, client_id, block_root), batched through ``ssz.hash.sha256_batch``
(one digest per client per block), so any run — or any single client —
is exactly reproducible, the ``sim/faults.stateless_unit`` posture at
population scale.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.ssz.hash import sha256_batch

__all__ = ["SamplingClientPopulation"]

# bytes of digest consumed per sample (u16 cell draw + u8 blob draw)
_BYTES_PER_SAMPLE = 3
_SAMPLES_PER_DIGEST = 32 // _BYTES_PER_SAMPLE  # 10


class SamplingClientPopulation:
    """N sampling clients with seeded per-client cell selection."""

    def __init__(self, n_clients: int, samples_per_client: int | None = None,
                 seed: int = 0):
        self.n = int(n_clients)
        self.samples = (cfg().das_samples_per_client
                        if samples_per_client is None
                        else int(samples_per_client))
        self.seed = int(seed)
        # per-client verdict bookkeeping across served blocks
        self.blocks_sampled = 0
        self.samples_drawn = 0

    def _digests(self, block_root: bytes, round_: int) -> np.ndarray:
        """(n, 32) per-client digests for one selection round."""
        msgs = np.zeros((self.n, 49), dtype=np.uint8)
        msgs[:, :8] = np.frombuffer(self.seed.to_bytes(8, "little"),
                                    dtype=np.uint8)
        msgs[:, 8:16] = np.arange(self.n, dtype="<u8").view(
            np.uint8).reshape(self.n, 8)
        msgs[:, 16:48] = np.frombuffer(bytes(block_root), dtype=np.uint8)
        msgs[:, 48] = round_ & 0xFF
        return sha256_batch(msgs)

    def select_cells(self, block_root: bytes, n_blobs: int,
                     n_cells: int) -> tuple[np.ndarray, np.ndarray]:
        """Seeded (blob_ids, cell_ids), each (n_clients, samples_per_client).

        One digest serves up to 10 samples; larger sample counts draw
        further digests with a round counter. The modulo draw is biased by
        < 2^-8 for power-of-two grids (n_cells divides 65536), i.e. exact
        for every valid config.
        """
        s = self.samples
        blob_ids = np.zeros((self.n, s), dtype=np.int64)
        cell_ids = np.zeros((self.n, s), dtype=np.int64)
        for j in range(s):
            round_, slot_in = divmod(j, _SAMPLES_PER_DIGEST)
            if slot_in == 0:
                digests = self._digests(block_root, round_)
            b = digests[:, slot_in * 3:slot_in * 3 + 3].astype(np.int64)
            cell_ids[:, j] = (b[:, 0] | (b[:, 1] << 8)) % n_cells
            blob_ids[:, j] = b[:, 2] % max(n_blobs, 1)
        self.blocks_sampled += 1
        self.samples_drawn += self.n * s
        return blob_ids, cell_ids

    def describe(self) -> dict:
        return {"kind": "das_population", "clients": self.n,
                "samples_per_client": self.samples, "seed": self.seed}
