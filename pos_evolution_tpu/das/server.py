"""DAS serving layer: request coalescing, LRU proof caches, latency metrics.

The full-node side of serving 10^5+ sampling clients. The pipeline per
served block:

1. the population draws its seeded (blob, cell) coordinates — arrays,
   never per-client objects (das/sampler.py);
2. requests are **coalesced**: 10^5 clients x 8 samples collapse onto at
   most ``n_blobs x 2k`` unique cells, so proof building and
   verification cost scales with the grid, not the crowd;
3. unique cells are answered from an **LRU proof-path cache** (hot cells
   of recent blocks stay resident; misses batch-build branches off one
   shared leaf tree per blob, through a per-(block, blob) single-flight
   so a new block's cache miss populates ONCE under concurrency — the
   cache and the stampede suppression are shared with the socket-facing
   serve tier, ``serve/server.py``);
4. the coalesced batch runs the ``ExecutionBackend`` sample-verification
   kernel (``ops/das_verify.py``) once, and verdicts fan back out to
   clients by the coalescing inverse index.

The same LRU machinery caches **best light-client updates** by head root
(``best_update``), so the per-slot ``build_update`` proof construction
in the driver's light-client serving runs once per distinct head instead
of once per slot.

Per-request p50/p95 serving latency and cache hit/miss counts land on
the ``MetricsRegistry``; the driver emits one ``das_serve`` event per
served block, which ``scripts/run_report.py`` folds into its
"DAS serving" section.
"""

from __future__ import annotations

import hashlib
import threading
import time as _time
from collections import OrderedDict

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.das.commitment import CellCommitmentScheme
from pos_evolution_tpu.ops.das_verify import DasSampleBatch, verify_das_samples
from pos_evolution_tpu.utils.singleflight import SingleFlight

__all__ = ["LRUCache", "DasServer"]

_MISS = object()


class LRUCache:
    """Minimal ordered-dict LRU with hit/miss counters (no extra deps).

    Concurrency-safe: the serving tier (``serve/server.py``) hits one
    shared cache from many worker threads, so every operation — lookup,
    insert+evict, clear — is atomic under one lock. ``move_to_end`` on a
    bare OrderedDict from two threads can corrupt the linked list; the
    lock is not optional hardening.
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._d: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            v = self._d.get(key, _MISS)
            if v is _MISS:
                self.misses += 1
                return _MISS
            self._d.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)

    def peek(self, key):
        """Lookup WITHOUT touching counters or recency — for the
        single-flight leader's double-check (its probes are bookkeeping,
        not client traffic, and must not inflate the hit rate)."""
        with self._lock:
            return self._d.get(key, _MISS)

    def clear(self) -> None:
        """Drop every entry (counters survive — the chaos mode's
        block-boundary cache wipe must stay visible in the hit rate)."""
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    @property
    def lookups(self) -> int:
        return self.hits + self.misses

    @property
    def hit_rate(self) -> float:
        # guarded: a freshly attached server reports 0.0, never ZeroDivision
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class DasServer:
    """Serves coalesced DAS samples (and cached best-updates) for one node."""

    def __init__(self, scheme: CellCommitmentScheme, registry=None,
                 proof_cache: int | LRUCache = 4096, update_cache: int = 64,
                 flight=None):
        self.scheme = scheme
        self.registry = registry
        # an existing LRUCache instance is shared as-is: the serve tier
        # (serve/server.py) and the in-process vectorized path warm the
        # SAME proof cache, so a block served to sockets answers the
        # sampling population from cache and vice versa
        self.proof_cache = (proof_cache if isinstance(proof_cache, LRUCache)
                            else LRUCache(proof_cache))
        self.update_cache = LRUCache(update_cache)
        # stampede suppression: a new-block miss populates the proof
        # cache ONCE per (block, blob) however many threads miss
        # concurrently; scheme_builds counts actual backing builds (the
        # regression contract of tests/test_serve.py). A worker PROCESS
        # passes a ``utils/singleflight.ProcessFlight`` here so the
        # same guarantee holds across the whole pool: one backing build
        # per (block, blob) however many processes stampede.
        self._flight = flight if flight is not None else SingleFlight()
        self.scheme_builds = 0
        self._stats_lock = threading.Lock()
        self.served_blocks = 0
        self.samples_served = 0

    # -- light-client best-update caching --------------------------------------

    def best_update(self, store, head_root: bytes, archive=None):
        """``lightclient.server.build_update`` memoized by head root —
        proofs for one head are built once however many slots (or
        clients) ask for it."""
        key = bytes(head_root)
        cached = self.update_cache.get(key)
        if cached is not _MISS:
            self._count("das_update_cache_hits_total",
                        "best-update LRU hits")
            return cached
        from pos_evolution_tpu.lightclient.server import build_update
        update = build_update(store, head_root, archive=archive)
        self.update_cache.put(key, update)
        self._count("das_update_cache_misses_total",
                    "best-update LRU misses (built fresh)")
        return update

    # -- sample serving --------------------------------------------------------

    def _count(self, name: str, help_: str, n: int = 1) -> None:
        if self.registry is not None:
            self.registry.counter(name, help_).inc(n)

    def build_blob_proofs(self, block_root: bytes, blob: int,
                          sidecar) -> dict[int, tuple]:
        """All of one blob's (cell, branch) pairs, built at most once per
        concurrent set of requesters (single-flight) and left in the
        proof-path cache.

        This is the new-block stampede path: before the single-flight,
        every concurrent requester that missed the cache re-ran the
        backing-scheme branch build for the same blob. The leader builds
        the WHOLE grid's branches off one shared leaf tree (the same
        amortized cost as building the missed subset, since the tree
        dominates) and populates the cache; waiters block on the leader
        and read its result. ``scheme_builds`` counts actual backing
        builds — the regression contract: concurrent misses on a fresh
        block bump it once per blob, not once per requester.
        """
        def _build() -> dict[int, tuple]:
            grid = np.ascontiguousarray(sidecar.cells, dtype=np.uint8)
            n = grid.shape[0]
            # double-check under the flight: a caller whose miss was
            # observed BEFORE an earlier flight finished lands here
            # after it — the cache already holds every cell, so there
            # is nothing left to build (this is what makes "one build
            # per (block, blob)" exact, not just likely)
            cached = {cell: self.proof_cache.peek((block_root, blob, cell))
                      for cell in range(n)}
            if all(v is not _MISS for v in cached.values()):
                return cached
            _leaves, built = self.scheme.branches(grid, list(range(n)))
            with self._stats_lock:
                self.scheme_builds += 1
            out = {}
            for cell in range(n):
                pair = (grid[cell].copy(), built[cell].copy())
                self.proof_cache.put((block_root, blob, cell), pair)
                out[cell] = pair
            return out

        def _absorb(built: dict) -> None:
            # another PROCESS led this build (cross-process flight):
            # populate our per-process LRU from its spooled result —
            # a cache fill, not a backing build, so scheme_builds
            # stays untouched (the global one-build-per-blob pin)
            for cell, pair in built.items():
                self.proof_cache.put((block_root, blob, cell), pair)

        key = ("blob_proofs", block_root, blob)
        if getattr(self._flight, "wants_absorb", False):
            return self._flight.do(key, _build, absorb=_absorb)
        return self._flight.do(key, _build)

    # -- aggregated proofs (kzg/ schemes with scheme.aggregates) ---------------

    @staticmethod
    def _coords_digest(coords) -> bytes:
        return hashlib.sha256(
            b"".join(b"%d:%d;" % (int(b), int(c)) for b, c in coords)
        ).digest()

    def build_aggregate_proof(self, block_root: bytes, sidecars: list,
                              coords) -> dict:
        """ONE opening proof for everything the population sampled from
        one block (``scheme.prove_aggregate``), built once per (block,
        sampled set) under the same single-flight/cache machinery as the
        branch path — the serve tier and the in-process sampling round
        share the cached aggregate, and concurrent misses on a fresh
        block bump ``scheme_builds`` once, not once per requester."""
        coords = tuple((int(b), int(c)) for b, c in coords)
        cache_key = ("das_agg", bytes(block_root), self._coords_digest(coords))
        hit = self.proof_cache.get(cache_key)
        if hit is not _MISS:
            return hit

        def _build() -> dict:
            cached = self.proof_cache.peek(cache_key)
            if cached is not _MISS:
                return cached
            grids = [np.ascontiguousarray(sc.cells, dtype=np.uint8)
                     for sc in sidecars]
            proof = self.scheme.prove_aggregate(grids, coords)
            with self._stats_lock:
                self.scheme_builds += 1
            self.proof_cache.put(cache_key, proof)
            return proof

        def _absorb(proof: dict) -> None:
            self.proof_cache.put(cache_key, proof)

        if getattr(self._flight, "wants_absorb", False):
            return self._flight.do(cache_key, _build, absorb=_absorb)
        return self._flight.do(cache_key, _build)

    def _serve_samples_aggregate(self, block_root: bytes, sidecars: list,
                                 blob_ids, cell_ids, uniq, inverse) -> dict:
        """Aggregate-scheme serving: instead of per-cell branches, the
        whole coalesced sampled set is answered by ONE opening proof and
        ONE pairing verification — proof bytes per sample collapse from
        depth*32 to |proof|/samples (the ISSUE 17 acceptance cut)."""
        c = cfg()
        n_cells = 2 * c.das_cells_per_blob
        u = uniq.shape[0]
        n_samples = int(blob_ids.size)
        coords = tuple((int(k) // n_cells, int(k) % n_cells) for k in uniq)

        h0 = self.proof_cache.hits
        t0 = _time.perf_counter()
        proof = self.build_aggregate_proof(bytes(block_root), sidecars,
                                           coords)
        build_s = _time.perf_counter() - t0
        cache_hit = self.proof_cache.hits > h0

        cells = [np.ascontiguousarray(sidecars[b].cells, dtype=np.uint8)[ci]
                 for b, ci in coords]
        wire_commitments = [bytes(sc.commitment) for sc in sidecars]
        t0 = _time.perf_counter()
        ok = bool(self.scheme.verify_aggregate(wire_commitments, coords,
                                               cells, proof))
        verify_s = _time.perf_counter() - t0
        per_req = (build_s + verify_s) / u
        latency = np.full(u, per_req, dtype=np.float64)

        proof_bytes = int(self.scheme.proof_n_bytes(proof))
        failed = 0 if ok else u
        clients = int(blob_ids.shape[0])
        with self._stats_lock:
            self.served_blocks += 1
            self.samples_served += n_samples
        self._count("das_samples_total",
                    "client cell samples served (pre-coalescing)", n_samples)
        self._count("das_unique_requests_total",
                    "coalesced unique (blob, cell) fetches", u)
        self._count("das_aggregate_proofs_total",
                    "aggregated opening proofs served")
        self._count("das_aggregate_proof_bytes_total",
                    "bytes of aggregated opening proofs served", proof_bytes)
        if failed:
            self._count("das_sample_verify_failures_total",
                        "samples whose proof failed verification", failed)
        if self.registry is not None:
            hist = self.registry.histogram(
                "das_request_seconds",
                "per coalesced request serving latency")
            for v in latency:
                hist.observe(float(v))

        return {
            "clients": clients,
            "samples": n_samples,
            "unique_requests": int(u),
            "coalescing": round(n_samples / u, 2),
            "blobs": len(sidecars),
            "cache_hits": int(bool(cache_hit)),
            "cache_misses": int(not cache_hit),
            "cache_hit_rate": round(self.proof_cache.hit_rate, 4),
            "verified": n_samples if ok else 0,
            "failed": failed,
            "clients_all_ok": clients if ok else 0,
            "p50_ms": round(per_req * 1e3, 4),
            "p95_ms": round(per_req * 1e3, 4),
            "verify_ms": round(verify_s * 1e3, 4),
            "scheme": self.scheme.name,
            "aggregated": True,
            "proof_bytes": proof_bytes,
            "proof_bytes_per_sample": round(proof_bytes / n_samples, 4),
        }

    def serve_samples(self, block_root: bytes, sidecars: list,
                      population) -> dict:
        """One block's sampling round for the whole population. Returns
        the summary dict the driver emits as a ``das_serve`` event."""
        c = cfg()
        n_cells = 2 * c.das_cells_per_blob
        n_blobs = len(sidecars)
        assert n_blobs > 0, "serve_samples needs at least one sidecar"
        blob_ids, cell_ids = population.select_cells(
            bytes(block_root), n_blobs, n_cells)
        flat = (blob_ids * n_cells + cell_ids).reshape(-1)
        uniq, inverse = np.unique(flat, return_inverse=True)
        u = uniq.shape[0]

        if getattr(self.scheme, "aggregates", False):
            # kzg-style schemes: no branch walk — one opening proof for
            # the whole coalesced set, one pairing verification
            return self._serve_samples_aggregate(
                bytes(block_root), sidecars, blob_ids, cell_ids,
                uniq, inverse)

        depth = self.scheme.depth_for(n_cells)
        cells = np.zeros((u, c.das_cell_bytes), dtype=np.uint8)
        branches = np.zeros((u, depth, 32), dtype=np.uint8)
        indices = np.zeros(u, dtype=np.int64)
        commitments = np.zeros((u, 32), dtype=np.uint8)
        latency = np.zeros(u, dtype=np.float64)

        # phase 1: cache lookups (individually timed — they ARE the fast path)
        miss_by_blob: dict[int, list[int]] = {}
        for j, key_flat in enumerate(uniq):
            blob, cell = int(key_flat) // n_cells, int(key_flat) % n_cells
            indices[j] = cell
            commitments[j] = np.frombuffer(
                bytes(sidecars[blob].commitment), dtype=np.uint8)
            t0 = _time.perf_counter()
            hit = self.proof_cache.get((bytes(block_root), blob, cell))
            latency[j] = _time.perf_counter() - t0
            if hit is _MISS:
                miss_by_blob.setdefault(blob, []).append(j)
            else:
                cells[j], branches[j] = hit

        # phase 2: batch-build missing branches through the per-(block,
        # blob) single-flight — one shared leaf tree per blob, built ONCE
        # even when many threads miss the same new block concurrently
        for blob, slots in miss_by_blob.items():
            t0 = _time.perf_counter()
            built = self.build_blob_proofs(bytes(block_root), blob,
                                           sidecars[blob])
            for j in slots:
                cells[j], branches[j] = built[int(indices[j])]
            per = (_time.perf_counter() - t0) / len(slots)
            for j in slots:
                latency[j] += per

        # phase 3: ONE backend verification call for the coalesced batch
        t0 = _time.perf_counter()
        result = verify_das_samples(DasSampleBatch(
            cells=cells, branches=branches, indices=indices,
            commitments=commitments))
        verify_s = _time.perf_counter() - t0
        latency += verify_s / u

        ok_flat = result["ok"][inverse].reshape(blob_ids.shape)
        clients_ok = int(ok_flat.all(axis=1).sum())
        n_samples = int(flat.shape[0])
        failed = int((~result["ok"]).sum())

        with self._stats_lock:
            self.served_blocks += 1
            self.samples_served += n_samples
        cache_hits = u - sum(len(s) for s in miss_by_blob.values())
        self._count("das_samples_total",
                    "client cell samples served (pre-coalescing)", n_samples)
        self._count("das_unique_requests_total",
                    "coalesced unique (blob, cell) fetches", u)
        self._count("das_proof_cache_hits_total",
                    "proof-path LRU hits", cache_hits)
        self._count("das_proof_cache_misses_total",
                    "proof-path LRU misses", u - cache_hits)
        if failed:
            self._count("das_sample_verify_failures_total",
                        "samples whose branch failed verification", failed)
        if self.registry is not None:
            hist = self.registry.histogram(
                "das_request_seconds",
                "per coalesced request serving latency")
            for v in latency:
                hist.observe(float(v))

        return {
            "clients": int(blob_ids.shape[0]),
            "samples": n_samples,
            "unique_requests": int(u),
            "coalescing": round(n_samples / u, 2),
            "blobs": n_blobs,
            "cache_hits": int(cache_hits),
            "cache_misses": int(u - cache_hits),
            "cache_hit_rate": round(self.proof_cache.hit_rate, 4),
            "verified": int(result["ok"].sum()),
            "failed": failed,
            "clients_all_ok": clients_ok,
            "p50_ms": round(float(np.percentile(latency, 50)) * 1e3, 4),
            "p95_ms": round(float(np.percentile(latency, 95)) * 1e3, 4),
            "verify_ms": round(verify_s * 1e3, 4),
            # proof-bytes accounting, comparable with the aggregate
            # path: every sample ships its own depth*32-byte branch
            "scheme": self.scheme.name,
            "aggregated": False,
            "proof_bytes": int(n_samples * depth * 32),
            "proof_bytes_per_sample": float(depth * 32),
        }
