"""DAS containers: blob sidecars over an erasure-extended cell grid.

A proposal's blob payload travels as per-blob ``BlobSidecar`` gossip
objects, each carrying the FULL 2k-cell extended grid (the sim's full
nodes hold whole blobs; sampling clients only ever pull cells). The block
itself commits to its blobs without changing the ``BeaconBlockBody``
layout: the 32-byte ``graffiti`` field carries a DAS marker binding the
blob count and the commitment set (``das_graffiti`` /
``parse_das_graffiti``) — the simulator's analogue of the
``blob_kzg_commitments`` list, chosen so every pinned SSZ root in the
repo stays valid and DAS remains a strictly opt-in layer.

Cell geometry (``das_cell_bytes`` x ``das_cells_per_blob``) comes from
``config.Config``; the ``CellRows`` sedes stores a grid as one
(n_cells, cell_bytes) uint8 array so hashing and erasure math stay
vectorized end to end.
"""

from __future__ import annotations

import numpy as np

from pos_evolution_tpu.config import cfg
from pos_evolution_tpu.ssz.core import Bytes32, Container, Sedes, uint64
from pos_evolution_tpu.ssz.hash import sha256, sha256_pairs
from pos_evolution_tpu.ssz.merkle import merkleize_chunks, mix_in_length

__all__ = [
    "MAX_EXTENDED_CELLS",
    "CellRows",
    "BlobSidecar",
    "das_graffiti",
    "parse_das_graffiti",
    "commitments_digest",
    "validate_das_config",
]


def validate_das_config(c=None) -> None:
    """Loud checks for the DAS geometry constraints the documentation
    promises (config.py): silently violating any of these produces
    structurally wrong roots or colliding blob payloads, not crashes."""
    c = c or cfg()
    k = int(c.das_cells_per_blob)
    if not (1 <= k <= MAX_EXTENDED_CELLS // 2) or (k & (k - 1)):
        raise ValueError(
            f"das_cells_per_blob must be a power of two in "
            f"[1, {MAX_EXTENDED_CELLS // 2}] (2k GF(2^8) evaluation "
            f"points, padded binary commitment tree), got {k}")
    chunks = max((int(c.das_cell_bytes) + 31) // 32, 1)
    if chunks & (chunks - 1):
        raise ValueError(
            f"das_cell_bytes={c.das_cell_bytes} pads to {chunks} 32-byte "
            f"chunks per cell — must be a power of two (the per-cell "
            f"merkle sweep pairs rows level by level)")
    if not (0 <= int(c.das_max_blobs_per_block) <= 255):
        raise ValueError(
            f"das_max_blobs_per_block must be in [0, 255] (blob_index is "
            f"one seed byte), got {c.das_max_blobs_per_block}")
    if int(c.das_samples_per_client) < 1:
        raise ValueError("das_samples_per_client must be >= 1")

#: SSZ list limit for the extended grid (2k <= 256 by the GF(2^8) bound).
MAX_EXTENDED_CELLS = 256

#: graffiti marker prefix for blocks that carry DAS blobs
_DAS_MAGIC = b"DAS\x01"


class CellRows(Sedes):
    """``List[ByteVector[cell_bytes], MAX_EXTENDED_CELLS]`` stored as an
    (n_cells, cell_bytes) uint8 array. The runtime array carries both its
    cell count and cell width (``cfg().das_cell_bytes`` resolves the width
    on deserialize), mirroring the ``Bytes32Rows`` preset-sharing rule."""

    def is_fixed(self):
        return False

    def serialize(self, value) -> bytes:
        return np.ascontiguousarray(value, dtype=np.uint8).tobytes()

    def deserialize(self, data: bytes) -> np.ndarray:
        width = cfg().das_cell_bytes
        return np.frombuffer(data, dtype=np.uint8).reshape(-1, width).copy()

    def htr(self, value) -> bytes:
        arr = np.ascontiguousarray(value, dtype=np.uint8)
        n = arr.shape[0]
        if n == 0:
            chunks = np.empty((0, 32), dtype=np.uint8)
            cell_roots = chunks
        else:
            width = arr.shape[1]
            chunks_per_cell = max((width + 31) // 32, 1)
            if chunks_per_cell & (chunks_per_cell - 1):
                raise ValueError(
                    f"cell width {width} pads to {chunks_per_cell} chunks "
                    f"per cell — the level sweep needs a power of two")
            padded = np.zeros((n, chunks_per_cell * 32), dtype=np.uint8)
            padded[:, :width] = arr
            # per-cell root: merkleize each cell's chunk run (all cells
            # share one geometry, so the level sweeps batch across cells)
            layer = padded.reshape(n * chunks_per_cell, 32)
            m = chunks_per_cell
            while m > 1:
                layer = sha256_pairs(layer[0::2], layer[1::2])
                m //= 2
            cell_roots = layer
        root = merkleize_chunks(cell_roots, MAX_EXTENDED_CELLS)
        return mix_in_length(root, n)

    def default(self) -> np.ndarray:
        return np.zeros((0, cfg().das_cell_bytes), dtype=np.uint8)


class BlobSidecar(Container):
    """One blob's worth of availability data, gossiped alongside its block.

    ``cells`` is the full extended grid; ``commitment`` is the pluggable
    cell-commitment root (``das/commitment.py``) the block's graffiti
    marker binds. ``n_blobs`` repeats the block's blob count so a store
    holding ANY sidecar knows how many siblings availability needs.
    """

    slot: uint64
    proposer_index: uint64
    block_root: Bytes32
    blob_index: uint64
    n_blobs: uint64
    cells: CellRows()
    commitment: Bytes32


def das_graffiti(commitments: list[bytes]) -> bytes:
    """32-byte graffiti marker binding a block to its blob commitments:
    magic(4) | n_blobs(2, LE) | sha256(commitment list)[:26]. Set at block
    build time, so the proposal SSZ-commits to its blob payload through a
    field every fork already carries."""
    n = len(commitments)
    if n == 0:
        return b"\x00" * 32
    digest = sha256(b"".join(bytes(c) for c in commitments))
    return _DAS_MAGIC + n.to_bytes(2, "little") + digest[:26]


def parse_das_graffiti(graffiti: bytes) -> tuple[int, bytes] | None:
    """``(n_blobs, commitment_digest26)`` when ``graffiti`` carries the DAS
    marker, else None (a block with no blob payload, or a free-form
    graffiti from a non-DAS proposer — both gate vacuously)."""
    g = bytes(graffiti)
    if not g.startswith(_DAS_MAGIC):
        return None
    n = int.from_bytes(g[4:6], "little")
    return (n, g[6:32]) if n else None


def commitments_digest(commitments: list[bytes]) -> bytes:
    """The 26-byte digest ``das_graffiti`` embeds, for availability checks."""
    return sha256(b"".join(bytes(c) for c in commitments))[:26]
