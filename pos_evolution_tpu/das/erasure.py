"""Reed-Solomon erasure coding over GF(2^8) for DAS blobs.

The data-availability scheme of arxiv 2604.16559 commits to an
erasure-*extended* blob: a blob's k data cells are treated, byte column by
byte column, as evaluations of a degree-<k polynomial at points 0..k-1,
and the extension evaluates the same polynomial at points k..2k-1. Any k
of the 2k extended cells then reconstruct the blob (Lagrange
interpolation), so a sampler that sees >=50% of cells responding knows
the whole blob is recoverable — the "any 50%" availability property the
reconstruction check in ``ops/das_verify.py`` enforces.

Everything is table-driven GF(2^8) arithmetic (AES polynomial 0x11B):
multiplies are log/exp gathers, accumulation is XOR — byte-lane
operations that vectorize on NumPy here and map 1:1 onto the uint8
gather/XOR path of the device twin (``ops/das_verify.py``), which is
pinned bit-identical to this module.

Cell geometry lives in ``config.Config`` (``das_cells_per_blob`` = k,
``das_cell_bytes``); 2k <= 256 so every evaluation point is one field
element.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

__all__ = [
    "GF_EXP", "GF_LOG", "gf_mul", "gf_inv", "gf_matmul",
    "lagrange_matrix", "extension_matrix", "extend_blob",
    "reconstruct_blob",
]


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(510, dtype=np.int64)
    log = np.zeros(256, dtype=np.int64)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        # multiply by the generator 0x03 = x * (0x02 ^ 0x01); note 0x02 is
        # NOT a generator of GF(256)^* under 0x11B (order 51) — using it
        # silently corrupts most log entries
        x = (x << 1) ^ x
        if x & 0x100:
            x ^= 0x11B  # AES reduction polynomial x^8+x^4+x^3+x+1
    exp[255:] = exp[:255]  # wrap so log[a]+log[b] never needs a mod
    return exp.astype(np.uint8), log.astype(np.int32)


# GF_EXP[(GF_LOG[a] + GF_LOG[b])] == a*b for a, b != 0.
GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + int(GF_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(GF_EXP[255 - int(GF_LOG[a])])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(256) matrix product: (r, k) u8 x (k, c) u8 -> (r, c) u8.

    One log/exp gather + XOR accumulate per inner index — k is the blob's
    cell count (small), r*c the byte volume (vectorized).
    """
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    log_a = GF_LOG[a]  # (r, k)
    log_b = GF_LOG[b]  # (k, c)
    for t in range(a.shape[1]):
        prod = GF_EXP[log_a[:, t][:, None] + log_b[t][None, :]]
        prod = np.where((a[:, t][:, None] == 0) | (b[t][None, :] == 0),
                        np.uint8(0), prod)
        out ^= prod
    return out


@lru_cache(maxsize=None)
def lagrange_matrix(xs_src: tuple, xs_dst: tuple) -> np.ndarray:
    """M with ``gf_matmul(M, values_at_src) = values_at_dst`` for any
    degree-<len(xs_src) polynomial: M[i, t] is the t-th Lagrange basis
    over ``xs_src`` evaluated at ``xs_dst[i]`` (GF addition is XOR)."""
    k = len(xs_src)
    m = np.zeros((len(xs_dst), k), dtype=np.uint8)
    for t in range(k):
        denom = 1
        for s in range(k):
            if s != t:
                denom = gf_mul(denom, xs_src[t] ^ xs_src[s])
        dinv = gf_inv(denom)
        for i, x in enumerate(xs_dst):
            num = 1
            for s in range(k):
                if s != t:
                    num = gf_mul(num, x ^ xs_src[s])
            m[i, t] = gf_mul(num, dinv)
    return m


def extension_matrix(k: int) -> np.ndarray:
    """(k, k) matrix mapping the k data cells to the k parity cells
    (evaluations at points k..2k-1)."""
    if not 1 <= k <= 128:
        raise ValueError(f"das_cells_per_blob must be in [1, 128], got {k}")
    return lagrange_matrix(tuple(range(k)), tuple(range(k, 2 * k)))


def extend_blob(data_cells: np.ndarray) -> np.ndarray:
    """(k, cell_bytes) data cells -> (2k, cell_bytes) extended grid whose
    first k rows ARE the data (systematic code)."""
    data_cells = np.ascontiguousarray(data_cells, dtype=np.uint8)
    k = data_cells.shape[0]
    parity = gf_matmul(extension_matrix(k), data_cells)
    return np.concatenate([data_cells, parity], axis=0)


def reconstruct_blob(cells: np.ndarray, present: np.ndarray
                     ) -> tuple[np.ndarray, np.ndarray, bool]:
    """Recover a blob from any >=50% of its extended cells.

    ``cells`` is the (2k, cell_bytes) grid with arbitrary garbage in the
    missing rows; ``present`` marks which rows are trusted. Interpolates
    the data cells from the first k present rows, re-extends, and checks
    every present row against the re-extension — the consistency verdict
    is False when any present cell disagrees with the unique degree-<k
    polynomial through the selection (a corrupted cell cannot hide).

    Returns ``(data_cells, full_grid, ok)``.
    """
    cells = np.ascontiguousarray(cells, dtype=np.uint8)
    present = np.asarray(present, dtype=bool)
    k = cells.shape[0] // 2
    avail = np.nonzero(present)[0]
    if avail.size < k:
        raise ValueError(
            f"reconstruction needs >= {k} of {2 * k} cells, got {avail.size}")
    sel = avail[:k]
    interp = lagrange_matrix(tuple(int(x) for x in sel), tuple(range(k)))
    data = gf_matmul(interp, cells[sel])
    full = extend_blob(data)
    ok = bool((full[avail] == cells[avail]).all())
    return data, full, ok
