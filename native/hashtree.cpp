// Native SSZ hashing core (component N2, SURVEY.md §2.7).
//
// The reference's implied native dependency: every real pyspec deployment
// links a native SHA-256/merkleization library for seed derivation
// (pos-evolution.md:486), the swap-or-not shuffle's per-round position
// hashes (:522-530), per-block state roots (:423), and the "<32 MB
// re-merkleized per epoch" balances array (:114).
//
// Exposed C ABI (loaded via ctypes from pos_evolution_tpu/native.py):
//   ht_sha256_batch   - N independent equal-length messages
//   ht_merkleize      - padded binary merkle root with zero-subtree
//                       virtualization (SSZ merkleize(chunks, limit))
//   ht_validator_roots- batched 8-leaf hash_tree_root per validator record
//
// Build: g++ -O3 -shared -fPIC (see Makefile). No external dependencies.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

constexpr uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline void compress(uint32_t state[8], const uint8_t *block) {
  uint32_t w[64];
  for (int t = 0; t < 16; ++t) {
    w[t] = (uint32_t(block[4 * t]) << 24) | (uint32_t(block[4 * t + 1]) << 16) |
           (uint32_t(block[4 * t + 2]) << 8) | uint32_t(block[4 * t + 3]);
  }
  for (int t = 16; t < 64; ++t) {
    uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
    uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
    w[t] = w[t - 16] + s0 + w[t - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int t = 0; t < 64; ++t) {
    uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + s1 + ch + K[t] + w[t];
    uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = s0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

inline void digest_to_bytes(const uint32_t state[8], uint8_t *out) {
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = uint8_t(state[i] >> 24);
    out[4 * i + 1] = uint8_t(state[i] >> 16);
    out[4 * i + 2] = uint8_t(state[i] >> 8);
    out[4 * i + 3] = uint8_t(state[i]);
  }
}

void sha256_one(const uint8_t *msg, uint64_t len, uint8_t *out) {
  uint32_t state[8];
  std::memcpy(state, H0, sizeof(H0));
  uint64_t full = len / 64;
  for (uint64_t b = 0; b < full; ++b) compress(state, msg + 64 * b);
  uint8_t tail[128];
  uint64_t rem = len - 64 * full;
  std::memset(tail, 0, sizeof(tail));
  std::memcpy(tail, msg + 64 * full, rem);
  tail[rem] = 0x80;
  uint64_t tail_blocks = (rem + 1 + 8 > 64) ? 2 : 1;
  uint64_t bits = len * 8;
  for (int i = 0; i < 8; ++i)
    tail[64 * tail_blocks - 1 - i] = uint8_t(bits >> (8 * i));
  for (uint64_t b = 0; b < tail_blocks; ++b) compress(state, tail + 64 * b);
  digest_to_bytes(state, out);
}

// hash of two concatenated 32-byte nodes: the merkle combiner
inline void hash_pair(const uint8_t *left, const uint8_t *right, uint8_t *out) {
  uint32_t state[8];
  std::memcpy(state, H0, sizeof(H0));
  uint8_t block[64];
  std::memcpy(block, left, 32);
  std::memcpy(block + 32, right, 32);
  compress(state, block);
  // padding block for a 64-byte message
  uint8_t pad[64];
  std::memset(pad, 0, sizeof(pad));
  pad[0] = 0x80;
  pad[62] = 0x02;  // 512 bits big-endian
  compress(state, pad);
  digest_to_bytes(state, out);
}

constexpr int MAX_DEPTH = 64;
uint8_t ZERO_HASHES[MAX_DEPTH + 1][32];
bool zero_ready = false;

void init_zero_hashes() {
  if (zero_ready) return;
  std::memset(ZERO_HASHES[0], 0, 32);
  for (int i = 0; i < MAX_DEPTH; ++i)
    hash_pair(ZERO_HASHES[i], ZERO_HASHES[i], ZERO_HASHES[i + 1]);
  zero_ready = true;
}

}  // namespace

extern "C" {

// msgs: n contiguous messages of `len` bytes; out: n x 32 bytes.
void ht_sha256_batch(const uint8_t *msgs, uint64_t n, uint64_t len,
                     uint8_t *out) {
  for (uint64_t i = 0; i < n; ++i)
    sha256_one(msgs + i * len, len, out + 32 * i);
}

// SSZ merkleize(chunks, limit): chunks = count x 32 bytes; depth =
// ceil(log2(max(limit,1))). Scratch must hold count*32 bytes (may alias a
// copy of chunks). Root written to out (32 bytes).
void ht_merkleize(const uint8_t *chunks, uint64_t count, uint32_t depth,
                  uint8_t *scratch, uint8_t *out) {
  init_zero_hashes();
  if (count == 0) {
    std::memcpy(out, ZERO_HASHES[depth], 32);
    return;
  }
  std::memcpy(scratch, chunks, count * 32);
  uint64_t width = count;
  for (uint32_t level = 0; level < depth; ++level) {
    uint64_t next = width / 2;
    for (uint64_t i = 0; i < next; ++i)
      hash_pair(scratch + 64 * i, scratch + 64 * i + 32, scratch + 32 * i);
    if (width % 2 == 1) {
      hash_pair(scratch + 32 * (width - 1), ZERO_HASHES[level],
                scratch + 32 * next);
      ++next;
    }
    width = next;
  }
  std::memcpy(out, scratch, 32);
}

// Batched Validator hash_tree_root: 8 leaves per validator, depth-3 tree
// (SURVEY.md §2.1 Validator layout). leaves: n x 256 bytes (8 chunks);
// out: n x 32.
void ht_validator_roots(const uint8_t *leaves, uint64_t n, uint8_t *out) {
  uint8_t level1[4 * 32];
  uint8_t level2[2 * 32];
  for (uint64_t v = 0; v < n; ++v) {
    const uint8_t *leaf = leaves + 256 * v;
    for (int i = 0; i < 4; ++i)
      hash_pair(leaf + 64 * i, leaf + 64 * i + 32, level1 + 32 * i);
    hash_pair(level1, level1 + 32, level2);
    hash_pair(level1 + 64, level1 + 96, level2 + 32);
    hash_pair(level2, level2 + 32, out + 32 * v);
  }
}

// Mix a list length into a root: sha256(root || le64(length) padded to 32).
void ht_mix_in_length(const uint8_t *root, uint64_t length, uint8_t *out) {
  uint8_t len_chunk[32];
  std::memset(len_chunk, 0, 32);
  for (int i = 0; i < 8; ++i) len_chunk[i] = uint8_t(length >> (8 * i));
  hash_pair(root, len_chunk, out);
}

}  // extern "C"
