// Native BLS12-381 (component N1, SURVEY.md §2.7).
//
// C++ port of the framework's from-scratch pairing stack
// (pos_evolution_tpu/crypto/bls12_381.py, the correctness oracle): 6x64-bit
// Montgomery field arithmetic, the Fp2/Fp6/Fp12 tower, affine curve ops on
// G1 and the sextic twist G2, the ate Miller loop + final exponentiation,
// the deterministic sha256 try-and-increment hash-to-G2, ZCash-style
// compressed serialization, and the min-pubkey-size signature scheme
// (Sign/Verify/Aggregate/FastAggregateVerify). Differential tests pin this
// bit-identical to the Python oracle.
//
// C ABI at the bottom; loaded via ctypes (pos_evolution_tpu/native.py).

#include <cstdint>
#include <cstring>

#include "bls_constants.h"

using u64 = uint64_t;
using u128 = unsigned __int128;

// ===========================================================================
// SHA-256 (for hash_to_g2; self-contained copy)
// ===========================================================================
namespace sha {
static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha256(const uint8_t *msg, size_t len, uint8_t out[32]) {
  uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  auto compress = [&](const uint8_t *b) {
    uint32_t w[64];
    for (int t = 0; t < 16; ++t)
      w[t] = (uint32_t(b[4 * t]) << 24) | (uint32_t(b[4 * t + 1]) << 16) |
             (uint32_t(b[4 * t + 2]) << 8) | b[4 * t + 3];
    for (int t = 16; t < 64; ++t) {
      uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^ (w[t - 15] >> 3);
      uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^ (w[t - 2] >> 10);
      w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint32_t a = st[0], bb = st[1], c = st[2], d = st[3], e = st[4], f = st[5],
             g = st[6], h = st[7];
    for (int t = 0; t < 64; ++t) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + s1 + ch + K[t] + w[t];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t mj = (a & bb) ^ (a & c) ^ (bb & c);
      h = g; g = f; f = e; e = d + t1; d = c; c = bb; bb = a; a = t1 + s0 + mj;
    }
    st[0] += a; st[1] += bb; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
  };
  size_t full = len / 64;
  for (size_t i = 0; i < full; ++i) compress(msg + 64 * i);
  uint8_t tail[128];
  size_t rem = len - 64 * full;
  std::memset(tail, 0, sizeof(tail));
  std::memcpy(tail, msg + 64 * full, rem);
  tail[rem] = 0x80;
  size_t blocks = (rem + 9 > 64) ? 2 : 1;
  u64 bits = u64(len) * 8;
  for (int i = 0; i < 8; ++i) tail[64 * blocks - 1 - i] = uint8_t(bits >> (8 * i));
  for (size_t i = 0; i < blocks; ++i) compress(tail + 64 * i);
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = uint8_t(st[i] >> 24);
    out[4 * i + 1] = uint8_t(st[i] >> 16);
    out[4 * i + 2] = uint8_t(st[i] >> 8);
    out[4 * i + 3] = uint8_t(st[i]);
  }
}
}  // namespace sha

// ===========================================================================
// Fp: 6x64-bit Montgomery arithmetic mod the BLS12-381 prime
// ===========================================================================
struct Fp { u64 l[6]; };

static u64 N0INV;       // -p^{-1} mod 2^64
static Fp FP_R;         // 2^384 mod p (Montgomery one)
static Fp FP_R2;        // (2^384)^2 mod p
static Fp FP_ZERO = {};

static inline bool fp_gte_p(const u64 a[6]) {
  for (int i = 5; i >= 0; --i) {
    if (a[i] > P_LIMBS[i]) return true;
    if (a[i] < P_LIMBS[i]) return false;
  }
  return true;  // equal
}

static inline void fp_sub_p(u64 a[6]) {
  u128 borrow = 0;
  for (int i = 0; i < 6; ++i) {
    u128 d = (u128)a[i] - P_LIMBS[i] - borrow;
    a[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
}

static inline Fp fp_add(const Fp &a, const Fp &b) {
  Fp r;
  u128 carry = 0;
  for (int i = 0; i < 6; ++i) {
    u128 s = (u128)a.l[i] + b.l[i] + carry;
    r.l[i] = (u64)s;
    carry = s >> 64;
  }
  if (carry || fp_gte_p(r.l)) fp_sub_p(r.l);
  return r;
}

static inline Fp fp_sub(const Fp &a, const Fp &b) {
  Fp r;
  u128 borrow = 0;
  for (int i = 0; i < 6; ++i) {
    u128 d = (u128)a.l[i] - b.l[i] - borrow;
    r.l[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) {  // add p back
    u128 carry = 0;
    for (int i = 0; i < 6; ++i) {
      u128 s = (u128)r.l[i] + P_LIMBS[i] + carry;
      r.l[i] = (u64)s;
      carry = s >> 64;
    }
  }
  return r;
}

static inline Fp fp_neg(const Fp &a) { return fp_sub(FP_ZERO, a); }

static inline bool fp_is_zero(const Fp &a) {
  u64 acc = 0;
  for (int i = 0; i < 6; ++i) acc |= a.l[i];
  return acc == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
  u64 acc = 0;
  for (int i = 0; i < 6; ++i) acc |= a.l[i] ^ b.l[i];
  return acc == 0;
}

// CIOS Montgomery multiplication
static Fp fp_mul(const Fp &a, const Fp &b) {
  u64 t[8] = {0};
  for (int i = 0; i < 6; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 6; ++j) {
      u128 cur = (u128)t[j] + (u128)a.l[i] * b.l[j] + carry;
      t[j] = (u64)cur;
      carry = cur >> 64;
    }
    u128 cur = (u128)t[6] + carry;
    t[6] = (u64)cur;
    t[7] = (u64)(cur >> 64);

    u64 m = t[0] * N0INV;
    carry = ((u128)t[0] + (u128)m * P_LIMBS[0]) >> 64;
    for (int j = 1; j < 6; ++j) {
      u128 c2 = (u128)t[j] + (u128)m * P_LIMBS[j] + carry;
      t[j - 1] = (u64)c2;
      carry = c2 >> 64;
    }
    cur = (u128)t[6] + carry;
    t[5] = (u64)cur;
    t[6] = t[7] + (u64)(cur >> 64);
    t[7] = 0;
  }
  Fp r;
  std::memcpy(r.l, t, 48);
  if (t[6] || fp_gte_p(r.l)) fp_sub_p(r.l);
  return r;
}

static inline Fp fp_sqr(const Fp &a) { return fp_mul(a, a); }

// pow by big-endian byte exponent (square-and-multiply MSB first)
static Fp fp_pow_bytes(const Fp &a, const uint8_t *exp, size_t n) {
  Fp r = FP_R;  // one
  for (size_t i = 0; i < n; ++i) {
    for (int bit = 7; bit >= 0; --bit) {
      r = fp_sqr(r);
      if ((exp[i] >> bit) & 1) r = fp_mul(r, a);
    }
  }
  return r;
}

static uint8_t P_MINUS_2[48];

static Fp fp_inv(const Fp &a) { return fp_pow_bytes(a, P_MINUS_2, 48); }

// to/from standard representation
static Fp fp_from_bytes_be(const uint8_t *b, size_t n) {
  // parse up to 48 bytes big-endian, reduce mod p, convert to Montgomery
  Fp r = {};
  for (size_t i = 0; i < n; ++i) {
    // r = r*256 + b[i]  (shift by 8 via adds; faster: limb shifting)
    u128 carry = b[i];
    for (int j = 0; j < 6; ++j) {
      u128 cur = ((u128)r.l[j] << 8) | (carry & 0xff);
      carry = (carry >> 8) | ((u128)r.l[j] >> 56);
      r.l[j] = (u64)cur;
    }
    while (fp_gte_p(r.l)) fp_sub_p(r.l);
  }
  return fp_mul(r, FP_R2);
}

static void fp_to_bytes_be(const Fp &a, uint8_t out[48]) {
  Fp one = {};
  one.l[0] = 1;
  Fp std_form = fp_mul(a, one);  // Montgomery reduce: a * 1 = a/R... careful
  // fp_mul(a, one) computes a*1*R^{-1} = standard form of a. Correct.
  for (int i = 0; i < 6; ++i)
    for (int j = 0; j < 8; ++j)
      out[47 - 8 * i - j] = uint8_t(std_form.l[i] >> (8 * j));
}

static bool fp_is_odd_std(const Fp &a) {
  Fp one = {};
  one.l[0] = 1;
  return fp_mul(a, one).l[0] & 1;
}

// standard-form comparison: a > (p-1)/2 ("lexicographically large")
static bool fp_is_large_std(const Fp &a) {
  uint8_t ab[48];
  fp_to_bytes_be(a, ab);
  static uint8_t half[48];
  static bool init = false;
  if (!init) {
    // (p-1)/2 big-endian: compute from P_LIMBS
    u64 h[6];
    u64 carry = 0;
    for (int i = 5; i >= 0; --i) {
      u64 cur = (P_LIMBS[i] >> 1) | (carry << 63);
      carry = P_LIMBS[i] & 1;
      h[i] = cur;
    }
    // p odd -> (p-1)/2 == p >> 1
    for (int i = 0; i < 6; ++i)
      for (int j = 0; j < 8; ++j) half[47 - 8 * i - j] = uint8_t(h[i] >> (8 * j));
    init = true;
  }
  return std::memcmp(ab, half, 48) > 0;
}

// ===========================================================================
// Fp2 = Fp[u]/(u^2+1)
// ===========================================================================
struct Fp2 { Fp a, b; };

static Fp2 FP2_ZERO, FP2_ONE, XI2;  // XI2 = u + 1

static inline Fp2 fp2_add(const Fp2 &x, const Fp2 &y) {
  return {fp_add(x.a, y.a), fp_add(x.b, y.b)};
}
static inline Fp2 fp2_sub(const Fp2 &x, const Fp2 &y) {
  return {fp_sub(x.a, y.a), fp_sub(x.b, y.b)};
}
static inline Fp2 fp2_neg(const Fp2 &x) { return {fp_neg(x.a), fp_neg(x.b)}; }

static Fp2 fp2_mul(const Fp2 &x, const Fp2 &y) {
  Fp t0 = fp_mul(x.a, y.a);
  Fp t1 = fp_mul(x.b, y.b);
  Fp t2 = fp_mul(fp_add(x.a, x.b), fp_add(y.a, y.b));
  return {fp_sub(t0, t1), fp_sub(fp_sub(t2, t0), t1)};
}

static Fp2 fp2_sqr(const Fp2 &x) {
  Fp t0 = fp_mul(fp_add(x.a, x.b), fp_sub(x.a, x.b));
  Fp t1 = fp_mul(x.a, x.b);
  return {t0, fp_add(t1, t1)};
}

static Fp2 fp2_inv(const Fp2 &x) {
  Fp d = fp_inv(fp_add(fp_mul(x.a, x.a), fp_mul(x.b, x.b)));
  return {fp_mul(x.a, d), fp_neg(fp_mul(x.b, d))};
}

static inline bool fp2_is_zero(const Fp2 &x) {
  return fp_is_zero(x.a) && fp_is_zero(x.b);
}
static inline bool fp2_eq(const Fp2 &x, const Fp2 &y) {
  return fp_eq(x.a, y.a) && fp_eq(x.b, y.b);
}

static Fp2 fp2_pow_bytes(const Fp2 &x, const uint8_t *exp, size_t n) {
  Fp2 r = FP2_ONE;
  for (size_t i = 0; i < n; ++i)
    for (int bit = 7; bit >= 0; --bit) {
      r = fp2_sqr(r);
      if ((exp[i] >> bit) & 1) r = fp2_mul(r, x);
    }
  return r;
}

static Fp2 EIGHTH_ROOTS[4];

// sqrt in Fp2 (q^2 = 9 mod 16 method, mirrors the Python); returns false if
// non-residue
static bool fp2_sqrt(const Fp2 &a, Fp2 *out) {
  Fp2 cand = fp2_pow_bytes(a, SQRT_EXP, SQRT_EXP_len);
  for (int k = 0; k < 4; ++k) {
    Fp2 x = fp2_mul(cand, EIGHTH_ROOTS[k]);
    if (fp2_eq(fp2_sqr(x), a)) {
      *out = x;
      return true;
    }
  }
  return false;
}

// ===========================================================================
// Fp6 = Fp2[v]/(v^3 - XI), Fp12 = Fp6[w]/(w^2 - v)
// ===========================================================================
struct Fp6 { Fp2 a, b, c; };
struct Fp12 { Fp6 a, b; };

static Fp6 FP6_ZERO, FP6_ONE;
static Fp12 FP12_ONE;

static inline Fp6 fp6_add(const Fp6 &x, const Fp6 &y) {
  return {fp2_add(x.a, y.a), fp2_add(x.b, y.b), fp2_add(x.c, y.c)};
}
static inline Fp6 fp6_sub(const Fp6 &x, const Fp6 &y) {
  return {fp2_sub(x.a, y.a), fp2_sub(x.b, y.b), fp2_sub(x.c, y.c)};
}
static inline Fp6 fp6_neg(const Fp6 &x) {
  return {fp2_neg(x.a), fp2_neg(x.b), fp2_neg(x.c)};
}

static Fp6 fp6_mul(const Fp6 &x, const Fp6 &y) {
  Fp2 t0 = fp2_mul(x.a, y.a);
  Fp2 t1 = fp2_mul(x.b, y.b);
  Fp2 t2 = fp2_mul(x.c, y.c);
  Fp2 r0 = fp2_add(t0, fp2_mul(fp2_sub(fp2_sub(
      fp2_mul(fp2_add(x.b, x.c), fp2_add(y.b, y.c)), t1), t2), XI2));
  Fp2 r1 = fp2_add(fp2_sub(fp2_sub(
      fp2_mul(fp2_add(x.a, x.b), fp2_add(y.a, y.b)), t0), t1),
      fp2_mul(t2, XI2));
  Fp2 r2 = fp2_add(fp2_sub(fp2_sub(
      fp2_mul(fp2_add(x.a, x.c), fp2_add(y.a, y.c)), t0), t2), t1);
  return {r0, r1, r2};
}

static inline Fp6 fp6_mul_by_v(const Fp6 &x) {
  return {fp2_mul(x.c, XI2), x.a, x.b};
}

static Fp6 fp6_inv(const Fp6 &x) {
  Fp2 c0 = fp2_sub(fp2_sqr(x.a), fp2_mul(fp2_mul(x.b, x.c), XI2));
  Fp2 c1 = fp2_sub(fp2_mul(fp2_sqr(x.c), XI2), fp2_mul(x.a, x.b));
  Fp2 c2 = fp2_sub(fp2_sqr(x.b), fp2_mul(x.a, x.c));
  Fp2 t = fp2_inv(fp2_add(fp2_mul(x.a, c0),
                          fp2_mul(fp2_add(fp2_mul(x.c, c1), fp2_mul(x.b, c2)),
                                  XI2)));
  return {fp2_mul(c0, t), fp2_mul(c1, t), fp2_mul(c2, t)};
}

static inline Fp12 fp12_add(const Fp12 &x, const Fp12 &y) {
  return {fp6_add(x.a, y.a), fp6_add(x.b, y.b)};
}
static inline Fp12 fp12_sub(const Fp12 &x, const Fp12 &y) {
  return {fp6_sub(x.a, y.a), fp6_sub(x.b, y.b)};
}

static Fp12 fp12_mul(const Fp12 &x, const Fp12 &y) {
  Fp6 t0 = fp6_mul(x.a, y.a);
  Fp6 t1 = fp6_mul(x.b, y.b);
  Fp6 r0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 r1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(x.a, x.b), fp6_add(y.a, y.b)), t0),
                   t1);
  return {r0, r1};
}

static inline Fp12 fp12_sqr(const Fp12 &x) { return fp12_mul(x, x); }

static Fp12 fp12_inv(const Fp12 &x) {
  Fp6 t = fp6_inv(fp6_sub(fp6_mul(x.a, x.a), fp6_mul_by_v(fp6_mul(x.b, x.b))));
  return {fp6_mul(x.a, t), fp6_neg(fp6_mul(x.b, t))};
}

static inline Fp12 fp12_conj(const Fp12 &x) { return {x.a, fp6_neg(x.b)}; }

static bool fp12_eq(const Fp12 &x, const Fp12 &y) {
  return fp2_eq(x.a.a, y.a.a) && fp2_eq(x.a.b, y.a.b) && fp2_eq(x.a.c, y.a.c) &&
         fp2_eq(x.b.a, y.b.a) && fp2_eq(x.b.b, y.b.b) && fp2_eq(x.b.c, y.b.c);
}

static Fp12 fp12_pow_bytes(const Fp12 &x, const uint8_t *exp, size_t n) {
  Fp12 r = FP12_ONE;
  for (size_t i = 0; i < n; ++i)
    for (int bit = 7; bit >= 0; --bit) {
      r = fp12_sqr(r);
      if ((exp[i] >> bit) & 1) r = fp12_mul(r, x);
    }
  return r;
}

// ===========================================================================
// Curves: G1 over Fp, G2 over Fp2 (affine, infinity flag)
// ===========================================================================
struct G1 { Fp x, y; bool inf; };
struct G2 { Fp2 x, y; bool inf; };

static G1 G1_GENERATOR;
static G2 G2_GENERATOR;
static Fp FP_FOUR;    // curve b = 4
static Fp2 FP2_B2;    // twist b' = 4(u+1)

template <typename P, typename F,
          F (*Fadd)(const F &, const F &), F (*Fsub)(const F &, const F &),
          F (*Fmul)(const F &, const F &), F (*Finv)(const F &),
          bool (*Feq)(const F &, const F &)>
static P ec_double_t(const P &p, const F &three) {
  if (p.inf) return p;
  F lam = Fmul(Fmul(Fmul(p.x, p.x), three), Finv(Fadd(p.y, p.y)));
  F x3 = Fsub(Fsub(Fmul(lam, lam), p.x), p.x);
  F y3 = Fsub(Fmul(lam, Fsub(p.x, x3)), p.y);
  return {x3, y3, false};
}

template <typename P, typename F,
          F (*Fadd)(const F &, const F &), F (*Fsub)(const F &, const F &),
          F (*Fmul)(const F &, const F &), F (*Finv)(const F &),
          bool (*Feq)(const F &, const F &)>
static P ec_add_t(const P &p, const P &q, const F &three) {
  if (p.inf) return q;
  if (q.inf) return p;
  if (Feq(p.x, q.x)) {
    if (Feq(p.y, q.y))
      return ec_double_t<P, F, Fadd, Fsub, Fmul, Finv, Feq>(p, three);
    P r;
    r.inf = true;
    return r;
  }
  F lam = Fmul(Fsub(q.y, p.y), Finv(Fsub(q.x, p.x)));
  F x3 = Fsub(Fsub(Fmul(lam, lam), p.x), q.x);
  F y3 = Fsub(Fmul(lam, Fsub(p.x, x3)), p.y);
  return {x3, y3, false};
}

static Fp FP_THREE;
static Fp2 FP2_THREE;

static G1 g1_add(const G1 &p, const G1 &q) {
  return ec_add_t<G1, Fp, fp_add, fp_sub, fp_mul, fp_inv, fp_eq>(p, q, FP_THREE);
}
static G2 g2_add(const G2 &p, const G2 &q) {
  return ec_add_t<G2, Fp2, fp2_add, fp2_sub, fp2_mul, fp2_inv, fp2_eq>(
      p, q, FP2_THREE);
}

// --- Jacobian-coordinate scalar multiplication (a = 0 curves) --------------
// Affine add/double need a field inversion per step (~500 muls); Jacobian
// formulas (dbl-2009-l / add-2007-bl) use ~10-16 muls per step with one
// inversion at the end, making scalar mults ~30x cheaper. Outputs are
// converted back to canonical affine, so results are unchanged.

template <typename F> struct Jac { F X, Y, Z; bool inf; };

template <typename F, F (*Fadd)(const F &, const F &),
          F (*Fsub)(const F &, const F &), F (*Fmul)(const F &, const F &)>
static Jac<F> jac_double(const Jac<F> &p) {
  if (p.inf) return p;
  F A = Fmul(p.X, p.X);
  F B = Fmul(p.Y, p.Y);
  F C = Fmul(B, B);
  F xb = Fadd(p.X, B);
  F D = Fsub(Fsub(Fmul(xb, xb), A), C);
  D = Fadd(D, D);
  F E = Fadd(Fadd(A, A), A);
  F Fq = Fmul(E, E);
  F X3 = Fsub(Fq, Fadd(D, D));
  F C8 = Fadd(C, C); C8 = Fadd(C8, C8); C8 = Fadd(C8, C8);
  F Y3 = Fsub(Fmul(E, Fsub(D, X3)), C8);
  F Z3 = Fmul(p.Y, p.Z);
  Z3 = Fadd(Z3, Z3);
  return {X3, Y3, Z3, false};
}

template <typename F, F (*Fadd)(const F &, const F &),
          F (*Fsub)(const F &, const F &), F (*Fmul)(const F &, const F &),
          bool (*Fzero)(const F &)>
static Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
  if (p.inf) return q;
  if (q.inf) return p;
  F Z1Z1 = Fmul(p.Z, p.Z);
  F Z2Z2 = Fmul(q.Z, q.Z);
  F U1 = Fmul(p.X, Z2Z2);
  F U2 = Fmul(q.X, Z1Z1);
  F S1 = Fmul(Fmul(p.Y, q.Z), Z2Z2);
  F S2 = Fmul(Fmul(q.Y, p.Z), Z1Z1);
  F H = Fsub(U2, U1);
  F rr = Fsub(S2, S1);
  if (Fzero(H)) {
    if (Fzero(rr)) return jac_double<F, Fadd, Fsub, Fmul>(p);
    Jac<F> r;
    r.inf = true;
    return r;
  }
  rr = Fadd(rr, rr);
  F H2 = Fadd(H, H);
  F I = Fmul(H2, H2);
  F J = Fmul(H, I);
  F V = Fmul(U1, I);
  F X3 = Fsub(Fsub(Fmul(rr, rr), J), Fadd(V, V));
  F SJ = Fmul(S1, J);
  F Y3 = Fsub(Fmul(rr, Fsub(V, X3)), Fadd(SJ, SJ));
  F Z12 = Fadd(p.Z, q.Z);
  F Z3 = Fmul(Fsub(Fsub(Fmul(Z12, Z12), Z1Z1), Z2Z2), H);
  return {X3, Y3, Z3, false};
}

template <typename P, typename F, F (*Fadd)(const F &, const F &),
          F (*Fsub)(const F &, const F &), F (*Fmul)(const F &, const F &),
          F (*Finv)(const F &), bool (*Fzero)(const F &)>
static P jac_mul_bytes(const P &p, const uint8_t *k, size_t n, const F &one) {
  if (p.inf) return p;
  Jac<F> acc;
  acc.inf = true;
  Jac<F> base = {p.x, p.y, one, false};
  // LSB-first over the byte string interpreted big-endian
  for (size_t i = n; i-- > 0;) {
    for (int bit = 0; bit < 8; ++bit) {
      if ((k[i] >> bit) & 1)
        acc = jac_add<F, Fadd, Fsub, Fmul, Fzero>(acc, base);
      base = jac_double<F, Fadd, Fsub, Fmul>(base);
    }
  }
  P out;
  if (acc.inf || Fzero(acc.Z)) {
    out.inf = true;
    return out;
  }
  F zinv = Finv(acc.Z);
  F zinv2 = Fmul(zinv, zinv);
  out.x = Fmul(acc.X, zinv2);
  out.y = Fmul(acc.Y, Fmul(zinv2, zinv));
  out.inf = false;
  return out;
}

static G1 ec_mul_bytes(const G1 &p, const uint8_t *k, size_t n) {
  return jac_mul_bytes<G1, Fp, fp_add, fp_sub, fp_mul, fp_inv, fp_is_zero>(
      p, k, n, FP_R);
}

static G2 ec_mul_bytes(const G2 &p, const uint8_t *k, size_t n) {
  return jac_mul_bytes<G2, Fp2, fp2_add, fp2_sub, fp2_mul, fp2_inv,
                       fp2_is_zero>(p, k, n, FP2_ONE);
}

static bool g2_subgroup_check(const G2 &p) {
  if (p.inf) return true;
  // on-curve
  Fp2 lhs = fp2_sqr(p.y);
  Fp2 rhs = fp2_add(fp2_mul(fp2_sqr(p.x), p.x), FP2_B2);
  if (!fp2_eq(lhs, rhs)) return false;
  G2 t = ec_mul_bytes(p, CURVE_ORDER_BYTES, CURVE_ORDER_BYTES_len);
  return t.inf;
}

static bool g1_subgroup_check(const G1 &p) {
  if (p.inf) return true;
  Fp lhs = fp_mul(p.y, p.y);
  Fp rhs = fp_add(fp_mul(fp_mul(p.x, p.x), p.x), FP_FOUR);
  if (!fp_eq(lhs, rhs)) return false;
  G1 t = ec_mul_bytes(p, CURVE_ORDER_BYTES, CURVE_ORDER_BYTES_len);
  return t.inf;
}

// ===========================================================================
// Pairing: untwist + generic Miller loop in Fp12 (mirrors the Python)
// ===========================================================================
struct P12 { Fp12 x, y; bool inf; };

static Fp12 W2_INV, W3_INV, FP12_THREE;

static Fp12 fp2_to_fp12(const Fp2 &x) {
  Fp12 r = {};
  r.a.a = x;
  return r;
}

static P12 untwist(const G2 &q) {
  return {fp12_mul(fp2_to_fp12(q.x), W2_INV),
          fp12_mul(fp2_to_fp12(q.y), W3_INV), false};
}

static P12 p12_double(const P12 &p) {
  Fp12 lam = fp12_mul(fp12_mul(fp12_mul(p.x, p.x), FP12_THREE),
                      fp12_inv(fp12_add(p.y, p.y)));
  Fp12 x3 = fp12_sub(fp12_sub(fp12_mul(lam, lam), p.x), p.x);
  Fp12 y3 = fp12_sub(fp12_mul(lam, fp12_sub(p.x, x3)), p.y);
  return {x3, y3, false};
}

static P12 p12_add(const P12 &p, const P12 &q) {
  if (p.inf) return q;
  if (q.inf) return p;
  if (fp12_eq(p.x, q.x)) {
    if (fp12_eq(p.y, q.y)) return p12_double(p);
    P12 r;
    r.inf = true;
    return r;
  }
  Fp12 lam = fp12_mul(fp12_sub(q.y, p.y), fp12_inv(fp12_sub(q.x, p.x)));
  Fp12 x3 = fp12_sub(fp12_sub(fp12_mul(lam, lam), p.x), q.x);
  Fp12 y3 = fp12_sub(fp12_mul(lam, fp12_sub(p.x, x3)), p.y);
  return {x3, y3, false};
}

// line through a,b evaluated at (px, py)
static Fp12 line(const P12 &a, const P12 &b, const Fp12 &px, const Fp12 &py) {
  if (!fp12_eq(a.x, b.x)) {
    Fp12 lam = fp12_mul(fp12_sub(b.y, a.y), fp12_inv(fp12_sub(b.x, a.x)));
    return fp12_sub(fp12_mul(fp12_sub(px, a.x), lam), fp12_sub(py, a.y));
  }
  if (fp12_eq(a.y, b.y)) {
    Fp12 lam = fp12_mul(fp12_mul(fp12_mul(a.x, a.x), FP12_THREE),
                        fp12_inv(fp12_add(a.y, a.y)));
    return fp12_sub(fp12_mul(fp12_sub(px, a.x), lam), fp12_sub(py, a.y));
  }
  return fp12_sub(px, a.x);
}

static const u64 BLS_X_VAL = 0xd201000000010000ULL;

static Fp12 miller_loop(const G2 &q, const G1 &p) {
  if (q.inf || p.inf) return FP12_ONE;
  P12 Q = untwist(q);
  Fp12 px = fp2_to_fp12({p.x, {}});
  Fp12 py = fp2_to_fp12({p.y, {}});
  P12 r = Q;
  Fp12 f = FP12_ONE;
  for (int i = 62; i >= 0; --i) {
    f = fp12_mul(fp12_mul(f, f), line(r, r, px, py));
    r = p12_double(r);
    if ((BLS_X_VAL >> i) & 1) {
      f = fp12_mul(f, line(r, Q, px, py));
      r = p12_add(r, Q);
    }
  }
  return fp12_conj(f);  // t < 0
}

// p^2-Frobenius: basis element w^k (v = w^2) scales by omega^k with
// omega = xi^((p^2-1)/6) in Fq2 (Fq2 itself is fixed by pi^2 since
// (p^2-1)/2 is even). Precomputed powers omega^0..omega^5.
static Fp2 OMEGA_POW[6];

static void init_frob2() {
  OMEGA_POW[0] = FP2_ONE;
  Fp2 omega = fp2_pow_bytes(XI2, OMEGA_EXP, OMEGA_EXP_len);
  for (int k = 1; k < 6; ++k) OMEGA_POW[k] = fp2_mul(OMEGA_POW[k - 1], omega);
}

static Fp12 fp12_frob2(const Fp12 &f) {
  // coefficient of v^i w^j is w^(2i+j)
  return {{f.a.a,
           fp2_mul(f.a.b, OMEGA_POW[2]),
           fp2_mul(f.a.c, OMEGA_POW[4])},
          {fp2_mul(f.b.a, OMEGA_POW[1]),
           fp2_mul(f.b.b, OMEGA_POW[3]),
           fp2_mul(f.b.c, OMEGA_POW[5])}};
}

static Fp12 final_exponentiation(const Fp12 &f) {
  // easy part: f^(p^6 - 1) = conj(f) * f^-1 (one inversion). The remaining
  // (p^6 + 1)/r = (p^2 + 1) * (p^4 - p^2 + 1)/r: pow by the ~1268-bit
  // quotient, then apply (p^2 + 1) as one Frobenius + one multiply —
  // ~1.6x fewer Fp12 ops than the direct ~2027-bit exponent.
  Fp12 g = fp12_mul(fp12_conj(f), fp12_inv(f));
  Fp12 h = fp12_pow_bytes(g, HARDER_EXP, HARDER_EXP_len);
  return fp12_mul(fp12_frob2(h), h);
}

static bool pairings_equal_2(const G1 &p1, const G2 &q1, const G1 &p2,
                             const G2 &q2) {
  // e(p1, q1) == e(p2, q2)  <=>  ml(p1,q1) * ml(p2,-q2) final-exps to 1
  G2 nq2 = q2;
  if (!nq2.inf) nq2.y = fp2_neg(nq2.y);
  Fp12 f = fp12_mul(miller_loop(q1, p1), miller_loop(nq2, p2));
  return fp12_eq(final_exponentiation(f), FP12_ONE);
}

// ===========================================================================
// hash_to_g2 (must match the Python oracle byte-for-byte)
// ===========================================================================
static G2 hash_to_g2(const uint8_t *msg, size_t msg_len) {
  uint8_t buf[4 + 64];  // "blsg2" prefix handled separately
  (void)buf;
  for (uint32_t ctr = 0;; ++ctr) {
    // seed = sha256(b"blsg2" + message + ctr_le32)
    uint8_t inbuf[5 + 256 + 4];
    size_t off = 0;
    std::memcpy(inbuf + off, "blsg2", 5);
    off += 5;
    std::memcpy(inbuf + off, msg, msg_len);
    off += msg_len;
    for (int i = 0; i < 4; ++i) inbuf[off + i] = uint8_t(ctr >> (8 * i));
    off += 4;
    uint8_t d0[32], d1[32], d2[32];
    sha::sha256(inbuf, off, d0);
    sha::sha256(d0, 32, d1);
    sha::sha256(d1, 32, d2);
    // x.a = int(d0 + d1[:16]) mod p ; x.b = int(d1[16:] + d2) mod p
    uint8_t xa[48], xb[48];
    std::memcpy(xa, d0, 32);
    std::memcpy(xa + 32, d1, 16);
    std::memcpy(xb, d1 + 16, 16);
    std::memcpy(xb + 16, d2, 32);
    Fp2 x = {fp_from_bytes_be(xa, 48), fp_from_bytes_be(xb, 48)};
    Fp2 rhs = fp2_add(fp2_mul(fp2_sqr(x), x), FP2_B2);
    Fp2 y;
    if (!fp2_sqrt(rhs, &y)) continue;
    if (fp_is_odd_std(y.a)) y = fp2_neg(y);
    G2 pt = {x, y, false};
    G2 cleared = ec_mul_bytes(pt, G2_COFACTOR_BYTES,
                                          G2_COFACTOR_BYTES_len);
    if (!cleared.inf) return cleared;
  }
}

// ===========================================================================
// serialization (ZCash flags; mirrors the Python)
// ===========================================================================
static void g1_compress(const G1 &p, uint8_t out[48]) {
  if (p.inf) {
    std::memset(out, 0, 48);
    out[0] = 0xc0;
    return;
  }
  fp_to_bytes_be(p.x, out);
  out[0] |= 0x80;
  if (fp_is_large_std(p.y)) out[0] |= 0x20;
}

static bool g1_decompress(const uint8_t in[48], G1 *out) {
  if (in[0] & 0x40) {
    out->inf = true;
    return true;
  }
  bool sign_large = in[0] & 0x20;
  uint8_t xb[48];
  std::memcpy(xb, in, 48);
  xb[0] &= 0x1f;
  Fp x = fp_from_bytes_be(xb, 48);
  Fp y2 = fp_add(fp_mul(fp_mul(x, x), x), FP_FOUR);
  // sqrt in Fp: y = y2^((p+1)/4); verify
  static uint8_t P_PLUS1_DIV4[48];
  static bool init = false;
  if (!init) {
    u64 t[6];
    u128 carry = 1;
    for (int i = 0; i < 6; ++i) {
      u128 s = (u128)P_LIMBS[i] + (i == 0 ? carry : (carry >> 64 ? 1 : 0));
      // simpler: add 1 then shift right twice below
      t[i] = (u64)s;
      carry = s >> 64 ? 1 : 0;
      if (i > 0) carry = s >> 64;
    }
    // (p+1) >> 2
    for (int shift = 0; shift < 2; ++shift) {
      u64 c = 0;
      for (int i = 5; i >= 0; --i) {
        u64 cur = (t[i] >> 1) | (c << 63);
        c = t[i] & 1;
        t[i] = cur;
      }
    }
    for (int i = 0; i < 6; ++i)
      for (int j = 0; j < 8; ++j)
        P_PLUS1_DIV4[47 - 8 * i - j] = uint8_t(t[i] >> (8 * j));
    init = true;
  }
  Fp y = fp_pow_bytes(y2, P_PLUS1_DIV4, 48);
  if (!fp_eq(fp_mul(y, y), y2)) return false;
  if (fp_is_large_std(y) != sign_large) y = fp_neg(y);
  *out = {x, y, false};
  return true;
}

static bool fp2_y_is_large(const Fp2 &y) {
  // (y.b, y.a) > ((p - y.b) % p, (p - y.a) % p) lexicographically
  Fp nb = fp_neg(y.b);
  Fp na = fp_neg(y.a);
  uint8_t yb[48], ya[48], nbb[48], nab[48];
  fp_to_bytes_be(y.b, yb);
  fp_to_bytes_be(y.a, ya);
  fp_to_bytes_be(nb, nbb);
  fp_to_bytes_be(na, nab);
  int c = std::memcmp(yb, nbb, 48);
  if (c != 0) return c > 0;
  return std::memcmp(ya, nab, 48) > 0;
}

static void g2_compress(const G2 &p, uint8_t out[96]) {
  if (p.inf) {
    std::memset(out, 0, 96);
    out[0] = 0xc0;
    return;
  }
  fp_to_bytes_be(p.x.b, out);
  fp_to_bytes_be(p.x.a, out + 48);
  out[0] |= 0x80;
  if (fp2_y_is_large(p.y)) out[0] |= 0x20;
}

static bool g2_decompress(const uint8_t in[96], G2 *out) {
  if (in[0] & 0x40) {
    out->inf = true;
    return true;
  }
  bool sign_large = in[0] & 0x20;
  uint8_t hb[48];
  std::memcpy(hb, in, 48);
  hb[0] &= 0x1f;
  Fp2 x = {fp_from_bytes_be(in + 48, 48), fp_from_bytes_be(hb, 48)};
  Fp2 rhs = fp2_add(fp2_mul(fp2_sqr(x), x), FP2_B2);
  Fp2 y;
  if (!fp2_sqrt(rhs, &y)) return false;
  if (fp2_y_is_large(y) != sign_large) y = fp2_neg(y);
  *out = {x, y, false};
  return true;
}

// ===========================================================================
// init
// ===========================================================================
static bool INITIALIZED = false;

static void bls_init() {
  if (INITIALIZED) return;
  // N0INV = -p^{-1} mod 2^64 (Newton)
  u64 inv = 1;
  for (int i = 0; i < 6; ++i) inv *= 2 - P_LIMBS[0] * inv;
  N0INV = ~inv + 1;

  // FP_R = 2^384 mod p by 384 modular doublings of 1
  Fp one_std = {};
  one_std.l[0] = 1;
  Fp r = one_std;
  for (int i = 0; i < 384; ++i) {
    // r = 2r mod p
    u64 carry = 0;
    Fp t;
    for (int j = 0; j < 6; ++j) {
      t.l[j] = (r.l[j] << 1) | carry;
      carry = r.l[j] >> 63;
    }
    if (carry || fp_gte_p(t.l)) fp_sub_p(t.l);
    r = t;
  }
  FP_R = r;
  // FP_R2 = R^2 mod p: double R 384 more times
  for (int i = 0; i < 384; ++i) {
    u64 carry = 0;
    Fp t;
    for (int j = 0; j < 6; ++j) {
      t.l[j] = (r.l[j] << 1) | carry;
      carry = r.l[j] >> 63;
    }
    if (carry || fp_gte_p(t.l)) fp_sub_p(t.l);
    r = t;
  }
  FP_R2 = r;

  // P_MINUS_2 bytes (big-endian)
  u64 pm2[6];
  std::memcpy(pm2, P_LIMBS, 48);
  pm2[0] -= 2;  // p ends in ...aaab, no borrow
  for (int i = 0; i < 6; ++i)
    for (int j = 0; j < 8; ++j)
      P_MINUS_2[47 - 8 * i - j] = uint8_t(pm2[i] >> (8 * j));

  FP2_ZERO = {FP_ZERO, FP_ZERO};
  FP2_ONE = {FP_R, FP_ZERO};
  XI2 = {FP_R, FP_R};  // 1 + u
  FP6_ZERO = {FP2_ZERO, FP2_ZERO, FP2_ZERO};
  FP6_ONE = {FP2_ONE, FP2_ZERO, FP2_ZERO};
  FP12_ONE = {FP6_ONE, FP6_ZERO};

  uint8_t three = 3, four = 4;
  FP_THREE = fp_from_bytes_be(&three, 1);
  FP_FOUR = fp_from_bytes_be(&four, 1);
  FP2_THREE = {FP_THREE, FP_ZERO};
  FP2_B2 = {FP_FOUR, FP_FOUR};  // 4(u+1)
  FP12_THREE = fp2_to_fp12(FP2_THREE);

  // eighth roots of unity: XI^((p^2-1)/8)^k
  Fp2 base = fp2_pow_bytes(XI2, EIGHTH_ROOT_EXP, EIGHTH_ROOT_EXP_len);
  EIGHTH_ROOTS[0] = FP2_ONE;
  for (int k = 1; k < 4; ++k) EIGHTH_ROOTS[k] = fp2_mul(EIGHTH_ROOTS[k - 1], base);

  // untwist constants: w = (0, 1) in Fp12; W2_INV = (w^2)^-1, W3_INV = (w^3)^-1
  Fp12 w = {FP6_ZERO, FP6_ONE};
  Fp12 w2 = fp12_mul(w, w);
  Fp12 w3 = fp12_mul(w2, w);
  W2_INV = fp12_inv(w2);
  W3_INV = fp12_inv(w3);

  init_frob2();
  G1_GENERATOR = {fp_from_bytes_be(G1X_BYTES, G1X_BYTES_len),
                  fp_from_bytes_be(G1Y_BYTES, G1Y_BYTES_len), false};
  G2_GENERATOR = {{fp_from_bytes_be(G2X0_BYTES, G2X0_BYTES_len),
                   fp_from_bytes_be(G2X1_BYTES, G2X1_BYTES_len)},
                  {fp_from_bytes_be(G2Y0_BYTES, G2Y0_BYTES_len),
                   fp_from_bytes_be(G2Y1_BYTES, G2Y1_BYTES_len)},
                  false};
  INITIALIZED = true;
}

// ===========================================================================
// C ABI
// ===========================================================================
extern "C" {

// sk (32 bytes big-endian) -> compressed G1 pubkey (48 bytes)
void bls_sk_to_pk(const uint8_t *sk, uint8_t *out48) {
  bls_init();
  G1 pk = ec_mul_bytes(G1_GENERATOR, sk, 32);
  g1_compress(pk, out48);
}

// sign: sk (32 BE) x message -> compressed G2 signature (96 bytes)
void bls_sign(const uint8_t *sk, const uint8_t *msg, uint64_t msg_len,
              uint8_t *out96) {
  bls_init();
  G2 h = hash_to_g2(msg, msg_len);
  G2 sig = ec_mul_bytes(h, sk, 32);
  g2_compress(sig, out96);
}

// verify: e(pk, H(m)) == e(g1, sig); returns 1/0
int bls_verify(const uint8_t *pk48, const uint8_t *msg, uint64_t msg_len,
               const uint8_t *sig96) {
  bls_init();
  G1 pk;
  G2 sig;
  if (!g1_decompress(pk48, &pk) || !g2_decompress(sig96, &sig)) return 0;
  if (pk.inf || sig.inf) return 0;
  if (!g2_subgroup_check(sig)) return 0;
  G2 h = hash_to_g2(msg, msg_len);
  return pairings_equal_2(pk, h, G1_GENERATOR, sig) ? 1 : 0;
}

// aggregate n compressed G2 signatures; returns 1 on success
int bls_aggregate(const uint8_t *sigs, uint64_t n, uint8_t *out96) {
  bls_init();
  if (n == 0) return 0;
  G2 acc;
  acc.inf = true;
  for (uint64_t i = 0; i < n; ++i) {
    G2 s;
    if (!g2_decompress(sigs + 96 * i, &s)) return 0;
    acc = g2_add(acc, s);
  }
  g2_compress(acc, out96);
  return 1;
}

// aggregate n compressed G1 pubkeys
int bls_aggregate_pks(const uint8_t *pks, uint64_t n, uint8_t *out48) {
  bls_init();
  if (n == 0) return 0;
  G1 acc;
  acc.inf = true;
  for (uint64_t i = 0; i < n; ++i) {
    G1 p;
    if (!g1_decompress(pks + 48 * i, &p)) return 0;
    acc = g1_add(acc, p);
  }
  g1_compress(acc, out48);
  return 1;
}

// FastAggregateVerify: all pks signed the same message
int bls_fast_aggregate_verify(const uint8_t *pks, uint64_t n,
                              const uint8_t *msg, uint64_t msg_len,
                              const uint8_t *sig96) {
  bls_init();
  if (n == 0) return 0;
  uint8_t agg[48];
  if (!bls_aggregate_pks(pks, n, agg)) return 0;
  return bls_verify(agg, msg, msg_len, sig96);
}

int bls_subgroup_check_g1(const uint8_t *pk48) {
  bls_init();
  G1 p;
  if (!g1_decompress(pk48, &p)) return 0;
  return g1_subgroup_check(p) ? 1 : 0;
}

}  // extern "C"
