"""All five BASELINE.md benchmark configs, reported as one JSON object
and written to BENCH_ALL_r{N}.json when --record N is passed.

(bench.py stays the single-line headline metric the driver records; this
harness documents the full matrix of SURVEY.md §6 / BASELINE.json configs.)

1.  LMD-GHOST fork choice, 1,024 validators / 32 slots — pure-Python spec
    ``get_head`` p50 (CPU reference), plus the DEVICE fork choice on a
    capacity-1024 tree with the full latest-message table (rescan pass
    and incremental bucket path)
2.  swap-or-not shuffle, 64K validators (device)
3.  attestation aggregation batch verify, 2048 aggregates / ~1M signers
    (fake_crypto: SHA/XOR FakeBLS pipeline), plus the REAL BLS12-381
    pairing path (ops/pairing.py) at its own recorded batch size
4.  full process_epoch sweep, 1M validators, shard_map over the mesh
5.  SSF supermajority tally, 1M validators, ICI->DCN psum

Device timings use the fused-loop work-difference recipe in
``pos_evolution_tpu/utils/benchtime.py`` (``block_until_ready`` does not
synchronize on the axon relay; prior methodology was invalid).

Usage: python bench_all.py [--record N]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def config1_forkchoice_python():
    from pos_evolution_tpu.config import mainnet_config, use_config
    with use_config(mainnet_config().replace(slots_per_epoch=32)):
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.specs.containers import LatestMessage
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root

        state, anchor = make_genesis(1024)
        store = fc.get_forkchoice_store(state, anchor)
        parent_state = state
        roots = [hash_tree_root(anchor)]
        for slot in range(1, 9):  # a chain with one fork
            fc.on_tick(store, store.genesis_time + slot * 12)
            sb = build_block(parent_state, slot,
                             graffiti=bytes([slot]) * 32)
            fc.on_block(store, sb)
            roots.append(hash_tree_root(sb.message))
            parent_state = store.block_states[roots[-1]]
        # every validator has a latest message spread over the chain
        rng = np.random.default_rng(0)
        for v in range(1024):
            store.latest_messages[v] = LatestMessage(
                epoch=0, root=roots[rng.integers(0, len(roots))])
        # HandlerTimer owns the percentile math (utils/metrics): one
        # accessor for benches, the sim driver and the profiling
        # exporters, instead of per-caller np.percentile re-derivations
        from pos_evolution_tpu.utils.metrics import HandlerTimer
        timer = HandlerTimer()
        for _ in range(20):
            with timer.track("get_head"):
                head = fc.get_head(store)
        out = {"p50_ms": round(timer.percentile("get_head", 50) * 1e3, 3),
               "p95_ms": round(timer.percentile("get_head", 95) * 1e3, 3)}
        try:
            from pos_evolution_tpu.ops.forkchoice import get_head_dense
            out["dense_matches"] = bool(get_head_dense(store) == head)
        except Exception as e:  # device path unavailable
            out["dense_error"] = str(e)[:80]
        return out


def config1_forkchoice_device(n_msgs, entropy, fused_measure, checksum_tree):
    """Device LMD-GHOST descent on a deep capacity-1024 tree with a full
    latest-message table: the rescan kernel (head_and_weights) and the
    resident incremental path (apply_latest_messages + head_from_buckets,
    64-vote delta per query — the per-slot shape of the reference's
    get_head-per-decision loop, pos-evolution.md:298,762)."""
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.ops.forkchoice import (
        DenseStore, apply_latest_messages, head_and_weights,
        head_from_buckets, rebuild_buckets,
    )

    capacity = 1024
    gwei = 10**9
    rng = np.random.default_rng(1)
    # a realistic deep tree: mostly a chain, with random forks
    parent = np.arange(-1, capacity - 1, dtype=np.int32)
    forks = rng.integers(1, capacity, capacity // 8)
    parent[forks] = rng.integers(0, forks)
    store = DenseStore(
        parent=jnp.asarray(parent),
        slot=jnp.arange(capacity, dtype=jnp.int32),
        rank=jnp.asarray(rng.permutation(capacity).astype(np.int32)),
        real=jnp.ones(capacity, bool),
        leaf_viable=jnp.ones(capacity, bool),
        justified_idx=jnp.int32(0),
        msg_block=jnp.asarray(rng.integers(0, capacity, n_msgs).astype(np.int32)),
        msg_epoch=jnp.zeros(n_msgs, jnp.int64),
        weight=jnp.asarray(np.full(n_msgs, 32 * gwei, np.int64)),
        boost_idx=jnp.int32(capacity - 1),
        boost_amount=jnp.int64(32 * gwei * (n_msgs // 32) // 4),
    )

    # the store rides through fused_measure as a TRACED capture — closed
    # over, its message table is an HLO constant and XLA constant-folds
    # the vote-bucket scatter at compile time (the >1 s stalls in the
    # BENCH_r05 tail; see benchtime.fused_measure's captures contract)
    def rescan_body(salt, acc, store):
        st = store._replace(
            msg_epoch=store.msg_epoch.at[0].set(salt.astype(jnp.int64)),
            boost_idx=(salt % capacity).astype(jnp.int32))
        h, w = head_and_weights(st, capacity)
        return acc + h.astype(jnp.int32) + checksum_tree(w)

    t_rescan = fused_measure(rescan_body, entropy=entropy,
                             tag="fc rescan cap1024", captures=store)

    buckets = rebuild_buckets(store.msg_block, store.weight, capacity)
    delta = 64
    vi = jnp.asarray(rng.integers(0, n_msgs, delta).astype(np.int32))

    def incr_body(salt, acc, cap):
        store, buckets = cap
        blocks = (salt + jnp.arange(delta, dtype=jnp.int32)) % capacity
        mb, me, bk = apply_latest_messages(
            store.msg_block, store.msg_epoch, buckets, vi, blocks,
            jnp.full(delta, 2, jnp.int64), store.weight[vi],
            jnp.ones(delta, bool))
        h, w = head_from_buckets(
            store.parent, store.real, store.rank, store.leaf_viable,
            jnp.int32(0), bk, (salt % capacity).astype(jnp.int32),
            jnp.int64(10**12), capacity)
        return acc + h.astype(jnp.int32) + checksum_tree((mb, me, w))

    t_incr = fused_measure(incr_body, entropy=entropy + 7,
                           tag="fc incremental cap1024",
                           captures=(store, buckets))
    return {"capacity": 1024, "latest_messages": n_msgs,
            "rescan_head_ms": round(t_rescan * 1e3, 3),
            "incremental_head_ms": round(t_incr * 1e3, 3),
            "incremental_delta_votes": delta}


def main():
    import jax
    import jax.numpy as jnp

    from pos_evolution_tpu.telemetry import MetricsRegistry, jaxrt
    from pos_evolution_tpu.utils.benchtime import checksum_tree, fused_measure
    from pos_evolution_tpu.utils.watchdog import Watchdog

    record = None
    if "--record" in sys.argv:
        try:
            record = int(sys.argv[sys.argv.index("--record") + 1])
        except (IndexError, ValueError):
            sys.exit("Usage: python bench_all.py [--record N]")

    # Each config runs as a supervised watchdog step: results are
    # committed to the partial-results JSON as they arrive, and one
    # config dying (compile OOM, kernel rejection, hang under
    # POS_BENCH_STEP_TIMEOUT) records an incident and the matrix keeps
    # going — the run exits 0 with every config that completed.
    wd = Watchdog.from_env(
        "bench_all.py",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "bench_all_partial.json"))

    def _failed(name):
        return {"error": f"step '{name}' failed; see watchdog_incidents"}

    # runtime telemetry across the whole matrix (recompiles, dispatches,
    # transfer bytes) — emitted under "telemetry" for scripts/perf_gate.py
    registry = MetricsRegistry()
    jaxrt.install(registry)

    entropy = int.from_bytes(os.urandom(3), "little")
    results = {"backend": jax.default_backend(),
               "n_devices": len(jax.devices()),
               "methodology": "benchtime.fused_measure (work-differenced, "
                              "transfer-synced, entropy-salted)"}

    results["config1_lmd_ghost_1024_python"] = wd.step(
        "config1_python", config1_forkchoice_python,
        default=_failed("config1_python"))

    on_accel = jax.default_backend() != "cpu"
    n = 1_000_000 if on_accel else 65_536
    scale = 1_000_000 // n
    rng = np.random.default_rng(0)
    gwei = 10**9

    results["config1_lmd_ghost_device"] = wd.step(
        "config1_device", config1_forkchoice_device,
        n, entropy, fused_measure, checksum_tree,
        default=_failed("config1_device"))

    # --- config 2: shuffle 64K (K pre-derived seeds, indexed by salt) ---
    def _config2():
        from pos_evolution_tpu.ops.shuffle import (
            _seed_words, _shuffle_device, host_pivots,
        )
        K = 16
        seeds = [os.urandom(32) for _ in range(K)]
        seed_words = jnp.asarray(np.stack([_seed_words(s) for s in seeds]))
        pivots = jnp.asarray(np.stack(
            [host_pivots(s, 65536, 90) for s in seeds]))

        def shuf_body(salt, acc):
            k = salt % K
            perm = _shuffle_device(seed_words[k], pivots[k], 65536, 90)
            return acc + checksum_tree(perm)

        t = fused_measure(shuf_body, entropy=entropy, tag="shuffle 64k")
        return {"ms": round(t * 1e3, 3)}

    results["config2_shuffle_64k"] = wd.step(
        "config2", _config2, default=_failed("config2"))

    # --- config 3: aggregation (fake crypto) ---
    def _config3():
        from pos_evolution_tpu.ops.aggregation import aggregate_verify_batch
        A, C = 2048, max(n // 2048, 8)
        pk_states = jnp.asarray(rng.integers(0, 2**32, (n, 8), dtype=np.uint64)
                                .astype(np.uint32))
        committees = jnp.asarray(
            rng.permutation(n)[:A * C].reshape(A, C).astype(np.int32))
        bits = jnp.asarray(rng.random((A, C)) < 0.99)
        msgs = jnp.asarray(rng.integers(0, 2**32, (A, 8), dtype=np.uint64)
                           .astype(np.uint32))
        sigs = jnp.asarray(rng.integers(0, 2**32, (A, 24), dtype=np.uint64)
                           .astype(np.uint32))

        def agg_body(salt, acc, cap):
            pk_states, committees, bits, msgs, sigs = cap
            ok = aggregate_verify_batch(
                pk_states, committees, bits,
                msgs.at[0, 0].set(salt.astype(jnp.uint32)), sigs)
            return acc + ok.sum(dtype=jnp.int32)

        t = fused_measure(agg_body, entropy=entropy,
                          tag="aggregation fake-bls",
                          captures=(pk_states, committees, bits, msgs, sigs))
        return {
            "fake_crypto": True,
            "note": "SHA/XOR FakeBLS pipeline shape, NOT real pairings — "
                    "~3 orders of magnitude less math than BLS12-381",
            "aggregates": A, "signers": A * C, "ms": round(t * 1e3, 2),
            "signer_verifies_per_s": int(A * C / t)}

    results["config3_aggregation_fakebls"] = wd.step(
        "config3", _config3, default=_failed("config3"))

    # --- config 3b: REAL BLS12-381 batched pairing verify ---
    if on_accel:
        results["config3b_real_bls_pairing"] = wd.step(
            "config3b", _config3b_real_bls, entropy, fused_measure,
            default=_failed("config3b"))
    elif os.environ.get("POS_BENCH_REAL3", "1") != "0":
        # Honest CPU measurement of the REAL pairing pipeline
        # (decompression + hash-to-G2 + batched Miller loop,
        # scripts/bench_config3_real.py). Reference scale (2048 aggregates
        # / 256K signers) takes hours on one CPU core, so the in-matrix
        # run uses a reduced-but-real scale by default; POS_BENCH_REAL3=
        # full runs reference scale, =0 opts out. The recorded full-scale
        # row is merged from the standalone run via
        # scripts/merge_config3_row.py (see the row's provenance field).
        full = os.environ.get("POS_BENCH_REAL3") == "full"

        def _config3b_cpu():
            from scripts.bench_config3_real import run as real3
            return (real3(verbose=False) if full else
                    real3(aggregates=64, signers=8192, distinct_keys=64,
                          verbose=False))

        results["config3b_real_bls_pairing"] = wd.step(
            "config3b", _config3b_cpu, default=_failed("config3b"))
    else:
        results["config3b_real_bls_pairing"] = {
            "skipped": "POS_BENCH_REAL3=0 (CPU real-pairing run opted out)"}

    # --- configs 4 + 5: sharded epoch sweep / SSF tally at 1M ---
    _mesh_state = {}

    def _mesh_setup():
        from pos_evolution_tpu.config import mainnet_config
        from pos_evolution_tpu.ops.epoch import DenseRegistry
        from pos_evolution_tpu.parallel.sharded import (
            make_mesh, shard_registry, sharded_epoch_step,
        )
        cfg = mainnet_config()
        reg = DenseRegistry(
            effective_balance=jnp.asarray(np.full(n, 32 * gwei, np.int64)),
            balance=jnp.asarray(
                rng.integers(31 * gwei, 33 * gwei, n).astype(np.int64)),
            activation_epoch=jnp.zeros(n, jnp.int64),
            exit_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            withdrawable_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
            slashed=jnp.zeros(n, bool),
            prev_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            cur_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
            inactivity_scores=jnp.zeros(n, jnp.int64),
        )
        mesh = make_mesh()
        _mesh_state.update(cfg=cfg, reg=reg, mesh=mesh,
                           step=sharded_epoch_step(mesh, cfg),
                           sharded=shard_registry(mesh, reg))
        return {"mesh": dict(zip(mesh.axis_names, mesh.devices.shape))}

    if wd.step("mesh_setup", _mesh_setup) is None:
        results["config4_epoch_1m_sharded"] = _failed("mesh_setup")
        results["config5_ssf_tally_1m"] = _failed("mesh_setup")
    else:
        cfg, reg, mesh, step, sharded = (
            _mesh_state["cfg"], _mesh_state["reg"], _mesh_state["mesh"],
            _mesh_state["step"], _mesh_state["sharded"])
        bits4 = jnp.zeros(4, bool)

        def _config4():
            # the registry rides as a traced capture (not a closure): a
            # closed-over column is an HLO constant and XLA can fold the
            # sweeps over it at compile time — the BENCH_r05 hazard
            def epoch_body(salt, acc, reg):
                out = step(reg._replace(
                    balance=reg.balance.at[0].set(
                        31 * gwei + salt.astype(jnp.int64))),
                    jnp.int64(10), jnp.int64(8), bits4, jnp.int64(8),
                    jnp.int64(9), jnp.int64(0))
                return acc + checksum_tree(out)

            t = fused_measure(epoch_body, entropy=entropy,
                              tag="epoch sharded", captures=sharded)
            return {"n_validators": n,
                    "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
                    "ms_scaled_to_1m": round(t * 1e3 * scale, 3)}

        results["config4_epoch_1m_sharded"] = wd.step(
            "config4", _config4, default=_failed("config4"))

        def _config5():
            from pos_evolution_tpu.parallel.sharded import (
                ssf_supermajority_tally,
            )
            tally = ssf_supermajority_tally(mesh)
            votes = jnp.asarray(np.arange(n) % 3 != 0)
            eff = reg.effective_balance
            total = jnp.int64(n * 32 * gwei)

            def ssf_body(salt, acc, cap):
                votes, eff = cap
                out = tally(votes.at[salt % n].set(salt % 2 == 0), eff, total)
                return acc + checksum_tree(out)

            t = fused_measure(ssf_body, entropy=entropy, tag="ssf tally",
                              captures=(votes, eff))
            return {"ms_scaled_to_1m": round(t * 1e3 * scale, 4)}

        results["config5_ssf_tally_1m"] = wd.step(
            "config5", _config5, default=_failed("config5"))

        def _config6():
            # Sharded END-TO-END loop (ISSUE 9): a small DenseSimulation
            # over the same mesh — per-slot sharded vote pass + committee
            # shuffle + aggregation verify + fused epoch sweeps — timed as
            # whole-run wall clock (it is a driver, not a kernel; the
            # fused-measure recipe applies to kernels).
            import time as _t

            from pos_evolution_tpu.config import mainnet_config
            from pos_evolution_tpu.sim.dense_driver import DenseSimulation
            dcfg = mainnet_config().replace(slots_per_epoch=8,
                                            max_committees_per_slot=8)
            sim = DenseSimulation(8192, cfg=dcfg, mesh=mesh, seed=1,
                                  shuffle_rounds=10, check_walk_every=8)
            t0 = _t.time()
            sim.run_epochs(4)
            wall = _t.time() - t0
            s = sim.summary()
            assert s["finality_reached"] and \
                s["resident_head_equals_spec_walk"], s
            return {"n_validators": 8192, "slots": s["slots"],
                    "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
                    "wall_s": round(wall, 2),
                    "ms_per_slot": round(wall / s["slots"] * 1e3, 2),
                    "finalized_epoch": s["finalized_epoch"],
                    "aggregates_verified": s["aggregates_verified"]}

        results["config6_sharded_e2e"] = wd.step(
            "config6", _config6, default=_failed("config6"))

    if wd.incidents:
        results["watchdog_incidents"] = wd.incidents
    results["telemetry"] = {"counts": registry.counts()}

    out = json.dumps(results, indent=1)
    print(out)
    here = os.path.dirname(os.path.abspath(__file__))
    if record is not None:
        path = os.path.join(here, f"BENCH_ALL_r{record:02d}.json")
        with open(path, "w") as f:
            f.write(out + "\n")

    # Bench history (profiling/history.py): the whole matrix lands as one
    # schema-versioned entry for scripts/perf_gate.py --history.
    if "--no-history" not in sys.argv:
        try:
            from pos_evolution_tpu.profiling import history as _history
            _history.append_entry(os.path.join(here, "bench_history.jsonl"),
                                  results, kind="bench_all")
            # the sharded end-to-end run also lands in its own namespace
            # so `perf_gate.py --kind bench_shard` bands it together with
            # scale_demo --sharded emissions (ISSUE 9 satellite)
            shard = results.get("config6_sharded_e2e")
            if shard and not shard.get("failed"):
                _history.append_entry(
                    os.path.join(here, "bench_history.jsonl"),
                    {"metric": "sharded_e2e_small", **shard},
                    kind="bench_shard")
        except Exception as e:
            print(f"# bench history append failed: {e!r:.120}",
                  file=sys.stderr)


def _config3b_real_bls(entropy, fused_measure):
    """Real BLS12-381 FastAggregateVerify throughput (ops/pairing.py):
    batched G1 aggregation + one fused Miller loop + final exponentiation
    per attestation, honest batch size recorded (no extrapolation).
    Accelerator-only (main() records a skip on CPU)."""
    import jax.numpy as jnp

    from pos_evolution_tpu.crypto import bls12_381 as oracle
    from pos_evolution_tpu.ops import pairing

    rng = np.random.default_rng(3)
    batch = 8
    lanes = 8
    n_keys = 16
    pks = [oracle.ec_mul(oracle.G1_GEN, int(sk)) for sk in range(2, n_keys + 2)]
    pk_table = jnp.asarray(np.stack(
        [pairing.g1_affine_encode(p) for p in pks]))
    committees = jnp.asarray(
        rng.integers(0, n_keys, (batch, lanes)).astype(np.int32))
    bits = jnp.asarray(np.ones((batch, lanes), dtype=bool))
    # random valid G2 points stand in for hashed messages / signatures
    # (identical pairing math; verdicts are expected-false, checksummed)
    g2s = [oracle.ec_mul(oracle.G2_GEN, int(rng.integers(2, 2**30)))
           for _ in range(batch)]
    msg_g2 = jnp.asarray(np.stack([pairing.g2_affine_encode(p) for p in g2s]))
    sig_g2 = jnp.asarray(np.stack(
        [pairing.g2_affine_encode(oracle.ec_mul(p, 3)) for p in g2s]))
    sig_inf = jnp.zeros(batch, bool)

    def body(salt, acc, cap):
        pk_table, committees, bits, msg_g2, sig_g2, sig_inf = cap
        comm = (committees + salt) % n_keys
        ok = pairing.fast_aggregate_verify_batch(
            pk_table, comm, bits, msg_g2, sig_g2, sig_inf)
        return acc + ok.sum(dtype=jnp.int32)

    t = fused_measure(body, k_hi=3, entropy=entropy,
                      tag=f"real-bls verify batch={batch}",
                      captures=(pk_table, committees, bits, msg_g2, sig_g2,
                                sig_inf))
    return {"fake_crypto": False, "batch": batch, "lanes_per_aggregate": lanes,
            "ms_per_batch": round(t * 1e3, 1),
            "aggregate_verifies_per_s": round(batch / t, 2)}


if __name__ == "__main__":
    main()
