"""All five BASELINE.md benchmark configs, reported as one JSON object.

(bench.py stays the single-line headline metric the driver records; this
harness documents the full matrix of SURVEY.md §6 / BASELINE.json configs.)

1. LMD-GHOST fork choice, 1,024 validators / 32 slots — pure-Python spec
   ``get_head`` p50 (CPU reference) + dense head for comparison
2. swap-or-not shuffle, 64K validators (device)
3. attestation aggregation batch verify, 2048 aggregates / ~1M signers
4. full process_epoch sweep, 1M validators, shard_map over the available mesh
5. SSF supermajority tally, 1M validators, ICI->DCN psum

Usage: python bench_all.py  (runs on TPU if present, CPU otherwise)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _timeit(fn, reps=5):
    fn(0)
    t0 = time.perf_counter()
    for i in range(1, reps + 1):
        fn(i)
    return (time.perf_counter() - t0) / reps


def config1_forkchoice_python():
    from pos_evolution_tpu.config import mainnet_config, use_config
    with use_config(mainnet_config().replace(slots_per_epoch=32)):
        from pos_evolution_tpu.specs import forkchoice as fc
        from pos_evolution_tpu.specs.containers import LatestMessage
        from pos_evolution_tpu.specs.genesis import make_genesis
        from pos_evolution_tpu.specs.validator import build_block
        from pos_evolution_tpu.ssz import hash_tree_root

        state, anchor = make_genesis(1024)
        store = fc.get_forkchoice_store(state, anchor)
        parent_state = state
        roots = [hash_tree_root(anchor)]
        for slot in range(1, 9):  # a chain with one fork
            fc.on_tick(store, store.genesis_time + slot * 12)
            sb = build_block(parent_state, slot,
                             graffiti=bytes([slot]) * 32)
            fc.on_block(store, sb)
            roots.append(hash_tree_root(sb.message))
            parent_state = store.block_states[roots[-1]]
        # every validator has a latest message spread over the chain
        rng = np.random.default_rng(0)
        for v in range(1024):
            store.latest_messages[v] = LatestMessage(
                epoch=0, root=roots[rng.integers(0, len(roots))])
        times = []
        for _ in range(20):
            t0 = time.perf_counter()
            head = fc.get_head(store)
            times.append(time.perf_counter() - t0)
        out = {"p50_ms": round(float(np.percentile(times, 50)) * 1e3, 3),
               "p95_ms": round(float(np.percentile(times, 95)) * 1e3, 3)}
        try:
            from pos_evolution_tpu.ops.forkchoice import get_head_dense
            t0 = time.perf_counter()
            dense_head = get_head_dense(store)
            out["dense_first_call_ms"] = round((time.perf_counter() - t0) * 1e3, 1)
            out["dense_matches"] = bool(dense_head == head)
        except Exception as e:  # device path unavailable
            out["dense_error"] = str(e)[:80]
        return out


def main():
    import jax
    import jax.numpy as jnp

    results = {"backend": jax.default_backend(),
               "n_devices": len(jax.devices())}

    results["config1_lmd_ghost_1024"] = config1_forkchoice_python()

    on_accel = jax.default_backend() != "cpu"
    n = 1_000_000 if on_accel else 65_536
    scale = 1_000_000 // n
    rng = np.random.default_rng(0)
    gwei = 10**9

    # --- config 2: shuffle 64K ---
    from pos_evolution_tpu.ops.shuffle import shuffle_permutation_jax
    def shuf(i):
        jax.block_until_ready(shuffle_permutation_jax(bytes([i]) * 32, 65536, 90))
    t = _timeit(shuf, reps=3)
    results["config2_shuffle_64k"] = {"ms": round(t * 1e3, 2)}

    # --- config 3: aggregation ---
    from pos_evolution_tpu.ops.aggregation import aggregate_verify_batch
    A, C = 2048, max(n // 2048, 8)
    pk_states = jnp.asarray(rng.integers(0, 2**32, (n, 8), dtype=np.uint64)
                            .astype(np.uint32))
    committees = jnp.asarray(rng.permutation(n)[:A * C].reshape(A, C).astype(np.int32))
    bits = jnp.asarray(rng.random((A, C)) < 0.99)
    msgs = jnp.asarray(rng.integers(0, 2**32, (A, 8), dtype=np.uint64)
                       .astype(np.uint32))
    sigs = jnp.asarray(rng.integers(0, 2**32, (A, 24), dtype=np.uint64)
                       .astype(np.uint32))

    def agg(i):
        jax.block_until_ready(aggregate_verify_batch(
            pk_states, committees, bits, msgs.at[0, 0].set(np.uint32(i)), sigs))
    t = _timeit(agg, reps=3)
    results["config3_aggregation"] = {
        "aggregates": A, "signers": A * C, "ms": round(t * 1e3, 1),
        "signer_verifies_per_s": int(A * C / t)}

    # --- config 4: sharded epoch sweep at 1M ---
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.ops.epoch import DenseRegistry
    from pos_evolution_tpu.parallel.sharded import (
        make_mesh, shard_registry, sharded_epoch_step,
    )
    cfg = mainnet_config()
    reg = DenseRegistry(
        effective_balance=jnp.asarray(np.full(n, 32 * gwei, np.int64)),
        balance=jnp.asarray(rng.integers(31 * gwei, 33 * gwei, n).astype(np.int64)),
        activation_epoch=jnp.zeros(n, jnp.int64),
        exit_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
        withdrawable_epoch=jnp.asarray(np.full(n, 2**62, np.int64)),
        slashed=jnp.zeros(n, bool),
        prev_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
        cur_flags=jnp.asarray(rng.integers(0, 8, n).astype(np.uint8)),
        inactivity_scores=jnp.zeros(n, jnp.int64),
    )
    mesh = make_mesh()
    step = sharded_epoch_step(mesh, cfg)
    sharded = shard_registry(mesh, reg)
    bits4 = jnp.zeros(4, bool)

    def epoch(i):
        out = step(sharded._replace(
            balance=sharded.balance.at[0].set(np.int64(31 * gwei + i))),
            jnp.int64(10), jnp.int64(8), bits4, jnp.int64(8), jnp.int64(9),
            jnp.int64(0))
        jax.block_until_ready(out)
    t = _timeit(epoch, reps=3)
    results["config4_epoch_1m_sharded"] = {
        "n_validators": n, "mesh": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "ms_scaled_to_1m": round(t * 1e3 * scale, 2)}

    # --- config 5: SSF supermajority tally ---
    from pos_evolution_tpu.parallel.sharded import ssf_supermajority_tally
    tally = ssf_supermajority_tally(mesh)
    votes = jnp.asarray(np.arange(n) % 3 != 0)
    eff = reg.effective_balance
    total = jnp.int64(n * 32 * gwei)

    def ssf(i):
        jax.block_until_ready(tally(
            votes.at[i % n].set(bool(i % 2)), eff, total))
    t = _timeit(ssf, reps=3)
    results["config5_ssf_tally_1m"] = {"ms_scaled_to_1m": round(t * 1e3 * scale, 3)}

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
