"""Merkle level-sweep microbench: device kernel vs host sweeps.

Times one tree level — N/2 sibling-pair SHA-256 compressions for an
N-leaf batch — on three paths and pins them bit-identical:

- **device**: the ops/merkle_device dispatch layer forced to
  ``"device"`` (Pallas on a real accelerator, the jitted XLA kernel
  otherwise), warmed before timing so compile never pollutes the
  number;
- **host numpy**: the pure uint32-lane NumPy kernel
  (``ssz.hash.sha256_pairs_lanes``) — the "host NumPy sweep" of the
  ROADMAP item 4 acceptance line (device ≥ 3x at ≥ 64K leaves);
- **host dispatched**: ``ssz.hash.sha256_pairs`` as production ships it
  (the native C++ core when built) — recorded for honesty: on a CPU box
  with the native core this wins, which is exactly why auto-dispatch
  keeps jax-on-CPU on the host path.

The emission (``metric: bench_merkle``) lands in
``bench_history.jsonl`` as ``kind=bench_merkle``;
``scripts/perf_gate.py --kind bench_merkle --strict-timing`` bands the
``*_ms`` leaves, so a regressed device sweep (or a silently vanished
device path — ``counts.device_sweeps`` is count-gated) fails CI. The
doctored-slow (x10) negative is pinned in the telemetry-smoke job.

Usage:
    python scripts/bench_merkle.py [--leaves 65536] [--repeats 5]
        [--json out.json] [--history bench_history.jsonl]
        [--require-speedup 3.0] [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _median_ms(fn, repeats: int) -> float:
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(sorted(times)[len(times) // 2])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--leaves", type=int, default=65536,
                    help="leaf batch per level sweep (pairs = leaves/2)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", help="write the bench_merkle emission here")
    ap.add_argument("--history",
                    help="append the emission to this bench_history.jsonl")
    ap.add_argument("--require-speedup", type=float, default=None,
                    help="exit nonzero unless device beats the host "
                         "NumPy sweep by this factor (the acceptance run)")
    args = ap.parse_args(argv)

    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.ops import merkle_device
    from pos_evolution_tpu.ssz.hash import sha256_pairs, sha256_pairs_lanes

    n_pairs = args.leaves // 2
    rng = np.random.default_rng(args.seed)
    left = rng.integers(0, 256, (n_pairs, 32), dtype=np.uint8)
    right = rng.integers(0, 256, (n_pairs, 32), dtype=np.uint8)

    set_backend("jax")
    import jax
    merkle_device.reset_stats()
    prev_mode = merkle_device.set_mode("device")
    try:
        device_out = merkle_device.pair_hash(left, right)  # compile warm-up
        device_ms = _median_ms(
            lambda: merkle_device.pair_hash(left, right), args.repeats)
        counts = merkle_device.stats()
    finally:
        merkle_device.set_mode(prev_mode)
        set_backend("numpy")

    host_numpy_out = sha256_pairs_lanes(left, right)
    host_numpy_ms = _median_ms(
        lambda: sha256_pairs_lanes(left, right), args.repeats)
    host_dispatch_ms = _median_ms(
        lambda: sha256_pairs(left, right), args.repeats)

    parity_ok = bool((device_out == host_numpy_out).all())
    speedup = host_numpy_ms / device_ms if device_ms else float("inf")
    fell_back = counts["fallback_numpy"] > 0

    print(f"merkle level sweep @ {args.leaves} leaves ({n_pairs} pairs), "
          f"jax backend = {jax.default_backend()}")
    print(f"  device        : {device_ms:9.2f} ms"
          + ("  [FELL BACK TO NUMPY]" if fell_back else ""))
    print(f"  host numpy    : {host_numpy_ms:9.2f} ms")
    print(f"  host dispatch : {host_dispatch_ms:9.2f} ms (native core "
          f"when built)")
    print(f"  device vs host-numpy speedup: {speedup:.2f}x; "
          f"parity: {'ok' if parity_ok else 'MISMATCH'}")
    print(f"  dispatch counters: {counts}")

    emission = {
        "metric": "bench_merkle",
        "leaves": args.leaves,
        "pairs": n_pairs,
        "jax_backend": jax.default_backend(),
        "sweeps": {
            "device_ms": round(device_ms, 4),
            "host_numpy_ms": round(host_numpy_ms, 4),
            "host_dispatch_ms": round(host_dispatch_ms, 4),
        },
        "speedup_vs_numpy": round(speedup, 3),
        "device_pairs_per_s": (round(n_pairs / (device_ms / 1e3))
                               if device_ms else None),
        "parity_ok": parity_ok,
        "counts": {k: v for k, v in counts.items() if k != "device_ms"},
    }
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(emission, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"emission -> {args.json}")
    if args.history:
        from pos_evolution_tpu.profiling import history
        history.append_entry(args.history, emission, kind="bench_merkle")
        print(f"history  -> {args.history} (kind=bench_merkle)")

    if not parity_ok:
        print("FAIL: device sweep diverged from the host kernel",
              file=sys.stderr)
        return 1
    if args.require_speedup is not None and speedup < args.require_speedup:
        print(f"FAIL: device speedup {speedup:.2f}x < required "
              f"{args.require_speedup}x", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
