#!/bin/bash
# Opportunistic on-TPU evidence capture: run when the axon tunnel is alive.
# Produces PALLAS_TPU_r03.json + ACCEL_TESTS_r03.txt + BENCH_ALL_r03.json
# + a fresh bench line.
set -u
cd "$(dirname "$0")/.."
echo "== probe =="
timeout 90 python -c "import jax,numpy,jax.numpy as jnp; d=jax.devices(); numpy.asarray(jnp.arange(4)+1); print('tunnel alive:', d)" || { echo "tunnel dead"; exit 3; }
echo "== accel-gated tests =="
POS_TEST_ACCEL=1 timeout 1800 python -m pytest tests/test_pallas.py tests/test_fp_device.py tests/test_tower_device.py -q 2>&1 | tail -3 | tee ACCEL_TESTS_r03.txt
echo "== pallas evidence =="
timeout 1800 python scripts/pallas_tpu_evidence.py 2>/dev/null | tail -1
echo "== bench matrix =="
timeout 3600 python bench_all.py --record 3 2>&1 | tail -5
echo "== headline bench =="
timeout 1800 python bench.py
