"""Chaos fuzzing: random adversary x fault compositions under monitors.

Jepsen for the consensus sim: every episode composes a seeded random
subset of in-loop Byzantine strategies (``sim/adversary.py``) with a
seeded random ``FaultPlan`` (drops / duplicates / reorders / crash
windows), runs it through ``Simulation`` with the full monitor stack
(``sim/monitors.py``) attached, and demands ZERO violations. Every
decision — which strategies, which probabilities, which windows — is a
``stateless_unit`` hash of (seed, episode), so any episode reproduces
in isolation, in any order, on any backend.

A violating episode writes a **repro bundle**:

    <out>/bundle_ep<N>/
        config.json      episode composition (seeds, strategies, faults)
        checkpoint.bin   Simulation.checkpoint() at the episode START
        events.jsonl     telemetry event log of the violating run
        violations.json  the monitor verdicts
        shrink.json      greedy shrink log (when shrinking ran)
        config.min.json  minimized composition that still violates

Replay contract: ``--replay <bundle>`` rebuilds the run from
``Simulation.resume(checkpoint.bin)`` + the config's seeds and must
reproduce the same violations (monitor, kind, slot). The shrink pass
greedily drops strategies / fault kinds / crash windows while the
violation persists — the minimized config is strictly smaller.

``--doctor`` forces conflicting finalized checkpoints into two views at
a chosen slot (no real equivocation behind them): the
``AccountableSafetyMonitor`` must flag a ``protocol_violation`` (its
evidence set cannot reach 1/3) and a bundle must appear — the CI
negative proving the pipeline fails loudly.

Bundles are flushed INCREMENTALLY (ISSUE 10): each episode's config,
episode-start checkpoint and event stream land in
``<out>/inflight_ep<N>/`` before and during the run — a crashed or
killed episode leaves a replayable partial bundle instead of nothing
(resume it with ``--resume-bundle``); violating episodes are finalized
by renaming the inflight dir to ``bundle_ep<N>``, clean ones remove it.

Usage:
    python scripts/chaos_fuzz.py --episodes 20 --seed 7 --out chaos_out/
    python scripts/chaos_fuzz.py --doctor --out chaos_out/
    python scripts/chaos_fuzz.py --replay chaos_out/bundle_ep0/
    python scripts/chaos_fuzz.py --resume-bundle chaos_out/inflight_ep3/
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402
from pos_evolution_tpu.sim.faults import stateless_unit  # noqa: E402

SCHEMA = 1

# stateless_unit decision domains for episode composition
_D_FAULTS, _D_CRASH, _D_STRAT, _D_PARAM = 10, 11, 12, 13


# -- episode composition (pure function of seed + episode index) ---------------

def episode_config(seed: int, episode: int, n_validators: int = 64,
                   n_slots: int = 24, doctor: bool = False,
                   variant: str = "gasper") -> dict:
    """Derive one episode's full composition from (seed, episode) alone
    (the protocol variant is part of the composition: every episode
    replays under the variant that produced it)."""
    from pos_evolution_tpu.variants import VARIANTS
    u = lambda dom, k: stateless_unit(seed, dom, episode, k)  # noqa: E731
    cfg = {
        "schema": SCHEMA,
        "seed": int(seed),
        "episode": int(episode),
        "n_validators": int(n_validators),
        "n_slots": int(n_slots),
        "n_groups": 2,
        "variant": VARIANTS[variant]().describe(),
        "monitors": {"accountable_broadcast": True,
                     # a <1/3-Byzantine faulted run legitimately trails
                     # 2-3 epochs post-GST (see DESIGN.md §13); the bound
                     # flags a STALL, not slowness. The monitor arms at
                     # ceil(gst/epoch)+bound — with GST at n_slots/3 it is
                     # live inside episodes of >= ~7 epochs (the slow
                     # sweep); short smoke episodes end before it arms,
                     # and audit only safety/parity.
                     "liveness_bound_epochs": 4},
        "doctor": None,
    }
    from pos_evolution_tpu.config import cfg as active_cfg
    c = active_cfg()
    gst_slot = max(2, n_slots // 3)
    faults = {
        "seed": int(seed) * 1_000_003 + episode,
        "drop_p": round(u(_D_FAULTS, 0) * 0.15, 4),
        "duplicate_p": round(u(_D_FAULTS, 1) * 0.10, 4),
        "reorder_p": round(u(_D_FAULTS, 2) * 0.20, 4),
        "reorder_max_delay": 4.0,
        "gst": gst_slot * c.seconds_per_slot,
        "crashes": [],
    }
    if u(_D_CRASH, 0) < 0.5:
        crash = 2 + int(u(_D_CRASH, 1) * 4)
        rejoin = crash + 2 + int(u(_D_CRASH, 2) * 4)
        if rejoin < n_slots - 2:
            # only group 1 ever crashes: group 0 must stay alive as the
            # checkpoint-sync donor
            faults["crashes"].append(
                {"group": 1, "crash_slot": crash, "rejoin_slot": rejoin})
    cfg["faults"] = faults

    # controlled sets: disjoint, total < 1/3 of the validator set
    budget = n_validators // 3 - 1
    cursor = 0
    adversaries = []
    if u(_D_STRAT, 0) < 0.8:
        k = min(budget - cursor, 4 + int(u(_D_PARAM, 0) * 8))
        adversaries.append({
            "kind": "RandomByzantine",
            "controlled": list(range(cursor, cursor + k)),
            "seed": int(seed) * 7_919 + episode,
            "p_equivocate": round(0.15 + u(_D_PARAM, 1) * 0.3, 4),
            "p_stale_vote": round(u(_D_PARAM, 2) * 0.3, 4),
            "p_abstain": round(u(_D_PARAM, 3) * 0.3, 4),
            "p_double_propose": round(u(_D_PARAM, 4), 4),
        })
        cursor += k
    if u(_D_STRAT, 1) < 0.5:
        k = min(budget - cursor, 2 + int(u(_D_PARAM, 5) * 4))
        if k > 0:
            adversaries.append({
                "kind": "Equivocator",
                "controlled": list(range(cursor, cursor + k)),
                "slots": None,
            })
            cursor += k
    if u(_D_STRAT, 2) < 0.5:
        k = min(budget - cursor, 2 + int(u(_D_PARAM, 6) * 4))
        release = 3 + int(u(_D_PARAM, 7) * (n_slots - 6))
        if k > 0:
            adversaries.append({
                "kind": "Withholder",
                "controlled": list(range(cursor, cursor + k)),
                "fork_slot": max(2, release - 2),
                "release_slot": release,
                "release_phase": "before_attest",
                "vote_slots": [max(2, release - 2), max(2, release - 1)],
                "propose_on_release": False,
            })
            cursor += k
    cfg["adversaries"] = adversaries
    if doctor:
        # strictly after every crash window's rejoin (rejoin <= n_slots-3
        # by construction above): a rejoin checkpoint-syncs a fresh store
        # and variant view, which would silently ERASE an earlier forgery
        # and turn the negative into a false pass
        cfg["doctor"] = {"slot": n_slots - 2, "epoch": 1}
    return cfg


# -- config -> live objects ----------------------------------------------------

def build_adversaries(cfg: dict) -> list:
    from pos_evolution_tpu.sim.adversary import (
        Equivocator,
        RandomByzantine,
        Withholder,
    )
    out = []
    for a in cfg.get("adversaries", ()):
        kind = a["kind"]
        if kind == "RandomByzantine":
            out.append(RandomByzantine(
                controlled=a["controlled"], seed=a["seed"],
                p_equivocate=a["p_equivocate"],
                p_stale_vote=a["p_stale_vote"], p_abstain=a["p_abstain"],
                p_double_propose=a["p_double_propose"]))
        elif kind == "Equivocator":
            out.append(Equivocator(controlled=a["controlled"],
                                   slots=a.get("slots")))
        elif kind == "Withholder":
            out.append(Withholder(
                controlled=a["controlled"], fork_slot=a["fork_slot"],
                release_slot=a["release_slot"],
                release_phase=a["release_phase"],
                vote_slots=a["vote_slots"],
                propose_on_release=a["propose_on_release"]))
        else:
            raise ValueError(f"unknown strategy kind {kind!r}")
    return out


def build_schedule(cfg: dict):
    from pos_evolution_tpu.sim.faults import CrashWindow, FaultPlan
    from pos_evolution_tpu.sim.schedule import (
        honest_schedule,
        partition_schedule,
    )
    f = cfg["faults"]
    plan = FaultPlan(
        seed=f["seed"], drop_p=f["drop_p"], duplicate_p=f["duplicate_p"],
        reorder_p=f["reorder_p"], reorder_max_delay=f["reorder_max_delay"],
        gst=f["gst"],
        crashes=tuple(CrashWindow(w["group"], w["crash_slot"],
                                  w["rejoin_slot"])
                      for w in f["crashes"]))
    n = cfg["n_validators"]
    sched = (honest_schedule(n) if cfg["n_groups"] == 1
             else partition_schedule(n, cfg["n_groups"]))
    sched.faults = plan
    return sched


def build_monitors(cfg: dict) -> list:
    from pos_evolution_tpu.sim.monitors import (
        AccountableSafetyMonitor,
        FinalityLivenessMonitor,
        ForkChoiceParityMonitor,
        VariantSafetyMonitor,
    )
    m = cfg.get("monitors", {})
    return [AccountableSafetyMonitor(
                broadcast_evidence=m.get("accountable_broadcast", True)),
            FinalityLivenessMonitor(
                bound_epochs=m.get("liveness_bound_epochs", 6)),
            ForkChoiceParityMonitor(),
            VariantSafetyMonitor()]


def _doctor_stores(sim, epoch: int) -> None:
    """Force CONFLICTING finalized checkpoints into the first two views —
    no equivocation behind them, so the monitor's evidence set cannot
    reach 1/3 and it must report a protocol_violation (the CI negative:
    a safety break the slasher cannot account for fails loudly)."""
    from pos_evolution_tpu.specs.containers import Checkpoint
    sim.groups[0].store.finalized_checkpoint = Checkpoint(
        epoch=epoch, root=b"\x0d" * 32)
    sim.groups[1].store.finalized_checkpoint = Checkpoint(
        epoch=epoch, root=b"\x0e" * 32)


def run_episode(cfg: dict, events_path: str | None = None,
                resume_from: bytes | None = None,
                bundle_dir: str | None = None) -> dict:
    """Run one composed episode; returns violations + the episode-start
    checkpoint (the repro-bundle payload). ``resume_from`` replays from a
    bundle's checkpoint through ``Simulation.resume`` instead of
    constructing fresh — the replay contract.

    ``bundle_dir`` flushes the bundle INCREMENTALLY (ISSUE 10): the
    config and the episode-start checkpoint land on disk (atomically)
    BEFORE the first slot runs, and the event log streams there
    line-at-a-time — a crashed or killed episode still leaves a
    replayable artifact (``--resume-bundle``), instead of evaporating
    with the process."""
    from pos_evolution_tpu.sim.driver import Simulation
    from pos_evolution_tpu.telemetry import Telemetry
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
    from pos_evolution_tpu.variants import variant_from_config

    if bundle_dir is not None:
        os.makedirs(bundle_dir, exist_ok=True)
        atomic_write_bytes(
            os.path.join(bundle_dir, "config.json"),
            (json.dumps(cfg, indent=1, sort_keys=True) + "\n").encode())
        if events_path is None:
            events_path = os.path.join(bundle_dir, "events.jsonl")
    telemetry = (Telemetry.to_file(events_path)
                 if events_path is not None else None)
    adversaries = build_adversaries(cfg)
    monitors = build_monitors(cfg)
    schedule = build_schedule(cfg)
    variant = variant_from_config(cfg.get("variant"))
    try:
        if resume_from is not None:
            sim = Simulation.resume(resume_from, schedule=schedule,
                                    telemetry=telemetry,
                                    adversaries=adversaries,
                                    monitors=monitors, variant=variant)
            checkpoint = resume_from
        else:
            sim = Simulation(cfg["n_validators"], schedule=schedule,
                             telemetry=telemetry, adversaries=adversaries,
                             monitors=monitors, variant=variant)
            checkpoint = sim.checkpoint()
        if bundle_dir is not None:
            atomic_write_bytes(os.path.join(bundle_dir, "checkpoint.bin"),
                               checkpoint)
        doctor = cfg.get("doctor")
        while sim.slot <= cfg["n_slots"]:
            sim.run_slot()
            if doctor is not None and sim.slot - 1 == doctor["slot"]:
                # variant-level forgery first (conflicting variant
                # finality / fast confirmations — the per-variant
                # negative); variants with no forgeable surface (Gasper,
                # RLMD) fall back to the FFG store doctor, which the
                # AccountableSafetyMonitor must catch under EVERY variant
                if not sim.variant.doctor(sim, doctor["slot"]):
                    _doctor_stores(sim, doctor["epoch"])
    finally:
        # a crashed episode must not leak the JSONL handle (the partial
        # log itself is the caller's to keep or remove)
        if telemetry is not None:
            telemetry.close()
    return {
        "violations": sim.monitor_violations,
        "finalized": [sim.finalized_epoch(g)
                      for g in range(len(sim.groups))],
        "checkpoint": checkpoint,
    }


# -- shrink --------------------------------------------------------------------

def _components(cfg: dict) -> list[tuple[str, object]]:
    """Every independently removable piece of a composition."""
    out = [("adversary", i) for i in range(len(cfg["adversaries"]))]
    out += [("fault", k) for k in ("drop_p", "duplicate_p", "reorder_p")
            if cfg["faults"][k] > 0]
    out += [("crash", i) for i in range(len(cfg["faults"]["crashes"]))]
    return out


def _without(cfg: dict, component: tuple[str, object]) -> dict:
    import copy
    out = copy.deepcopy(cfg)
    kind, key = component
    if kind == "adversary":
        del out["adversaries"][key]
    elif kind == "fault":
        out["faults"][key] = 0.0
    elif kind == "crash":
        del out["faults"]["crashes"][key]
    return out


def _same_violation(violations: list[dict], reference: dict) -> bool:
    return any(v["monitor"] == reference["monitor"]
               and v["kind"] == reference["kind"] for v in violations)


def shrink(cfg: dict, reference_violation: dict) -> tuple[dict, list[dict]]:
    """Greedy delta-debugging: drop one component at a time, keep the
    removal whenever the reference violation still reproduces. Each
    accepted step strictly reduces the composition; the loop restarts
    after every acceptance so index-shifting removals stay sound."""
    log = []
    current = cfg
    progress = True
    while progress:
        progress = False
        for comp in _components(current):
            candidate = _without(current, comp)
            result = run_episode(candidate)
            ok = _same_violation(result["violations"], reference_violation)
            log.append({"removed": list(comp), "still_violates": ok,
                        "n_components": len(_components(candidate))})
            if ok:
                current = candidate
                progress = True
                break
    return current, log


# -- bundles -------------------------------------------------------------------

def write_bundle(out_dir: str, cfg: dict, result: dict,
                 events_src: str | None = None, do_shrink: bool = True,
                 inflight_dir: str | None = None) -> str:
    """Finalize a violating episode's bundle. With ``inflight_dir`` the
    incrementally-flushed directory (config + checkpoint + streamed
    events already inside) is renamed into place; otherwise the legacy
    shape writes everything here."""
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
    bundle = os.path.join(out_dir, f"bundle_ep{cfg['episode']}")
    if inflight_dir is not None and os.path.isdir(inflight_dir):
        if os.path.isdir(bundle):
            shutil.rmtree(bundle)
        os.replace(inflight_dir, bundle)
    os.makedirs(bundle, exist_ok=True)
    atomic_write_bytes(
        os.path.join(bundle, "config.json"),
        (json.dumps(cfg, indent=1, sort_keys=True) + "\n").encode())
    if not os.path.exists(os.path.join(bundle, "checkpoint.bin")):
        atomic_write_bytes(os.path.join(bundle, "checkpoint.bin"),
                           result["checkpoint"])
    atomic_write_bytes(
        os.path.join(bundle, "violations.json"),
        (json.dumps(result["violations"], indent=1, sort_keys=True)
         + "\n").encode())
    if events_src and os.path.exists(events_src):
        shutil.move(events_src, os.path.join(bundle, "events.jsonl"))
    if do_shrink and result["violations"]:
        minimized, log = shrink(cfg, result["violations"][0])
        with open(os.path.join(bundle, "shrink.json"), "w") as fh:
            json.dump({"steps": log,
                       "before": len(_components(cfg)),
                       "after": len(_components(minimized))}, fh, indent=1)
            fh.write("\n")
        with open(os.path.join(bundle, "config.min.json"), "w") as fh:
            json.dump(minimized, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return bundle


def replay_bundle(bundle: str) -> dict:
    """Re-run a bundle from its checkpoint via ``Simulation.resume`` and
    compare the violations against the recorded ones.

    Also accepts a PARTIAL (inflight) bundle — the incremental flush of
    a crashed episode, which has config + checkpoint but no
    ``violations.json`` yet. The episode then runs to completion and
    ``match`` is None (there is no recorded verdict to compare): the
    ``--resume-bundle`` contract."""
    with open(os.path.join(bundle, "config.json")) as fh:
        cfg = json.load(fh)
    cpath = os.path.join(bundle, "checkpoint.bin")
    checkpoint = None
    if os.path.exists(cpath):
        with open(cpath, "rb") as fh:
            checkpoint = fh.read()
    # else: the episode died BEFORE the start checkpoint flushed. For a
    # non-resumed episode the start checkpoint is a pure function of the
    # config (a freshly constructed Simulation), so running from scratch
    # reproduces the identical episode.
    vpath = os.path.join(bundle, "violations.json")
    recorded = None
    if os.path.exists(vpath):
        with open(vpath) as fh:
            recorded = json.load(fh)
    result = run_episode(cfg, resume_from=checkpoint)
    key = lambda v: (v["slot"], v["monitor"], v["kind"])  # noqa: E731
    match = (None if recorded is None else
             sorted(map(key, result["violations"]))
             == sorted(map(key, recorded)))
    return {"match": match, "replayed": result["violations"],
            "recorded": recorded,
            "finalized": result["finalized"]}


# -- CLI -----------------------------------------------------------------------

def fuzz(episodes: int, seed: int, n_validators: int, n_slots: int,
         out_dir: str, doctor: bool = False, do_shrink: bool = True,
         step_timeout: float | None = None, episode_indices=None,
         variant: str = "gasper") -> dict:
    from pos_evolution_tpu.utils.watchdog import Watchdog
    os.makedirs(out_dir, exist_ok=True)
    wd = Watchdog(path=os.path.join(out_dir, "chaos_partial.json"),
                  tag="chaos_fuzz", timeout_s=step_timeout)
    summary = {"episodes": 0, "violating": 0, "bundles": [],
               "incidents": 0, "variant": variant, "accountable": 0}
    indices = (range(episodes) if episode_indices is None
               else episode_indices)
    for ep in indices:
        cfg = episode_config(seed, ep, n_validators, n_slots, doctor=doctor,
                             variant=variant)
        # incremental flush (ISSUE 10): config + start checkpoint +
        # streamed events land in an inflight dir BEFORE the run, so a
        # crashed/killed episode leaves a --resume-bundle artifact
        inflight = os.path.join(out_dir, f"inflight_ep{ep}")
        result = wd.step(f"episode_{ep}", run_episode, cfg,
                         bundle_dir=inflight)
        summary["episodes"] += 1
        if result is None:         # watchdog incident (timeout / crash)
            summary["incidents"] += 1
            summary.setdefault("inflight", []).append(inflight)
            print(f"episode {ep}: DIED mid-run — partial bundle kept at "
                  f"{inflight} (replay with --resume-bundle)")
            continue
        # An accountable_fault is the protocol SURVIVING as designed —
        # the adversary bought a break by burning >= 1/3 of the relevant
        # quorum's stake into slashing evidence (committee-subsampled
        # SSF can be double-finalized per slot at exactly that price).
        # It is explained, bundled for audit, and does NOT fail the
        # sweep; anything else is an unexplained violation and does.
        unexplained = [v for v in result["violations"]
                       if v.get("kind") != "accountable_fault"]
        if result["violations"]:
            bundle = write_bundle(out_dir, cfg, result,
                                  do_shrink=do_shrink and bool(unexplained),
                                  inflight_dir=inflight)
            summary["bundles"].append(bundle)
        if unexplained:
            summary["violating"] += 1
            print(f"episode {ep}: {len(unexplained)} unexplained "
                  f"violation(s) -> {bundle}")
        elif result["violations"]:
            summary["accountable"] += 1
            print(f"episode {ep}: {len(result['violations'])} accountable "
                  f"fault(s), evidence bundled -> {bundle}")
        else:
            shutil.rmtree(inflight, ignore_errors=True)
            print(f"episode {ep}: clean "
                  f"(finalized={result['finalized']})")
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="chaos fuzz: adversary x fault compositions under "
                    "safety/liveness monitors")
    ap.add_argument("--episodes", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--slots", type=int, default=24)
    ap.add_argument("--out", default="chaos_out")
    ap.add_argument("--doctor", action="store_true",
                    help="force conflicting finalized checkpoints (the "
                         "monitor must trip; CI negative)")
    ap.add_argument("--no-shrink", action="store_true")
    ap.add_argument("--step-timeout", type=float, default=None,
                    help="watchdog per-episode timeout (seconds)")
    ap.add_argument("--variant", default="gasper",
                    choices=("gasper", "goldfish", "rlmd", "ssf", "all"),
                    help="protocol variant to fuzz under (DESIGN.md §16); "
                         "'all' sweeps every variant into per-variant "
                         "subdirectories")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="replay a repro bundle and verify the violation")
    ap.add_argument("--resume-bundle", metavar="BUNDLE",
                    help="resume a PARTIAL (inflight) bundle left by a "
                         "crashed episode: run it to completion from its "
                         "flushed config + checkpoint; verifies the "
                         "violations only when the bundle recorded some")
    args = ap.parse_args(argv)

    with use_config(minimal_config()):
        if args.replay or args.resume_bundle:
            out = replay_bundle(args.replay or args.resume_bundle)
            print(json.dumps({"match": out["match"],
                              "replayed": out["replayed"],
                              "finalized": out["finalized"]}, indent=1))
            if args.replay:
                return 0 if out["match"] else 1
            # resume mode: completing the episode IS the success
            # criterion; a recorded verdict, when present, must agree
            return 0 if out["match"] in (True, None) else 1
        variants = (("gasper", "goldfish", "rlmd", "ssf")
                    if args.variant == "all" else (args.variant,))
        rc = 0
        for name in variants:
            out_dir = (args.out if len(variants) == 1
                       else os.path.join(args.out, name))
            summary = fuzz(args.episodes, args.seed, args.validators,
                           args.slots, out_dir, doctor=args.doctor,
                           do_shrink=not args.no_shrink,
                           step_timeout=args.step_timeout, variant=name)
            print(json.dumps({k: summary[k] for k in
                              ("variant", "episodes", "violating",
                               "accountable", "incidents")}, indent=1))
            if args.doctor:
                # the doctored run MUST trip a safety monitor, per variant
                rc |= 0 if summary["violating"] > 0 else 1
            else:
                # an episode that hung or crashed verified nothing — a
                # clean verdict requires every episode to have actually run
                rc |= 1 if (summary["violating"]
                            or summary["incidents"]) else 0
        return rc


if __name__ == "__main__":
    sys.exit(main())
