"""Chaos fuzzing: random adversary x fault compositions under monitors.

Jepsen for the consensus sim: every episode composes a seeded random
subset of in-loop Byzantine strategies (``sim/adversary.py``) with a
seeded random ``FaultPlan`` (drops / duplicates / reorders / crash
windows), runs it through ``Simulation`` with the full monitor stack
(``sim/monitors.py``) attached, and demands ZERO violations. Every
decision — which strategies, which probabilities, which windows — is a
``stateless_unit`` hash of (seed, episode), so any episode reproduces
in isolation, in any order, on any backend.

A violating episode writes a **repro bundle**:

    <out>/bundle_ep<N>/
        config.json      episode composition (seeds, strategies, faults)
        checkpoint.bin   Simulation.checkpoint() at the episode START
        events.jsonl     telemetry event log of the violating run
        violations.json  the monitor verdicts
        shrink.json      greedy shrink log (when shrinking ran)
        config.min.json  minimized composition that still violates

Replay contract: ``--replay <bundle>`` rebuilds the run from
``Simulation.resume(checkpoint.bin)`` + the config's seeds and must
reproduce the same violations (monitor, kind, slot). The shrink pass
greedily drops strategies / fault kinds / crash windows while the
violation persists — the minimized config is strictly smaller.

``--doctor`` forces conflicting finalized checkpoints into two views at
a chosen slot (no real equivocation behind them): the
``AccountableSafetyMonitor`` must flag a ``protocol_violation`` (its
evidence set cannot reach 1/3) and a bundle must appear — the CI
negative proving the pipeline fails loudly.

Bundles are flushed INCREMENTALLY (ISSUE 10): each episode's config,
episode-start checkpoint and event stream land in
``<out>/inflight_ep<N>/`` before and during the run — a crashed or
killed episode leaves a replayable partial bundle instead of nothing
(resume it with ``--resume-bundle``); violating episodes are finalized
by renaming the inflight dir to ``bundle_ep<N>``, clean ones remove it.

Usage:
    python scripts/chaos_fuzz.py --episodes 20 --seed 7 --out chaos_out/
    python scripts/chaos_fuzz.py --doctor --out chaos_out/
    python scripts/chaos_fuzz.py --replay chaos_out/bundle_ep0/
    python scripts/chaos_fuzz.py --resume-bundle chaos_out/inflight_ep3/
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402
from pos_evolution_tpu.sim.faults import stateless_unit  # noqa: E402

SCHEMA = 1

# stateless_unit decision domains for episode composition
_D_FAULTS, _D_CRASH, _D_STRAT, _D_PARAM = 10, 11, 12, 13
_D_DENSE, _D_SERVE = 14, 15


# -- episode composition (pure function of seed + episode index) ---------------

def episode_config(seed: int, episode: int, n_validators: int = 64,
                   n_slots: int = 24, doctor: bool = False,
                   variant: str = "gasper", serve: bool = False,
                   scheme: str = "merkle") -> dict:
    """Derive one episode's full composition from (seed, episode) alone
    (the protocol variant is part of the composition: every episode
    replays under the variant that produced it)."""
    from pos_evolution_tpu.variants import VARIANTS
    u = lambda dom, k: stateless_unit(seed, dom, episode, k)  # noqa: E731
    cfg = {
        "schema": SCHEMA,
        "seed": int(seed),
        "episode": int(episode),
        "n_validators": int(n_validators),
        "n_slots": int(n_slots),
        "n_groups": 2,
        "variant": VARIANTS[variant]().describe(),
        # cell-commitment scheme for the serve composition's DAS engine
        # ("merkle"/"kzg") — part of the replayable composition, and of
        # the checkpoint's engine.describe() fingerprint, so a resume
        # under the other scheme refuses loudly
        "scheme": str(scheme),
        "monitors": {"accountable_broadcast": True,
                     # a <1/3-Byzantine faulted run legitimately trails
                     # 2-3 epochs post-GST (see DESIGN.md §13); the bound
                     # flags a STALL, not slowness. The monitor arms at
                     # ceil(gst/epoch)+bound — with GST at n_slots/3 it is
                     # live inside episodes of >= ~7 epochs (the slow
                     # sweep); short smoke episodes end before it arms,
                     # and audit only safety/parity.
                     "liveness_bound_epochs": 4},
        "doctor": None,
    }
    from pos_evolution_tpu.config import cfg as active_cfg
    c = active_cfg()
    gst_slot = max(2, n_slots // 3)
    faults = {
        "seed": int(seed) * 1_000_003 + episode,
        "drop_p": round(u(_D_FAULTS, 0) * 0.15, 4),
        "duplicate_p": round(u(_D_FAULTS, 1) * 0.10, 4),
        "reorder_p": round(u(_D_FAULTS, 2) * 0.20, 4),
        "reorder_max_delay": 4.0,
        "gst": gst_slot * c.seconds_per_slot,
        "crashes": [],
    }
    if u(_D_CRASH, 0) < 0.5:
        crash = 2 + int(u(_D_CRASH, 1) * 4)
        rejoin = crash + 2 + int(u(_D_CRASH, 2) * 4)
        if rejoin < n_slots - 2:
            # only group 1 ever crashes: group 0 must stay alive as the
            # checkpoint-sync donor
            faults["crashes"].append(
                {"group": 1, "crash_slot": crash, "rejoin_slot": rejoin})
    cfg["faults"] = faults

    # controlled sets: disjoint, total < 1/3 of the validator set
    budget = n_validators // 3 - 1
    cursor = 0
    adversaries = []
    if u(_D_STRAT, 0) < 0.8:
        k = min(budget - cursor, 4 + int(u(_D_PARAM, 0) * 8))
        adversaries.append({
            "kind": "RandomByzantine",
            "controlled": list(range(cursor, cursor + k)),
            "seed": int(seed) * 7_919 + episode,
            "p_equivocate": round(0.15 + u(_D_PARAM, 1) * 0.3, 4),
            "p_stale_vote": round(u(_D_PARAM, 2) * 0.3, 4),
            "p_abstain": round(u(_D_PARAM, 3) * 0.3, 4),
            "p_double_propose": round(u(_D_PARAM, 4), 4),
        })
        cursor += k
    if u(_D_STRAT, 1) < 0.5:
        k = min(budget - cursor, 2 + int(u(_D_PARAM, 5) * 4))
        if k > 0:
            adversaries.append({
                "kind": "Equivocator",
                "controlled": list(range(cursor, cursor + k)),
                "slots": None,
            })
            cursor += k
    if u(_D_STRAT, 2) < 0.5:
        k = min(budget - cursor, 2 + int(u(_D_PARAM, 6) * 4))
        release = 3 + int(u(_D_PARAM, 7) * (n_slots - 6))
        if k > 0:
            adversaries.append({
                "kind": "Withholder",
                "controlled": list(range(cursor, cursor + k)),
                "fork_slot": max(2, release - 2),
                "release_slot": release,
                "release_phase": "before_attest",
                "vote_slots": [max(2, release - 2), max(2, release - 1)],
                "propose_on_release": False,
            })
            cursor += k
    cfg["adversaries"] = adversaries
    if serve:
        # serve x chaos composition (ISSUE 13 satellite / ROADMAP item 3
        # remainder): the episode carries a live socket front + an
        # open-loop load generator with REMOTE target discovery, so
        # adversarial chain conditions and serving overload compose;
        # the SLO/goodput outcome joins the episode verdict
        patterns = ("uniform", "bursty", "hotspot")
        cfg["serve"] = {
            "arrivals": 800 + int(u(_D_SERVE, 0) * 800),
            "rate": 300.0 + round(u(_D_SERVE, 1) * 300.0, 1),
            "pattern": patterns[int(u(_D_SERVE, 2) * len(patterns))
                                % len(patterns)],
            "bulk_fraction": 0.6,
            "workers": 2,
            "slo_ms": 250.0,
        }
    if doctor:
        # strictly after every crash window's rejoin (rejoin <= n_slots-3
        # by construction above): a rejoin checkpoint-syncs a fresh store
        # and variant view, which would silently ERASE an earlier forgery
        # and turn the negative into a false pass
        cfg["doctor"] = {"slot": n_slots - 2, "epoch": 1}
    return cfg


# -- config -> live objects ----------------------------------------------------

def build_adversaries(cfg: dict) -> list:
    from pos_evolution_tpu.sim.adversary import (
        Equivocator,
        RandomByzantine,
        Withholder,
    )
    out = []
    for a in cfg.get("adversaries", ()):
        kind = a["kind"]
        if kind == "RandomByzantine":
            out.append(RandomByzantine(
                controlled=a["controlled"], seed=a["seed"],
                p_equivocate=a["p_equivocate"],
                p_stale_vote=a["p_stale_vote"], p_abstain=a["p_abstain"],
                p_double_propose=a["p_double_propose"]))
        elif kind == "Equivocator":
            out.append(Equivocator(controlled=a["controlled"],
                                   slots=a.get("slots")))
        elif kind == "Withholder":
            out.append(Withholder(
                controlled=a["controlled"], fork_slot=a["fork_slot"],
                release_slot=a["release_slot"],
                release_phase=a["release_phase"],
                vote_slots=a["vote_slots"],
                propose_on_release=a["propose_on_release"]))
        else:
            raise ValueError(f"unknown strategy kind {kind!r}")
    return out


def build_schedule(cfg: dict):
    from pos_evolution_tpu.sim.faults import CrashWindow, FaultPlan
    from pos_evolution_tpu.sim.schedule import (
        honest_schedule,
        partition_schedule,
    )
    f = cfg["faults"]
    plan = FaultPlan(
        seed=f["seed"], drop_p=f["drop_p"], duplicate_p=f["duplicate_p"],
        reorder_p=f["reorder_p"], reorder_max_delay=f["reorder_max_delay"],
        gst=f["gst"],
        crashes=tuple(CrashWindow(w["group"], w["crash_slot"],
                                  w["rejoin_slot"])
                      for w in f["crashes"]))
    n = cfg["n_validators"]
    sched = (honest_schedule(n) if cfg["n_groups"] == 1
             else partition_schedule(n, cfg["n_groups"]))
    sched.faults = plan
    return sched


def build_monitors(cfg: dict) -> list:
    from pos_evolution_tpu.sim.monitors import (
        AccountableSafetyMonitor,
        FinalityLivenessMonitor,
        ForkChoiceParityMonitor,
        VariantSafetyMonitor,
    )
    m = cfg.get("monitors", {})
    return [AccountableSafetyMonitor(
                broadcast_evidence=m.get("accountable_broadcast", True)),
            FinalityLivenessMonitor(
                bound_epochs=m.get("liveness_bound_epochs", 6)),
            ForkChoiceParityMonitor(),
            VariantSafetyMonitor()]


def _doctor_stores(sim, epoch: int) -> None:
    """Force CONFLICTING finalized checkpoints into the first two views —
    no equivocation behind them, so the monitor's evidence set cannot
    reach 1/3 and it must report a protocol_violation (the CI negative:
    a safety break the slasher cannot account for fails loudly)."""
    from pos_evolution_tpu.specs.containers import Checkpoint
    sim.groups[0].store.finalized_checkpoint = Checkpoint(
        epoch=epoch, root=b"\x0d" * 32)
    sim.groups[1].store.finalized_checkpoint = Checkpoint(
        epoch=epoch, root=b"\x0e" * 32)


def run_episode(cfg: dict, events_path: str | None = None,
                resume_from: bytes | None = None,
                bundle_dir: str | None = None) -> dict:
    """Run one composed episode; returns violations + the episode-start
    checkpoint (the repro-bundle payload). ``resume_from`` replays from a
    bundle's checkpoint through ``Simulation.resume`` instead of
    constructing fresh — the replay contract.

    ``bundle_dir`` flushes the bundle INCREMENTALLY (ISSUE 10): the
    config and the episode-start checkpoint land on disk (atomically)
    BEFORE the first slot runs, and the event log streams there
    line-at-a-time — a crashed or killed episode still leaves a
    replayable artifact (``--resume-bundle``), instead of evaporating
    with the process."""
    from pos_evolution_tpu.sim.driver import Simulation
    from pos_evolution_tpu.telemetry import Telemetry
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
    from pos_evolution_tpu.variants import variant_from_config

    if bundle_dir is not None:
        os.makedirs(bundle_dir, exist_ok=True)
        atomic_write_bytes(
            os.path.join(bundle_dir, "config.json"),
            (json.dumps(cfg, indent=1, sort_keys=True) + "\n").encode())
        if events_path is None:
            events_path = os.path.join(bundle_dir, "events.jsonl")
    telemetry = (Telemetry.to_file(events_path)
                 if events_path is not None else None)
    adversaries = build_adversaries(cfg)
    monitors = build_monitors(cfg)
    schedule = build_schedule(cfg)
    variant = variant_from_config(cfg.get("variant"))
    serve_cfg = cfg.get("serve") if resume_from is None else None
    serve_state = front = loader = None
    serve_out = None
    if serve_cfg is not None:
        from pos_evolution_tpu.serve import ServingState
        serve_state = ServingState()
    try:
        if resume_from is not None:
            sim = Simulation.resume(resume_from, schedule=schedule,
                                    telemetry=telemetry,
                                    adversaries=adversaries,
                                    monitors=monitors, variant=variant)
            checkpoint = resume_from
        else:
            sim = Simulation(cfg["n_validators"], schedule=schedule,
                             telemetry=telemetry, adversaries=adversaries,
                             monitors=monitors, variant=variant,
                             das=(cfg.get("scheme", "merkle")
                                  if serve_cfg else None),
                             serve=serve_state)
            checkpoint = sim.checkpoint()
        if bundle_dir is not None:
            atomic_write_bytes(os.path.join(bundle_dir, "checkpoint.bin"),
                               checkpoint)
        if serve_cfg is not None:
            front, loader = _start_serve(sim, serve_state, serve_cfg,
                                         telemetry)
        doctor = cfg.get("doctor")
        while sim.slot <= cfg["n_slots"]:
            sim.run_slot()
            if doctor is not None and sim.slot - 1 == doctor["slot"]:
                # variant-level forgery first (conflicting variant
                # finality / fast confirmations — the per-variant
                # negative); variants with no forgeable surface (Gasper,
                # RLMD) fall back to the FFG store doctor, which the
                # AccountableSafetyMonitor must catch under EVERY variant
                if not sim.variant.doctor(sim, doctor["slot"]):
                    _doctor_stores(sim, doctor["epoch"])
        if front is not None:
            serve_out = _finish_serve(front, loader, serve_cfg, telemetry)
    finally:
        if front is not None:
            front.stop()
        # a crashed episode must not leak the JSONL handle (the partial
        # log itself is the caller's to keep or remove)
        if telemetry is not None:
            telemetry.close()
    out = {
        "violations": sim.monitor_violations,
        "finalized": [sim.finalized_epoch(g)
                      for g in range(len(sim.groups))],
        "checkpoint": checkpoint,
    }
    if serve_out is not None:
        out["serve"] = serve_out
    return out


def _start_serve(sim, serve_state, serve_cfg, telemetry):
    """Attach the socket front + remote-discovery open-loop loadgen to a
    running episode: the generator learns its targets from the front's
    own head/finality RPCs (``serve/loadgen.discover_targets``) — it
    drives a front it did not build, under whatever chain conditions the
    episode's adversaries and faults produce."""
    import threading

    from pos_evolution_tpu.serve import LoadGenerator, ServeFront
    from pos_evolution_tpu.telemetry.registry import MetricsRegistry
    front = ServeFront(serve_state, scheme=sim.das.scheme,
                       registry=MetricsRegistry(),
                       workers=serve_cfg.get("workers", 2))
    addr = front.start()
    lg = LoadGenerator(
        addr, serve_cfg["arrivals"], serve_cfg["rate"],
        pattern=serve_cfg.get("pattern", "uniform"),
        seed=serve_cfg.get("seed", 0),
        bulk_fraction=serve_cfg.get("bulk_fraction", 0.6),
        client_threads=24, discover=True)
    thread = threading.Thread(target=lg.run, name="chaos-serve-load",
                              daemon=True)
    if telemetry is not None:
        telemetry.bus.emit("serve_attach", workers=front.workers,
                           pattern=lg.pattern, arrivals=lg.n,
                           rate=lg.rate, chaos="episode")
    thread.start()
    return front, (lg, thread)


def _finish_serve(front, loader, serve_cfg, telemetry):
    """Join the loadgen, collect the SLO/goodput verdict for the
    episode. Wrong proofs are a hard failure; latency/goodput are
    recorded (CI wall-clock is noisy — the SLO verdict is part of the
    episode record, the verification count is the gate)."""
    lg, thread = loader
    thread.join(timeout=120.0)
    load = lg.summary()
    server = front.summary()
    inter = load["tiers"]["interactive"]
    slo_ms = serve_cfg.get("slo_ms", 250.0)
    verdict = {
        "arrivals": load["arrivals"],
        "interactive_goodput_pct": inter["goodput_pct"],
        "bulk_goodput_pct": load["tiers"]["bulk"]["goodput_pct"],
        "interactive_p99_ms": inter["p99_ms"],
        "slo_ms": slo_ms,
        "slo_ok": (inter["p99_ms"] is not None
                   and inter["p99_ms"] <= slo_ms),
        "verified_proofs": load["verified_proofs"],
        "verify_failures": load["verify_failures"],
        "remote_discovery": load.get("remote_discovery"),
        "shed_by_reason": server.get("shed_by_reason"),
    }
    if telemetry is not None:
        telemetry.bus.emit("serve_summary", server=server, load=load,
                           slo_ms=slo_ms, slo_ok=verdict["slo_ok"])
    return verdict


# -- dense episodes (ISSUE 13: chaos at mainnet scale) -------------------------

_DENSE_SCENARIOS = ("equivocator_faulted", "withholder", "splitvoter",
                    "balancer")
_DENSE_VARIANTS = ("gasper", "goldfish", "rlmd", "ssf")
_DENSE_WORKLOADS = ("none", "das-merkle", "das-kzg")


def _dense_workload(choice: str, seed: int, episode: int) -> dict:
    """Rider configs for one workload draw (ISSUE 20): the DAS sidecar
    pipeline (merkle or kzg cell commitments, built/verified/sampled per
    dense proposal) plus the dense light-client population following the
    active variant's own decision rule."""
    if choice == "none":
        return {"choice": "none", "riders": []}
    scheme = "kzg" if choice.endswith("kzg") else "merkle"
    return {"choice": choice, "riders": [
        # the erasure-reconstruction leg is the expensive half (kzg
        # additionally runs the Fr/NTT engine), so it thins to every
        # N-th proposal; commitments + sampling run on every one
        {"kind": "das", "scheme": scheme, "n_blobs": 1, "n_clients": 16,
         "samples_per_client": 2, "seed": int(seed) * 31 + episode,
         "verify_every": 4 if scheme == "kzg" else 2},
        {"kind": "lightclient", "n_clients": 16,
         "seed": int(seed) * 17 + episode},
    ]}


def episode_config_dense(seed: int, episode: int, n_validators: int = 576,
                         n_epochs: int = 4, slots_per_epoch: int = 8,
                         mesh: str | None = None, doctor: bool = False,
                         scenario: str | None = None,
                         scheme: str = "merkle",
                         variant: str | None = None,
                         workload: str | None = None) -> dict:
    """One DENSE episode's composition from (seed, episode) alone: a
    protocol variant, a scenario (which vectorized strategy + network
    shape), a workload draw (DAS sidecars + light clients, or none), a
    seeded ``DenseFaultPlan``, and the expectation the verdict is judged
    against — the full protocol x attack x workload product (ISSUE 20).
    ``n_validators`` should divide by 24 (mesh divisibility x the
    exactly-1/3 SplitVoter split)."""
    u = lambda dom, k: stateless_unit(seed, dom, episode, k)  # noqa: E731
    n = int(n_validators)
    n_slots = n_epochs * slots_per_epoch
    if variant is None:
        variant = _DENSE_VARIANTS[min(int(u(_D_DENSE, 7) * 4), 3)]
    if workload is None:
        workload = _DENSE_WORKLOADS[min(int(u(_D_DENSE, 8) * 3), 2)]
    if scenario is None:
        # the balancer's table-balancing model assumes committee duty;
        # the full-participation variants swap it for the ex-ante cell
        opts = (_DENSE_SCENARIOS + ("exante",) if variant == "gasper"
                else ("equivocator_faulted", "withholder", "splitvoter",
                      "exante"))
        r = u(_D_DENSE, 0)
        scenario = opts[min(int(r * len(opts)), len(opts) - 1)]
    if doctor:
        scenario = "doctor"
    two_view = scenario in ("splitvoter", "balancer", "doctor")
    faults: dict = {"seed": int(seed) * 1_000_003 + episode}
    adversaries: list = []
    expect: dict = {"clean": True}
    if scenario == "equivocator_faulted":
        gst = max(2, n_slots // 3)
        faults.update(drop_p=round(u(_D_DENSE, 1) * 0.12, 4),
                      delay_p=round(u(_D_DENSE, 2) * 0.10, 4),
                      gst_slot=gst)
        if u(_D_DENSE, 3) < 0.5:
            lo = int(u(_D_DENSE, 4) * (n // 2))
            hi = min(n, lo + max(n // 16, 1))
            faults["crashes"] = [{"lo": lo, "hi": hi, "crash_slot": 2,
                                  "rejoin_slot": 2 + slots_per_epoch}]
        k = max(n // 16, 4) + int(u(_D_DENSE, 5) * (n // 8))
        adversaries.append({"kind": "DenseEquivocator",
                            "controlled": [[0, min(k, n // 3 - 1)]],
                            "p_fork": round(0.3 + u(_D_DENSE, 6) * 0.4, 4),
                            "seed": int(seed) * 7_919 + episode})
    elif scenario == "withholder":
        fork = 2 + int(u(_D_DENSE, 1) * slots_per_epoch)
        span = 2 + int(u(_D_DENSE, 2) * 3)
        k = max(n // 16, 4) + int(u(_D_DENSE, 3) * (n // 8))
        adversaries.append({"kind": "DenseWithholder",
                            "controlled": [[0, min(k, n // 3 - 1)]],
                            "fork_slot": fork,
                            "release_slot": min(fork + span, n_slots - 2)})
    elif scenario == "splitvoter":
        faults["partition"] = "full"
        adversaries.append({"kind": "DenseSplitVoter",
                            "controlled": [[0, n // 3]]})
        # the attack MUST reproduce: double finality, accountable,
        # evidence pinned at exactly 1/3 of stake
        expect = {"clean": False, "accountable_double_finality": True,
                  "exact_third": True}
        if variant == "ssf":
            # the per-slot gadget must ALSO double-finalize accountably
            # (accountable_double_finality from the variant monitor)
            expect["ssf_double_finality"] = True
        elif variant in ("goldfish", "rlmd"):
            # kappa-deep confirmation diverges UNACCOUNTABLY under the
            # partition — the paper's motivation for SSF, named by the
            # variant monitor
            expect["confirmation_divergence"] = True
    elif scenario == "balancer":
        faults["partition"] = "delay"
        # strictly below 1/3 so the liveness monitor stays armed
        adversaries.append({"kind": "DenseBalancer",
                            "controlled": [[0, (n * 5) // 16]]})
        expect = {"clean": False, "liveness_stall": True}
    elif scenario == "exante":
        # committee-targeted multi-slot ex-ante reorg: the banked
        # margin is span*f - (span-1)*(1-f) committees, so f=0.45 keeps
        # the outcome several sigma past committee-shuffle variance
        # even at smoke sizes. A pure fork-choice attack — no monitor
        # fires either way; full-participation variants must defend
        # structurally (latest-message collapse on the revealed chain).
        adversaries.append({"kind": "DenseExAnteReorg",
                            "controlled": [[0, int(n * 0.45)]],
                            "fork_slot": 2, "span": 2})
        expect = ({"clean": True} if variant == "gasper"
                  else {"clean": True, "exante_defended": True})
    else:   # doctor: honest partitioned pair + forged double finality
        faults["partition"] = "full"
        expect = {"clean": False, "protocol_violation": True}
        if variant in ("goldfish", "rlmd"):
            # the honest halves legitimately confirm diverging chains
            # under the partition — explained, not required
            expect["confirmation_divergence_ok"] = True
    wl = _dense_workload(workload, seed, episode)
    return {
        "schema": SCHEMA, "dense": True,
        "seed": int(seed), "episode": int(episode),
        "n_validators": n, "n_epochs": int(n_epochs),
        "slots_per_epoch": int(slots_per_epoch),
        "n_groups": 2 if two_view else 1,
        "mesh": mesh, "scenario": scenario,
        # the protocol variant is part of the composition (ISSUE 20):
        # every episode replays under the variant that produced it, and
        # the checkpoint's variant fingerprint refuses cross-variant
        # resume. Ex-ante cells run pre-boost (the boost defense is a
        # pinned variant_matrix cell, not a fuzz draw).
        "variant": ({"kind": variant, "boost_percent": 0}
                    if scenario == "exante" else {"kind": variant}),
        # workload draw: rider configs ride the composition AND the
        # checkpoint, so a replay rebuilds byte-identical sidecars
        "workload": wl,
        "scheme": (wl["riders"][0]["scheme"] if wl["riders"]
                   else str(scheme)),
        "faults": faults, "adversaries": adversaries,
        "monitors": {"bound_epochs": 2 if scenario == "balancer" else 4,
                     "parity_every": 2},
        "expect": expect,
        "doctor": ({"slot": n_slots - 2} if doctor else None),
    }


def _dense_mesh(spec: str | None):
    if not spec:
        return None
    import jax

    from pos_evolution_tpu.parallel.sharded import make_mesh
    pods, shard = (int(x) for x in spec.lower().split("x"))
    if len(jax.devices()) < pods * shard:
        # the run is bit-identical on any layout, so falling back is
        # semantically safe — but the operator asked for the SHARDED
        # code path, so say that it was not exercised
        print(f"chaos_fuzz: mesh {spec} needs {pods * shard} devices, "
              f"only {len(jax.devices())} present — running this "
              f"episode single-device (bit-identical results, sharded "
              f"path NOT exercised)", file=sys.stderr)
        return None
    return make_mesh(pods * shard, pods)


def _doctor_dense(sim) -> None:
    """Forge conflicting finalized checkpoints into the two dense views
    with NO double-vote evidence behind them: the
    ``DenseAccountableSafetyMonitor`` must classify the break as a
    ``protocol_violation`` (the CI negative at the dense tier)."""
    epoch = sim.slot // sim.S
    tips = [i for i in range(len(sim.roots))
            if sim.block_slots[i] == sim.slot]
    assert len(tips) >= 2, "dense doctor needs the two views' tip blocks"
    sim.views[0].finalized = (epoch, tips[0])
    sim.views[1].finalized = (epoch, tips[1])


def run_dense_episode(cfg: dict, events_path: str | None = None,
                      resume_from: bytes | None = None,
                      bundle_dir: str | None = None,
                      phase_profile: int | None = 8) -> dict:
    """Run one dense episode; same bundle/replay shape as
    ``run_episode``. ``resume_from`` replays from the bundle's
    episode-start checkpoint via ``DenseSimulation.resume`` — the
    checkpoint carries the full chaos composition + adversary/monitor/
    variant/rider state in-band, and the run is bit-identical on ANY
    mesh layout, so a 2x4 bundle replays exactly on a single device.

    Attack runs get the same observability as benign ones (ISSUE 20
    satellite): when the episode records events, the PR-19
    ``FlightRecorder`` arms (compile attribution, HBM watermarks) and
    the dense phase profiler fences every ``phase_profile``-th slot —
    ``variant_tally`` / ``workload`` phases included."""
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_adversary import (
        dense_adversary_from_config,
    )
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    from pos_evolution_tpu.sim.dense_monitors import default_dense_monitors
    from pos_evolution_tpu.sim.dense_variants import dense_rider_from_config
    from pos_evolution_tpu.sim.faults import DenseFaultPlan
    from pos_evolution_tpu.telemetry import FlightRecorder, Telemetry
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes

    if bundle_dir is not None:
        os.makedirs(bundle_dir, exist_ok=True)
        atomic_write_bytes(
            os.path.join(bundle_dir, "config.json"),
            (json.dumps(cfg, indent=1, sort_keys=True) + "\n").encode())
        if events_path is None:
            events_path = os.path.join(bundle_dir, "events.jsonl")
    telemetry = (Telemetry.to_file(events_path)
                 if events_path is not None else None)
    mesh = _dense_mesh(cfg.get("mesh"))
    n_slots = cfg["n_epochs"] * cfg["slots_per_epoch"]
    # the DAS riders size their blob grids off the ACTIVE config, so the
    # episode pins it (fresh run, resume and replay alike) — sidecars
    # rebuild byte-identical across all three
    cfg_obj = mainnet_config().replace(
        slots_per_epoch=cfg["slots_per_epoch"],
        max_committees_per_slot=4)
    flight = (FlightRecorder(telemetry=telemetry, sample_every=8).install()
              if telemetry is not None else None)
    profile = phase_profile if telemetry is not None else None
    try:
        with use_config(cfg_obj):
            if resume_from is not None:
                sim = DenseSimulation.resume(
                    resume_from, mesh=mesh, telemetry=telemetry,
                    expect_variant=(cfg.get("variant") or {}).get("kind"),
                    phase_profile=profile, flight_recorder=flight)
                checkpoint = resume_from
            else:
                m = cfg.get("monitors", {})
                wl = cfg.get("workload") or {}
                sim = DenseSimulation(
                    cfg["n_validators"], cfg=cfg_obj, mesh=mesh,
                    seed=cfg["seed"] * 101 + cfg["episode"],
                    shuffle_rounds=6, verify_aggregates=False,
                    check_walk_every=0,
                    n_groups=cfg.get("n_groups", 1),
                    fault_plan=DenseFaultPlan.from_config(cfg.get("faults")),
                    adversaries=[dense_adversary_from_config(a)
                                 for a in cfg.get("adversaries", ())],
                    monitors=default_dense_monitors(
                        bound_epochs=m.get("bound_epochs", 4),
                        parity_every=m.get("parity_every", 2)),
                    variant=cfg.get("variant"),
                    riders=[dense_rider_from_config(r)
                            for r in wl.get("riders", ())],
                    telemetry=telemetry, phase_profile=profile,
                    flight_recorder=flight)
                checkpoint = sim.checkpoint()
            if bundle_dir is not None:
                atomic_write_bytes(
                    os.path.join(bundle_dir, "checkpoint.bin"), checkpoint)
            doctor = cfg.get("doctor")
            while sim.slot < n_slots:
                sim.run_slot()
                if doctor is not None and sim.slot == doctor["slot"]:
                    _doctor_dense(sim)
    finally:
        if flight is not None:
            flight.detach()
        if telemetry is not None:
            telemetry.close()
    summary = sim.summary()
    result = {
        "violations": sim.monitor_violations,
        "finalized": [v.finalized[0] for v in sim.views],
        "checkpoint": checkpoint,
        "summary": summary,
    }
    # ex-ante verdict: did the withheld proposal capture the head?
    for adv in sim.adversaries:
        if getattr(adv, "name", "") == "dense_exante_reorg" \
                and getattr(adv, "priv", None):
            result["reorged"] = bool(
                sim._descends(sim._head(0), adv.priv[0]))
    if flight is not None and bundle_dir is not None:
        flight.write_artifact(
            os.path.join(bundle_dir, "device_ledger.json"))
    result.update(_dense_expectations(cfg, result))
    return result


def _dense_expectations(cfg: dict, result: dict) -> dict:
    """Judge an episode against its scenario's expectation: unexpected
    violations fail it, and so does a scripted attack that did NOT
    reproduce (a SplitVoter run without accountable double finality
    verified nothing)."""
    expect = cfg.get("expect", {"clean": True})
    violations = result["violations"]
    explained_kinds = {"accountable_fault"}
    if expect.get("liveness_stall"):
        explained_kinds.add("liveness_violation")
    if expect.get("protocol_violation"):
        explained_kinds.add("protocol_violation")
    if expect.get("ssf_double_finality"):
        explained_kinds.add("accountable_double_finality")
    if expect.get("confirmation_divergence") \
            or expect.get("confirmation_divergence_ok"):
        explained_kinds.add("confirmation_divergence")
    unexpected = [v for v in violations
                  if v.get("kind") not in explained_kinds]
    missed = []
    if expect.get("accountable_double_finality"):
        fin = [v for v in violations
               if v.get("kind") == "accountable_fault"
               and v.get("checkpoint") == "finalized"]
        if not fin:
            missed.append("accountable_double_finality")
        elif expect.get("exact_third") and not any(
                3 * v["slashable_stake"] == v["total_stake"] for v in fin):
            missed.append("evidence_exactly_one_third")
    if expect.get("liveness_stall"):
        if not any(v.get("kind") == "liveness_violation"
                   for v in violations):
            missed.append("liveness_stall")
        if any(g["justified_epoch"] > 0
               for g in result["summary"].get("views", [])):
            missed.append("justification_not_stalled")
    if expect.get("ssf_double_finality"):
        ssf = [v for v in violations
               if v.get("kind") == "accountable_double_finality"]
        if not ssf:
            missed.append("ssf_double_finality")
        elif expect.get("exact_third") and not any(
                3 * v["slashable_stake"] == v["total_stake"] for v in ssf):
            missed.append("ssf_evidence_exactly_one_third")
    if expect.get("confirmation_divergence") and not any(
            v.get("kind") == "confirmation_divergence"
            for v in violations):
        missed.append("confirmation_divergence_not_observed")
    if expect.get("exante_defended") and result.get("reorged"):
        missed.append("exante_reorg_not_defended")
    if expect.get("protocol_violation") and not any(
            v.get("kind") == "protocol_violation" for v in violations):
        missed.append("protocol_violation_not_tripped")
    if expect.get("clean") and not result["summary"]["finality_reached"]:
        missed.append("finality_not_reached")
    return {"unexpected": unexpected, "missed": missed}


def fuzz_dense(episodes: int, seed: int, n_validators: int, n_epochs: int,
               out_dir: str, mesh: str | None = None, doctor: bool = False,
               step_timeout: float | None = None,
               history: str | None = None, scheme: str = "merkle",
               variant: str | None = None,
               workload: str | None = None) -> dict:
    """The dense episode matrix: every episode is a sharded adversarial
    run with the full dense monitor stack, drawn from the protocol x
    attack x workload product (``variant``/``workload`` force one axis);
    bundles are replayable via ``--replay`` exactly like spec bundles."""
    import time as _time

    from pos_evolution_tpu.utils.watchdog import Watchdog
    os.makedirs(out_dir, exist_ok=True)
    wd = Watchdog(path=os.path.join(out_dir, "chaos_partial.json"),
                  tag="chaos_fuzz_dense", timeout_s=step_timeout)
    summary = {"mode": "dense", "episodes": 0, "violating": 0,
               "bundles": [], "incidents": 0, "accountable": 0,
               "scenarios": {}, "variants": {}, "workloads": {}}
    t0 = _time.time()
    n_blocks = n_slots_total = n_violations = 0
    for ep in range(episodes):
        cfg = episode_config_dense(seed, ep, n_validators, n_epochs,
                                   mesh=mesh, doctor=doctor, scheme=scheme,
                                   variant=variant, workload=workload)
        inflight = os.path.join(out_dir, f"inflight_ep{ep}")
        result = wd.step(f"dense_episode_{ep}", run_dense_episode, cfg,
                         bundle_dir=inflight)
        summary["episodes"] += 1
        vn = (cfg.get("variant") or {}).get("kind", "gasper")
        wl = (cfg.get("workload") or {}).get("choice", "none")
        sc = f"{cfg['scenario']} x {vn}"
        summary["scenarios"][sc] = summary["scenarios"].get(sc, 0) + 1
        summary["variants"][vn] = summary["variants"].get(vn, 0) + 1
        summary["workloads"][wl] = summary["workloads"].get(wl, 0) + 1
        if result is None:
            summary["incidents"] += 1
            summary.setdefault("inflight", []).append(inflight)
            print(f"dense episode {ep} ({sc}): DIED mid-run — partial "
                  f"bundle kept at {inflight} (replay with "
                  f"--resume-bundle)")
            continue
        n_blocks += result["summary"]["n_blocks"]
        n_slots_total += result["summary"]["slots"]
        n_violations += len(result["violations"])
        bad = result["unexpected"] or result["missed"]
        if result["violations"] or bad:
            bundle = write_bundle(out_dir, cfg, result, do_shrink=bool(bad),
                                  inflight_dir=inflight)
            summary["bundles"].append(bundle)
        if bad:
            summary["violating"] += 1
            print(f"dense episode {ep} ({sc}): "
                  f"{len(result['unexpected'])} unexpected violation(s), "
                  f"missed={result['missed']} -> {bundle}")
        elif result["violations"]:
            summary["accountable"] += 1
            print(f"dense episode {ep} ({sc}): "
                  f"{len(result['violations'])} expected/accountable "
                  f"verdict(s), evidence bundled -> {bundle}")
        else:
            shutil.rmtree(inflight, ignore_errors=True)
            print(f"dense episode {ep} ({sc}): clean "
                  f"(finalized={result['finalized']})")
    summary["run_s"] = round(_time.time() - t0, 3)
    if history:
        from pos_evolution_tpu.profiling import history as hist
        emission = {
            "metric": "dense_chaos",
            "run_s": summary["run_s"],
            "counts": {
                "episodes": summary["episodes"],
                "slots": n_slots_total,
                "blocks": n_blocks,
                "violations": n_violations,
                "violating_episodes": summary["violating"],
            },
        }
        hist.append_entry(history, emission, kind="bench_dense_chaos")
        summary["history"] = history
    return summary


def _run_any(cfg: dict, **kw) -> dict:
    """Dispatch an episode config to the spec or dense runner (the
    shrink pass and bundle replay are shape-agnostic)."""
    if cfg.get("dense"):
        return run_dense_episode(cfg, **kw)
    return run_episode(cfg, **kw)


# -- shrink --------------------------------------------------------------------

def _components(cfg: dict) -> list[tuple[str, object]]:
    """Every independently removable piece of a composition (spec and
    dense configs share the shape; dense adds ``delay_p``)."""
    out = [("adversary", i) for i in range(len(cfg["adversaries"]))]
    out += [("fault", k)
            for k in ("drop_p", "duplicate_p", "reorder_p", "delay_p")
            if cfg["faults"].get(k, 0) > 0]
    out += [("crash", i)
            for i in range(len(cfg["faults"].get("crashes", ())))]
    return out


def _without(cfg: dict, component: tuple[str, object]) -> dict:
    import copy
    out = copy.deepcopy(cfg)
    kind, key = component
    if kind == "adversary":
        del out["adversaries"][key]
    elif kind == "fault":
        out["faults"][key] = 0.0
    elif kind == "crash":
        del out["faults"]["crashes"][key]
    return out


def _same_violation(violations: list[dict], reference: dict) -> bool:
    return any(v["monitor"] == reference["monitor"]
               and v["kind"] == reference["kind"] for v in violations)


def shrink(cfg: dict, reference_violation: dict) -> tuple[dict, list[dict]]:
    """Greedy delta-debugging: drop one component at a time, keep the
    removal whenever the reference violation still reproduces. Each
    accepted step strictly reduces the composition; the loop restarts
    after every acceptance so index-shifting removals stay sound."""
    log = []
    current = cfg
    progress = True
    while progress:
        progress = False
        for comp in _components(current):
            candidate = _without(current, comp)
            result = _run_any(candidate)
            ok = _same_violation(result["violations"], reference_violation)
            log.append({"removed": list(comp), "still_violates": ok,
                        "n_components": len(_components(candidate))})
            if ok:
                current = candidate
                progress = True
                break
    return current, log


# -- bundles -------------------------------------------------------------------

def write_bundle(out_dir: str, cfg: dict, result: dict,
                 events_src: str | None = None, do_shrink: bool = True,
                 inflight_dir: str | None = None) -> str:
    """Finalize a violating episode's bundle. With ``inflight_dir`` the
    incrementally-flushed directory (config + checkpoint + streamed
    events already inside) is renamed into place; otherwise the legacy
    shape writes everything here."""
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
    bundle = os.path.join(out_dir, f"bundle_ep{cfg['episode']}")
    if inflight_dir is not None and os.path.isdir(inflight_dir):
        if os.path.isdir(bundle):
            shutil.rmtree(bundle)
        os.replace(inflight_dir, bundle)
    os.makedirs(bundle, exist_ok=True)
    atomic_write_bytes(
        os.path.join(bundle, "config.json"),
        (json.dumps(cfg, indent=1, sort_keys=True) + "\n").encode())
    if not os.path.exists(os.path.join(bundle, "checkpoint.bin")):
        atomic_write_bytes(os.path.join(bundle, "checkpoint.bin"),
                           result["checkpoint"])
    atomic_write_bytes(
        os.path.join(bundle, "violations.json"),
        (json.dumps(result["violations"], indent=1, sort_keys=True)
         + "\n").encode())
    if events_src and os.path.exists(events_src):
        shutil.move(events_src, os.path.join(bundle, "events.jsonl"))
    if do_shrink and result["violations"]:
        minimized, log = shrink(cfg, result["violations"][0])
        with open(os.path.join(bundle, "shrink.json"), "w") as fh:
            json.dump({"steps": log,
                       "before": len(_components(cfg)),
                       "after": len(_components(minimized))}, fh, indent=1)
            fh.write("\n")
        with open(os.path.join(bundle, "config.min.json"), "w") as fh:
            json.dump(minimized, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return bundle


def replay_bundle(bundle: str) -> dict:
    """Re-run a bundle from its checkpoint via ``Simulation.resume`` and
    compare the violations against the recorded ones.

    Also accepts a PARTIAL (inflight) bundle — the incremental flush of
    a crashed episode, which has config + checkpoint but no
    ``violations.json`` yet. The episode then runs to completion and
    ``match`` is None (there is no recorded verdict to compare): the
    ``--resume-bundle`` contract."""
    with open(os.path.join(bundle, "config.json")) as fh:
        cfg = json.load(fh)
    cpath = os.path.join(bundle, "checkpoint.bin")
    checkpoint = None
    if os.path.exists(cpath):
        with open(cpath, "rb") as fh:
            checkpoint = fh.read()
    # else: the episode died BEFORE the start checkpoint flushed. For a
    # non-resumed episode the start checkpoint is a pure function of the
    # config (a freshly constructed Simulation), so running from scratch
    # reproduces the identical episode.
    vpath = os.path.join(bundle, "violations.json")
    recorded = None
    if os.path.exists(vpath):
        with open(vpath) as fh:
            recorded = json.load(fh)
    result = _run_any(cfg, resume_from=checkpoint)
    key = lambda v: (v["slot"], v["monitor"], v["kind"])  # noqa: E731
    match = (None if recorded is None else
             sorted(map(key, result["violations"]))
             == sorted(map(key, recorded)))
    return {"match": match, "replayed": result["violations"],
            "recorded": recorded,
            "finalized": result["finalized"]}


# -- CLI -----------------------------------------------------------------------

def fuzz(episodes: int, seed: int, n_validators: int, n_slots: int,
         out_dir: str, doctor: bool = False, do_shrink: bool = True,
         step_timeout: float | None = None, episode_indices=None,
         variant: str = "gasper", serve: bool = False,
         scheme: str = "merkle") -> dict:
    from pos_evolution_tpu.utils.watchdog import Watchdog
    os.makedirs(out_dir, exist_ok=True)
    wd = Watchdog(path=os.path.join(out_dir, "chaos_partial.json"),
                  tag="chaos_fuzz", timeout_s=step_timeout)
    summary = {"episodes": 0, "violating": 0, "bundles": [],
               "incidents": 0, "variant": variant, "accountable": 0}
    indices = (range(episodes) if episode_indices is None
               else episode_indices)
    for ep in indices:
        cfg = episode_config(seed, ep, n_validators, n_slots, doctor=doctor,
                             variant=variant, serve=serve, scheme=scheme)
        # incremental flush (ISSUE 10): config + start checkpoint +
        # streamed events land in an inflight dir BEFORE the run, so a
        # crashed/killed episode leaves a --resume-bundle artifact
        inflight = os.path.join(out_dir, f"inflight_ep{ep}")
        result = wd.step(f"episode_{ep}", run_episode, cfg,
                         bundle_dir=inflight)
        summary["episodes"] += 1
        if result is None:         # watchdog incident (timeout / crash)
            summary["incidents"] += 1
            summary.setdefault("inflight", []).append(inflight)
            print(f"episode {ep}: DIED mid-run — partial bundle kept at "
                  f"{inflight} (replay with --resume-bundle)")
            continue
        # An accountable_fault is the protocol SURVIVING as designed —
        # the adversary bought a break by burning >= 1/3 of the relevant
        # quorum's stake into slashing evidence (committee-subsampled
        # SSF can be double-finalized per slot at exactly that price).
        # It is explained, bundled for audit, and does NOT fail the
        # sweep; anything else is an unexplained violation and does.
        unexplained = [v for v in result["violations"]
                       if v.get("kind") != "accountable_fault"]
        serve_out = result.get("serve")
        serve_failed = False
        if serve_out is not None:
            # the serve x chaos verdict: a WRONG proof is a hard
            # failure (overload may shed, never corrupt); the SLO
            # outcome rides the episode record. The serve outcome
            # stays OUT of result["violations"]: replay resumes the
            # CHAIN from the checkpoint without re-serving, so a
            # synthetic violation there could never replay (it lands
            # in the bundle as serve.json instead).
            serve_failed = serve_out["verify_failures"] > 0
            summary.setdefault("serve", []).append(
                {"episode": ep, **{k: serve_out[k] for k in
                 ("interactive_goodput_pct", "interactive_p99_ms",
                  "slo_ok", "verified_proofs", "verify_failures")}})
        if result["violations"] or serve_failed:
            bundle = write_bundle(out_dir, cfg, result,
                                  do_shrink=do_shrink and bool(unexplained),
                                  inflight_dir=inflight)
            summary["bundles"].append(bundle)
            if serve_out is not None:
                from pos_evolution_tpu.utils.snapshot import (
                    atomic_write_bytes,
                )
                atomic_write_bytes(
                    os.path.join(bundle, "serve.json"),
                    (json.dumps(serve_out, indent=1, sort_keys=True)
                     + "\n").encode())
        if unexplained or serve_failed:
            summary["violating"] += 1
            reasons = [f"{len(unexplained)} unexplained violation(s)"]
            if serve_failed:
                reasons.append(f"{serve_out['verify_failures']} served "
                               f"proofs failed verification")
            print(f"episode {ep}: {' + '.join(reasons)} -> {bundle}")
        elif result["violations"]:
            summary["accountable"] += 1
            print(f"episode {ep}: {len(result['violations'])} accountable "
                  f"fault(s), evidence bundled -> {bundle}")
        else:
            shutil.rmtree(inflight, ignore_errors=True)
            print(f"episode {ep}: clean "
                  f"(finalized={result['finalized']})")
    return summary


def _serve_mp(args) -> int:
    """The multi-process serving chaos scenario (ISSUE 16): the shared
    harness runs the pool + swarm under seeded SIGKILLs / wedges /
    fd exhaustion and self-judges; the bundle here is one JSON."""
    from pos_evolution_tpu.serve.harness import run_mp_scenario
    with use_config(minimal_config()):
        out = run_mp_scenario(
            arrivals=args.serve_arrivals, rate=args.serve_rate,
            seed=args.seed, kills=args.serve_kills,
            wedges=args.serve_wedges, fd_exhaust_n=64)
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, f"serve_mp_seed{args.seed}.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True, default=str)
        fh.write("\n")
    verdict = out["verdict"]
    print(json.dumps({"verdict": verdict, "bundle": path}, indent=1))
    return 0 if verdict["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="chaos fuzz: adversary x fault compositions under "
                    "safety/liveness monitors")
    ap.add_argument("--episodes", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--slots", type=int, default=24)
    ap.add_argument("--out", default="chaos_out")
    ap.add_argument("--doctor", action="store_true",
                    help="force conflicting finalized checkpoints (the "
                         "monitor must trip; CI negative)")
    ap.add_argument("--no-shrink", action="store_true")
    ap.add_argument("--step-timeout", type=float, default=None,
                    help="watchdog per-episode timeout (seconds)")
    ap.add_argument("--variant", default="gasper",
                    choices=("gasper", "goldfish", "rlmd", "ssf", "all"),
                    help="protocol variant to fuzz under (DESIGN.md §16); "
                         "'all' sweeps every variant into per-variant "
                         "subdirectories")
    ap.add_argument("--serve", action="store_true",
                    help="attach a live ServeFront + remote-discovery "
                         "open-loop loadgen to every episode; the "
                         "SLO/goodput outcome joins the verdict and a "
                         "wrong served proof fails the episode")
    ap.add_argument("--scheme", choices=("merkle", "kzg"), default="merkle",
                    help="cell-commitment scheme for serve episodes' DAS "
                         "engine (DESIGN.md §23); recorded in every "
                         "episode composition and checkpoint fingerprint "
                         "so cross-scheme resume refuses loudly")
    ap.add_argument("--serve-mp", action="store_true",
                    help="run the MULTI-PROCESS serving chaos scenario "
                         "instead of episodes: a supervised worker pool "
                         "behind SO_REUSEPORT fronts under seeded "
                         "process-level injections (worker SIGKILLs, "
                         "heartbeat wedges, an fd-exhaustion window); "
                         "exit code follows the scenario verdict")
    ap.add_argument("--serve-arrivals", type=int, default=30000)
    ap.add_argument("--serve-rate", type=float, default=10000.0)
    ap.add_argument("--serve-kills", type=int, default=2)
    ap.add_argument("--serve-wedges", type=int, default=1)
    ap.add_argument("--dense", type=int, default=0, metavar="N",
                    help="run N DENSE episodes instead (ISSUE 13): "
                         "mainnet-scale DenseSimulation runs with "
                         "vectorized adversaries, DenseFaultPlan masks "
                         "and the dense monitor stack")
    ap.add_argument("--dense-validators", type=int, default=576,
                    help="validators per dense episode (divisible by 24)")
    ap.add_argument("--dense-epochs", type=int, default=4,
                    help="epochs per dense episode (>= 4: the first "
                         "finalization lands entering epoch 4)")
    ap.add_argument("--mesh", default=None, metavar="PxS",
                    help="run dense episodes sharded on a virtual mesh "
                         "(re-execs with forced host device count)")
    ap.add_argument("--dense-variant", default=None,
                    choices=("gasper", "goldfish", "rlmd", "ssf"),
                    help="force the dense episodes' protocol variant "
                         "(default: drawn per episode from the full "
                         "protocol x attack x workload product)")
    ap.add_argument("--dense-workload", default=None,
                    choices=_DENSE_WORKLOADS,
                    help="force the dense episodes' workload draw "
                         "(DAS sidecars + light clients, or none)")
    ap.add_argument("--history", default=None,
                    help="append a kind=bench_dense_chaos emission to "
                         "this bench history (gate with perf_gate.py)")
    ap.add_argument("--replay", metavar="BUNDLE",
                    help="replay a repro bundle (spec or dense) and "
                         "verify the violation")
    ap.add_argument("--resume-bundle", metavar="BUNDLE",
                    help="resume a PARTIAL (inflight) bundle left by a "
                         "crashed episode: run it to completion from its "
                         "flushed config + checkpoint; verifies the "
                         "violations only when the bundle recorded some")
    args = ap.parse_args(argv)
    if args.serve_mp:
        return _serve_mp(args)
    if args.dense and args.mesh:
        from pos_evolution_tpu.utils.hostdev import reexec_with_host_devices
        pods, shard = (int(x) for x in args.mesh.lower().split("x"))
        reexec_with_host_devices(pods * shard, "POS_CHAOS_CHILD")

    if args.dense:
        summary = fuzz_dense(args.dense, args.seed, args.dense_validators,
                             args.dense_epochs, args.out, mesh=args.mesh,
                             doctor=args.doctor,
                             step_timeout=args.step_timeout,
                             history=args.history, scheme=args.scheme,
                             variant=args.dense_variant,
                             workload=args.dense_workload)
        print(json.dumps({k: summary[k] for k in
                          ("mode", "episodes", "violating", "accountable",
                           "incidents", "scenarios", "variants",
                           "workloads", "run_s")}, indent=1))
        if args.doctor:
            # the forged double finality MUST trip protocol_violation —
            # which the doctor scenario records as an EXPECTED verdict,
            # so success = zero unexpected/missed episodes
            return 0 if (summary["violating"] == 0
                         and summary["incidents"] == 0
                         and summary["accountable"] > 0) else 1
        return 1 if (summary["violating"] or summary["incidents"]) else 0

    with use_config(minimal_config()):
        if args.replay or args.resume_bundle:
            out = replay_bundle(args.replay or args.resume_bundle)
            print(json.dumps({"match": out["match"],
                              "replayed": out["replayed"],
                              "finalized": out["finalized"]}, indent=1))
            if args.replay:
                return 0 if out["match"] else 1
            # resume mode: completing the episode IS the success
            # criterion; a recorded verdict, when present, must agree
            return 0 if out["match"] in (True, None) else 1
        variants = (("gasper", "goldfish", "rlmd", "ssf")
                    if args.variant == "all" else (args.variant,))
        rc = 0
        for name in variants:
            out_dir = (args.out if len(variants) == 1
                       else os.path.join(args.out, name))
            summary = fuzz(args.episodes, args.seed, args.validators,
                           args.slots, out_dir, doctor=args.doctor,
                           do_shrink=not args.no_shrink,
                           step_timeout=args.step_timeout, variant=name,
                           serve=args.serve, scheme=args.scheme)
            keys = ["variant", "episodes", "violating", "accountable",
                    "incidents"]
            row = {k: summary[k] for k in keys}
            if "serve" in summary:
                row["serve"] = summary["serve"]
            print(json.dumps(row, indent=1))
            if args.doctor:
                # the doctored run MUST trip a safety monitor, per variant
                rc |= 0 if summary["violating"] > 0 else 1
            else:
                # an episode that hung or crashed verified nothing — a
                # clean verdict requires every episode to have actually run
                rc |= 1 if (summary["violating"]
                            or summary["incidents"]) else 0
        return rc


if __name__ == "__main__":
    sys.exit(main())
