"""Self-healing long runs: supervised, autocheckpointed, resumable
(ISSUE 10, DESIGN.md §18).

One entry point, two roles:

- **parent** (default): launches itself as a ``--child`` process under
  ``resilience.supervise`` — crash detection by exit code, hang
  detection by heartbeat-file age, resume with capped jittered backoff,
  loud refusal after ``--max-failures`` consecutive failures. After
  success it folds the interruption/retry/overhead story into a
  ``bench_resilience`` emission (gated by ``perf_gate.py`` via
  ``--history``) and a ``goodput`` telemetry event;
- **child**: builds (or ``resume_latest``-resumes) the requested driver
  with ``autocheckpoint=`` armed — atomic checksummed steps every
  ``--every`` slots, async writer, per-slot heartbeats, optional
  integrity audits — runs to the target epoch, takes a final
  checkpoint, and writes ``result.json`` (slot, state digest, overhead
  stats) atomically.

Failure injection for smokes/CI: ``--crash-at-slot N`` SIGKILLs the
child the first time slot N completes (a marker file keeps the resumed
attempt from re-crashing) — the honest simulation of preemption, OOM
kills, and device loss. ``--degraded-sharded AxB`` makes every
*resumed* attempt come up on a smaller mesh: the device-loss path of
PR 9's resume-across-mesh-shapes, exercised end-to-end.

Bit-identity contract: the final ``state_digest`` of a killed-and-
resumed run equals an uninterrupted twin's, whatever the interruption
history or mesh shape (pinned in tests/test_resilience.py and the
resilience-smoke CI job).

Usage:
    python scripts/resilient_run.py --validators 64 --epochs 3 \
        --ckpt-dir /tmp/res [--sharded 2x2] [--dense] [--every 8] \
        [--crash-at-slot 14] [--degraded-sharded 1x2] \
        [--events events.jsonl] [--json bench.json] [--history h.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_mesh(s: str | None):
    if not s:
        return None
    pods, shard = (int(x) for x in s.lower().split("x"))
    return pods, shard


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--ckpt-dir", required=True,
                    help="CheckpointManager store (also heartbeat + "
                         "result.json)")
    ap.add_argument("--every", type=int, default=8,
                    help="autocheckpoint interval in slots")
    ap.add_argument("--retain", type=int, default=3)
    ap.add_argument("--sync", action="store_true",
                    help="synchronous checkpoint writes (default: async "
                         "writer thread)")
    ap.add_argument("--guard-every", type=int, default=0,
                    help="IntegrityGuard audit interval in slots (0=off)")
    ap.add_argument("--dense", action="store_true",
                    help="drive sim/dense_driver.DenseSimulation instead "
                         "of the spec-level Simulation")
    ap.add_argument("--sharded", default=None,
                    help="mesh shape PxS (spec driver: "
                         "Simulation(sharded=...); dense: a make_mesh)")
    ap.add_argument("--degraded-sharded", default=None,
                    help="mesh shape for RESUMED attempts (device-loss "
                         "path: resume on fewer devices)")
    ap.add_argument("--config", choices=("minimal", "mainnet"),
                    default="minimal")
    ap.add_argument("--crash-at-slot", type=int, default=None,
                    help="SIGKILL the child once after this slot "
                         "completes (failure injection)")
    ap.add_argument("--hang-timeout", type=float, default=300.0)
    ap.add_argument("--max-failures", type=int, default=3)
    ap.add_argument("--backoff", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--events", default=None,
                    help="append-mode telemetry JSONL shared by the "
                         "supervisor and every attempt")
    ap.add_argument("--json", default=None,
                    help="write the bench_resilience emission here")
    ap.add_argument("--history", default=None,
                    help="append the emission to this bench_history.jsonl")
    ap.add_argument("--no-cpu-pin", action="store_true",
                    help="do not force JAX_PLATFORMS=cpu + virtual host "
                         "devices onto the child (real-hardware runs)")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    return ap


# -- child ---------------------------------------------------------------------

def _crash_marker(args) -> str:
    return os.path.join(args.ckpt_dir, "crash_injected")


def _maybe_crash(args, slot: int) -> None:
    if args.crash_at_slot is None or slot != args.crash_at_slot:
        return
    marker = _crash_marker(args)
    if os.path.exists(marker):
        return  # already crashed once; the resumed attempt runs through
    with open(marker, "w") as fh:
        fh.write(f"SIGKILL after slot {slot}\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.kill(os.getpid(), signal.SIGKILL)


def _autocheckpoint_spec(args) -> dict:
    return {"every_n_slots": args.every, "dir": args.ckpt_dir,
            "retain": args.retain, "async_mode": not args.sync,
            "guard_every": args.guard_every,
            "heartbeat": os.path.join(args.ckpt_dir, "heartbeat.json")}


def _refuse_unless_virgin_store(args) -> None:
    """Called when ``resume_latest`` found nothing valid. A store that
    never held a checkpoint (crash before the first interval) may
    legitimately start fresh; a store with steps that were REFUSED
    (fingerprint mismatch) or quarantined (corruption) must NOT be
    silently laundered into a from-genesis run that exits 0 — that
    would invert the refuse-loudly contract (DESIGN.md §18)."""
    remnants = []
    if os.path.isdir(args.ckpt_dir):
        from pos_evolution_tpu.resilience import CheckpointManager
        steps = CheckpointManager(args.ckpt_dir).steps()
        if steps:
            remnants.append(f"{len(steps)} refused step(s) {steps}")
        qdir = os.path.join(args.ckpt_dir, "quarantine")
        if os.path.isdir(qdir) and os.listdir(qdir):
            remnants.append(
                f"{len(os.listdir(qdir))} quarantined step(s)")
    if remnants:
        raise SystemExit(
            f"resilient_run: checkpoint store {args.ckpt_dir!r} holds "
            f"{' and '.join(remnants)} but nothing resumable — refusing "
            f"to restart from genesis as if nothing happened; inspect "
            f"the store (wrong --config? corrupted disk?)")
    print("# child: no checkpoints yet — starting fresh", file=sys.stderr)


def run_child(args) -> int:
    from pos_evolution_tpu.config import (
        mainnet_config,
        minimal_config,
        use_config,
    )
    from pos_evolution_tpu.resilience import state_digest
    from pos_evolution_tpu.telemetry import Telemetry
    cfg_obj = (minimal_config() if args.config == "minimal"
               else mainnet_config())
    sharded = _parse_mesh(args.sharded)
    degraded = _parse_mesh(args.degraded_sharded)
    resumed_degraded = degraded if os.path.exists(_crash_marker(args)) \
        else None
    telemetry = (Telemetry.to_file(args.events, append=True)
                 if args.events else None)
    if telemetry is not None:
        # bus-less emitters (CheckpointManager quarantine/reject, the
        # dense driver's supervision) reach the same log via the
        # global sink — without this their events silently vanish
        telemetry.install_global()
    spec = _autocheckpoint_spec(args)
    t0 = time.perf_counter()
    with use_config(cfg_obj):
        if args.dense:
            sim, target = _build_dense(args, cfg_obj, sharded,
                                       resumed_degraded, spec)
            while sim.slot < target:
                sim.run_slot()
                _maybe_crash(args, sim.slot)
        else:
            sim, target = _build_spec(args, sharded, resumed_degraded,
                                      spec, telemetry)
            while sim.slot <= target:
                sim.run_slot()
                _maybe_crash(args, sim.slot)
        stats = sim.finish_autocheckpoint()
        run_wall = time.perf_counter() - t0
        result = {
            "driver": "dense" if args.dense else "sim",
            "n_validators": args.validators,
            "slot": sim.slot,
            "finalized_epoch": (sim.finalized[0] if args.dense
                                else sim.finalized_epoch()),
            "state_digest": state_digest(sim),
            "run_wall_s": round(run_wall, 3),
            "checkpoint": stats,
            "resumed_on_degraded_mesh": (
                list(resumed_degraded) if resumed_degraded else None),
        }
    from pos_evolution_tpu.utils.snapshot import atomic_write_bytes
    atomic_write_bytes(os.path.join(args.ckpt_dir, "result.json"),
                       (json.dumps(result, indent=1, sort_keys=True)
                        + "\n").encode())
    if telemetry is not None:
        telemetry.bus.emit("run_segment", wall_s=result["run_wall_s"],
                           final_slot=sim.slot)
        telemetry.close()
    print(json.dumps(result, indent=1, sort_keys=True))
    return 0


def _build_spec(args, sharded, resumed_degraded, spec, telemetry):
    from pos_evolution_tpu.backend import set_backend
    from pos_evolution_tpu.config import cfg as active_cfg
    from pos_evolution_tpu.sim import Simulation
    if sharded or resumed_degraded:
        set_backend("jax")
    use_sharded = resumed_degraded or sharded
    try:
        sim = Simulation.resume_latest(args.ckpt_dir, telemetry=telemetry,
                                       sharded=use_sharded,
                                       autocheckpoint=spec)
        print(f"# child: resumed at slot {sim.slot} "
              f"(mesh {use_sharded or 'single'})", file=sys.stderr)
    except FileNotFoundError:
        _refuse_unless_virgin_store(args)
        sim = Simulation(args.validators, sharded=sharded,
                         telemetry=telemetry, autocheckpoint=spec)
    return sim, args.epochs * active_cfg().slots_per_epoch


def _build_dense(args, cfg_obj, sharded, resumed_degraded, spec):
    from pos_evolution_tpu.parallel.sharded import make_mesh
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    shape = resumed_degraded or sharded
    mesh = make_mesh(shape[0] * shape[1], shape[0]) if shape else None
    try:
        sim = DenseSimulation.resume_latest(args.ckpt_dir, mesh=mesh,
                                            autocheckpoint=spec)
        print(f"# child: resumed at slot {sim.slot} "
              f"(mesh {shape or 'single'})", file=sys.stderr)
    except FileNotFoundError:
        _refuse_unless_virgin_store(args)
        sim = DenseSimulation(args.validators, cfg=cfg_obj, mesh=mesh,
                              verify_aggregates=False, check_walk_every=8,
                              autocheckpoint=spec)
    return sim, args.epochs * cfg_obj.slots_per_epoch


# -- parent --------------------------------------------------------------------

class _AppendBus:
    """Emit supervisor events into the shared JSONL without holding the
    file open across a child's lifetime: each emission reopens in
    append mode, so the seq ordinal continues past everything the child
    wrote and the two writers never interleave."""

    def __init__(self, path: str | None):
        self.path = path

    def emit(self, type_: str, **fields) -> None:
        if self.path is None:
            return
        from pos_evolution_tpu.telemetry.events import EventBus
        bus = EventBus(self.path, keep_in_memory=False, append=True)
        bus.emit(type_, **fields)
        bus.close()


def _child_env(args) -> dict:
    env = dict(os.environ)
    if not args.no_cpu_pin:
        env["JAX_PLATFORMS"] = "cpu"
        mesh = _parse_mesh(args.sharded)
        n_dev = mesh[0] * mesh[1] if mesh else 1
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{max(n_dev, 1)}").strip()
    return env


def _replayed_slots(events_path: str | None) -> int:
    if not events_path or not os.path.exists(events_path):
        return 0
    from pos_evolution_tpu.resilience import replayed_slots_from_events
    from pos_evolution_tpu.telemetry import read_jsonl
    return replayed_slots_from_events(read_jsonl(events_path))


def run_parent(args, argv: list[str]) -> int:
    from pos_evolution_tpu.resilience import SupervisorGaveUp, supervise
    os.makedirs(args.ckpt_dir, exist_ok=True)
    heartbeat = os.path.join(args.ckpt_dir, "heartbeat.json")
    bus = _AppendBus(args.events)

    def build_argv(attempt: int) -> list[str]:
        # the PARSED invocation, not sys.argv: a programmatic
        # main([...]) caller must supervise the child it asked for
        return [sys.executable, os.path.abspath(__file__), "--child",
                *argv]

    try:
        summary = supervise(
            build_argv, heartbeat_path=heartbeat,
            hang_timeout_s=args.hang_timeout,
            max_failures=args.max_failures, backoff_s=args.backoff,
            seed=args.seed, env=_child_env(args), events_bus=bus)
    except SupervisorGaveUp as e:
        print(f"resilient_run: GAVE UP — {e}", file=sys.stderr)
        print(json.dumps(e.summary, indent=1))
        return 1

    with open(os.path.join(args.ckpt_dir, "result.json")) as fh:
        result = json.load(fh)
    ckpt = result.get("checkpoint") or {}
    run_wall = max(result.get("run_wall_s") or 0.0, 1e-9)
    replayed = _replayed_slots(args.events)
    final_slot = result["slot"]
    emission = {
        "metric": "resilient_run",
        "driver": result["driver"],
        "n_validators": args.validators,
        "epochs": args.epochs,
        "sharded": args.sharded,
        "attempts": summary["attempts"],
        "interruptions": len(summary["interruptions"]),
        "interruption_reasons": sorted(
            {i["reason"] for i in summary["interruptions"]}),
        "replayed_slots": replayed,
        "final_slot": final_slot,
        "goodput_pct": round(100.0 * final_slot
                             / max(final_slot + replayed, 1), 2),
        "ckpt_saves": ckpt.get("saves", 0),
        "ckpt_bytes": ckpt.get("bytes", 0),
        "ckpt_blocked_s": ckpt.get("loop_blocked_s", 0.0),
        "ckpt_background_s": ckpt.get("background_s", 0.0),
        "ckpt_overhead_pct": round(
            100.0 * ckpt.get("loop_blocked_s", 0.0) / run_wall, 3),
        "run_wall_s": result["run_wall_s"],
        "total_wall_s": summary["total_wall_s"],
        "resumed_on_degraded_mesh": result.get("resumed_on_degraded_mesh"),
        "state_digest": result["state_digest"],
        "finalized_epoch": result["finalized_epoch"],
        # count leaves for perf_gate.py (timing leaves gate via their
        # *_s suffixes): more interruptions / replayed slots / saves at
        # the same workload is a resilience regression
        "counts": {"attempts": summary["attempts"],
                   "interruptions": len(summary["interruptions"]),
                   "replayed_slots": replayed,
                   "ckpt_saves": ckpt.get("saves", 0)},
    }
    bus.emit("goodput", **{k: v for k, v in emission.items()
                           if k != "metric"})
    print(json.dumps(emission, indent=1, sort_keys=True))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(emission, fh, indent=1, sort_keys=True)
            fh.write("\n")
    if args.history:
        from pos_evolution_tpu.profiling import history
        history.append_entry(args.history, emission,
                             kind="bench_resilience")
        print(f"# appended bench_resilience emission to {args.history}",
              file=sys.stderr)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if args.child:
        return run_child(args)
    return run_parent(args, argv)


if __name__ == "__main__":
    sys.exit(main())
