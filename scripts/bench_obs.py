"""Observability self-bench: phase-budget coverage + profiler overhead.

ISSUE 18 leg c ships a per-slot phase profiler inside
``DenseSimulation.run_slot`` (``profiling/phases.py``). This bench is
the acceptance harness for its TWO promises, which pull in opposite
directions:

- **coverage** — at sampled (device-fenced) slots the phase taxonomy
  must account for >= 95% of the slot wall, or the budget is decoration
  (``--min-accounted`` gates it);
- **cheapness** — at steady state (unfenced slots: two clock reads and
  a dict add per phase) the instrumented loop must cost < a few percent
  over a genuinely uninstrumented twin, or nobody leaves it on
  (``--max-overhead`` gates it; off by default because one-shot CPU-CI
  walls are noisy — the acceptance run passes 5).

Four runs, same seed and shape:

1. **budget**: ``phase_profile=--sample-every`` with a live telemetry
   bundle — emits ``dense_phase`` events (the ``scripts/run_report.py``
   "Dense phase budget" section reads these via ``--events``) and the
   ``dense_phase_ms`` histogram, and yields ``accounted_pct``. Also
   warms every jit cache so the timed pair below never pays compile;
2. **twin**: ``phase_profile=None`` — threads ``NULL_TIMER``, the
   genuinely uninstrumented loop;
3. **steady**: ``phase_profile=n_slots+1`` — the instrumented loop in
   which only slot 0 ever fences, i.e. the leave-it-on configuration;
4. **armed** (ISSUE 19): steady + the full device flight recorder
   (memory watermarks, compile ledger, skew probes at the default
   cadence) — ``armed_overhead_pct`` bounds its cost
   (``--max-armed-overhead``), and the budget run's compile ledger must
   name >= ``--min-ledger-attribution`` %% of
   ``jax_backend_compiles_total`` by (function, phase).

``overhead_pct = (steady_wall - twin_wall) / twin_wall``; with
``--repeats N`` the twin/steady timings interleave and the minimum wall
of each wins (adjacent runs see the same box noise).

The emission (``metric: bench_obs``) lands in ``bench_history.jsonl``
as ``kind=bench_obs``; ``scripts/perf_gate.py --kind bench_obs`` bands
the ``counts`` leaves (slots, sampled slots, per-phase row counts —
deterministic properties of the instrumented path, unlike this box's
walls), so a phase that silently stops recording fails CI. The
doctored (x10) negative is pinned in the obs-smoke job.

Usage:
    python scripts/bench_obs.py [--validators 256] [--epochs 2]
        [--slots-per-epoch 8] [--sample-every 8] [--seed 0]
        [--repeats 1] [--min-accounted 95] [--max-overhead 5]
        [--json out.json] [--history bench_history.jsonl]
        [--events events.jsonl]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(args, phase_profile, telemetry=None, flight_recorder=None):
    from pos_evolution_tpu.config import mainnet_config
    from pos_evolution_tpu.sim.dense_driver import DenseSimulation
    cfg = mainnet_config().replace(slots_per_epoch=args.slots_per_epoch)
    return DenseSimulation(
        args.validators, cfg=cfg, mesh=None, seed=args.seed,
        verify_aggregates=True, check_walk_every=16,
        telemetry=telemetry, phase_profile=phase_profile,
        flight_recorder=flight_recorder)


def _timed_run(args, phase_profile, flight: bool = False) -> float:
    fr = None
    if flight:
        # fully-armed twin (ISSUE 19): fresh in-memory telemetry +
        # flight recorder at the default cadence — the leave-it-on
        # configuration whose steady-state cost the gate bounds
        from pos_evolution_tpu.telemetry import FlightRecorder, Telemetry
        fr = FlightRecorder(telemetry=Telemetry())
    sim = _build(args, phase_profile, flight_recorder=fr)
    t0 = time.perf_counter()
    sim.run_epochs(args.epochs)
    return time.perf_counter() - t0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--validators", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--slots-per-epoch", type=int, default=8)
    ap.add_argument("--sample-every", type=int, default=8,
                    help="fence every N-th slot in the budget run")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--repeats", type=int, default=1,
                    help="twin/steady timing pairs; min wall of each wins")
    ap.add_argument("--min-accounted", type=float, default=None,
                    help="exit 1 unless the sampled budget accounts for "
                         "at least this %% of the slot wall")
    ap.add_argument("--max-overhead", type=float, default=None,
                    help="exit 1 if steady-state instrumentation costs "
                         "more than this %% over the uninstrumented twin")
    ap.add_argument("--max-armed-overhead", type=float, default=None,
                    help="exit 1 if the fully-armed flight recorder "
                         "costs more than this %% over the twin")
    ap.add_argument("--min-ledger-attribution", type=float, default=None,
                    help="exit 1 unless the compile ledger names at "
                         "least this %% of jax_backend_compiles_total")
    ap.add_argument("--json", help="write the bench_obs emission here")
    ap.add_argument("--history",
                    help="append the emission to this bench_history.jsonl")
    ap.add_argument("--events",
                    help="write the budget run's telemetry JSONL here "
                         "(dense_phase events for run_report.py)")
    args = ap.parse_args(argv)

    from pos_evolution_tpu.telemetry import Telemetry

    import jax

    n_slots = args.epochs * args.slots_per_epoch

    # 1. budget run: fenced sampling + events; doubles as the jit warmer
    if args.events:
        os.makedirs(os.path.dirname(os.path.abspath(args.events)),
                    exist_ok=True)
        telemetry = Telemetry.to_file(args.events)
    else:
        telemetry = Telemetry()
    from pos_evolution_tpu.telemetry import FlightRecorder
    fr = FlightRecorder(telemetry=telemetry,
                        sample_every=args.sample_every)
    sim = _build(args, args.sample_every, telemetry=telemetry,
                 flight_recorder=fr)
    t0 = time.perf_counter()
    sim.run_epochs(args.epochs)
    budget_wall = time.perf_counter() - t0
    phases = sim.phases.summary()
    accounted = phases.get("accounted_pct")
    dense_phase_events = len(telemetry.bus.of_type("dense_phase"))
    # compile attribution vs the registry's own backend-compile count:
    # both armed at the first run_slot, so the denominators align
    compiles_total = int(telemetry.registry.counts().get(
        "jax_backend_compiles_total", 0))
    attribution = fr.ledger.attribution(total=compiles_total)
    device_summary = fr.summary()
    if args.events:
        stem = args.events[:-6] if args.events.endswith(".jsonl") \
            else args.events
        device_artifact = f"{stem}.device_ledger.json"
        fr.write_artifact(device_artifact)
    else:
        device_artifact = None
    telemetry.close()

    # 2/3. uninstrumented twin vs steady-state (slot 0 alone fences) —
    # interleaved so both sides of each pair share the box's mood
    twin_wall = steady_wall = armed_wall = float("inf")
    for _ in range(max(args.repeats, 1)):
        twin_wall = min(twin_wall, _timed_run(args, None))
        steady_wall = min(steady_wall, _timed_run(args, n_slots + 1))
        # 4. armed: steady-state profiler + full flight recorder
        armed_wall = min(armed_wall,
                         _timed_run(args, n_slots + 1, flight=True))
    overhead_pct = (100.0 * (steady_wall - twin_wall) / twin_wall
                    if twin_wall > 0 else None)
    armed_overhead_pct = (100.0 * (armed_wall - twin_wall) / twin_wall
                          if twin_wall > 0 else None)

    sampled = phases.get("sampled_phases") or {}
    counts = {
        "slots": phases.get("slots"),
        "sampled_slots": phases.get("sampled_slots"),
        "dense_phase_events": dense_phase_events,
        "phases_recorded": len(sampled),
        "device_memory_samples": device_summary.get(
            "memory", {}).get("samples"),
        "ledger_rows": len(device_summary.get(
            "compile_ledger", {}).get("rows", ())),
    }
    for name, row in sampled.items():
        counts[f"phase_rows;phase={name}"] = row.get("count")

    print(f"dense obs bench @ {args.validators} validators x "
          f"{n_slots} slots, jax backend = {jax.default_backend()}")
    print(f"  budget run   : {budget_wall * 1e3:9.2f} ms wall, "
          f"{phases.get('sampled_slots')} fenced slot(s), "
          f"accounted {accounted}%")
    print(f"  twin         : {twin_wall * 1e3:9.2f} ms wall "
          f"(uninstrumented)")
    print(f"  steady       : {steady_wall * 1e3:9.2f} ms wall "
          f"(instrumented, unfenced) -> overhead "
          f"{overhead_pct:+.2f}%")
    print(f"  armed        : {armed_wall * 1e3:9.2f} ms wall "
          f"(flight recorder on) -> overhead "
          f"{armed_overhead_pct:+.2f}%")
    print(f"  compile ledger: {attribution['named']}/"
          f"{attribution['backend_compiles']} backend compiles on a "
          f"named (function, phase) row "
          f"({attribution['named_pct']}%)")
    top = sorted(((row.get("total_ms", 0), name)
                  for name, row in sampled.items()), reverse=True)[:5]
    for ms, name in top:
        print(f"    {name:<22} {ms:9.2f} ms "
              f"({sampled[name].get('share_pct')}%)")

    emission = {
        "metric": "bench_obs",
        "validators": args.validators,
        "slots": n_slots,
        "sample_every": args.sample_every,
        "jax_backend": jax.default_backend(),
        "accounted_pct": accounted,
        "overhead_pct": (round(overhead_pct, 3)
                         if overhead_pct is not None else None),
        "armed_overhead_pct": (round(armed_overhead_pct, 3)
                               if armed_overhead_pct is not None
                               else None),
        "walls": {
            "budget_ms": round(budget_wall * 1e3, 3),
            "twin_ms": round(twin_wall * 1e3, 3),
            "steady_ms": round(steady_wall * 1e3, 3),
            "armed_ms": round(armed_wall * 1e3, 3),
        },
        "phases": sampled,
        "async_phases": phases.get("async_phases"),
        "device": device_summary,
        "counts": counts,
    }
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(emission, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"emission -> {args.json}")
    if args.history:
        from pos_evolution_tpu.profiling import history
        history.append_entry(args.history, emission, kind="bench_obs")
        print(f"history  -> {args.history} (kind=bench_obs)")
    if args.events:
        print(f"events   -> {args.events} "
              f"({dense_phase_events} dense_phase events; "
              f"next: python scripts/run_report.py {args.events})")
    if device_artifact:
        print(f"device   -> {device_artifact} "
              f"(flight-recorder artifact; run_report auto-discovers "
              f"it beside the event log)")

    ok = True
    if args.min_accounted is not None and \
            (accounted is None or accounted < args.min_accounted):
        print(f"FAIL: sampled budget accounts for {accounted}% of the "
              f"slot wall < required {args.min_accounted}%",
              file=sys.stderr)
        ok = False
    if args.max_overhead is not None and overhead_pct is not None \
            and overhead_pct > args.max_overhead:
        print(f"FAIL: steady-state overhead {overhead_pct:.2f}% > "
              f"allowed {args.max_overhead}%", file=sys.stderr)
        ok = False
    if args.max_armed_overhead is not None \
            and armed_overhead_pct is not None \
            and armed_overhead_pct > args.max_armed_overhead:
        print(f"FAIL: armed flight-recorder overhead "
              f"{armed_overhead_pct:.2f}% > allowed "
              f"{args.max_armed_overhead}%", file=sys.stderr)
        ok = False
    if args.min_ledger_attribution is not None:
        pct = attribution.get("named_pct")
        if pct is None or pct < args.min_ledger_attribution:
            print(f"FAIL: compile ledger names {pct}% of backend "
                  f"compiles < required {args.min_ledger_attribution}%",
                  file=sys.stderr)
            ok = False
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
