"""Merge a scripts/bench_config3_real.py result (JSON on stdin or path
argv[1]) into BENCH_ALL_r{N}.json as the config3b_real_bls_pairing row.

Exists so the multi-hour single-core CPU run doesn't have to be repeated
inside bench_all.py just to land in the recorded matrix; the row carries
its own backend/scale labels and a provenance note.

Usage: python scripts/merge_config3_row.py CFG3.json [--record N]
"""

import json
import os
import sys


def main():
    args = [a for a in sys.argv[1:]]
    record = 5
    if "--record" in args:
        i = args.index("--record")
        record = int(args[i + 1])
        del args[i:i + 2]
    src = args[0] if args else None
    data = json.load(open(src)) if src else json.load(sys.stdin)
    data["provenance"] = "scripts/bench_config3_real.py (standalone run)"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, f"BENCH_ALL_r{record:02d}.json")
    matrix = json.load(open(path))
    matrix["config3b_real_bls_pairing"] = data
    with open(path, "w") as f:
        json.dump(matrix, f, indent=1)
    print(f"merged config3b row into {path}")


if __name__ == "__main__":
    main()
