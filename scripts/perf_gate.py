"""Perf-regression gate: compare a fresh bench/report emission against a
baseline on COUNT-based metrics; timing metrics are report-only by
default.

Why counts: on CPU CI, wall-clock is noise, but the counters the
telemetry registry tracks — XLA recompiles, handler calls, device
dispatches — are deterministic properties of the code path taken. A PR
that doubles ``jax_backend_compiles_total`` or starts rejecting half the
on_block calls regressed the hot path even if this box can't time it;
that is exactly the class of silent TPU regression this gate exists to
catch before a device run does.

Accepted emissions (count sources, in order of preference):

- a bench emission (``bench.py`` / ``bench_all.py`` JSON) with a
  ``telemetry.counts`` mapping (flattened ``MetricsRegistry.counts()``);
- a ``scripts/run_report.py`` ``--json`` report (handler call counts);
- any JSON whose top level has a ``counts`` mapping.

Gate rule, per count key present in BOTH emissions:

    candidate <= baseline * rel_tol + abs_slack        (default 1.25 / 4)

Count keys present on only one side are listed and skipped (a new
counter is not a regression; a vanished one is suspicious but may be a
renamed metric — the listing makes it visible either way). If NO count
key is comparable: when the baseline carries no counts at all
(pre-telemetry emission) the gate passes vacuously, loudly; when BOTH
sides carry counts in disjoint namespaces (e.g. a bench emission vs a
run report) the shapes are incomparable and the gate refuses with
exit 2 rather than manufacture a vacuous pass.

Timing keys (``value`` seconds, ``*_ms`` leaves) are compared as ratios
and printed; they fail the gate only under ``--strict-timing`` (meant
for same-hardware A/B runs, never CPU CI).

**History mode** (``--history bench_history.jsonl``): instead of one
baseline file, gate against the robust band of the last ``--window``
entries of a ``profiling/history.py`` time-series — a count metric fails
only when it exceeds ``median + max(mad_k · 1.4826 · MAD, abs_slack)``
of its own recent history, so one noisy run neither poisons the band
nor slips a slow drift through. Empty history passes vacuously
(loudly) — EXCEPT when ``--kind`` was requested explicitly and the
filter matched nothing: an emission family the caller named that has
never emitted is a typo or a CI wiring error, and a vacuous pass there
would disable the gate forever without anyone noticing — that refuses
with exit 2. (``--list-kinds`` prints what the history actually
holds.) A history whose counts share no keys with the candidate is
incomparable and refuses with exit 2, same as baseline mode.

**Outlier quarantine** (``--max-abs-ratio R``, default off): MAD bands
are robust, which cuts both ways — a grossly contaminated history entry
(a bench that ran concurrently with a test suite, say the 18.7s run of
CHANGES PR 6) is silently *absorbed* instead of surfaced, and with a
small window it can drag the median enough to wave a regression
through. With the flag on, any entry whose value differs from the
median of the OTHER entries by more than a factor of R (either
direction) is flagged LOUDLY as ``[QUARANTINE]`` in the report and
excluded from the band. Series with fewer than 3 entries are never
quarantined (too few points to tell an outlier from a level shift),
zero-vs-nonzero comparisons are exempt (sparse counters legitimately
toggle 0 <-> small; no meaningful ratio exists), and a series where
EVERY entry implicates the others is kept raw but reported loudly as
mutually inconsistent.

Usage:
    python scripts/perf_gate.py --candidate fresh.json
        [--baseline BENCH_r05.json] [--rel-tol 1.25] [--abs-slack 4]
        [--count-only] [--strict-timing]
        [--history bench_history.jsonl] [--window 20] [--mad-k 4.0]
        [--kind bench] [--max-abs-ratio 8.0]
    python scripts/perf_gate.py --history bench_history.jsonl --list-kinds

``--baseline`` defaults to the newest ``BENCH_r*.json`` /
``BENCH_ALL_r*.json`` in the repo root, falling back to
``BASELINE.json``. Exit 0 = pass, 1 = regression, 2 = usage error or
incomparable emission shapes.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
# scripts/ itself, so the failure path can import the perf_diff sibling
sys.path.insert(0, os.path.join(_REPO, "scripts"))


def extract_counts(obj: dict) -> dict[str, float]:
    """Flat {metric-key: numeric} count emission from any accepted shape."""
    out: dict[str, float] = {}
    tel = obj.get("telemetry")
    if isinstance(tel, dict):
        counts = tel.get("counts", tel)
        for k, v in counts.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = v
    if isinstance(obj.get("counts"), dict):
        for k, v in obj["counts"].items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = v
    # registry counts() keys carry the status label
    # (handler_calls_total;handler=X;status=Y); fold in the per-handler
    # aggregate so they intersect the report-derived keys below
    agg: dict[str, float] = {}
    for k, v in out.items():
        if k.startswith("handler_calls_total;handler=") and ";status=" in k:
            base = k.split(";status=", 1)[0]
            agg[base] = agg.get(base, 0) + v
    out.update(agg)
    handlers = obj.get("handlers")
    if isinstance(handlers, dict):  # run_report.py --json shape
        for name, row in handlers.items():
            if isinstance(row, dict) and isinstance(row.get("count"), int):
                out[f"handler_calls_total;handler={name}"] = row["count"]
    return out


def extract_timings(obj: dict, prefix: str = "") -> dict[str, float]:
    """Numeric timing leaves: the bench headline ``value`` (seconds) and
    any ``*_ms`` / ``*_s`` key, recursively."""
    out: dict[str, float] = {}
    for k, v in obj.items():
        path = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(extract_timings(v, path))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            if k == "value" or re.search(r"(^|_)ms(_|$)|_s$|_seconds$", k):
                out[path] = float(v)
    return out


def default_baseline() -> str | None:
    def round_of(path: str) -> int:
        m = re.search(r"_r(\d+)\.json$", path)
        return int(m.group(1)) if m else -1

    cands = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json"))
                   + glob.glob(os.path.join(_REPO, "BENCH_ALL_r*.json")),
                   key=round_of)
    if cands:
        return cands[-1]
    base = os.path.join(_REPO, "BASELINE.json")
    return base if os.path.exists(base) else None


def gate(baseline: dict, candidate: dict, rel_tol: float, abs_slack: float,
         count_only: bool = True, strict_timing: bool = False,
         out=None) -> int:
    """Compare two emissions; returns the process exit code."""
    out = out if out is not None else sys.stdout  # late-bound: capsys swaps
    b_counts, c_counts = extract_counts(baseline), extract_counts(candidate)
    shared = sorted(set(b_counts) & set(c_counts))
    failures = []
    print(f"count metrics: {len(shared)} comparable "
          f"({len(c_counts) - len(shared)} candidate-only, "
          f"{len(b_counts) - len(shared)} baseline-only)", file=out)
    for key in shared:
        b, c = b_counts[key], c_counts[key]
        limit = b * rel_tol + abs_slack
        verdict = "FAIL" if c > limit else "ok"
        if c > limit:
            failures.append(key)
        print(f"  [{verdict}] {key}: baseline={b} candidate={c} "
              f"limit={limit:.1f}", file=out)
    for key in sorted(set(c_counts) - set(b_counts)):
        print(f"  [skip] {key}: no baseline (candidate={c_counts[key]})",
              file=out)
    for key in sorted(set(b_counts) - set(c_counts)):
        print(f"  [skip] {key}: vanished from candidate "
              f"(baseline={b_counts[key]})", file=out)
    if not shared:
        if b_counts and c_counts:
            # both emissions carry counts but in disjoint namespaces —
            # comparing a bench emission against a run report, or two
            # incompatible formats. Passing here would let a real
            # regression ship behind a "vacuous pass".
            print("  both emissions have counts but share NO keys — "
                  "incomparable emission shapes; refusing to gate",
                  file=out)
            return 2
        print("  no comparable count metrics — gate passes VACUOUSLY "
              "(baseline predates telemetry counts?)", file=out)

    if not count_only:
        b_times, c_times = (extract_timings(baseline),
                            extract_timings(candidate))
        t_shared = sorted(set(b_times) & set(c_times))
        print(f"timing metrics ({'GATED' if strict_timing else 'report-only'}"
              f"): {len(t_shared)} comparable", file=out)
        for key in t_shared:
            b, c = b_times[key], c_times[key]
            ratio = c / b if b else float("inf")
            flag = strict_timing and ratio > rel_tol
            if flag:
                failures.append(f"timing:{key}")
            print(f"  [{'FAIL' if flag else '--'}] {key}: "
                  f"baseline={b:.6g} candidate={c:.6g} ratio={ratio:.3f}",
                  file=out)

    if failures:
        print(f"PERF GATE: FAIL ({len(failures)} regression"
              f"{'s' if len(failures) != 1 else ''}): "
              + ", ".join(failures), file=out)
        _print_attribution(baseline, candidate, out)
        return 1
    print("PERF GATE: pass", file=out)
    return 0


def _print_attribution(baseline: dict, candidate: dict, out) -> None:
    """On gate failure, rank WHAT regressed via scripts/perf_diff.py —
    the attribution table (ISSUE 19 leg 4). Diagnostic only: any
    failure here must never change the gate's exit code."""
    try:
        import perf_diff
        print("--- attribution (scripts/perf_diff.py) ---", file=out)
        print(perf_diff.render(perf_diff.diff(baseline, candidate)),
              file=out)
    except Exception as e:  # pev: ignore[PEV005] — diagnostic only
        print(f"(perf_diff attribution unavailable: {e!r:.120})", file=out)


def quarantine_series(series: dict[str, list[float]], ratio: float,
                      out, label: str = "") -> dict[str, list[float]]:
    """Leave-one-out outlier quarantine for history series: drop (and
    loudly flag) any value whose ratio to the median of the remaining
    entries exceeds ``ratio`` in either direction. Returns the filtered
    series; series shorter than 3 entries pass through untouched."""
    from pos_evolution_tpu.profiling.history import median

    tiny = 1e-12
    cleaned: dict[str, list[float]] = {}
    for key, xs in series.items():
        if len(xs) < 3:
            cleaned[key] = xs
            continue
        keep, dropped = [], []
        for i, v in enumerate(xs):
            m = median(xs[:i] + xs[i + 1:])
            lo, hi_v = sorted((abs(v), abs(m)))
            # zero-vs-anything has no meaningful ratio: sparse counters
            # legitimately toggle 0 <-> small, and crying wolf on them
            # would train operators to ignore the quarantine signal —
            # leave those entries to the MAD band
            r = 1.0 if lo <= tiny else hi_v / lo
            (dropped if r > ratio else keep).append(v)
        if dropped and keep:
            print(f"  [QUARANTINE] {label}{key}: {len(dropped)} contaminated "
                  f"history entr{'y' if len(dropped) == 1 else 'ies'} "
                  f"(value{'s' if len(dropped) != 1 else ''} "
                  f"{[round(d, 6) for d in dropped]} vs clean median "
                  f"{median(keep):.6g}) exceed --max-abs-ratio {ratio:g} — "
                  f"excluded from the band", file=out)
            cleaned[key] = keep
        elif dropped:
            # every entry implicates every other: there is no clean core
            # to band against, so keep the raw series but say so LOUDLY
            # (silently passing it through is exactly what the flag is
            # meant to prevent)
            print(f"  [QUARANTINE] {label}{key}: series is mutually "
                  f"inconsistent — all {len(xs)} entries exceed "
                  f"--max-abs-ratio {ratio:g} against the others; keeping "
                  f"the raw series, inspect this history by hand", file=out)
            cleaned[key] = xs
        else:
            cleaned[key] = xs
    return cleaned


def gate_history(history_path: str, candidate: dict, window: int,
                 mad_k: float, abs_slack: float, rel_tol: float = 1.25,
                 kind: str | None = None, count_only: bool = True,
                 strict_timing: bool = False,
                 max_abs_ratio: float | None = None, out=None) -> int:
    """Gate one emission against the robust band of its own history
    (``profiling/history.py``); returns the process exit code.

    Timing metrics get a RELATIVE slack floor (``rel_tol`` - 1, matching
    baseline mode's ratio semantics) instead of the count-calibrated
    ``abs_slack`` — 4 absolute units would swallow any regression of a
    sub-4ms metric.

    ``kind`` selects which emission family the band is computed over.
    ``bench.py`` and ``bench_all.py`` share one history file and share
    count KEYS at very different magnitudes; a band over the mixture is
    bimodal garbage, so a mixed-kind history without an explicit
    ``--kind`` refuses with exit 2 rather than gate against it."""
    from pos_evolution_tpu.profiling import history as hist

    out = out if out is not None else sys.stdout  # late-bound: capsys swaps
    try:
        # window applies AFTER the kind filter: the band must cover the
        # last N entries of the candidate's own family
        entries = hist.read_history(history_path)
    except (OSError, ValueError) as e:
        print(f"perf_gate: history unreadable: {e}", file=out)
        return 2
    if kind is not None:
        entries = [e for e in entries if e.get("kind") == kind]
        if not entries:
            # the caller NAMED this family: a filter that matches
            # nothing is a typo or a CI wiring error, and a vacuous
            # pass here would silently disable the gate forever
            print(f"history {history_path}: zero entries of kind "
                  f"{kind!r} — an explicitly requested emission family "
                  f"with no history is a typo or a wiring error, not a "
                  f"clean slate. Run --list-kinds to see what the "
                  f"history holds; refusing to gate vacuously.",
                  file=out)
            return 2
    else:
        # an entry with no "kind" sorts as None — key it explicitly or
        # sorted() raises TypeError instead of the deliberate exit 2
        kinds = sorted({e.get("kind") for e in entries},
                       key=lambda k: (k is None, k or ""))
        if len(kinds) > 1:
            print(f"history holds MIXED emission kinds {kinds} sharing "
                  f"count keys at different magnitudes — a band over the "
                  f"mixture would gate nothing honestly. Pass --kind.",
                  file=out)
            return 2
    entries = entries[-window:]
    c_counts = extract_counts(candidate)
    # benches append their emission BEFORE anyone gates it: when the
    # newest entry IS the candidate (identical count emission), gating
    # against it would let the candidate vouch for itself — and a
    # regressed run re-gated N times would self-legitimize as its own
    # entries fill the window. Exclude it from the band.
    if entries and extract_counts(
            entries[-1].get("emission") or {}) == c_counts:
        entries = entries[:-1]
        print("note: newest history entry matches the candidate emission "
              "— excluded from the band (no self-gating)", file=out)
    series = hist.series_from_history(entries, extract_counts)
    if max_abs_ratio:
        series = quarantine_series(series, max_abs_ratio, out)
    if not entries:
        print(f"history {history_path}: EMPTY — gate passes VACUOUSLY "
              f"(first entry seeds the band)", file=out)
        print("PERF GATE: pass", file=out)
        return 0
    print(f"history: {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'} (window {window}), "
          f"band = median ± max({mad_k}·1.4826·MAD, {abs_slack})", file=out)

    rows = hist.band_verdicts(c_counts, series, k=mad_k,
                              abs_slack=abs_slack)
    failures = []
    compared = 0
    for row in rows:
        if row["verdict"] == "skip":
            print(f"  [skip] {row['key']}: no history "
                  f"(candidate={row['value']})", file=out)
            continue
        compared += 1
        if row["verdict"] == "FAIL":
            failures.append(row["key"])
        print(f"  [{row['verdict']}] {row['key']}: "
              f"candidate={row['value']} median={row['median']:.6g} "
              f"mad={row['mad']:.6g} hi={row['hi']:.6g} (n={row['n']})",
              file=out)
    for key in sorted(set(series) - set(c_counts)):
        # baseline mode reports vanished metrics; a renamed counter must
        # stay visible here too, not silently fall out of the band
        print(f"  [skip] {key}: vanished from candidate "
              f"(history n={len(series[key])})", file=out)
    if not compared:
        if c_counts and series:
            print("  candidate and history both carry counts but share NO "
                  "keys — incomparable emission shapes; refusing to gate",
                  file=out)
            return 2
        print("  no comparable count metrics — gate passes VACUOUSLY "
              "(history predates telemetry counts?)", file=out)

    if not count_only:
        c_times = extract_timings(candidate)
        t_series = hist.series_from_history(entries, extract_timings)
        if max_abs_ratio:
            t_series = quarantine_series(t_series, max_abs_ratio, out,
                                         label="timing:")
        t_rows = hist.band_verdicts(c_times, t_series, k=mad_k,
                                    abs_slack=0.0,
                                    rel_slack=max(rel_tol - 1.0, 0.0))
        print(f"timing metrics ({'GATED' if strict_timing else 'report-only'}"
              f"): {sum(r['verdict'] != 'skip' for r in t_rows)} comparable",
              file=out)
        for row in t_rows:
            if row["verdict"] == "skip":
                continue
            flag = strict_timing and row["verdict"] == "FAIL"
            if flag:
                failures.append(f"timing:{row['key']}")
            print(f"  [{'FAIL' if flag else '--'}] {row['key']}: "
                  f"candidate={row['value']:.6g} median={row['median']:.6g} "
                  f"hi={row['hi']:.6g} (n={row['n']})", file=out)

    if failures:
        print(f"PERF GATE: FAIL ({len(failures)} regression"
              f"{'s' if len(failures) != 1 else ''} vs history band): "
              + ", ".join(failures), file=out)
        # attribution baseline: the newest history emission that is not
        # the candidate itself (same no-self-gating rule as the band)
        base = next((e.get("emission") for e in reversed(entries)
                     if e.get("emission") != candidate), None)
        if base is not None:
            _print_attribution(base, candidate, out)
        return 1
    print("PERF GATE: pass", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--candidate",
                    help="fresh bench/report JSON emission (required "
                         "for gating; optional with --list-kinds)")
    ap.add_argument("--baseline",
                    help="baseline emission (default: newest BENCH_*.json, "
                         "else BASELINE.json)")
    ap.add_argument("--rel-tol", type=float, default=1.25)
    ap.add_argument("--abs-slack", type=float, default=4.0)
    ap.add_argument("--count-only", action="store_true",
                    help="skip the timing report entirely (CPU CI mode)")
    ap.add_argument("--strict-timing", action="store_true",
                    help="timing regressions also fail the gate "
                         "(same-hardware A/B only)")
    ap.add_argument("--history",
                    help="gate against a bench_history.jsonl robust band "
                         "instead of a single baseline file")
    ap.add_argument("--window", type=int, default=20,
                    help="history entries the band is computed over")
    ap.add_argument("--mad-k", type=float, default=4.0,
                    help="band halfwidth in scaled-MAD units")
    ap.add_argument("--kind",
                    help="history emission kind to gate against (e.g. "
                         "bench / bench_all); required when the history "
                         "file holds mixed kinds")
    ap.add_argument("--max-abs-ratio", type=float, default=None,
                    help="history-mode outlier quarantine: flag LOUDLY and "
                         "exclude history entries whose value differs from "
                         "the median of the other entries by more than this "
                         "factor (default: off — contaminated entries are "
                         "only absorbed by the MAD band, silently)")
    ap.add_argument("--list-kinds", action="store_true",
                    help="print the emission kinds (and entry counts) a "
                         "--history file holds, then exit — the lookup "
                         "for a --kind refusal")
    args = ap.parse_args(argv)

    if args.list_kinds:
        if not args.history:
            print("perf_gate: --list-kinds requires --history",
                  file=sys.stderr)
            return 2
        from pos_evolution_tpu.profiling import history as hist
        try:
            entries = hist.read_history(args.history)
        except (OSError, ValueError) as e:
            print(f"perf_gate: history unreadable: {e}", file=sys.stderr)
            return 2
        by_kind: dict[str, int] = {}
        for e in entries:
            k = e.get("kind") or "(none)"
            by_kind[k] = by_kind.get(k, 0) + 1
        print(f"history: {args.history} ({len(entries)} "
              f"entr{'y' if len(entries) == 1 else 'ies'})")
        for k in sorted(by_kind):
            print(f"  {k}: {by_kind[k]}")
        return 0
    if not args.candidate:
        ap.error("--candidate is required (except with --list-kinds)")

    if args.history:
        try:
            with open(args.candidate) as fh:
                candidate = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perf_gate: {e}", file=sys.stderr)
            return 2
        print(f"history:   {args.history}")
        print(f"candidate: {args.candidate}")
        return gate_history(args.history, candidate, window=args.window,
                            mad_k=args.mad_k, abs_slack=args.abs_slack,
                            rel_tol=args.rel_tol, kind=args.kind,
                            count_only=args.count_only,
                            strict_timing=args.strict_timing,
                            max_abs_ratio=args.max_abs_ratio)

    baseline_path = args.baseline or default_baseline()
    if baseline_path is None or not os.path.exists(baseline_path):
        print(f"perf_gate: no baseline found ({baseline_path!r})",
              file=sys.stderr)
        return 2
    try:
        with open(baseline_path) as fh:
            baseline = json.load(fh)
        with open(args.candidate) as fh:
            candidate = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    print(f"baseline:  {baseline_path}")
    print(f"candidate: {args.candidate}")
    return gate(baseline, candidate, args.rel_tol, args.abs_slack,
                count_only=args.count_only,
                strict_timing=args.strict_timing)


if __name__ == "__main__":
    sys.exit(main())
