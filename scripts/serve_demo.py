"""Serving-tier demo: real socket traffic against the hardened RPC front.

The "heavy traffic from millions of users" story as an actual traffic
story (ROADMAP item 3): a DAS-enabled simulation records per-slot
``ServeView`` snapshots, then a multi-worker ``serve.ServeFront`` serves
them over sockets while a seeded **open-loop** load generator drives
head/finality/update + cell-sampling traffic at it, in two phases:

1. **steady state** — uniform arrivals, no chaos: interactive p99 must
   land inside the SLO;
2. **chaos** — 10x burst windows, seeded worker stalls, proof-cache
   wipes at block boundaries, a backing-store outage window, and a
   slow-loris swarm: the tier must shed with honest rejections instead
   of collapsing (interactive goodput > 95%), and **every proof served
   must still verify** — zero correctness violations.

Usage:
    python scripts/serve_demo.py [--arrivals 100000] [--rate 6000]
        [--workers 4] [--validators 32] [--epochs 2] [--slo-ms 50]
        [--pattern hotspot] [--no-chaos] [--seed 7]
        [--events events.jsonl] [--json bench_serve.json]
        [--history bench_history.jsonl] [--record N]

``--events`` records ``serve_attach``/``serve_summary`` for
``scripts/run_report.py`` (the "Serving" section); ``--json`` writes a
``bench_serve`` emission gated by
``scripts/perf_gate.py --history --kind bench_serve``.

``--mp`` switches to the **multi-process** plane (PR 16): shared-memory
``ShmViewBoard`` view publication, a supervised ``WorkerPool`` of
SO_REUSEPORT worker *processes* across ``--fronts`` listeners, a
health-routed ``Balancer``, and the pipelined ``SwarmLoadGenerator`` —
driven 10x harder (20000/s default) while seeded chaos SIGKILLs
workers, wedges heartbeats, and exhausts fds. The emission kind becomes
``bench_serve_mp`` and the run fails unless the harness verdict is ok:
goodput >= 99%, p99 inside the SLO, zero verify failures, every kill
and wedge detected, every respawned worker on the current
shared-memory generation, and (since ISSUE 18) the fleet metrics
scraped off the ``metrics`` RPC consistent with the loadgen's ledger.

Under ``--mp``, ``--trace-rate``/``--trace-dir`` switch on end-to-end
request tracing (per-process span files, merged into one Chrome trace
by ``scripts/trace_merge.py``) and ``--metrics-out`` saves the fleet
Prometheus text scraped off the admission-exempt ``metrics`` RPC —
the ``obs-smoke`` CI artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402


def _replay(state, views, duration_s: float, stop: threading.Event) -> None:
    """Publish recorded views evenly across the load window — every
    publish is a block boundary (new cache keys, chaos wipe hook)."""
    if not views:
        return
    gap = duration_s / len(views)
    for view in views:
        if stop.is_set():
            return
        state.publish(view)
        stop.wait(gap)


def _targets_fn(state):
    def fn():
        view = state.current()
        if view is None:
            return {"roots": [], "n_cells": 0, "n_blobs": {}}
        return {"roots": [r.hex() for r in view.sidecars],
                "n_cells": view.n_cells,
                "n_blobs": {r.hex(): len(s)
                            for r, s in view.sidecars.items()}}
    return fn


def _verify_update_fn():
    from pos_evolution_tpu.lightclient.containers import LightClientUpdate
    from pos_evolution_tpu.ssz import deserialize, hash_tree_root

    def verify(result: dict) -> bool:
        if result.get("update") is None:
            return True  # "no update yet" is honest, not a violation
        data = bytes.fromhex(result["update"])
        obj = deserialize(data, LightClientUpdate)
        return bytes(hash_tree_root(obj)).hex() == result["update_root"]
    return verify


def _emit_artifacts(args, emission: dict, kind: str) -> None:
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(emission, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"emission -> {args.json}")
    if args.record is not None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"SERVE_DEMO_r{args.record:02d}.json")
        with open(path, "w") as fh:
            json.dump(emission, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"record   -> {path}")
    if args.history:
        from pos_evolution_tpu.profiling import history
        history.append_entry(args.history, emission, kind=kind)
        print(f"history  -> {args.history} (kind={kind})")


def _phase_line(tag: str, result: dict) -> None:
    load, verdict = result["load"], result["verdict"]
    inter = load["tiers"]["interactive"]
    bulk = load["tiers"]["bulk"]
    print(f"{tag}: interactive p50 {inter['p50_ms']} ms / "
          f"p99 {inter['p99_ms']} ms, goodput {inter['goodput_pct']}% "
          f"| bulk goodput {bulk['goodput_pct']}% | wall "
          f"{load['wall_s']}s | resends {verdict['resends']}, "
          f"lost {verdict['lost']}")


def _main_mp(args, telemetry) -> int:
    """The multi-process plane, in the same two-phase shape as the
    classic demo: a **steady** SLO phase at the full (10x) rate with no
    injections, then a **chaos** phase where workers are SIGKILLed and
    wedged mid-traffic — each phase is one ``run_mp_scenario`` call and
    must return a clean self-judged verdict."""
    from pos_evolution_tpu.serve import run_mp_scenario
    n_workers = args.fronts * args.workers_per_front
    chaos_on = not args.no_chaos
    print(f"== mp serving demo: steady {args.arrivals} arrivals @ "
          f"{args.rate:.0f}/s"
          + (f" + chaos {args.chaos_arrivals} @ "
             f"{args.chaos_rate:.0f}/s" if chaos_on else "")
          + f", {args.fronts} fronts x {args.workers_per_front} worker "
          f"processes, seed={args.seed} ==")
    telemetry.bus.emit(
        "serve_mp_attach", fronts=args.fronts, workers=n_workers,
        arrivals=args.arrivals, rate=args.rate,
        chaos=({"seed": args.seed, "arrivals": args.chaos_arrivals,
                "rate": args.chaos_rate, "kills": args.kills,
                "wedges": args.wedges, "fd_exhaust": args.fd_exhaust}
               if chaos_on else None))

    trace_dir = None
    if args.trace_rate > 0:
        trace_dir = args.trace_dir or tempfile.mkdtemp(
            prefix="serve_mp_trace_")
        os.makedirs(trace_dir, exist_ok=True)

    # phase 1: steady state at the headline rate — the SLO phase
    steady = run_mp_scenario(
        n_fronts=args.fronts, workers_per_front=args.workers_per_front,
        arrivals=args.arrivals, rate=args.rate, seed=args.seed,
        kills=0, wedges=0, fd_exhaust_n=0, slo_ms=args.slo_ms,
        events_bus=telemetry.bus,
        trace_rate=args.trace_rate, trace_dir=trace_dir)
    _phase_line("steady", steady)
    s_verdict = steady["verdict"]

    # phase 2: process chaos — SIGKILLs, a heartbeat wedge, and an
    # fd-exhaustion window against front 0, at a rate the survivors
    # can still absorb while their peers respawn
    chaos = None
    if chaos_on:
        chaos = run_mp_scenario(
            n_fronts=args.fronts,
            workers_per_front=args.workers_per_front,
            arrivals=args.chaos_arrivals, rate=args.chaos_rate,
            seed=args.seed, kills=args.kills, wedges=args.wedges,
            fd_exhaust_n=args.fd_exhaust, slo_ms=args.slo_ms,
            events_bus=telemetry.bus,
            trace_rate=args.trace_rate, trace_dir=trace_dir)
        _phase_line("chaos ", chaos)
        c_verdict = chaos["verdict"]
        print(f"pool:  {c_verdict['kills_delivered']} SIGKILLs "
              f"delivered ({c_verdict['crash_interruptions']} crash "
              f"interruptions), {c_verdict['hang_interruptions']} "
              f"hangs detected, {c_verdict['restarts']} respawns; "
              f"live workers on current generation: "
              f"{c_verdict['respawned_on_current_generation']}")

    verified = s_verdict["verified_proofs"] + (
        chaos["verdict"]["verified_proofs"] if chaos else 0)
    failures = s_verdict["verify_failures"] + (
        chaos["verdict"]["verify_failures"] if chaos else 0)
    print(f"SLO (steady interactive p99 <= {args.slo_ms} ms at "
          f"{args.rate:.0f}/s): "
          f"{'MET' if s_verdict['slo_ok'] else 'MISSED'}; verified "
          f"proofs {verified} (failures: {failures})")
    telemetry.bus.emit("serve_mp_summary", steady=steady, chaos=chaos)
    for tag, result in (("steady", steady),
                        ("chaos", chaos)) if chaos else (
                            ("steady", steady),):
        verdict = result["verdict"]
        detail = json.dumps({k: v for k, v in verdict.items()
                             if k != "ok"}, sort_keys=True)
        print(f"{tag} verdict: {'ok' if verdict['ok'] else 'FAILED'} "
              f"({detail})")
    assert failures == 0, \
        "a served proof failed verification — correctness violation"
    assert s_verdict["ok"], "steady mp verdict failed"
    assert chaos is None or chaos["verdict"]["ok"], \
        "chaos mp verdict failed"

    s_inter = steady["load"]["tiers"]["interactive"]
    emission = {
        "metric": "bench_serve_mp",
        "arrivals": args.arrivals + (args.chaos_arrivals
                                     if chaos_on else 0),
        "rate": args.rate,
        "fronts": args.fronts,
        "workers": n_workers,
        "seed": args.seed,
        "slo_ms": args.slo_ms,
        "slo_ok": s_verdict["slo_ok"],
        "serving": {
            "steady": {k: s_inter[k] for k in
                       ("p50_ms", "p99_ms", "p999_ms", "goodput_pct")},
            "verified_proofs": verified,
            "verify_failures": failures,
        },
        "board_generation": steady["board_generation"],
        "fleet": {
            "workers_reporting":
                s_verdict.get("fleet_workers_reporting"),
            "requests_by_worker":
                s_verdict.get("fleet_requests_by_worker"),
            "requests_total": s_verdict.get("fleet_requests_total"),
            "consistent": s_verdict.get("fleet_consistent"),
        },
    }
    if trace_dir is not None:
        emission["traced"] = (steady["load"].get("traced", 0)
                              + (chaos["load"].get("traced", 0)
                                 if chaos else 0))
        emission["trace_dir"] = trace_dir
        print(f"traces   -> {trace_dir}\n  next: "
              f"python scripts/trace_merge.py {trace_dir}")
    if args.metrics_out:
        prom = (chaos or steady).get("fleet_prometheus")
        if prom:
            with open(args.metrics_out, "w") as fh:
                fh.write(prom)
            print(f"metrics  -> {args.metrics_out}")
    if chaos is not None:
        c_inter = chaos["load"]["tiers"]["interactive"]
        c_bulk = chaos["load"]["tiers"]["bulk"]
        c_verdict = chaos["verdict"]
        emission["serving"]["chaos_interactive"] = {
            k: c_inter[k] for k in ("p50_ms", "p99_ms", "goodput_pct")}
        emission["serving"]["chaos_bulk"] = {
            "goodput_pct": c_bulk["goodput_pct"],
            "shed_pct": c_bulk["shed_pct"]}
        emission["serving"]["chaos_resends"] = c_verdict["resends"]
        emission["serving"]["chaos_lost"] = c_verdict["lost"]
        emission["chaos"] = {
            "arrivals": args.chaos_arrivals,
            "rate": args.chaos_rate,
            "injections": chaos["chaos"]["injections"],
            "fd_exhaust": chaos.get("fd_exhaust"),
        }
        emission["supervision"] = {
            "kills_delivered": c_verdict["kills_delivered"],
            "crash_interruptions": c_verdict["crash_interruptions"],
            "hang_interruptions": c_verdict["hang_interruptions"],
            "restarts": c_verdict["restarts"],
            "live_workers": c_verdict["live_workers"],
            "respawned_on_current_generation":
                c_verdict["respawned_on_current_generation"],
        }
    _emit_artifacts(args, emission, kind="bench_serve_mp")
    if args.events:
        telemetry.close()
        print(f"events   -> {args.events}\n  next: "
              f"python scripts/run_report.py {args.events}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mp", action="store_true",
                    help="drive the multi-process plane (board + "
                         "supervised worker pool + balancer) instead of "
                         "the in-process ServeFront")
    ap.add_argument("--arrivals", type=int, default=None,
                    help="total client arrivals (default 100000, "
                         "or 60000 with --mp)")
    ap.add_argument("--rate", type=float, default=None,
                    help="mean arrival rate per second (default 6000, "
                         "or 20000 with --mp)")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--validators", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--pattern", default="hotspot",
                    choices=("uniform", "diurnal", "bursty", "hotspot"),
                    help="chaos-phase arrival pattern")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="interactive p99 SLO (default 50 steady-state, "
                         "or 300 under --mp process chaos)")
    ap.add_argument("--no-chaos", action="store_true")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--fronts", type=int, default=2,
                    help="[--mp] SO_REUSEPORT listener groups")
    ap.add_argument("--workers-per-front", type=int, default=2,
                    help="[--mp] worker processes per front")
    ap.add_argument("--kills", type=int, default=2,
                    help="[--mp] seeded worker SIGKILLs")
    ap.add_argument("--wedges", type=int, default=1,
                    help="[--mp] seeded heartbeat-wedge windows")
    ap.add_argument("--fd-exhaust", type=int, default=32,
                    help="[--mp] idle connections held against front 0")
    ap.add_argument("--chaos-arrivals", type=int, default=30_000,
                    help="[--mp] chaos-phase arrivals")
    ap.add_argument("--chaos-rate", type=float, default=10_000.0,
                    help="[--mp] chaos-phase arrival rate — the rate "
                         "the surviving workers must hold while their "
                         "peers are killed, wedged, and respawned")
    ap.add_argument("--trace-rate", type=float, default=0.0,
                    help="[--mp] seeded fraction of arrivals carrying "
                         "an end-to-end trace id (0 = tracing off)")
    ap.add_argument("--trace-dir",
                    help="[--mp] directory for per-process span files "
                         "(default: a fresh temp dir when --trace-rate "
                         "> 0); merge with scripts/trace_merge.py")
    ap.add_argument("--metrics-out",
                    help="[--mp] write the fleet Prometheus text scraped "
                         "off the metrics RPC here")
    ap.add_argument("--events", help="telemetry JSONL output path")
    ap.add_argument("--json", help="write the bench emission here")
    ap.add_argument("--history",
                    help="append the emission to this bench_history.jsonl")
    ap.add_argument("--record", type=int, default=None,
                    help="also write SERVE_DEMO_r{N}.json at the repo root")
    args = ap.parse_args(argv)
    if args.arrivals is None:
        args.arrivals = 60_000 if args.mp else 100_000
    if args.rate is None:
        args.rate = 20_000.0 if args.mp else 6000.0
    if args.slo_ms is None:
        args.slo_ms = 300.0 if args.mp else 50.0

    with use_config(minimal_config()):
        if args.mp:
            from pos_evolution_tpu.telemetry import Telemetry
            if args.events:
                os.makedirs(os.path.dirname(
                    os.path.abspath(args.events)), exist_ok=True)
                telemetry = Telemetry.to_file(args.events)
            else:
                telemetry = Telemetry()
            return _main_mp(args, telemetry)
        from pos_evolution_tpu.serve import (
            LoadGenerator,
            ServeChaos,
            ServeFront,
            ServingState,
            SlowLorisSwarm,
        )
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.telemetry import Telemetry
        telemetry = (Telemetry.to_file(args.events) if args.events
                     else Telemetry())

        print(f"== serving demo: {args.arrivals} arrivals @ "
              f"{args.rate:.0f}/s, {args.workers} workers, "
              f"chaos={'off' if args.no_chaos else 'on'} ==")
        # 1. record the chain + per-slot serving views
        sim = Simulation(args.validators, das=True, serve=True,
                         telemetry=telemetry)
        sim.run_epochs(args.epochs)
        views = sim.serving_state.views
        assert views, "the simulation never published a serving view"
        print(f"recorded {len(views)} serving views "
              f"({sum(len(v.sidecars) for v in views)} windowed blob "
              f"blocks)")

        # 2. the live front over a replayed view stream
        state = ServingState()
        state.publish(views[0])
        chaos = None if args.no_chaos else ServeChaos(
            args.seed, stall_prob=0.0, stall_s=0.08, wipe_prob=0.5)
        # read timeout below the loris dribble interval: a connection
        # stalled MID-frame longer than this is closed (real requests
        # arrive in one sendall; only an attacker dribbles)
        front = ServeFront(state, scheme=sim.das.scheme,
                           registry=telemetry.registry, workers=args.workers,
                           read_timeout_s=0.4, chaos=chaos)
        addr = front.start()
        n_steady = args.arrivals // 2
        n_chaos = args.arrivals - n_steady
        steady_dur = n_steady / args.rate
        chaos_dur = n_chaos / args.rate
        telemetry.bus.emit(
            "serve_attach", workers=args.workers, pattern=args.pattern,
            arrivals=args.arrivals, rate=args.rate,
            chaos=(None if args.no_chaos else
                   {"seed": args.seed, "stall_s": 0.08, "wipe_prob": 0.5,
                    "bursts": 2, "slow_loris": 8}))

        # warmup: a short ping/head burst before the SLO phase — the
        # SLO is a STEADY-STATE contract, and the first packets pay
        # one-time costs (connection setup, code-path warmth) that say
        # nothing about serving capacity
        from pos_evolution_tpu.serve import ServeClient
        warm = ServeClient(addr, connections=4)
        for _ in range(50):
            warm.request("head", deadline_s=1.0, tier=0)
        warm.close()

        # 3. phase 1: steady state (SLO phase)
        mid = max(len(views) // 2, 1)
        stop = threading.Event()
        replayer = threading.Thread(
            target=_replay, args=(state, views[1:mid], steady_dur, stop),
            daemon=True)
        replayer.start()
        steady = LoadGenerator(
            addr, n_steady, args.rate, pattern="uniform",
            seed=args.seed, targets_fn=_targets_fn(state),
            verify_update=_verify_update_fn()).run()
        stop.set()  # the load is done: no stale steady-phase publishes
        replayer.join(timeout=5.0)
        s_int = steady["tiers"]["interactive"]
        print(f"steady: interactive p50 {s_int['p50_ms']} ms / "
              f"p99 {s_int['p99_ms']} ms / p999 {s_int['p999_ms']} ms, "
              f"goodput {s_int['goodput_pct']}%")

        # 4. phase 2: chaos (burst + stalls + wipes + outage + loris)
        loris = None
        burst_windows = ()
        if chaos is not None:
            burst_windows = chaos.burst_windows(chaos_dur, n_bursts=2,
                                                mult=10.0,
                                                width_frac=0.05)
        chaos_gen = LoadGenerator(
            addr, n_chaos, args.rate, pattern=args.pattern,
            seed=args.seed + 1, burst_windows=burst_windows,
            targets_fn=_targets_fn(state),
            verify_update=_verify_update_fn())
        # 10x bursts COMPRESS the realized schedule (the same n arrives
        # sooner), so injections are armed against the actual span of
        # the generated arrivals, not the nominal duration — chaos that
        # fires after the last arrival tests nothing
        span = float(chaos_gen.offsets[-1])
        if chaos is not None:
            # two seeded worker-stall windows inside the active span —
            # each freezes one of the workers for half a second
            chaos.arm_stalls(time.monotonic(), span * 0.8, n_stalls=2,
                             stall_s=0.5, workers=args.workers)
            loris = SlowLorisSwarm(addr, n=8, dribble_s=0.6)
            loris.start()
            # backing outage in the middle of the chaos window
            threading.Timer(span * 0.4,
                            chaos.fail_backing_for, (0.4,)).start()
        stop = threading.Event()
        replayer = threading.Thread(
            target=_replay, args=(state, views[mid:], span, stop),
            daemon=True)
        replayer.start()
        chaos_load = chaos_gen.run()
        stop.set()
        replayer.join(timeout=5.0)
        if loris is not None:
            loris.stop()
        c_int = chaos_load["tiers"]["interactive"]
        c_blk = chaos_load["tiers"]["bulk"]
        print(f"chaos:  interactive p50 {c_int['p50_ms']} ms / "
              f"p99 {c_int['p99_ms']} ms, goodput {c_int['goodput_pct']}%"
              f" | bulk goodput {c_blk['goodput_pct']}%, "
              f"shed {c_blk['shed_pct']}%")

        server_summary = front.summary()
        front.stop()

        # 5. the acceptance contract
        slo_ok = (s_int["p99_ms"] or 0) <= args.slo_ms
        verified = steady["verified_proofs"] + chaos_load["verified_proofs"]
        failures = (steady["verify_failures"]
                    + chaos_load["verify_failures"])
        int_goodput = c_int["goodput_pct"] or 0.0
        honest_rejects = (server_summary["by_status"].get("shed", 0)
                          + server_summary["by_status"].get("unavailable",
                                                            0)
                          + server_summary["by_status"].get("timeout", 0))
        print(f"verified proofs: {verified} (failures: {failures}); "
              f"honest rejections: {honest_rejects} "
              f"(shed/unavailable/timeout); hedges: "
              f"{steady['hedges'] + chaos_load['hedges']}")
        print(f"SLO (steady interactive p99 <= {args.slo_ms} ms): "
              f"{'MET' if slo_ok else 'MISSED'}; chaos interactive "
              f"goodput {int_goodput}%")
        assert failures == 0, \
            "a served proof failed verification — correctness violation"
        assert slo_ok, "steady-state p99 blew the SLO"
        assert int_goodput > 95.0, \
            "interactive goodput collapsed under chaos"

        load_combined = dict(chaos_load)
        load_combined["arrivals"] = (steady["arrivals"]
                                     + chaos_load["arrivals"])
        load_combined["verified_proofs"] = verified
        load_combined["verify_failures"] = failures
        load_combined["hedges"] = steady["hedges"] + chaos_load["hedges"]
        load_combined["retries"] = (steady["retries"]
                                    + chaos_load["retries"])
        load_combined["wall_s"] = round(steady["wall_s"]
                                        + chaos_load["wall_s"], 3)
        telemetry.bus.emit(
            "serve_summary", server=server_summary, load=load_combined,
            chaos=(chaos.summary() if chaos is not None else None),
            steady=steady, slo_ms=args.slo_ms, slo_ok=slo_ok)

        emission = {
            "metric": "bench_serve",
            "arrivals": args.arrivals,
            "rate": args.rate,
            "workers": args.workers,
            "pattern": args.pattern,
            "chaos": not args.no_chaos,
            "slo_ms": args.slo_ms,
            "slo_ok": slo_ok,
            "serving": {
                "steady": {k: s_int[k] for k in
                           ("p50_ms", "p99_ms", "p999_ms",
                            "goodput_pct")},
                "chaos_interactive": {k: c_int[k] for k in
                                      ("p50_ms", "p99_ms",
                                       "goodput_pct")},
                "chaos_bulk": {"goodput_pct": c_blk["goodput_pct"],
                               "shed_pct": c_blk["shed_pct"]},
                "shed_rate": server_summary["shed_rate"],
                "verified_proofs": verified,
                "verify_failures": failures,
                "scheme_builds": server_summary["scheme_builds"],
                "singleflight_waits":
                    server_summary["singleflight"]["waits"],
            },
            "telemetry": {"counts": telemetry.registry.counts()},
        }
        _emit_artifacts(args, emission, kind="bench_serve")
        if args.events:
            telemetry.close()
            print(f"events   -> {args.events}\n  next: "
                  f"python scripts/run_report.py {args.events}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
