"""DEPRECATED shim — use ``scripts/run_report.py --xplane`` instead.

This tool has been a thin wrapper over ``profiling/xplane.py`` since
PR 4; ISSUE 19 folded it into ``run_report.py`` (``--xplane TRACE``
summarizes a trace into the report's top-device-ops table, with
``--top-n`` for the row count). The importable names below still
forward to ``pos_evolution_tpu.profiling.xplane`` so old callers keep
working, and the CLI still prints the same JSON — but both emit a
DeprecationWarning and will be removed after the next milestone.

Old:  python scripts/trace_summary.py TRACE [TOP_N]
New:  python scripts/run_report.py events.jsonl --xplane TRACE [--top-n N]
"""

from __future__ import annotations

import json
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.profiling.xplane import (  # noqa: E402,F401
    summarize_path,
    summarize_xplane,   # re-exported for legacy importers
    top_table,          # re-exported for legacy importers
)

_DEPRECATION = ("scripts/trace_summary.py is deprecated; use "
                "scripts/run_report.py --xplane TRACE [--top-n N]")

warnings.warn(_DEPRECATION, DeprecationWarning, stacklevel=2)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    print(f"# {_DEPRECATION}", file=sys.stderr)
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    top_n = int(argv[1]) if len(argv) > 1 else 10
    print(json.dumps(summarize_path(argv[0], top_n), indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
