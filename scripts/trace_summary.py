"""Summarize a jax.profiler xplane trace into a top-N op table.

Thin CLI shim: the wire-format parser lives in
``pos_evolution_tpu/profiling/xplane.py`` (importable; also feeds the
Chrome-trace exporter and the span-attribution pass). This entry point
keeps the historic invocation working:

Usage: python scripts/trace_summary.py <trace_dir_or_xplane.pb> [top_n]
Prints the top-N table as JSON — device planes first.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.profiling.xplane import (  # noqa: E402,F401
    summarize_path,
    summarize_xplane,   # re-exported for legacy importers
    top_table,          # re-exported for legacy importers
)

if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    top = summarize_path(sys.argv[1],
                         int(sys.argv[2]) if len(sys.argv) > 2 else 10)
    print(json.dumps(top, indent=1))
