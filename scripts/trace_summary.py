"""Summarize a jax.profiler xplane trace into a top-N op table.

No xplane_pb2 bindings ship in this image, so this walks the protobuf
wire format directly with the field numbers from
tsl/profiler/protobuf/xplane.proto (stable public schema):

    XSpace.planes = 1
    XPlane.name = 2, XPlane.lines = 3, XPlane.event_metadata = 4 (map)
    XLine.name = 2, XLine.events = 4
    XEvent.metadata_id = 1, XEvent.duration_ps = 3
    XEventMetadata.id = 1, XEventMetadata.name = 2

Usage: python scripts/trace_summary.py <trace_dir_or_xplane.pb> [top_n]
Prints one line per op: total_ms, count, op name — device planes first.
"""

import glob
import json
import os
import sys


def _varint(buf, i):
    out = shift = 0
    while True:
        b = buf[i]
        out |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) over a message buffer."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, i = _varint(buf, i)
        elif wtype == 1:
            val, i = buf[i:i + 8], i + 8
        elif wtype == 2:
            ln, i = _varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wtype == 5:
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def summarize_xplane(data: bytes):
    """-> list of planes: {name, ops: {op_name: [total_ps, count]}}."""
    planes = []
    for fnum, _, plane_buf in _fields(data):
        if fnum != 1:
            continue
        name, metadata, lines = "", {}, []
        for pf, _, pv in _fields(plane_buf):
            if pf == 2:
                name = pv.decode("utf-8", "replace")
            elif pf == 3:
                lines.append(pv)
            elif pf == 4:  # map<int64, XEventMetadata> entry
                mid, mname = 0, ""
                for mf, _, mv in _fields(pv):
                    if mf == 1:
                        mid = mv
                    elif mf == 2:  # XEventMetadata
                        for ef, _, ev in _fields(mv):
                            if ef == 1:
                                mid = ev
                            elif ef == 2:
                                mname = ev.decode("utf-8", "replace")
                metadata[mid] = mname
        ops = {}
        for line_buf in lines:
            for lf, _, lv in _fields(line_buf):
                if lf != 4:
                    continue
                mid = dur = 0
                for ef, _, ev in _fields(lv):
                    if ef == 1:
                        mid = ev
                    elif ef == 3:
                        dur = ev
                key = metadata.get(mid, f"#{mid}")
                tot = ops.get(key)
                if tot is None:
                    ops[key] = [dur, 1]
                else:
                    tot[0] += dur
                    tot[1] += 1
        if ops:
            planes.append({"name": name, "ops": ops})
    return planes


def top_table(planes, top_n=10):
    """-> dict plane name -> top-N [{op, total_ms, count}] (device-ish
    planes sorted first)."""
    def rank(p):
        n = p["name"].lower()
        return (0 if ("device" in n or "tpu" in n or "gpu" in n
                      or "xla" in n) else 1, p["name"])

    out = {}
    for p in sorted(planes, key=rank):
        rows = sorted(p["ops"].items(), key=lambda kv: -kv[1][0])[:top_n]
        out[p["name"]] = [
            {"op": k, "total_ms": round(v[0] / 1e9, 3), "count": v[1]}
            for k, v in rows if v[0] > 0]
    return {k: v for k, v in out.items() if v}


def summarize_path(path, top_n=10):
    files = ([path] if os.path.isfile(path) else
             glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                       recursive=True))
    if not files:
        raise FileNotFoundError(f"no .xplane.pb under {path}")
    planes = []
    for f in files:
        with open(f, "rb") as fh:
            planes.extend(summarize_xplane(fh.read()))
    return top_table(planes, top_n)


if __name__ == "__main__":
    if len(sys.argv) < 2:
        sys.exit(__doc__)
    top = summarize_path(sys.argv[1],
                         int(sys.argv[2]) if len(sys.argv) > 2 else 10)
    print(json.dumps(top, indent=1))
