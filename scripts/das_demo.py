"""DAS serving demo: 10^5+ sampling clients over a blob-carrying chain.

Runs a small DAS-enabled simulation (proposals carry erasure-coded blob
sidecars, every view group verifies availability before importing), then
attaches a vectorized sampling-client population and serves it once per
slot through the coalescing ``DasServer`` — the "millions of users,
heavy traffic" workload of ROADMAP item 4 made concrete: population cost
is arrays, serving cost is the coalesced unique-cell set, verification
is one ``ExecutionBackend`` batch kernel per served block.

Usage:
    python scripts/das_demo.py [--clients 100000] [--epochs 3]
        [--validators 64] [--samples N] [--backend numpy|jax]
        [--scheme merkle|kzg] [--events events.jsonl]
        [--json bench_das.json] [--history bench_history.jsonl]
        [--seed 3]

``--scheme kzg`` swaps the cell commitments to the pairing-backed
``KzgCellScheme`` (kzg/, DESIGN.md §23): the population is answered by
ONE aggregated opening proof per served block instead of per-cell
merkle branches, and the emission becomes ``bench_kzg`` (gated by
``scripts/perf_gate.py --history --kind bench_kzg``) with the served
proof-bytes-per-sample cut asserted against the 128-byte merkle
baseline.

``--events`` records the run for ``scripts/run_report.py`` (the "DAS
serving" section); ``--json`` writes a ``bench_das``/``bench_kzg``
emission (telemetry counts + serving latency summary) and ``--history``
appends it to a ``profiling/history.py`` time-series so
``scripts/perf_gate.py --history --kind bench_das`` bands it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pos_evolution_tpu.config import minimal_config, use_config  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--clients", type=int, default=100_000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--samples", type=int, default=None,
                    help="samples per client per block "
                         "(default: cfg.das_samples_per_client)")
    ap.add_argument("--backend", choices=("numpy", "jax"), default="numpy")
    ap.add_argument("--scheme", choices=("merkle", "kzg"), default="merkle",
                    help="cell-commitment scheme (kzg = aggregated "
                         "multiproofs, one opening per served block)")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--events", help="telemetry JSONL output path")
    ap.add_argument("--json", help="write the bench_das emission here")
    ap.add_argument("--history",
                    help="append the emission to this bench_history.jsonl")
    ap.add_argument("--record", type=int, default=None,
                    help="also write the emission to DAS_DEMO_r{N}.json "
                         "at the repo root (the ROADMAP item 4 artifact)")
    args = ap.parse_args(argv)

    from pos_evolution_tpu.backend import set_backend
    set_backend(args.backend)

    with use_config(minimal_config()) as c:
        from pos_evolution_tpu.sim import Simulation
        from pos_evolution_tpu.telemetry import Telemetry
        telemetry = (Telemetry.to_file(args.events) if args.events
                     else Telemetry())
        telemetry.install_jax_runtime()

        print(f"== DAS serving demo: {args.clients} sampling clients, "
              f"{args.validators} validators, backend={args.backend}, "
              f"scheme={args.scheme} ==")
        sim = Simulation(args.validators, das=args.scheme,
                         telemetry=telemetry)
        sim.attach_das_clients(args.clients,
                               samples_per_client=args.samples,
                               seed=args.seed)
        t0 = time.perf_counter()
        sim.run_epochs(args.epochs)
        wall_s = time.perf_counter() - t0

        serves = telemetry.bus.of_type("das_serve")
        assert serves, "no das_serve events — the chain carried no blobs?"
        total_samples = sum(e["samples"] for e in serves)
        total_unique = sum(e["unique_requests"] for e in serves)
        failures = sum(e["failed"] for e in serves)
        # medians across served blocks of the per-block per-request
        # percentiles (matches run_report.py's "typical served block");
        # the worst block's p95 is reported separately
        p50s = sorted(e["p50_ms"] for e in serves)
        p95s = sorted(e["p95_ms"] for e in serves)
        p50 = p50s[len(p50s) // 2]
        p95 = p95s[len(p95s) // 2]
        worst_p95 = p95s[-1]
        hit_rate = serves[-1]["cache_hit_rate"]

        print(f"slots run: {sim.slot}, blocks served: {len(serves)}, "
              f"wall: {wall_s:.1f}s")
        print(f"samples served: {total_samples} "
              f"(coalesced to {total_unique} unique cell fetches, "
              f"{total_samples / max(total_unique, 1):.0f}x)")
        print(f"serving latency per coalesced request: "
              f"p50 {p50:.3f} ms, p95 {p95:.3f} ms "
              f"(typical block; worst block p95 {worst_p95:.3f} ms)")
        print(f"proof-path cache hit rate: {hit_rate:.1%}")
        print(f"verification failures: {failures}")
        print(f"clients fully satisfied at last serve: "
              f"{serves[-1]['clients_all_ok']}/{args.clients}")
        assert failures == 0, "honest chain must verify clean"
        assert serves[-1]["clients_all_ok"] == args.clients

        # proof-bytes accounting (both schemes emit it; the kzg run
        # asserts the aggregate's cut against the merkle baseline)
        proof_bytes = sum(e.get("proof_bytes", 0) for e in serves)
        bytes_per_sample = proof_bytes / max(total_samples, 1)
        merkle_depth = max(int(2 * c.das_cells_per_blob - 1).bit_length(), 0)
        merkle_bps = float(merkle_depth * 32)
        print(f"served proof bytes/sample: {bytes_per_sample:.4f} "
              f"(merkle branch baseline: {merkle_bps:.0f})")
        if args.scheme == "kzg":
            assert all(e.get("aggregated") for e in serves), \
                "kzg serves must be aggregated"
            assert bytes_per_sample * 4 <= merkle_bps, (
                f"aggregated proofs must cut served bytes/sample >= 4x vs "
                f"merkle ({bytes_per_sample:.4f} vs {merkle_bps:.0f})")

        emission = {
            "metric": "bench_das" if args.scheme == "merkle" else "bench_kzg",
            "scheme": args.scheme,
            "proof_bytes_per_sample": round(bytes_per_sample, 4),
            "merkle_bytes_per_sample": merkle_bps,
            "backend": args.backend,
            "clients": args.clients,
            "validators": args.validators,
            "epochs": args.epochs,
            "wall_s": round(wall_s, 3),
            "serving": {
                "served_blocks": len(serves),
                "samples_total": total_samples,
                "unique_requests_total": total_unique,
                "p50_ms": round(p50, 4),
                "p95_ms": round(p95, 4),
                "worst_p95_ms": round(worst_p95, 4),
                "cache_hit_rate": hit_rate,
                "failures": failures,
            },
            "telemetry": {"counts": telemetry.registry.counts()},
        }
        if args.json:
            with open(args.json, "w") as fh:
                json.dump(emission, fh, indent=1, sort_keys=True)
                fh.write("\n")
            print(f"emission -> {args.json}")
        if args.record is not None:
            path = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                f"DAS_DEMO_r{args.record:02d}.json")
            with open(path, "w") as fh:
                json.dump(emission, fh, indent=1, sort_keys=True)
                fh.write("\n")
            print(f"record   -> {path}")
        if args.history:
            from pos_evolution_tpu.profiling import history
            kind = emission["metric"]
            history.append_entry(args.history, emission, kind=kind)
            print(f"history  -> {args.history} (kind={kind})")
        if args.events:
            telemetry.close()
            print(f"events   -> {args.events}\n  next: "
                  f"python scripts/run_report.py {args.events}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
